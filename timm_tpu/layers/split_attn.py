"""Split attention (ResNeSt 'splat') over NHWC features
(reference: timm/layers/split_attn.py:18-112).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import nnx

from .create_act import get_act_fn
from .create_conv2d import create_conv2d
from .helpers import make_divisible
from .norm_act import BatchNormAct2d

__all__ = ['RadixSoftmax', 'SplitAttn']


def radix_softmax(x, radix: int, cardinality: int):
    """Softmax across the radix axis per (cardinality) group; sigmoid at radix 1
    (reference split_attn.py:18-32). x: (B, 1, 1, C*radix) → (B, C*radix)."""
    B = x.shape[0]
    if radix > 1:
        # radix-major flatten (reference transposes (card, radix) → (radix, card)
        # before flattening) so the caller's (B, radix, C) reshape aligns
        x = x.reshape(B, cardinality, radix, -1)
        x = jax.nn.softmax(x, axis=2)
        return x.transpose(0, 2, 1, 3).reshape(B, -1)
    return jax.nn.sigmoid(x.reshape(B, -1))


RadixSoftmax = radix_softmax


class SplitAttn(nnx.Module):
    """Radix-grouped conv with learned soft attention over the radix splits."""

    def __init__(
            self,
            in_channels: int,
            out_channels: Optional[int] = None,
            kernel_size: int = 3,
            stride: int = 1,
            padding=None,
            dilation: int = 1,
            groups: int = 1,
            bias: bool = False,
            radix: int = 2,
            rd_ratio: float = 0.25,
            rd_channels: Optional[int] = None,
            rd_divisor: int = 8,
            act_layer='relu',
            norm_layer=None,
            drop_layer=None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        out_channels = out_channels or in_channels
        self.radix = radix
        self.cardinality = groups
        self.out_channels = out_channels
        mid_chs = out_channels * radix
        if rd_channels is None:
            attn_chs = make_divisible(in_channels * radix * rd_ratio, divisor=rd_divisor, min_value=32)
        else:
            attn_chs = rd_channels * radix

        conv_kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv = create_conv2d(
            in_channels, mid_chs, kernel_size, stride=stride, padding=padding,
            dilation=dilation, groups=groups * radix, bias=bias, **conv_kw)
        norm_layer = norm_layer or BatchNormAct2d
        self.bn0 = norm_layer(mid_chs, apply_act=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop = drop_layer(rngs=rngs) if drop_layer is not None else None
        self.act0 = get_act_fn(act_layer)
        self.fc1 = create_conv2d(out_channels, attn_chs, 1, groups=groups, bias=True, **conv_kw)
        self.bn1 = norm_layer(attn_chs, apply_act=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.act1 = get_act_fn(act_layer)
        self.fc2 = create_conv2d(attn_chs, mid_chs, 1, groups=groups, bias=True, **conv_kw)

    def __call__(self, x):
        x = self.conv(x)
        x = self.bn0(x)
        if self.drop is not None:
            x = self.drop(x)
        x = self.act0(x)

        B, H, W, RC = x.shape
        if self.radix > 1:
            xr = x.reshape(B, H, W, self.radix, RC // self.radix)
            x_gap = xr.sum(axis=3)
        else:
            x_gap = x
        x_gap = x_gap.mean(axis=(1, 2), keepdims=True)
        x_gap = self.act1(self.bn1(self.fc1(x_gap)))
        x_attn = self.fc2(x_gap)  # (B, 1, 1, RC)

        x_attn = radix_softmax(x_attn, self.radix, self.cardinality)  # (B, RC)
        if self.radix > 1:
            attn = x_attn.reshape(B, 1, 1, self.radix, RC // self.radix)
            return (xr * attn).sum(axis=3)
        return x * x_attn.reshape(B, 1, 1, RC)
