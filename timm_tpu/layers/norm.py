"""Normalization layers (reference: timm/layers/norm.py:1-575, fast_norm.py).

All activations live in NHWC / NLC layouts, so the channel axis is always the
last axis and every '2d' variant is the same computation as its 1d cousin —
no permutes, no special cases. XLA fuses these for free, which subsumes the
reference's fast_norm/APEX machinery.

Compute-precision policy: LayerNorm / RmsNorm / SimpleNorm consult
`config.norm_internal_dtype()` (or a per-instance `internal_dtype` override).
When unset (the default) the framework path runs untouched — bit-identical to
the pre-policy code. When set (e.g. bf16), statistics are computed in that
dtype, removing the fp32 upcast of ~25 LayerNorms on the ViT hot path
(PERF.md §2 item 2); the output dtype is unchanged either way.

Frameworks note: these subclass flax.nnx norm modules but expose the
reference's constructor conventions (`eps`, `affine`, positional num_channels).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import nnx

from .config import norm_internal_dtype, resolve_dtype_arg

__all__ = [
    'LayerNorm', 'LayerNorm2d', 'LayerNormFp32', 'RmsNorm', 'RmsNorm2d',
    'SimpleNorm', 'SimpleNorm2d', 'GroupNorm', 'GroupNorm1', 'BatchNorm2d',
]


def _param_value(p):
    # affine=False is Param(None) on older flax, plain None on newer
    if p is None or p.value is None:
        return None
    return p[...]


def _resolve_internal(instance_dtype):
    """Per-instance override wins; else the process policy. fp32 (or None)
    means 'take the framework path' — flax already computes stats in fp32,
    so only a reduced dtype needs the custom trace."""
    dt = instance_dtype if instance_dtype is not None else norm_internal_dtype()
    if dt is None or dt == jnp.float32:
        return None
    return dt


def _layernorm_fast(x, scale, bias, eps, dt):
    """LayerNorm with stats in `dt` (flax fast-variance semantics:
    var = E[x²] − E[x]², clamped at 0). Output keeps x.dtype."""
    xf = x.astype(dt)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.maximum(jnp.mean(xf * xf, axis=-1, keepdims=True) - mean * mean, 0.0)
    y = (xf - mean) * jax.lax.rsqrt(var + jnp.asarray(eps, dt))
    if scale is not None:
        y = y * scale.astype(dt)
    if bias is not None:
        y = y + bias.astype(dt)
    return y.astype(x.dtype)


def _rmsnorm_fast(x, scale, eps, dt):
    """RMSNorm with the mean-square reduction in `dt`."""
    xf = x.astype(dt)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(ms + jnp.asarray(eps, dt))
    if scale is not None:
        y = y * scale.astype(dt)
    return y.astype(x.dtype)


class LayerNorm(nnx.LayerNorm):
    """LayerNorm over the channel (last) axis.

    `internal_dtype` pins this instance's statistics dtype regardless of the
    process policy ('float32' = always the framework fp32 path); None defers
    to `config.norm_internal_dtype()`.
    """

    def __init__(
            self,
            num_channels: int,
            eps: float = 1e-6,
            affine: bool = True,
            internal_dtype=None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        super().__init__(
            num_channels,
            epsilon=eps,
            use_bias=affine,
            use_scale=affine,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )
        self.internal_dtype = resolve_dtype_arg(internal_dtype)

    def __call__(self, x):
        dt = _resolve_internal(getattr(self, 'internal_dtype', None))
        if dt is None:
            return super().__call__(x)
        return _layernorm_fast(x, _param_value(self.scale), _param_value(self.bias), self.epsilon, dt)


# NHWC: channels are already last, identical computation.
LayerNorm2d = LayerNorm


class LayerNormFp32(LayerNorm):
    """LayerNorm forced to fp32 statistics (reference norm.py LayerNormFp32).
    Pinned: the precision policy never downgrades this variant."""

    def __init__(self, num_channels, eps: float = 1e-6, affine: bool = True, *, rngs: nnx.Rngs, **kw):
        super().__init__(
            num_channels, eps=eps, affine=affine, internal_dtype=jnp.float32,
            dtype=jnp.float32, rngs=rngs)


class RmsNorm(nnx.RMSNorm):
    """RMSNorm over the channel axis; `internal_dtype` as in LayerNorm."""

    def __init__(
            self,
            num_channels: int,
            eps: float = 1e-6,
            affine: bool = True,
            internal_dtype=None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        super().__init__(
            num_channels,
            epsilon=eps,
            use_scale=affine,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )
        self.internal_dtype = resolve_dtype_arg(internal_dtype)

    def __call__(self, x):
        dt = _resolve_internal(getattr(self, 'internal_dtype', None))
        if dt is None:
            return super().__call__(x)
        return _rmsnorm_fast(x, _param_value(self.scale), self.epsilon, dt)


RmsNorm2d = RmsNorm


class SimpleNorm(nnx.Module):
    """x * rsqrt(var(x) + eps) — mean-centered UNBIASED variance but no mean
    subtraction of x itself (reference norm.py:394-439 via fast_norm.py
    simple_norm, which uses torch.var's default correction=1). Distinct from
    RMSNorm, which divides by sqrt(mean(x²))."""

    def __init__(
            self,
            num_channels: int,
            eps: float = 1e-6,
            affine: bool = True,
            internal_dtype=None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.eps = eps
        self.scale = nnx.Param(jnp.ones((num_channels,), param_dtype)) if affine else None
        self.internal_dtype = resolve_dtype_arg(internal_dtype)

    def __call__(self, x):
        dtype = x.dtype
        dt = _resolve_internal(getattr(self, 'internal_dtype', None)) or jnp.float32
        xf = x.astype(dt)
        v = jnp.var(xf, axis=-1, keepdims=True, ddof=1)
        xf = xf * jax.lax.rsqrt(v + jnp.asarray(self.eps, dt))
        if self.scale is not None:
            xf = xf * self.scale[...].astype(dt)
        return xf.astype(dtype)


SimpleNorm2d = SimpleNorm


class GroupNorm(nnx.GroupNorm):
    def __init__(
            self,
            num_channels: int,
            num_groups: int = 32,
            eps: float = 1e-5,
            affine: bool = True,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        super().__init__(
            num_channels,
            num_groups=num_groups,
            epsilon=eps,
            use_bias=affine,
            use_scale=affine,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )


class GroupNorm1(GroupNorm):
    """Group normalization with 1 group == LayerNorm over (H, W, C)."""

    def __init__(self, num_channels, **kwargs):
        super().__init__(num_channels, num_groups=1, **kwargs)


class BatchNorm2d(nnx.BatchNorm):
    """BatchNorm over N,H,W for NHWC inputs.

    Under pjit with a batch-sharded input, the mean/var reductions are global
    across the device mesh — XLA inserts the cross-replica collectives — so
    this is natively a SyncBatchNorm (reference norm_act.py SyncBatchNormAct /
    convert_sync_batchnorm have no separate TPU equivalent).
    """

    def __init__(
            self,
            num_features: int,
            eps: float = 1e-5,
            momentum: float = 0.1,
            affine: bool = True,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        # torch-style momentum (weight of the *new* batch stat) → flax decay
        super().__init__(
            num_features,
            use_running_average=False,
            momentum=1.0 - momentum,
            epsilon=eps,
            use_bias=affine,
            use_scale=affine,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )
