"""Normalization layers (reference: timm/layers/norm.py:1-575, fast_norm.py).

All activations live in NHWC / NLC layouts, so the channel axis is always the
last axis and every '2d' variant is the same computation as its 1d cousin —
no permutes, no special cases. XLA fuses these for free, which subsumes the
reference's fast_norm/APEX machinery.

Frameworks note: these subclass flax.nnx norm modules but expose the
reference's constructor conventions (`eps`, `affine`, positional num_channels).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import nnx

__all__ = [
    'LayerNorm', 'LayerNorm2d', 'LayerNormFp32', 'RmsNorm', 'RmsNorm2d',
    'SimpleNorm', 'SimpleNorm2d', 'GroupNorm', 'GroupNorm1', 'BatchNorm2d',
]


class LayerNorm(nnx.LayerNorm):
    """LayerNorm over the channel (last) axis."""

    def __init__(
            self,
            num_channels: int,
            eps: float = 1e-6,
            affine: bool = True,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        super().__init__(
            num_channels,
            epsilon=eps,
            use_bias=affine,
            use_scale=affine,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )


# NHWC: channels are already last, identical computation.
LayerNorm2d = LayerNorm


class LayerNormFp32(LayerNorm):
    """LayerNorm forced to fp32 statistics (reference norm.py LayerNormFp32)."""

    def __init__(self, num_channels, eps: float = 1e-6, affine: bool = True, *, rngs: nnx.Rngs, **kw):
        super().__init__(num_channels, eps=eps, affine=affine, dtype=jnp.float32, rngs=rngs)


class RmsNorm(nnx.RMSNorm):
    def __init__(
            self,
            num_channels: int,
            eps: float = 1e-6,
            affine: bool = True,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        super().__init__(
            num_channels,
            epsilon=eps,
            use_scale=affine,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )


RmsNorm2d = RmsNorm


class SimpleNorm(nnx.Module):
    """x * rsqrt(var(x) + eps) — mean-centered UNBIASED variance but no mean
    subtraction of x itself (reference norm.py:394-439 via fast_norm.py
    simple_norm, which uses torch.var's default correction=1). Distinct from
    RMSNorm, which divides by sqrt(mean(x²))."""

    def __init__(
            self,
            num_channels: int,
            eps: float = 1e-6,
            affine: bool = True,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.eps = eps
        self.scale = nnx.Param(jnp.ones((num_channels,), param_dtype)) if affine else None

    def __call__(self, x):
        dtype = x.dtype
        xf = x.astype(jnp.float32)
        v = jnp.var(xf, axis=-1, keepdims=True, ddof=1)
        xf = xf * jax.lax.rsqrt(v + self.eps)
        if self.scale is not None:
            xf = xf * self.scale[...].astype(jnp.float32)
        return xf.astype(dtype)


SimpleNorm2d = SimpleNorm


class GroupNorm(nnx.GroupNorm):
    def __init__(
            self,
            num_channels: int,
            num_groups: int = 32,
            eps: float = 1e-5,
            affine: bool = True,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        super().__init__(
            num_channels,
            num_groups=num_groups,
            epsilon=eps,
            use_bias=affine,
            use_scale=affine,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )


class GroupNorm1(GroupNorm):
    """Group normalization with 1 group == LayerNorm over (H, W, C)."""

    def __init__(self, num_channels, **kwargs):
        super().__init__(num_channels, num_groups=1, **kwargs)


class BatchNorm2d(nnx.BatchNorm):
    """BatchNorm over N,H,W for NHWC inputs.

    Under pjit with a batch-sharded input, the mean/var reductions are global
    across the device mesh — XLA inserts the cross-replica collectives — so
    this is natively a SyncBatchNorm (reference norm_act.py SyncBatchNormAct /
    convert_sync_batchnorm have no separate TPU equivalent).
    """

    def __init__(
            self,
            num_features: int,
            eps: float = 1e-5,
            momentum: float = 0.1,
            affine: bool = True,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        # torch-style momentum (weight of the *new* batch stat) → flax decay
        super().__init__(
            num_features,
            use_running_average=False,
            momentum=1.0 - momentum,
            epsilon=eps,
            use_bias=affine,
            use_scale=affine,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )
