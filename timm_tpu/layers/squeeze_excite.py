"""Squeeze-and-Excitation modules (reference: timm/layers/squeeze_excite.py)."""
from __future__ import annotations

from typing import Callable, Optional, Union

import jax.numpy as jnp
from flax import nnx

from .create_act import get_act_fn
from .helpers import make_divisible
from .weight_init import variance_scaling_, zeros_

__all__ = ['SEModule', 'EffectiveSEModule', 'SqueezeExcite']


class SEModule(nnx.Module):
    """SE over NHWC features: squeeze (mean HW) → fc → act → fc → gate."""

    def __init__(
            self,
            channels: int,
            rd_ratio: float = 1. / 16,
            rd_channels: Optional[int] = None,
            rd_divisor: int = 8,
            add_maxpool: bool = False,
            bias: bool = True,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer=None,
            gate_layer: Union[str, Callable] = 'sigmoid',
            force_act_layer: Union[str, Callable, None] = None,
            rd_round_fn: Optional[Callable] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        if not rd_channels:
            rd_round_fn = rd_round_fn or (lambda v: make_divisible(v, rd_divisor, round_limit=0.0))
            rd_channels = rd_round_fn(channels * rd_ratio)
        act_layer = force_act_layer or act_layer
        self.add_maxpool = add_maxpool
        conv = lambda ci, co: nnx.Linear(
            ci, co, use_bias=bias, dtype=dtype, param_dtype=param_dtype,
            kernel_init=variance_scaling_(2.0, 'fan_out', 'normal'), bias_init=zeros_, rngs=rngs,
        )
        self.fc1 = conv(channels, rd_channels)
        self.bn = norm_layer(rd_channels, rngs=rngs) if norm_layer is not None else None
        self.act = get_act_fn(act_layer)
        self.fc2 = conv(rd_channels, channels)
        self.gate = get_act_fn(gate_layer)

    def __call__(self, x):
        # x: (B, H, W, C)
        x_se = x.mean(axis=(1, 2), keepdims=True)
        if self.add_maxpool:
            x_se = 0.5 * (x_se + x.max(axis=(1, 2), keepdims=True))
        x_se = self.fc1(x_se)
        if self.bn is not None:
            x_se = self.bn(x_se)
        x_se = self.act(x_se)
        x_se = self.fc2(x_se)
        return x * self.gate(x_se)


SqueezeExcite = SEModule


class EffectiveSEModule(nnx.Module):
    """'Effective' SE — single fc, hard-sigmoid gate (reference squeeze_excite.py:~90)."""

    def __init__(
            self,
            channels: int,
            add_maxpool: bool = False,
            gate_layer: Union[str, Callable] = 'hard_sigmoid',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.add_maxpool = add_maxpool
        self.fc = nnx.Linear(
            channels, channels, use_bias=True, dtype=dtype, param_dtype=param_dtype,
            kernel_init=variance_scaling_(2.0, 'fan_out', 'normal'), bias_init=zeros_, rngs=rngs,
        )
        self.gate = get_act_fn(gate_layer)

    def __call__(self, x):
        x_se = x.mean(axis=(1, 2), keepdims=True)
        if self.add_maxpool:
            x_se = 0.5 * (x_se + x.max(axis=(1, 2), keepdims=True))
        x_se = self.fc(x_se)
        return x * self.gate(x_se)
