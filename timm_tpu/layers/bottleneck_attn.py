"""Bottleneck self-attention (BoTNet), TPU-native NHWC
(reference: timm/layers/bottleneck_attn.py:1-190; Srinivas et al. 2021).

The decomposed relative-position logits use a static GATHER over a trace-time
index (out[i, j] = x[i, j - i + win - 1]) instead of the reference's
pad/flatten/reshape shifting trick — identical math, no dynamic reshapes for
XLA to chase.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import nnx

from .helpers import make_divisible, to_2tuple

__all__ = ['BottleneckAttn', 'PosEmbedRel', 'rel_logits_1d']


def rel_logits_1d(q, rel_k, permute_mask: Tuple[int, ...], k_other: int):
    """Relative logits along one dimension via static gather.

    Args:
        q: (B, H, W, dim) queries (W = query positions along this axis)
        rel_k: (2 * win - 1, dim) relative embedding (win = key positions)
        permute_mask: output permutation
        k_other: key size along the OTHER axis (tiled dimension)
    Returns (permuted) (B, H, k_other, W, win).
    """
    B, H, W, dim = q.shape
    rel_size = rel_k.shape[0]
    win = (rel_size + 1) // 2
    x = jnp.einsum('bhwd,rd->bhwr', q, rel_k)  # (B, H, W, 2*win-1)
    # absolute index: key j relative to query i → j - i + win - 1
    idx = np.arange(win)[None, :] - np.arange(W)[:, None] + (win - 1)  # (W, win)
    x = jnp.take_along_axis(x, jnp.asarray(idx)[None, None], axis=-1)  # (B, H, W, win)
    x = jnp.broadcast_to(x[:, :, None], (B, H, k_other, W, win))
    return x.transpose(permute_mask)


class PosEmbedRel(nnx.Module):
    """Decomposed 2D relative position embedding over a full feature map
    (reference bottleneck_attn.py:45-81)."""

    def __init__(self, feat_size, dim_head: int, scale: float,
                 *, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.height, self.width = to_2tuple(feat_size)
        self.dim_head = dim_head
        # reference re-inits these with trunc_normal_(std=scale)
        self.height_rel = nnx.Param(
            jax.random.truncated_normal(rngs.params(), -2, 2, (self.height * 2 - 1, dim_head), param_dtype) * scale)
        self.width_rel = nnx.Param(
            jax.random.truncated_normal(rngs.params(), -2, 2, (self.width * 2 - 1, dim_head), param_dtype) * scale)

    def __call__(self, q):
        # q: (B', HW, dim) → logits (B', HW, HW)
        B, HW, _ = q.shape
        q = q.reshape(B, self.height, self.width, -1)
        rel_logits_w = rel_logits_1d(q, self.width_rel[...], (0, 1, 3, 2, 4), k_other=self.height)
        q = q.transpose(0, 2, 1, 3)
        rel_logits_h = rel_logits_1d(q, self.height_rel[...], (0, 3, 1, 4, 2), k_other=self.width)
        return (rel_logits_h + rel_logits_w).reshape(B, HW, HW)


class BottleneckAttn(nnx.Module):
    """Bottleneck attention block (reference bottleneck_attn.py:83-190)."""

    def __init__(
            self,
            dim: int,
            dim_out: Optional[int] = None,
            feat_size=None,
            stride: int = 1,
            num_heads: int = 4,
            dim_head: Optional[int] = None,
            qk_ratio: float = 1.0,
            qkv_bias: bool = False,
            scale_pos_embed: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert feat_size is not None, 'bottleneck attention requires a static feat_size'
        dim_out = dim_out or dim
        assert dim_out % num_heads == 0
        self.num_heads = num_heads
        self.dim_head_qk = dim_head or make_divisible(dim_out * qk_ratio, divisor=8) // num_heads
        self.dim_head_v = dim_out // num_heads
        self.dim_out_qk = num_heads * self.dim_head_qk
        self.dim_out_v = num_heads * self.dim_head_v
        self.scale = self.dim_head_qk ** -0.5
        self.scale_pos_embed = scale_pos_embed
        self.stride = stride

        fan_in = dim
        self.qkv = nnx.Conv(
            dim, self.dim_out_qk * 2 + self.dim_out_v, kernel_size=(1, 1), use_bias=qkv_bias,
            kernel_init=nnx.initializers.truncated_normal(stddev=fan_in ** -0.5),
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.pos_embed = PosEmbedRel(feat_size, dim_head=self.dim_head_qk, scale=self.scale,
                                     param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        B, H, W, C = x.shape
        assert H == self.pos_embed.height and W == self.pos_embed.width
        x = self.qkv(x)  # (B, H, W, 2*qk + v)
        M = H * W
        q, k, v = jnp.split(x.reshape(B, M, -1), [self.dim_out_qk, self.dim_out_qk * 2], axis=-1)
        # channel layout is (heads, dim_head) head-major, matching torch's
        # B*heads reshape of the NCHW channel axis
        q = q.reshape(B, M, self.num_heads, self.dim_head_qk).transpose(0, 2, 1, 3)
        k = k.reshape(B, M, self.num_heads, self.dim_head_qk).transpose(0, 2, 1, 3)
        v = v.reshape(B, M, self.num_heads, self.dim_head_v).transpose(0, 2, 1, 3)

        pos = self.pos_embed(q.reshape(B * self.num_heads, M, self.dim_head_qk))
        pos = pos.reshape(B, self.num_heads, M, M)
        logits = jnp.einsum('bhmd,bhnd->bhmn', q, k)
        if self.scale_pos_embed:
            attn = (logits + pos) * self.scale
        else:
            attn = logits * self.scale + pos
        attn = jax.nn.softmax(attn, axis=-1)
        out = jnp.einsum('bhmn,bhnd->bhmd', attn, v)
        out = out.transpose(0, 2, 1, 3).reshape(B, H, W, self.dim_out_v)
        if self.stride == 2:
            # AvgPool2d(2, 2) floors odd maps: crop trailing row/col first
            out = out[:, :2 * (H // 2), :2 * (W // 2)]
            out = out.reshape(B, H // 2, 2, W // 2, 2, -1).mean(axis=(2, 4))
        return out
