"""Efficient Channel Attention (reference: timm/layers/eca.py:1-170)."""
from __future__ import annotations

import math
from typing import Optional


import jax.numpy as jnp
from flax import nnx

from .create_act import get_act_fn
from .weight_init import variance_scaling_

__all__ = ['EcaModule', 'CecaModule']


class EcaModule(nnx.Module):
    """1D conv over channel descriptors (no dimensionality reduction)."""

    def __init__(
            self,
            channels: Optional[int] = None,
            kernel_size: int = 3,
            gamma: float = 2,
            beta: float = 1,
            gate_layer='sigmoid',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        if channels is not None:
            t = int(abs(math.log(channels, 2) + beta) / gamma)
            kernel_size = max(t if t % 2 else t + 1, 3)
        assert kernel_size % 2 == 1
        self.conv = nnx.Conv(
            1, 1, kernel_size=(kernel_size,), padding='SAME', use_bias=False,
            dtype=dtype, param_dtype=param_dtype,
            kernel_init=variance_scaling_(1.0, 'fan_in', 'normal'), rngs=rngs)
        self.gate = get_act_fn(gate_layer)

    def __call__(self, x):
        # x: (B, H, W, C)
        y = x.mean(axis=(1, 2))[:, :, None]  # (B, C, 1)
        y = self.conv(y)[:, :, 0]            # (B, C)
        return x * self.gate(y)[:, None, None, :]


class CecaModule(EcaModule):
    """Circular-padding ECA variant; SAME padding approximates the circular pad
    for the small kernels used (reference eca.py CecaModule)."""
    pass
