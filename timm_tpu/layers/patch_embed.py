"""Image-to-patch embedding (reference: timm/layers/patch_embed.py:26-170).

TPU-first: input images are NHWC; the patch projection is an NHWC conv with
stride == kernel == patch size (XLA lowers this to a single reshaped matmul on
the MXU). Output is (B, N, C) tokens when flatten=True else an NHWC grid.
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple, Union

import jax.numpy as jnp
from flax import nnx

from .helpers import to_2tuple
from .weight_init import lecun_normal_, zeros_

__all__ = ['PatchEmbed', 'resample_patch_embed']


class PatchEmbed(nnx.Module):
    def __init__(
            self,
            img_size: Optional[int] = 224,
            patch_size: int = 16,
            in_chans: int = 3,
            embed_dim: int = 768,
            norm_layer: Optional[Callable] = None,
            flatten: bool = True,
            bias: bool = True,
            strict_img_size: bool = True,
            dynamic_img_pad: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.patch_size = to_2tuple(patch_size)
        if img_size is not None:
            self.img_size = to_2tuple(img_size)
            self.grid_size = tuple(s // p for s, p in zip(self.img_size, self.patch_size))
            self.num_patches = self.grid_size[0] * self.grid_size[1]
        else:
            self.img_size = None
            self.grid_size = None
            self.num_patches = None
        self.flatten = flatten
        self.strict_img_size = strict_img_size
        self.dynamic_img_pad = dynamic_img_pad

        self.proj = nnx.Conv(
            in_chans, embed_dim,
            kernel_size=self.patch_size,
            strides=self.patch_size,
            padding='VALID',
            use_bias=bias,
            dtype=dtype,
            param_dtype=param_dtype,
            kernel_init=lecun_normal_(),
            bias_init=zeros_,
            rngs=rngs,
        )
        self.norm = norm_layer(embed_dim, rngs=rngs) if norm_layer is not None else None

    def set_input_size(self, img_size=None, patch_size=None):
        if patch_size is not None:
            assert to_2tuple(patch_size) == self.patch_size, 'patch resize not supported post-init'
        if img_size is not None:
            self.img_size = to_2tuple(img_size)
            self.grid_size = tuple(s // p for s, p in zip(self.img_size, self.patch_size))
            self.num_patches = self.grid_size[0] * self.grid_size[1]

    def dynamic_feat_size(self, img_size: Tuple[int, int]) -> Tuple[int, int]:
        if self.dynamic_img_pad:
            return tuple(-(-s // p) for s, p in zip(img_size, self.patch_size))
        return tuple(s // p for s, p in zip(img_size, self.patch_size))

    def __call__(self, x):
        B, H, W, C = x.shape
        if self.img_size is not None and self.strict_img_size and not self.dynamic_img_pad:
            assert (H, W) == self.img_size, f'Input size ({H},{W}) != model ({self.img_size})'
        if self.dynamic_img_pad:
            ph, pw = self.patch_size
            pad_h = (ph - H % ph) % ph
            pad_w = (pw - W % pw) % pw
            if pad_h or pad_w:
                x = jnp.pad(x, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)))
        x = self.proj(x)
        if self.norm is not None:
            x = self.norm(x)
        if self.flatten:
            x = x.reshape(x.shape[0], -1, x.shape[-1])  # (B, H*W, C)
        return x


def resample_patch_embed(kernel, new_size, interpolation: str = 'cubic', antialias: bool = True):
    """PI-resize a patch-projection kernel (HWIO) to a new patch size.

    FlexiViT-style resampling (reference patch_embed.py:176+) approximated with
    a direct resize of the spatial dims; adequate for fine-tuning conversions.
    """
    import jax
    kh, kw, ci, co = kernel.shape
    if (kh, kw) == tuple(new_size):
        return kernel
    return jax.image.resize(kernel, (*new_size, ci, co), method=interpolation, antialias=antialias)
