"""Anti-aliased downsampling (reference: timm/layers/blur_pool.py:1-155)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from flax import nnx

__all__ = ['BlurPool2d', 'AvgPool2dAA', 'get_aa_layer']


class BlurPool2d(nnx.Module):
    """Fixed binomial low-pass filter + stride (Zhang 2019), NHWC depthwise."""

    def __init__(self, channels: int, filt_size: int = 3, stride: int = 2,
                 pad_mode: str = 'reflect', *, rngs=None):
        assert filt_size > 1
        self.channels = channels
        self.stride = stride
        self.pad_mode = pad_mode
        coeffs = np.poly1d((0.5, 0.5)) ** (filt_size - 1)
        blur_1d = np.asarray(coeffs.coeffs, np.float32)
        blur_2d = blur_1d[:, None] * blur_1d[None, :]
        # HWIO depthwise kernel: (H, W, 1, C) with feature_group_count=C
        # nnx.Variable: raw array attrs break nnx graph traversal on older flax
        self._kernel = nnx.Variable(jnp.asarray(np.tile(blur_2d[:, :, None, None], (1, 1, 1, channels))))
        self.filt_size = filt_size

    def __call__(self, x):
        pad = (self.filt_size - 1) // 2
        pad_cfg = [(0, 0), (pad, self.filt_size - 1 - pad), (pad, self.filt_size - 1 - pad), (0, 0)]
        x = jnp.pad(x, pad_cfg, mode=self.pad_mode)
        return jax.lax.conv_general_dilated(
            x, self._kernel[...].astype(x.dtype),
            window_strides=(self.stride, self.stride),
            padding='VALID',
            dimension_numbers=('NHWC', 'HWIO', 'NHWC'),
            feature_group_count=self.channels,
        )


class AvgPool2dAA(nnx.Module):
    """Plain 2x2 average-pool 'anti-aliasing' layer (reference create_aa's
    'avg' option) — used by the CLIP ResNets' strided blocks."""

    def __init__(self, channels: int = 0, stride: int = 2, *, rngs=None):
        self.stride = stride

    def __call__(self, x):
        s = self.stride
        return jax.lax.reduce_window(
            x, 0.0, jax.lax.add, (1, s, s, 1), (1, s, s, 1), 'SAME') / (s * s)


def get_aa_layer(aa_layer):
    """Resolve an anti-aliasing layer from name/callable
    (reference blur_pool.py create_aa)."""
    if aa_layer is None or aa_layer == '':
        return None
    if not isinstance(aa_layer, str):
        return aa_layer
    name = aa_layer.lower().replace('_', '').replace('2d', '')
    if name == 'avg' or name == 'avgpool':
        return AvgPool2dAA
    if name in ('blur', 'blurpool'):
        return BlurPool2d
    if name == 'blurpc':
        # constant-pad BlurPool (reference blur_pool.py:97-99)
        import functools
        return functools.partial(BlurPool2d, pad_mode='constant')
    raise ValueError(f'Unknown anti-aliasing layer {aa_layer}')
