"""Anti-aliased downsampling (reference: timm/layers/blur_pool.py:1-155)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from flax import nnx

__all__ = ['BlurPool2d']


class BlurPool2d(nnx.Module):
    """Fixed binomial low-pass filter + stride (Zhang 2019), NHWC depthwise."""

    def __init__(self, channels: int, filt_size: int = 3, stride: int = 2, *, rngs=None):
        assert filt_size > 1
        self.channels = channels
        self.stride = stride
        coeffs = np.poly1d((0.5, 0.5)) ** (filt_size - 1)
        blur_1d = np.asarray(coeffs.coeffs, np.float32)
        blur_2d = blur_1d[:, None] * blur_1d[None, :]
        # HWIO depthwise kernel: (H, W, 1, C) with feature_group_count=C
        self._kernel = jnp.asarray(np.tile(blur_2d[:, :, None, None], (1, 1, 1, channels)))
        self.filt_size = filt_size

    def __call__(self, x):
        pad = (self.filt_size - 1) // 2
        pad_cfg = [(0, 0), (pad, self.filt_size - 1 - pad), (pad, self.filt_size - 1 - pad), (0, 0)]
        x = jnp.pad(x, pad_cfg, mode='reflect')
        return jax.lax.conv_general_dilated(
            x, self._kernel.astype(x.dtype),
            window_strides=(self.stride, self.stride),
            padding='VALID',
            dimension_numbers=('NHWC', 'HWIO', 'NHWC'),
            feature_group_count=self.channels,
        )
