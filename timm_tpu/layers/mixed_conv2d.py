"""Mixed grouped convolution (MixConv, arXiv:1907.09595)
(reference: timm/layers/mixed_conv2d.py:21-68): channel splits each get a
different kernel size.
"""
from __future__ import annotations

from typing import List, Union

import jax.numpy as jnp
from flax import nnx

from .create_conv2d import create_conv2d

__all__ = ['MixedConv2d']


def _split_channels(num_chan: int, num_groups: int) -> List[int]:
    split = [num_chan // num_groups for _ in range(num_groups)]
    split[0] += num_chan - sum(split)
    return split


class MixedConv2d(nnx.Module):

    def __init__(
            self,
            in_channels: int,
            out_channels: int,
            kernel_size: Union[int, List[int]] = 3,
            stride: int = 1,
            padding='',
            dilation: int = 1,
            depthwise: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
            **kwargs,
    ):
        kernel_size = kernel_size if isinstance(kernel_size, list) else [kernel_size]
        num_groups = len(kernel_size)
        in_splits = _split_channels(in_channels, num_groups)
        out_splits = _split_channels(out_channels, num_groups)
        self.in_channels = sum(in_splits)
        self.out_channels = sum(out_splits)
        self.convs = nnx.List([
            create_conv2d(
                in_ch, out_ch, k, stride=stride, padding=padding, dilation=dilation,
                groups=in_ch if depthwise else 1,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs, **kwargs)
            for k, in_ch, out_ch in zip(kernel_size, in_splits, out_splits)])
        self.splits = in_splits

    def __call__(self, x):
        start = 0
        outs = []
        for conv, n in zip(self.convs, self.splits):
            outs.append(conv(x[..., start:start + n]))
            start += n
        return jnp.concatenate(outs, axis=-1)
