"""Norm + activation composite layers (reference: timm/layers/norm_act.py:1-690).

The reference fuses norm+act into single modules so conv blocks can treat them
as one unit; we keep that API. On TPU the fusion itself is XLA's job.
"""
from __future__ import annotations

from typing import Callable, Optional, Union

import jax.numpy as jnp
from flax import nnx

from .create_act import get_act_fn
from .norm import BatchNorm2d, GroupNorm, LayerNorm

__all__ = [
    'BatchNormAct2d', 'GroupNormAct', 'GroupNorm1Act', 'LayerNormAct', 'LayerNormAct2d',
    'FrozenBatchNormAct2d', 'get_norm_act_layer',
]


class BatchNormAct2d(BatchNorm2d):
    def __init__(
            self,
            num_features: int,
            eps: float = 1e-5,
            momentum: float = 0.1,
            affine: bool = True,
            apply_act: bool = True,
            act_layer: Union[str, Callable, None] = 'relu',
            act_kwargs=None,
            drop_layer=None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        super().__init__(
            num_features, eps=eps, momentum=momentum, affine=affine,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs,
        )
        self.act = get_act_fn(act_layer) if apply_act else None
        self.drop = drop_layer() if drop_layer is not None else None

    def __call__(self, x):
        x = super().__call__(x)
        if self.drop is not None:
            x = self.drop(x)
        if self.act is not None:
            x = self.act(x)
        return x


class FrozenBatchNormAct2d(nnx.Module):
    """BN with frozen statistics and affine params (reference norm_act.py:~300)."""

    def __init__(
            self,
            num_features: int,
            eps: float = 1e-5,
            apply_act: bool = True,
            act_layer: Union[str, Callable, None] = 'relu',
            *,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.eps = eps
        self.scale = nnx.Variable(jnp.ones((num_features,), param_dtype))
        self.bias = nnx.Variable(jnp.zeros((num_features,), param_dtype))
        self.mean = nnx.Variable(jnp.zeros((num_features,), param_dtype))
        self.var = nnx.Variable(jnp.ones((num_features,), param_dtype))
        self.act = get_act_fn(act_layer) if apply_act else None

    def __call__(self, x):
        scale = self.scale[...] * jnp.reciprocal(jnp.sqrt(self.var[...] + self.eps))
        bias = self.bias[...] - self.mean[...] * scale
        x = x * scale.astype(x.dtype) + bias.astype(x.dtype)
        if self.act is not None:
            x = self.act(x)
        return x


class GroupNormAct(GroupNorm):
    def __init__(
            self,
            num_channels: int,
            num_groups: int = 32,
            eps: float = 1e-5,
            affine: bool = True,
            group_size: int = None,
            apply_act: bool = True,
            act_layer: Union[str, Callable, None] = 'relu',
            act_kwargs=None,
            drop_layer=None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        if group_size:
            # channels-per-group spec overrides num_groups (reference norm_act.py _num_groups)
            assert num_channels % group_size == 0
            num_groups = num_channels // group_size
        super().__init__(
            num_channels, num_groups=num_groups, eps=eps, affine=affine,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs,
        )
        self.act = get_act_fn(act_layer) if apply_act else None

    def __call__(self, x):
        x = super().__call__(x)
        if self.act is not None:
            x = self.act(x)
        return x


class GroupNorm1Act(GroupNormAct):
    def __init__(self, num_channels, **kwargs):
        super().__init__(num_channels, num_groups=1, **kwargs)


class LayerNormAct(LayerNorm):
    def __init__(
            self,
            num_channels: int,
            eps: float = 1e-6,
            affine: bool = True,
            apply_act: bool = True,
            act_layer: Union[str, Callable, None] = 'relu',
            act_kwargs=None,
            drop_layer=None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        super().__init__(
            num_channels, eps=eps, affine=affine,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs,
        )
        self.act = get_act_fn(act_layer) if apply_act else None

    def __call__(self, x):
        x = super().__call__(x)
        if self.act is not None:
            x = self.act(x)
        return x


LayerNormAct2d = LayerNormAct  # NHWC: identical


def get_norm_act_layer(norm_layer, act_layer=None):
    """Resolve a (norm+act) composite layer class from a name or callable
    (reference create_norm_act.py:107 get_norm_act_layer). When `act_layer`
    is given, it is bound as the composite's default activation.

    EvoNorms carry their own activation and accept/ignore `act_layer`.
    """
    import functools
    import inspect
    if norm_layer is None:
        return None
    if not isinstance(norm_layer, str):
        cls = norm_layer
    else:
        from .evo_norm import EvoNorm2dB0, EvoNorm2dS0
        from .filter_response_norm import FilterResponseNormAct2d, FilterResponseNormTlu2d
        name = norm_layer.replace('_', '').lower()
        _MAP = dict(
            batchnorm=BatchNormAct2d,
            batchnorm2d=BatchNormAct2d,
            groupnorm=GroupNormAct,
            groupnorm1=GroupNorm1Act,
            layernorm=LayerNormAct,
            layernorm2d=LayerNormAct2d,
            evonormb0=EvoNorm2dB0,
            evonorms0=EvoNorm2dS0,
            frn=FilterResponseNormAct2d,
            frntlu=FilterResponseNormTlu2d,
        )
        if name not in _MAP:
            raise ValueError(f'Unknown norm+act layer {norm_layer}')
        cls = _MAP[name]
    base = cls.func if isinstance(cls, functools.partial) else cls
    if act_layer is not None and 'act_layer' in inspect.signature(base.__init__).parameters:
        cls = functools.partial(cls, act_layer=act_layer)
    return cls
