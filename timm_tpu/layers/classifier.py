"""Classifier heads (reference: timm/layers/classifier.py:1-300)."""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Union

import jax.numpy as jnp
from flax import nnx

from .create_act import get_act_fn
from .drop import Dropout
from .norm import LayerNorm
from .pool import SelectAdaptivePool2d
from .weight_init import trunc_normal_, zeros_

__all__ = ['ClNormMlpClassifierHead', 'ClassifierHead', 'NormMlpClassifierHead', 'create_classifier']


def create_classifier(
        num_features: int,
        num_classes: int,
        pool_type: str = 'avg',
        *,
        dtype=None,
        param_dtype=jnp.float32,
        rngs: nnx.Rngs,
):
    pool = SelectAdaptivePool2d(pool_type=pool_type, flatten=True)
    num_pooled = num_features * pool.feat_mult()
    if num_classes <= 0:
        fc = None
    else:
        fc = nnx.Linear(
            num_pooled, num_classes, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs,
        )
    return pool, fc


class ClassifierHead(nnx.Module):
    """Pool → drop → fc, with reset support (reference classifier.py:ClassifierHead)."""

    def __init__(
            self,
            in_features: int,
            num_classes: int,
            pool_type: str = 'avg',
            drop_rate: float = 0.0,
            input_fmt: str = 'NHWC',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.in_features = in_features
        self.num_classes = num_classes
        self._dtype = dtype
        self._param_dtype = param_dtype
        self.global_pool, self.fc = create_classifier(
            in_features, num_classes, pool_type=pool_type, dtype=dtype, param_dtype=param_dtype, rngs=rngs,
        )
        self.drop = Dropout(drop_rate, rngs=rngs)

    def reset(self, num_classes: int, pool_type: Optional[str] = None, *, rngs: Optional[nnx.Rngs] = None):
        self.num_classes = num_classes
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        pool_type = pool_type if pool_type is not None else self.global_pool.pool_type
        self.global_pool, self.fc = create_classifier(
            self.in_features, num_classes, pool_type=pool_type,
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs,
        )

    def __call__(self, x, pre_logits: bool = False):
        x = self.global_pool(x)
        x = self.drop(x)
        if pre_logits or self.fc is None:
            return x
        return self.fc(x)


class NormMlpClassifierHead(nnx.Module):
    """Pool → norm → (hidden mlp) → drop → fc (reference classifier.py:~180)."""

    def __init__(
            self,
            in_features: int,
            num_classes: int,
            hidden_size: Optional[int] = None,
            pool_type: str = 'avg',
            drop_rate: float = 0.0,
            norm_layer: Union[str, Callable] = LayerNorm,
            act_layer: Union[str, Callable] = 'tanh',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.in_features = in_features
        self.hidden_size = hidden_size
        self.num_classes = num_classes
        self.num_features = hidden_size or in_features
        self._dtype = dtype
        self._param_dtype = param_dtype

        self.global_pool = SelectAdaptivePool2d(pool_type=pool_type, flatten=True)
        self.norm = norm_layer(in_features, rngs=rngs)
        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs,
        )
        if hidden_size:
            self.pre_logits_fc = linear(in_features, hidden_size)
            self.pre_logits_act = get_act_fn(act_layer)
        else:
            self.pre_logits_fc = None
            self.pre_logits_act = None
        self.drop = Dropout(drop_rate, rngs=rngs)
        self.fc = linear(self.num_features, num_classes) if num_classes > 0 else None

    def reset(self, num_classes: int, pool_type: Optional[str] = None, *, rngs: Optional[nnx.Rngs] = None):
        self.num_classes = num_classes
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        if pool_type is not None:
            self.global_pool = SelectAdaptivePool2d(pool_type=pool_type, flatten=True)
        if num_classes > 0:
            self.fc = nnx.Linear(
                self.num_features, num_classes, dtype=self._dtype, param_dtype=self._param_dtype,
                kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs,
            )
        else:
            self.fc = None

    def __call__(self, x, pre_logits: bool = False):
        if x.ndim == 4:
            x = self.global_pool(x)
        x = self.norm(x)
        if self.pre_logits_fc is not None:
            x = self.pre_logits_act(self.pre_logits_fc(x))
        x = self.drop(x)
        if pre_logits or self.fc is None:
            return x
        return self.fc(x)


class _FcAct(nnx.Module):
    """fc + act pre-logits submodule (keys: pre_logits.fc / pre_logits.act)."""

    def __init__(self, in_features, hidden_size, act_layer='gelu',
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.fc = nnx.Linear(
            in_features, hidden_size, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.act = get_act_fn(act_layer)

    def __call__(self, x):
        return self.act(self.fc(x))


class ClNormMlpClassifierHead(nnx.Module):
    """Pool → norm → (fc+act) → drop → fc for channels-last tensors
    (reference classifier.py:223-300 ClNormMlpClassifierHead)."""

    def __init__(
            self,
            in_features: int,
            num_classes: int,
            hidden_size: Optional[int] = None,
            pool_type: str = 'avg',
            drop_rate: float = 0.0,
            norm_layer: Union[str, Callable] = LayerNorm,
            act_layer: Union[str, Callable] = 'gelu',
            input_fmt: str = 'NHWC',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert pool_type in ('', 'avg', 'max', 'avgmax')
        assert input_fmt in ('NHWC', 'NLC')
        self.in_features = in_features
        self.hidden_size = hidden_size
        self.num_features = hidden_size or in_features
        self.num_classes = num_classes
        self.pool_type = pool_type
        self.pool_dim = (1,) if input_fmt == 'NLC' else (1, 2)
        self._dd = dict(dtype=dtype, param_dtype=param_dtype)

        self.norm = norm_layer(in_features, rngs=rngs)
        self.pre_logits = _FcAct(in_features, hidden_size, act_layer,
                                 dtype=dtype, param_dtype=param_dtype, rngs=rngs) if hidden_size else None
        self.drop = Dropout(drop_rate, rngs=rngs)
        self.fc = nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None

    def reset(self, num_classes: int, pool_type: Optional[str] = None,
              reset_other: bool = False, *, rngs: Optional[nnx.Rngs] = None):
        self.num_classes = num_classes
        if pool_type is not None:
            self.pool_type = pool_type
        if reset_other:
            self.pre_logits = None
            self.norm = None
            self.num_features = self.in_features
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.fc = nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            rngs=rngs, **self._dd) if num_classes > 0 else None

    def _global_pool(self, x):
        if self.pool_type:
            if self.pool_type == 'avg':
                x = x.mean(axis=self.pool_dim)
            elif self.pool_type == 'max':
                x = x.max(axis=self.pool_dim)
            elif self.pool_type == 'avgmax':
                x = 0.5 * (x.mean(axis=self.pool_dim) + x.max(axis=self.pool_dim))
        return x

    def __call__(self, x, pre_logits: bool = False):
        x = self._global_pool(x)
        if self.norm is not None:
            x = self.norm(x)
        if self.pre_logits is not None:
            x = self.pre_logits(x)
        x = self.drop(x)
        if pre_logits or self.fc is None:
            return x
        return self.fc(x)
