"""EvoNorm B0/S0 (reference: timm/layers/evo_norm.py:1-470 — which itself
carries TPU-workaround variants instance_std_tpu/group_std_tpu; NHWC makes the
straightforward forms efficient here).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import nnx

__all__ = ['EvoNorm2dB0', 'EvoNorm2dS0', 'EvoNorm2dS0a']


class EvoNorm2dB0(nnx.Module):
    """Batch-variant EvoNorm: running batch std + instance gating."""

    def __init__(self, num_features: int, apply_act: bool = True, momentum: float = 0.1,
                 eps: float = 1e-3, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs, **kwargs):
        self.apply_act = apply_act
        self.momentum = momentum
        self.eps = eps
        self.weight = nnx.Param(jnp.ones((num_features,), param_dtype))
        self.bias = nnx.Param(jnp.zeros((num_features,), param_dtype))
        self.v = nnx.Param(jnp.ones((num_features,), param_dtype)) if apply_act else None
        self.running_var = nnx.BatchStat(jnp.ones((num_features,), param_dtype))
        self.use_running_average = False

    def __call__(self, x):
        x32 = x.astype(jnp.float32)
        if self.apply_act:
            if self.use_running_average:
                var = self.running_var[...]
            else:
                var = x32.var(axis=(0, 1, 2))
                n = x32.size / x32.shape[-1]
                # unbiased correction for the running stat (reference evo_norm.py)
                self.running_var[...] = (
                    self.running_var[...] * (1 - self.momentum)
                    + var * self.momentum * (n / max(n - 1, 1)))
            batch_std = jnp.sqrt(var + self.eps).astype(x.dtype)
            # instance std over spatial dims
            inst_var = x32.var(axis=(1, 2), keepdims=True)
            inst_std = jnp.sqrt(inst_var + self.eps).astype(x.dtype)
            v = self.v[...].astype(x.dtype)
            denom = jnp.maximum(batch_std[None, None, None, :], v * x + inst_std)
            x = x / denom
        return x * self.weight[...].astype(x.dtype) + self.bias[...].astype(x.dtype)


class EvoNorm2dS0(nnx.Module):
    """Sample-variant EvoNorm: group std + SiLU-style gating."""

    def __init__(self, num_features: int, groups: int = 32, group_size: Optional[int] = None,
                 apply_act: bool = True, eps: float = 1e-5,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs, **kwargs):
        if group_size:
            assert num_features % group_size == 0
            groups = num_features // group_size
        self.groups = groups
        self.apply_act = apply_act
        self.eps = eps
        self.weight = nnx.Param(jnp.ones((num_features,), param_dtype))
        self.bias = nnx.Param(jnp.zeros((num_features,), param_dtype))
        self.v = nnx.Param(jnp.ones((num_features,), param_dtype)) if apply_act else None

    def __call__(self, x):
        import jax
        B, H, W, C = x.shape
        if self.apply_act:
            v = self.v[...].astype(x.dtype)
            xg = x.astype(jnp.float32).reshape(B, H, W, self.groups, C // self.groups)
            var = xg.var(axis=(1, 2, 4), keepdims=True)
            std = jnp.sqrt(var + self.eps)
            std = jnp.broadcast_to(std, xg.shape).reshape(B, H, W, C).astype(x.dtype)
            x = x * jax.nn.sigmoid(v * x) / std
        return x * self.weight[...].astype(x.dtype) + self.bias[...].astype(x.dtype)


class EvoNorm2dS0a(EvoNorm2dS0):
    """S0 variant that always divides by the group std, act or not
    (reference evo_norm.py:284-316). Default eps is 1e-3."""

    def __init__(self, num_features: int, groups: int = 32, group_size: Optional[int] = None,
                 apply_act: bool = True, eps: float = 1e-3,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs, **kwargs):
        super().__init__(
            num_features, groups=groups, group_size=group_size, apply_act=apply_act,
            eps=eps, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        import jax
        B, H, W, C = x.shape
        xg = x.astype(jnp.float32).reshape(B, H, W, self.groups, C // self.groups)
        var = xg.var(axis=(1, 2, 4), keepdims=True)
        std = jnp.broadcast_to(jnp.sqrt(var + self.eps), xg.shape).reshape(B, H, W, C).astype(x.dtype)
        if self.v is not None:
            v = self.v[...].astype(x.dtype)
            x = x * jax.nn.sigmoid(v * x)
        x = x / std
        return x * self.weight[...].astype(x.dtype) + self.bias[...].astype(x.dtype)
