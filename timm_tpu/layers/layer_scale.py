"""LayerScale (reference: timm/layers/layer_scale.py)."""
from __future__ import annotations

import jax.numpy as jnp
from flax import nnx

__all__ = ['LayerScale', 'LayerScale2d']


class LayerScale(nnx.Module):
    def __init__(self, dim: int, init_values: float = 1e-5, *, param_dtype=jnp.float32, rngs: nnx.Rngs = None):
        self.gamma = nnx.Param(jnp.full((dim,), init_values, param_dtype))

    def __call__(self, x):
        return x * self.gamma[...].astype(x.dtype)


# NHWC: channel axis is last in both token and spatial layouts.
LayerScale2d = LayerScale
