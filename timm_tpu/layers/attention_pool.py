"""Latent-query attention pooling (reference: timm/layers/attention_pool.py).

Used by ViT 'map' pooling — a learned latent attends over the token sequence.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Union

import jax.numpy as jnp
from flax import nnx

from .attention import scaled_dot_product_attention
from .drop import Dropout
from .mlp import Mlp
from .norm import LayerNorm
from .weight_init import trunc_normal_, zeros_

__all__ = ['AttentionPoolLatent']


class AttentionPoolLatent(nnx.Module):
    def __init__(
            self,
            in_features: int,
            out_features: Optional[int] = None,
            embed_dim: Optional[int] = None,
            num_heads: int = 8,
            feat_size: Optional[int] = None,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = True,
            qk_norm: bool = False,
            latent_len: int = 1,
            latent_dim: Optional[int] = None,
            pos_embed: str = '',
            pool_type: str = 'token',
            norm_layer: Optional[Callable] = None,
            act_layer: Union[str, Callable] = 'gelu',
            drop: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        embed_dim = embed_dim or in_features
        out_features = out_features or in_features
        assert embed_dim % num_heads == 0
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.scale = self.head_dim ** -0.5
        self.pool = pool_type
        self.latent_len = latent_len

        norm_layer = norm_layer or LayerNorm
        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs,
        )

        if pos_embed == 'abs':
            assert feat_size is not None
            self.pos_embed = nnx.Param(jnp.zeros((feat_size, in_features), param_dtype))
        else:
            self.pos_embed = None

        self.latent_dim = latent_dim or embed_dim
        self.latent = nnx.Param(
            trunc_normal_(std=in_features ** -0.5)(rngs.params(), (1, self.latent_len, embed_dim), param_dtype))

        self.q = linear(embed_dim, embed_dim, use_bias=qkv_bias)
        self.kv = linear(in_features, embed_dim * 2, use_bias=qkv_bias)
        self.q_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.k_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.proj = linear(embed_dim, out_features)
        self.proj_drop = Dropout(drop, rngs=rngs)

        self.norm = norm_layer(out_features, rngs=rngs)
        self.mlp = Mlp(out_features, int(out_features * mlp_ratio), act_layer=act_layer,
                       dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        B, N, C = x.shape
        if self.pos_embed is not None:
            x = x + self.pos_embed[...].astype(x.dtype)[None]
        lat = self.latent[...].astype(x.dtype)
        q_latent = jnp.broadcast_to(lat, (B, self.latent_len, lat.shape[-1]))
        q = self.q(q_latent).reshape(B, self.latent_len, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        kv = self.kv(x).reshape(B, N, 2, self.num_heads, self.head_dim).transpose(2, 0, 3, 1, 4)
        k, v = kv[0], kv[1]
        if self.q_norm is not None:
            q = self.q_norm(q)
        if self.k_norm is not None:
            k = self.k_norm(k)
        x = scaled_dot_product_attention(q, k, v, scale=self.scale)
        x = x.transpose(0, 2, 1, 3).reshape(B, self.latent_len, -1)
        x = self.proj(x)
        x = self.proj_drop(x)
        x = x + self.mlp(self.norm(x))
        if self.pool == 'token':
            x = x[:, 0]
        elif self.pool == 'avg':
            x = x.mean(axis=1)
        return x
