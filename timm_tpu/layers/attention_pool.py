"""Attention pooling heads (reference: timm/layers/attention_pool.py +
attention_pool2d.py).

`AttentionPoolLatent` — ViT 'map' pooling (learned latent attends over tokens).
`AttentionPool2d` / `RotAttentionPool2d` — CLIP-style replacements for global
average pooling over an NHWC feature map, with learned-absolute vs rotary
position embedding respectively.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple, Union

import jax.numpy as jnp
from flax import nnx

from .attention import apply_rot_embed_cat, scaled_dot_product_attention
from .drop import Dropout
from .helpers import to_2tuple
from .mlp import Mlp
from .norm import LayerNorm
from .weight_init import trunc_normal_, zeros_

__all__ = ['AttentionPoolLatent', 'AttentionPool2d', 'RotAttentionPool2d']


class AttentionPoolLatent(nnx.Module):
    def __init__(
            self,
            in_features: int,
            out_features: Optional[int] = None,
            embed_dim: Optional[int] = None,
            num_heads: int = 8,
            feat_size: Optional[int] = None,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = True,
            qk_norm: bool = False,
            latent_len: int = 1,
            latent_dim: Optional[int] = None,
            pos_embed: str = '',
            pool_type: str = 'token',
            norm_layer: Optional[Callable] = None,
            act_layer: Union[str, Callable] = 'gelu',
            drop: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        embed_dim = embed_dim or in_features
        out_features = out_features or in_features
        assert embed_dim % num_heads == 0
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.scale = self.head_dim ** -0.5
        self.pool = pool_type
        self.latent_len = latent_len

        norm_layer = norm_layer or LayerNorm
        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs,
        )

        if pos_embed == 'abs':
            assert feat_size is not None
            self.pos_embed = nnx.Param(jnp.zeros((feat_size, in_features), param_dtype))
        else:
            self.pos_embed = None

        self.latent_dim = latent_dim or embed_dim
        self.latent = nnx.Param(
            trunc_normal_(std=in_features ** -0.5)(rngs.params(), (1, self.latent_len, embed_dim), param_dtype))

        self.q = linear(embed_dim, embed_dim, use_bias=qkv_bias)
        self.kv = linear(in_features, embed_dim * 2, use_bias=qkv_bias)
        self.q_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.k_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.proj = linear(embed_dim, out_features)
        self.proj_drop = Dropout(drop, rngs=rngs)

        self.norm = norm_layer(out_features, rngs=rngs)
        self.mlp = Mlp(out_features, int(out_features * mlp_ratio), act_layer=act_layer,
                       dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x, attn_mask=None):
        """`attn_mask` is an optional key-padding mask over the N input tokens
        (bool, True = valid; (B, N) or (B, 1, 1, N)) so the latent query can
        pool a tile-padded sequence without attending to pad tokens."""
        B, N, C = x.shape
        if self.pos_embed is not None:
            x = x + self.pos_embed[...].astype(x.dtype)[None]
        lat = self.latent[...].astype(x.dtype)
        q_latent = jnp.broadcast_to(lat, (B, self.latent_len, lat.shape[-1]))
        q = self.q(q_latent).reshape(B, self.latent_len, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        kv = self.kv(x).reshape(B, N, 2, self.num_heads, self.head_dim).transpose(2, 0, 3, 1, 4)
        k, v = kv[0], kv[1]
        if self.q_norm is not None:
            q = self.q_norm(q)
        if self.k_norm is not None:
            k = self.k_norm(k)
        if attn_mask is not None and attn_mask.ndim == 2:
            attn_mask = attn_mask[:, None, None, :]  # (B, N) → (B, 1, 1, N)
        x = scaled_dot_product_attention(q, k, v, attn_mask=attn_mask, scale=self.scale)
        x = x.transpose(0, 2, 1, 3).reshape(B, self.latent_len, -1)
        x = self.proj(x)
        x = self.proj_drop(x)
        x = x + self.mlp(self.norm(x))
        if self.pool == 'token':
            x = x[:, 0]
        elif self.pool == 'avg':
            x = x.mean(axis=1)
        return x


class _AttentionPool2dBase(nnx.Module):
    """Shared machinery for the CLIP-style 2D attention pools
    (reference attention_pool2d.py:22-320). Input is an NHWC feature map;
    a mean (or cls) token is prepended and one MHSA layer runs over the
    N+1 tokens; 'token' pooling returns the first output token."""

    def __init__(
            self,
            in_features: int,
            out_features: Optional[int] = None,
            embed_dim: Optional[int] = None,
            head_dim: Optional[int] = 64,
            num_heads: Optional[int] = None,
            qkv_bias: bool = True,
            qkv_separate: bool = False,
            pool_type: str = 'token',
            class_token: bool = False,
            drop_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert pool_type in ('', 'token')
        self.embed_dim = embed_dim = embed_dim or in_features
        self.in_features = in_features
        if out_features is None:
            self.out_features = in_features
        elif out_features > 0:
            self.out_features = out_features
        else:
            self.out_features = embed_dim  # out_features=0 disables projection
        if num_heads is not None:
            assert embed_dim % num_heads == 0
            head_dim = embed_dim // num_heads
        else:
            assert embed_dim % head_dim == 0
            num_heads = embed_dim // head_dim
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.pool_type = pool_type.lower()
        self.scale = head_dim ** -0.5
        self._dtype = dtype
        self._param_dtype = param_dtype

        self.cls_token = nnx.Param(jnp.zeros((1, embed_dim), param_dtype)) if class_token else None

        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=in_features ** -0.5), bias_init=zeros_, rngs=rngs)
        if qkv_separate:
            self.q = linear(in_features, embed_dim, use_bias=qkv_bias)
            self.k = linear(in_features, embed_dim, use_bias=qkv_bias)
            self.v = linear(in_features, embed_dim, use_bias=qkv_bias)
            self.qkv = None
        else:
            self.q = self.k = self.v = None
            self.qkv = linear(in_features, embed_dim * 3, use_bias=qkv_bias)
        self.drop = Dropout(drop_rate, rngs=rngs)
        self.proj = linear(embed_dim, self.out_features) if out_features != 0 else None

    def reset(self, num_classes: Optional[int] = None, pool_type: Optional[str] = None, *, rngs=None):
        if pool_type is not None:
            assert pool_type in ('', 'token')
            self.pool_type = pool_type
        if num_classes is not None:
            if num_classes > 0:
                self.proj = nnx.Linear(
                    self.embed_dim, num_classes, dtype=self._dtype, param_dtype=self._param_dtype,
                    kernel_init=trunc_normal_(std=self.embed_dim ** -0.5), bias_init=zeros_,
                    rngs=rngs or nnx.Rngs(0))
            else:
                self.proj = None
            self.out_features = num_classes if num_classes > 0 else self.embed_dim

    def _tokens(self, x):
        """(B, H, W, C) → (B, N+1, C) with mean/cls token prepended."""
        B, H, W, C = x.shape
        x = x.reshape(B, H * W, C)
        if self.cls_token is None:
            x = jnp.concatenate([x.mean(axis=1, keepdims=True), x], axis=1)
        else:
            cls = jnp.broadcast_to(self.cls_token[...].astype(x.dtype)[None], (B, 1, self.embed_dim))
            x = jnp.concatenate([cls, x], axis=1)
        return x

    def _qkv_heads(self, x):
        B, N, _ = x.shape
        if self.qkv is None:
            q = self.q(x).reshape(B, N, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
            k = self.k(x).reshape(B, N, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
            v = self.v(x).reshape(B, N, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        else:
            qkv = self.qkv(x).reshape(B, N, 3, self.num_heads, self.head_dim).transpose(2, 0, 3, 1, 4)
            q, k, v = qkv[0], qkv[1], qkv[2]
        return q, k, v

    def _pool(self, x, H: int, W: int):
        if self.pool_type == 'token':
            return x[:, 0]
        return x[:, 1:].reshape(x.shape[0], H, W, -1)


class AttentionPool2d(_AttentionPool2dBase):
    """Learned absolute-position attention pool (reference attention_pool2d.py:175).

    Requires `feat_size` at construction; the pos embed is resampled at call
    time when the runtime feature size differs.
    """

    def __init__(self, in_features: int, feat_size: Union[int, Tuple[int, int]] = 7, **kwargs):
        super().__init__(in_features, **kwargs)
        self.feat_size = to_2tuple(feat_size)
        self.seq_len = self.feat_size[0] * self.feat_size[1]
        key = kwargs.get('rngs', nnx.Rngs(0)).params()
        self.pos_embed = nnx.Param(
            trunc_normal_(std=in_features ** -0.5)(key, (self.seq_len + 1, in_features), self._param_dtype))

    def __call__(self, x, pre_logits: bool = False):
        from .pos_embed import resample_abs_pos_embed
        B, H, W, C = x.shape
        x = self._tokens(x)
        pos = self.pos_embed[...][None]
        if (H, W) != self.feat_size:
            pos = resample_abs_pos_embed(pos, (H, W), old_size=self.feat_size, num_prefix_tokens=1)
        x = x + pos.astype(x.dtype)
        q, k, v = self._qkv_heads(x)
        x = scaled_dot_product_attention(q, k, v, scale=self.scale)
        x = x.transpose(0, 2, 1, 3).reshape(B, H * W + 1, -1)
        x = self.drop(x)
        if pre_logits or self.proj is None:
            return self._pool(x, H, W)
        return self._pool(self.proj(x), H, W)


class RotAttentionPool2d(_AttentionPool2dBase):
    """Rotary-position attention pool (reference attention_pool2d.py:22).

    No fixed feature size — the ROPE table is built for the runtime (H, W)
    relative to `ref_feat_size`.
    """

    def __init__(self, in_features: int, ref_feat_size: Union[int, Tuple[int, int]] = 7, **kwargs):
        from .pos_embed_sincos import RotaryEmbeddingCat
        super().__init__(in_features, **kwargs)
        self.pos_embed = RotaryEmbeddingCat(
            self.embed_dim // self.num_heads,  # table is (N, 2*head_dim) = cat(sin, cos)
            in_pixels=False,
            ref_feat_shape=to_2tuple(ref_feat_size),
        )

    def __call__(self, x, pre_logits: bool = False):
        B, H, W, C = x.shape
        x = self._tokens(x)
        q, k, v = self._qkv_heads(x)
        rope = self.pos_embed.get_embed((H, W))
        q = jnp.concatenate(
            [q[:, :, :1], apply_rot_embed_cat(q[:, :, 1:], rope)], axis=2).astype(v.dtype)
        k = jnp.concatenate(
            [k[:, :, :1], apply_rot_embed_cat(k[:, :, 1:], rope)], axis=2).astype(v.dtype)
        x = scaled_dot_product_attention(q, k, v, scale=self.scale)
        x = x.transpose(0, 2, 1, 3).reshape(B, H * W + 1, -1)
        x = self.drop(x)
        if pre_logits or self.proj is None:
            return self._pool(x, H, W)
        return self._pool(self.proj(x), H, W)
