"""Gather-Excite attention over NHWC features
(reference: timm/layers/gather_excite.py:26-105).
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import nnx

from .create_act import get_act_fn
from .create_conv2d import create_conv2d
from .helpers import make_divisible
from .mlp import ConvMlp
from .norm_act import BatchNormAct2d

__all__ = ['GatherExcite']


class GatherExcite(nnx.Module):
    """Gather (spatial aggregate) → excite (gate). `extent=0` is global."""

    def __init__(
            self,
            channels: int,
            feat_size: Optional[Tuple[int, int]] = None,
            extra_params: bool = False,
            extent: int = 0,
            use_mlp: bool = True,
            rd_ratio: float = 1. / 16,
            rd_channels: Optional[int] = None,
            rd_divisor: int = 1,
            add_maxpool: bool = False,
            act_layer='relu',
            norm_layer=None,
            gate_layer='sigmoid',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.add_maxpool = add_maxpool
        self.extent = extent
        self.act = get_act_fn(act_layer)
        norm_layer = norm_layer or BatchNormAct2d
        conv_kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        if extra_params:
            convs, norms = [], []
            if extent == 0:
                assert feat_size is not None, 'spatial feature size required for global extent w/ params'
                convs.append(create_conv2d(channels, channels, kernel_size=feat_size, depthwise=True, **conv_kw))
                norms.append(norm_layer(channels, apply_act=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs))
            else:
                assert extent % 2 == 0
                for _ in range(int(math.log2(extent))):
                    convs.append(create_conv2d(channels, channels, kernel_size=3, stride=2, **conv_kw, depthwise=True))
                    norms.append(norm_layer(channels, apply_act=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs))
            self.gather = nnx.List(convs)
            self.gather_norms = nnx.List(norms)
        else:
            self.gather = None
            self.gather_norms = None
            if self.extent == 0:
                self.gk = self.gs = 0
            else:
                assert extent % 2 == 0
                self.gk = self.extent * 2 - 1
                self.gs = self.extent

        if not rd_channels:
            rd_channels = make_divisible(channels * rd_ratio, rd_divisor, round_limit=0.)
        self.mlp = ConvMlp(channels, rd_channels, act_layer=act_layer,
                       dtype=dtype, param_dtype=param_dtype, rngs=rngs) if use_mlp else None
        self.gate = get_act_fn(gate_layer)

    def __call__(self, x):
        B, H, W, C = x.shape
        if self.gather is not None:
            x_ge = x
            n = len(self.gather)
            for i, (conv, norm) in enumerate(zip(self.gather, self.gather_norms)):
                x_ge = norm(conv(x_ge))
                if i != n - 1:
                    x_ge = self.act(x_ge)
        elif self.extent == 0:
            x_ge = x.mean(axis=(1, 2), keepdims=True)
            if self.add_maxpool:
                x_ge = 0.5 * x_ge + 0.5 * x.max(axis=(1, 2), keepdims=True)
        else:
            pad = self.gk // 2
            x_ge = jax.lax.reduce_window(
                x, 0.0, jax.lax.add, (1, self.gk, self.gk, 1), (1, self.gs, self.gs, 1),
                [(0, 0), (pad, pad), (pad, pad), (0, 0)])
            ones = jnp.ones((1, H, W, 1), x.dtype)
            counts = jax.lax.reduce_window(
                ones, 0.0, jax.lax.add, (1, self.gk, self.gk, 1), (1, self.gs, self.gs, 1),
                [(0, 0), (pad, pad), (pad, pad), (0, 0)])
            x_ge = x_ge / counts  # count_include_pad=False
            if self.add_maxpool:
                x_max = jax.lax.reduce_window(
                    x, -jnp.inf, jax.lax.max, (1, self.gk, self.gk, 1), (1, self.gs, self.gs, 1),
                    [(0, 0), (pad, pad), (pad, pad), (0, 0)])
                x_ge = 0.5 * x_ge + 0.5 * x_max
        if self.mlp is not None:
            x_ge = self.mlp(x_ge)
        if x_ge.shape[1] != 1 or x_ge.shape[2] != 1:
            x_ge = jax.image.resize(x_ge, (B, H, W, C), 'nearest')
        return x * self.gate(x_ge)
