"""Test-time average-max pooling head (reference: timm/layers/test_time_pool.py).

When eval resolution exceeds the pretrained train resolution, pool the larger
feature map with the *original* pool window (stride 1), classify each window,
then avg+max pool the per-window logits.
"""
from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
from flax import nnx

_logger = logging.getLogger(__name__)

__all__ = ['TestTimePoolHead', 'apply_test_time_pool']


class TestTimePoolHead(nnx.Module):
    """Wraps a model; `original_pool` is the pretrained pool window."""

    def __init__(self, base: nnx.Module, original_pool=7):
        self.base = base
        self.original_pool = (original_pool, original_pool) if isinstance(original_pool, int) \
            else tuple(original_pool)
        self.num_classes = base.num_classes
        # reuse the trained classifier weights directly (reference copies them
        # into a 1x1 conv; NHWC makes the Linear directly applicable)
        self.fc = base.get_classifier()

    def __call__(self, x):
        x = self.base.forward_features(x)  # (B, H, W, C) for conv nets
        if x.ndim == 3:  # (B, N, C) token models: plain masked-free mean+max
            logits = self.fc(x)
            return 0.5 * (logits.mean(axis=1) + logits.max(axis=1))
        ph, pw = self.original_pool
        x = jax.lax.reduce_window(
            x, 0.0, jax.lax.add, (1, ph, pw, 1), (1, 1, 1, 1), 'VALID') / (ph * pw)
        logits = self.fc(x)  # (B, h', w', num_classes)
        return 0.5 * (logits.mean(axis=(1, 2)) + logits.max(axis=(1, 2)))

    def forward_features(self, x):
        return self.base.forward_features(x)


def apply_test_time_pool(model, config, use_test_size: bool = False):
    """Enable TTA pooling when the eval input size exceeds the pretrained
    default (reference test_time_pool.py:39-52)."""
    if not getattr(model, 'pretrained_cfg', None):
        return model, False
    cfg = model.pretrained_cfg
    get = (lambda k, d=None: cfg.get(k, d)) if isinstance(cfg, dict) else (lambda k, d=None: getattr(cfg, k, d))
    df_input_size = (get('test_input_size') if use_test_size else None) or get('input_size')
    pool_size = get('pool_size')
    if df_input_size is None or pool_size is None:
        return model, False
    if config['input_size'][-1] > df_input_size[-1] and config['input_size'][-2] > df_input_size[-2]:
        _logger.info(
            f'Target input size {config["input_size"][-2:]} > pretrained default '
            f'{df_input_size[-2:]}, using test time pooling')
        return TestTimePoolHead(model, original_pool=pool_size), True
    return model, False
