"""Differential attention (reference: timm/layers/diff_attention.py:21-179).

Attn = softmax(Q1 K1ᵀ) − λ · softmax(Q2 K2ᵀ), λ reparameterized via
exp(λq1·λk1) − exp(λq2·λk2) + λ_init with depth-dependent λ_init
(0.8 − 0.6·exp(−0.3·depth)); per-head RMS sub-norm scaled by (1 − λ_init).
"""
from __future__ import annotations

import math
from functools import partial
from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp
from flax import nnx

from .attention import maybe_add_mask
from .drop import Dropout, dropout_rng_key
from .norm import RmsNorm
from .weight_init import normal_, trunc_normal_, zeros_

__all__ = ['DiffAttention']


class DiffAttention(nnx.Module):
    def __init__(
            self,
            dim: int,
            num_heads: int = 8,
            qkv_bias: bool = False,
            qk_norm: bool = False,
            scale_norm: bool = False,
            proj_bias: bool = True,
            attn_drop: float = 0.0,
            proj_drop: float = 0.0,
            norm_layer: Optional[Callable] = None,
            depth: int = 0,
            dual_lambda: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert dim % num_heads == 0, 'dim should be divisible by num_heads'
        norm_layer = norm_layer or RmsNorm
        self.num_heads = num_heads
        self.head_dim = dim // num_heads // 2
        self.scale = self.head_dim ** -0.5
        self.attn_drop_rate = attn_drop

        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs)
        self.qkv = linear(dim, dim * 3, use_bias=qkv_bias)
        self.q_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.k_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.norm = norm_layer(dim, rngs=rngs) if scale_norm else None
        self.proj = linear(dim, dim, use_bias=proj_bias)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

        self.dual_lambda = dual_lambda
        if dual_lambda:
            self.lambda_a = nnx.Param(jnp.zeros((), jnp.float32))
            self.lambda_b = nnx.Param(jnp.zeros((), jnp.float32))
            self.lambda_q1 = self.lambda_k1 = self.lambda_q2 = self.lambda_k2 = None
        else:
            self.lambda_a = self.lambda_b = None
            init = normal_(0.1)
            self.lambda_q1 = nnx.Param(init(rngs.params(), (self.head_dim,), jnp.float32))
            self.lambda_k1 = nnx.Param(init(rngs.params(), (self.head_dim,), jnp.float32))
            self.lambda_q2 = nnx.Param(init(rngs.params(), (self.head_dim,), jnp.float32))
            self.lambda_k2 = nnx.Param(init(rngs.params(), (self.head_dim,), jnp.float32))

        self.sub_norm = RmsNorm(2 * self.head_dim, eps=1e-5, rngs=rngs)
        self.lambda_init = 0.8 - 0.6 * math.exp(-0.3 * depth)

    def _compute_lambda(self):
        if self.lambda_a is not None:
            l1 = jnp.exp(self.lambda_a[...])
            l2 = jnp.exp(self.lambda_b[...])
        else:
            l1 = jnp.exp(jnp.sum(self.lambda_q1[...] * self.lambda_k1[...]))
            l2 = jnp.exp(jnp.sum(self.lambda_q2[...] * self.lambda_k2[...]))
        return l1 - l2 + self.lambda_init

    def __call__(self, x, attn_mask=None):
        B, N, C = x.shape
        qkv = self.qkv(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, N, 2 * self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        k = k.reshape(B, N, 2 * self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        v = v.reshape(B, N, self.num_heads, 2 * self.head_dim).transpose(0, 2, 1, 3)
        if self.q_norm is not None:
            q = self.q_norm(q)
        if self.k_norm is not None:
            k = self.k_norm(k)

        lam = self._compute_lambda().astype(jnp.float32)

        q = q * self.scale
        attn = jnp.einsum('bhqd,bhkd->bhqk', q, k).astype(jnp.float32)
        attn = maybe_add_mask(attn, attn_mask)
        attn = jax.nn.softmax(attn, axis=-1)
        if self.attn_drop_rate > 0.0 and not self.attn_drop.deterministic:
            key = dropout_rng_key(self.attn_drop)
            if key is not None:
                keep = jax.random.bernoulli(key, 1.0 - self.attn_drop_rate, attn.shape)
                attn = jnp.where(keep, attn / (1.0 - self.attn_drop_rate), 0.0)
        attn = attn.reshape(B, self.num_heads, 2, N, N)
        attn = attn[:, :, 0] - lam * attn[:, :, 1]
        x = jnp.einsum('bhqk,bhkd->bhqd', attn.astype(v.dtype), v)

        x = self.sub_norm(x)
        x = x * (1.0 - self.lambda_init)
        x = x.transpose(0, 2, 1, 3).reshape(B, N, C)
        if self.norm is not None:
            x = self.norm(x)
        x = self.proj(x)
        return self.proj_drop(x)
