"""Halo attention (HaloNet), TPU-native NHWC
(reference: timm/layers/halo_attn.py:1-280; Vaswani et al. 2021).

Blocked local attention: queries are non-overlapping blocks, keys/values are
the blocks extended by a halo. The reference's `tensor.unfold` (not lowered
for torch-XLA, as its own comment notes) is replaced here by a static python
loop of strided slices over the padded map — one slice per block, all shapes
fixed at trace time, which XLA fuses into the attention matmuls. Relative
position logits share the static-gather `rel_logits_1d` with bottleneck_attn.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import nnx

from .bottleneck_attn import rel_logits_1d
from .helpers import make_divisible

__all__ = ['HaloAttn']


class PosEmbedRelHalo(nnx.Module):
    """Relative position embedding over (block, win) query/key grids
    (reference halo_attn.py PosEmbedRel)."""

    def __init__(self, block_size: int, win_size: int, dim_head: int, scale: float,
                 *, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.block_size = block_size
        self.win_size = win_size
        self.dim_head = dim_head
        # reference re-inits these with trunc_normal_(std=scale)
        self.height_rel = nnx.Param(
            jax.random.truncated_normal(rngs.params(), -2, 2, (win_size * 2 - 1, dim_head), param_dtype) * scale)
        self.width_rel = nnx.Param(
            jax.random.truncated_normal(rngs.params(), -2, 2, (win_size * 2 - 1, dim_head), param_dtype) * scale)

    def __call__(self, q):
        # q: (B, BB, block_size^2, dim) → (B, BB, block_size^2, win_size^2)
        B, BB, HW, _ = q.shape
        q = q.reshape(-1, self.block_size, self.block_size, self.dim_head)
        rel_logits_w = rel_logits_1d(q, self.width_rel[...], (0, 1, 3, 2, 4), k_other=self.win_size)
        q = q.transpose(0, 2, 1, 3)
        rel_logits_h = rel_logits_1d(q, self.height_rel[...], (0, 3, 1, 4, 2), k_other=self.win_size)
        rel_logits = rel_logits_h + rel_logits_w
        return rel_logits.reshape(B, BB, HW, -1)


class HaloAttn(nnx.Module):
    """Halo attention block (reference halo_attn.py:101-250)."""

    def __init__(
            self,
            dim: int,
            dim_out: Optional[int] = None,
            feat_size=None,  # unused; arg compat with bottleneck/lambda
            stride: int = 1,
            num_heads: int = 8,
            dim_head: Optional[int] = None,
            block_size: int = 8,
            halo_size: int = 3,
            qk_ratio: float = 1.0,
            qkv_bias: bool = False,
            avg_down: bool = False,
            scale_pos_embed: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        dim_out = dim_out or dim
        assert dim_out % num_heads == 0
        assert stride in (1, 2)
        self.num_heads = num_heads
        self.dim_head_qk = dim_head or make_divisible(dim_out * qk_ratio, divisor=8) // num_heads
        self.dim_head_v = dim_out // num_heads
        self.dim_out_qk = num_heads * self.dim_head_qk
        self.dim_out_v = num_heads * self.dim_head_v
        self.scale = self.dim_head_qk ** -0.5
        self.scale_pos_embed = scale_pos_embed
        self.block_size = self.block_size_ds = block_size
        self.halo_size = halo_size
        self.win_size = block_size + halo_size * 2
        self.block_stride = 1
        self.use_avg_pool = False
        if stride > 1:
            self.use_avg_pool = avg_down or block_size % stride != 0
            self.block_stride = 1 if self.use_avg_pool else stride
            self.block_size_ds = self.block_size // self.block_stride

        init = nnx.initializers.truncated_normal(stddev=dim ** -0.5)
        self.q = nnx.Conv(
            dim, self.dim_out_qk, kernel_size=(1, 1), strides=self.block_stride,
            use_bias=qkv_bias, kernel_init=init, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.kv = nnx.Conv(
            dim, self.dim_out_qk + self.dim_out_v, kernel_size=(1, 1), use_bias=qkv_bias,
            kernel_init=init, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.pos_embed = PosEmbedRelHalo(
            block_size=self.block_size_ds, win_size=self.win_size,
            dim_head=self.dim_head_qk, scale=self.scale, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        B, H, W, C = x.shape
        assert H % self.block_size == 0 and W % self.block_size == 0
        nH = H // self.block_size
        nW = W // self.block_size
        nblocks = nH * nW
        bs = self.block_size_ds

        q = self.q(x)  # (B, H', W', heads*dqk)
        q = q.reshape(B, nH, bs, nW, bs, self.num_heads, self.dim_head_qk)
        q = q.transpose(0, 5, 1, 3, 2, 4, 6).reshape(B, self.num_heads, nblocks, bs * bs, self.dim_head_qk)

        kv = self.kv(x)
        kv = jnp.pad(kv, ((0, 0), (self.halo_size, self.halo_size), (self.halo_size, self.halo_size), (0, 0)))
        # overlapping (win, win) windows at block stride: static slice per block
        win = self.win_size
        rows = []
        for bh in range(nH):
            cols = []
            for bw in range(nW):
                cols.append(kv[:, bh * self.block_size: bh * self.block_size + win,
                               bw * self.block_size: bw * self.block_size + win, :])
            rows.append(jnp.stack(cols, axis=1))
        kv = jnp.stack(rows, axis=1)  # (B, nH, nW, win, win, Ckv)
        kv = kv.reshape(B, nblocks, win * win, self.num_heads, self.dim_head_qk + self.dim_head_v)
        kv = kv.transpose(0, 3, 1, 2, 4)  # (B, heads, nblocks, win^2, dqk+dv)
        k, v = jnp.split(kv, [self.dim_head_qk], axis=-1)

        pos = self.pos_embed(q.reshape(B * self.num_heads, nblocks, bs * bs, self.dim_head_qk))
        pos = pos.reshape(B, self.num_heads, nblocks, bs * bs, win * win)
        logits = jnp.einsum('bhnqd,bhnkd->bhnqk', q, k)
        if self.scale_pos_embed:
            attn = (logits + pos) * self.scale
        else:
            attn = logits * self.scale + pos
        attn = jax.nn.softmax(attn, axis=-1)
        out = jnp.einsum('bhnqk,bhnkd->bhnqd', attn, v)  # (B, heads, nblocks, bs^2, dv)
        out = out.reshape(B, self.num_heads, nH, nW, bs, bs, self.dim_head_v)
        out = out.transpose(0, 2, 4, 3, 5, 1, 6).reshape(
            B, nH * bs, nW * bs, self.dim_out_v)
        if self.use_avg_pool:
            Ho, Wo = out.shape[1], out.shape[2]
            out = out[:, :2 * (Ho // 2), :2 * (Wo // 2)]
            out = out.reshape(B, Ho // 2, 2, Wo // 2, 2, -1).mean(axis=(2, 4))
        return out
