"""Conditionally parameterized convolution (CondConv, arXiv:1904.04971)
(reference: timm/layers/cond_conv2d.py:36-139).

TPU-first: per-sample kernels are built by one (B, E) x (E, P) matmul and the
per-sample conv runs as a vmap'd conv — XLA batches it; no grouped-conv
reshaping hackery is needed.
"""
from __future__ import annotations

import math
from typing import Union

import jax
import jax.numpy as jnp
from flax import nnx

from .create_conv2d import _resolve_padding
from .helpers import to_2tuple

__all__ = ['CondConv2d', 'get_condconv_initializer']


def get_condconv_initializer(initializer, num_experts, expert_shape):
    """Init each expert row as if it were an independent kernel of
    `expert_shape` (reference cond_conv2d.py:23-33)."""
    def condconv_initializer(key, shape, dtype):
        assert shape[0] == num_experts and shape[1] == math.prod(expert_shape)
        keys = jax.random.split(key, num_experts)
        rows = [initializer(k, expert_shape, dtype).reshape(-1) for k in keys]
        return jnp.stack(rows)
    return condconv_initializer


class CondConv2d(nnx.Module):
    """NHWC conditionally-parameterized conv. `__call__(x, routing_weights)`
    with routing (B, num_experts); expert kernels stored flat (E, P) with
    HWIO expert shape."""

    def __init__(
            self,
            in_channels: int,
            out_channels: int,
            kernel_size: Union[int, tuple] = 3,
            stride: int = 1,
            padding='',
            dilation: int = 1,
            groups: int = 1,
            bias: bool = False,
            num_experts: int = 4,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = to_2tuple(kernel_size)
        self.stride = to_2tuple(stride)
        self.dilation = to_2tuple(dilation)
        self.groups = groups
        self.num_experts = num_experts
        self.dtype = dtype
        self.padding = _resolve_padding(padding, self.kernel_size, stride, dilation)
        # HWIO expert kernel shape (flax conv convention)
        self.weight_shape = self.kernel_size + (in_channels // groups, out_channels)
        fan_in = math.prod(self.weight_shape[:-1])
        bound = 1.0 / math.sqrt(fan_in)
        kaiming = jax.nn.initializers.variance_scaling(1.0 / 3.0, 'fan_in', 'uniform')
        self.weight = nnx.Param(get_condconv_initializer(
            kaiming, num_experts, self.weight_shape)(
            rngs.params(), (num_experts, math.prod(self.weight_shape)), param_dtype))
        if bias:
            uni = jax.nn.initializers.uniform(scale=2 * bound)
            self.bias = nnx.Param(
                uni(rngs.params(), (num_experts, out_channels), param_dtype) - bound)
        else:
            self.bias = None

    def __call__(self, x, routing_weights):
        B = x.shape[0]
        dt = self.dtype or x.dtype
        weight = (routing_weights.astype(dt) @ self.weight[...].astype(dt))
        weight = weight.reshape((B,) + self.weight_shape)  # (B, kh, kw, Cin/g, Cout)

        def conv_one(xi, wi):
            return jax.lax.conv_general_dilated(
                xi[None], wi, window_strides=self.stride, padding=self.padding,
                rhs_dilation=self.dilation, feature_group_count=self.groups,
                dimension_numbers=('NHWC', 'HWIO', 'NHWC'))[0]

        out = jax.vmap(conv_one)(x.astype(dt), weight)
        if self.bias is not None:
            b = routing_weights.astype(dt) @ self.bias[...].astype(dt)  # (B, Cout)
            out = out + b[:, None, None, :]
        return out
