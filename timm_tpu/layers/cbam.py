"""CBAM: channel + spatial attention (reference: timm/layers/cbam.py:1-181)."""
from __future__ import annotations



import jax.numpy as jnp
from flax import nnx

from .create_act import get_act_fn
from .helpers import make_divisible
from .weight_init import variance_scaling_, zeros_

__all__ = ['CbamModule', 'LightCbamModule', 'ChannelAttn', 'SpatialAttn']


class ChannelAttn(nnx.Module):
    def __init__(self, channels: int, rd_ratio=1. / 16, rd_channels=None, rd_divisor=1,
                 act_layer='relu', gate_layer='sigmoid', mlp_bias=False,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        if not rd_channels:
            rd_channels = make_divisible(channels * rd_ratio, rd_divisor, round_limit=0.0)
        lin = lambda ci, co: nnx.Linear(
            ci, co, use_bias=mlp_bias, dtype=dtype, param_dtype=param_dtype,
            kernel_init=variance_scaling_(2.0, 'fan_out', 'normal'), bias_init=zeros_, rngs=rngs)
        self.fc1 = lin(channels, rd_channels)
        self.act = get_act_fn(act_layer)
        self.fc2 = lin(rd_channels, channels)
        self.gate = get_act_fn(gate_layer)

    def __call__(self, x):
        x_avg = self.fc2(self.act(self.fc1(x.mean(axis=(1, 2)))))
        x_max = self.fc2(self.act(self.fc1(x.max(axis=(1, 2)))))
        return x * self.gate(x_avg + x_max)[:, None, None, :]


class SpatialAttn(nnx.Module):
    def __init__(self, kernel_size: int = 7, gate_layer='sigmoid',
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.conv = nnx.Conv(
            2, 1, kernel_size=(kernel_size, kernel_size), padding='SAME', use_bias=False,
            dtype=dtype, param_dtype=param_dtype,
            kernel_init=variance_scaling_(2.0, 'fan_out', 'normal'), rngs=rngs)
        self.gate = get_act_fn(gate_layer)

    def __call__(self, x):
        attn = jnp.concatenate([
            x.mean(axis=-1, keepdims=True), x.max(axis=-1, keepdims=True)], axis=-1)
        return x * self.gate(self.conv(attn))


class CbamModule(nnx.Module):
    def __init__(self, channels: int, rd_ratio=1. / 16, rd_channels=None, rd_divisor=1,
                 spatial_kernel_size: int = 7, act_layer='relu', gate_layer='sigmoid', mlp_bias=False,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.channel = ChannelAttn(
            channels, rd_ratio=rd_ratio, rd_channels=rd_channels, rd_divisor=rd_divisor,
            act_layer=act_layer, gate_layer=gate_layer, mlp_bias=mlp_bias,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.spatial = SpatialAttn(spatial_kernel_size, gate_layer=gate_layer,
                                   dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        return self.spatial(self.channel(x))


class LightChannelAttn(ChannelAttn):
    """Avg+max fused before the MLP (reference cbam.py LightChannelAttn)."""

    def __call__(self, x):
        x_pool = 0.5 * x.mean(axis=(1, 2)) + 0.5 * x.max(axis=(1, 2))
        attn = self.fc2(self.act(self.fc1(x_pool)))
        return x * self.gate(attn)[:, None, None, :]


class LightCbamModule(nnx.Module):
    def __init__(self, channels: int, spatial_kernel_size: int = 7,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs, **kwargs):
        self.channel = LightChannelAttn(channels, dtype=dtype, param_dtype=param_dtype, rngs=rngs, **kwargs)
        self.spatial = SpatialAttn(spatial_kernel_size, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        return self.spatial(self.channel(x))
