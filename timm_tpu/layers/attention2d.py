"""2D (spatial) attention for conv nets, NHWC
(reference: timm/layers/attention2d.py:1-380).

TPU notes: everything stays NHWC end-to-end — the reference's NCHW permute
dance disappears because a 1x1 conv on NHWC IS the (B*H*W, C) matmul the MXU
wants. The multi-query variant's spatial down/upsampling (query avg-pool,
key/value strided dw conv, bilinear output upsample) are static-shape ops XLA
fuses around the single batched attention matmul.
"""
from __future__ import annotations

import math
from typing import Callable, List, Optional, Union

import jax
import jax.numpy as jnp
from flax import nnx

from .attention import maybe_add_mask
from .create_conv2d import create_conv2d
from .drop import Dropout, dropout_rng_key
from .helpers import to_2tuple

__all__ = ['MultiQueryAttentionV2', 'MultiQueryAttention2d', 'Attention2d']


class MultiQueryAttentionV2(nnx.Module):
    """Multi-query attention (one shared K/V head) over flattened spatial
    positions (reference attention2d.py:13-92). Einsum-first layout."""

    def __init__(
            self,
            dim: int,
            dim_out: Optional[int] = None,
            num_heads: int = 8,
            key_dim: int = 64,
            value_dim: int = 64,
            attn_drop: float = 0.0,
            proj_drop: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        dim_out = dim_out or dim
        self.num_heads = num_heads
        self.key_dim = key_dim
        self.value_dim = value_dim
        self.scale = key_dim ** -0.5
        scale_init = dim ** -0.5
        k = jax.random.split(rngs.params(), 4)
        self.query_proj = nnx.Param(jax.random.normal(k[0], (num_heads, key_dim, dim), param_dtype) * scale_init)
        self.key_proj = nnx.Param(jax.random.normal(k[1], (dim, key_dim), param_dtype) * scale_init)
        self.value_proj = nnx.Param(jax.random.normal(k[2], (dim, value_dim), param_dtype) * scale_init)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.out_proj = nnx.Param(jax.random.normal(k[3], (dim_out, num_heads, value_dim), param_dtype) * dim_out ** -0.5)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x, m=None):
        B, H, W, C = x.shape
        m = m if m is not None else x
        xr = x.reshape(B, -1, C)
        mr = m.reshape(B, -1, m.shape[-1])
        q = jnp.einsum('bnd,hkd->bnhk', xr, self.query_proj[...].astype(x.dtype))
        k = jnp.einsum('bmd,dk->bmk', mr, self.key_proj[...].astype(x.dtype))
        attn = jnp.einsum('bnhk,bmk->bnhm', q, k) * self.scale
        attn = jax.nn.softmax(attn, axis=-1)
        attn = self.attn_drop(attn)
        v = jnp.einsum('bmd,dv->bmv', mr, self.value_proj[...].astype(x.dtype))
        o = jnp.einsum('bnhm,bmv->bnhv', attn, v)
        out = jnp.einsum('bnhv,dhv->bnd', o, self.out_proj[...].astype(x.dtype))
        out = self.proj_drop(out)
        return out.reshape(B, H, W, -1)


class _QueryDown(nnx.Module):
    """query branch: optional avg-pool down + norm, then 1x1 proj
    (keeps the reference's ``query.{down_pool,norm,proj}`` state names)."""

    def __init__(self, dim, out_dim, query_strides, norm_layer, use_bias, pad_same,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.query_strides = query_strides
        self.pad_same = pad_same
        has_stride = any(s > 1 for s in query_strides)
        self.norm = norm_layer(dim, rngs=rngs) if has_stride else None
        self.proj = create_conv2d(
            dim, out_dim, 1, bias=use_bias, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        if self.norm is not None:
            # torch AvgPool2d / AvgPool2dSame divide by k*k even over padding
            # (count_include_pad=True) — Pool2d's valid-count divisor differs
            # on padded edges, so keep the fixed divisor here
            k = self.query_strides
            pad = 'SAME' if self.pad_same else 'VALID'
            x = jax.lax.reduce_window(
                x, 0.0, jax.lax.add, (1, k[0], k[1], 1), (1, k[0], k[1], 1), pad) / (k[0] * k[1])
            x = self.norm(x)
        return self.proj(x)


class _KvDown(nnx.Module):
    """key/value branch: optional strided dw down conv + norm, then 1x1 proj
    (reference ``key.{down_conv,norm,proj}``)."""

    def __init__(self, dim, out_dim, kv_stride, dw_kernel_size, dilation, padding,
                 norm_layer, use_bias, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        if kv_stride > 1:
            self.down_conv = create_conv2d(
                dim, dim, dw_kernel_size, stride=kv_stride, dilation=dilation,
                padding=padding, depthwise=True, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            self.norm = norm_layer(dim, rngs=rngs)
        else:
            self.down_conv = None
            self.norm = None
        self.proj = create_conv2d(
            dim, out_dim, 1, bias=use_bias, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        if self.down_conv is not None:
            x = self.norm(self.down_conv(x))
        return self.proj(x)


class _UpProj(nnx.Module):
    """output branch: optional bilinear upsample then 1x1 proj
    (reference ``output.{upsample,proj,drop}``)."""

    def __init__(self, dim, out_dim, query_strides, proj_drop, use_bias,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.query_strides = query_strides
        self.upsample = any(s > 1 for s in query_strides)
        self.proj = create_conv2d(
            dim, out_dim, 1, bias=use_bias, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x):
        if self.upsample:
            B, H, W, C = x.shape
            # torch Upsample(bilinear, align_corners=False)
            x = jax.image.resize(
                x, (B, H * self.query_strides[0], W * self.query_strides[1], C), method='bilinear')
        return self.drop(self.proj(x))


class MultiQueryAttention2d(nnx.Module):
    """Multi-query attention with spatial down-sampling on Q (avg pool) and
    K/V (strided dw conv), and bilinear upsampling of the output
    (reference attention2d.py:94-318)."""

    def __init__(
            self,
            dim: int,
            dim_out: Optional[int] = None,
            num_heads: int = 8,
            key_dim: Optional[int] = None,
            value_dim: Optional[int] = None,
            query_strides: Union[int, tuple] = 1,
            kv_stride: int = 1,
            dw_kernel_size: int = 3,
            dilation: int = 1,
            padding: Union[str, int, List[int]] = '',
            attn_drop: float = 0.0,
            proj_drop: float = 0.0,
            norm_layer: Optional[Callable] = None,
            use_bias: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        from .norm import BatchNorm2d
        norm_layer = norm_layer or BatchNorm2d
        dim_out = dim_out or dim
        self.num_heads = num_heads
        self.key_dim = key_dim or dim // num_heads
        self.value_dim = value_dim or dim // num_heads
        self.query_strides = to_2tuple(query_strides)
        self.kv_stride = kv_stride
        self.scale = self.key_dim ** -0.5
        self.attn_drop_rate = attn_drop
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.query = _QueryDown(
            dim, num_heads * self.key_dim, self.query_strides, norm_layer, use_bias,
            pad_same=padding == 'same', **kw)
        self.key = _KvDown(
            dim, self.key_dim, kv_stride, dw_kernel_size, dilation, padding, norm_layer, use_bias, **kw)
        self.value = _KvDown(
            dim, self.value_dim, kv_stride, dw_kernel_size, dilation, padding, norm_layer, use_bias, **kw)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.output = _UpProj(
            self.value_dim * num_heads, dim_out, self.query_strides, proj_drop, use_bias, **kw)

    def __call__(self, x, attn_mask=None):
        B, H, W, C = x.shape
        q = self.query(x)   # (B, H/qs, W/qs, h*k)
        k = self.key(x)     # (B, H/kv, W/kv, k)
        v = self.value(x)   # (B, H/kv, W/kv, v)
        num_q = q.shape[1] * q.shape[2]
        q = q.reshape(B, num_q, self.num_heads, self.key_dim)
        k = k.reshape(B, -1, self.key_dim)
        v = v.reshape(B, -1, self.value_dim)

        attn = jnp.einsum('blhk,bpk->blhp', q, k) * self.scale
        attn = maybe_add_mask(attn, attn_mask)
        attn = jax.nn.softmax(attn, axis=-1)
        attn = self.attn_drop(attn)
        o = jnp.einsum('blhp,bpv->blhv', attn, v)   # (B, L, h, v)
        o = o.reshape(B, H // self.query_strides[0], W // self.query_strides[1], -1)
        return self.output(o)


class Attention2d(nnx.Module):
    """Multi-head attention over flattened spatial positions of an NHWC map
    (reference attention2d.py:320-380)."""

    def __init__(
            self,
            dim: int,
            dim_out: Optional[int] = None,
            num_heads: int = 32,
            bias: bool = True,
            expand_first: bool = False,
            head_first: bool = False,
            attn_drop: float = 0.0,
            proj_drop: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        dim_out = dim_out or dim
        dim_attn = dim_out if expand_first else dim
        self.num_heads = num_heads
        self.dim_head = dim_attn // num_heads
        self.head_first = head_first
        self.scale = self.dim_head ** -0.5
        self.qkv = create_conv2d(
            dim, dim_attn * 3, 1, bias=bias, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.proj = create_conv2d(
            dim_attn, dim_out, 1, bias=bias, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x, attn_mask=None):
        B, H, W, C = x.shape
        N = H * W
        qkv = self.qkv(x).reshape(B, N, -1)
        if self.head_first:
            qkv = qkv.reshape(B, N, self.num_heads, 3 * self.dim_head)
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            qkv = qkv.reshape(B, N, 3, self.num_heads, self.dim_head)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = q.transpose(0, 2, 1, 3)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
        attn = (q * self.scale) @ k.transpose(0, 1, 3, 2)
        attn = maybe_add_mask(attn, attn_mask)
        attn = jax.nn.softmax(attn, axis=-1)
        attn = self.attn_drop(attn)
        x = (attn @ v).transpose(0, 2, 1, 3).reshape(B, H, W, -1)
        x = self.proj(x)
        return self.proj_drop(x)
