"""Relative position bias tables (reference: timm/layers/pos_embed_rel.py).

TPU-first design notes: the relative-position *index* is a trace-time
constant (numpy, computed once at module build), so the bias lookup lowers
to a single static gather that XLA folds into the attention fusion. The
*table* is the only learnable state. Swin-V2-style log-CPB (`RelPosMlp`)
keeps the log-coordinate grid static as well and runs the tiny MLP on it
per forward (cheap: (2W-1)^2 x heads).
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import nnx

from .mlp import Mlp
from .weight_init import trunc_normal_

__all__ = [
    'gen_relative_position_index', 'gen_relative_log_coords', 'RelPosBias', 'RelPosBiasTf',
    'RelPosMlp', 'resize_rel_pos_bias_table_simple',
]


def gen_relative_position_index(
        q_size: Tuple[int, int],
        k_size: Optional[Tuple[int, int]] = None,
        class_token: bool = False,
) -> np.ndarray:
    """Pairwise relative position index for tokens in a (h, w) window
    (reference pos_embed_rel.py:21-75). With `class_token`, rows/cols 0 get
    the three extra BEiT cls bucket ids."""
    assert k_size is None, 'q/k size mismatch not supported'
    h, w = q_size
    coords = np.stack(np.meshgrid(np.arange(h), np.arange(w), indexing='ij')).reshape(2, -1)
    rel = coords[:, :, None] - coords[:, None, :]  # (2, N, N)
    rel = rel.transpose(1, 2, 0).astype(np.int64)  # (N, N, 2)
    rel[:, :, 0] += h - 1
    rel[:, :, 1] += w - 1
    rel[:, :, 0] *= 2 * w - 1
    num_rel_dist = (2 * h - 1) * (2 * w - 1)
    index = rel.sum(-1)  # (N, N)
    if class_token:
        index = np.pad(index, ((1, 0), (1, 0)))
        index[0, :] = num_rel_dist
        index[:, 0] = num_rel_dist + 1
        index[0, 0] = num_rel_dist + 2
    return index


def resize_rel_pos_bias_table_simple(table: np.ndarray, new_window_size: Tuple[int, int],
                                     new_bias_shape: Tuple[int, ...]) -> np.ndarray:
    """Bilinear resize of a (L, H) rel-pos table to a new window size,
    preserving trailing cls-token buckets (reference pos_embed_rel.py:77-121)."""
    dst_h, dst_w = 2 * new_window_size[0] - 1, 2 * new_window_size[1] - 1
    num_extra = new_bias_shape[0] - dst_h * dst_w
    src_len = table.shape[0] - num_extra
    src_size = int(math.sqrt(src_len))
    if src_size * src_size != src_len:
        return table  # non-square source; give up
    extra = table[src_len:] if num_extra > 0 else None
    core = table[:src_len].reshape(src_size, src_size, -1)
    core = jax.image.resize(jnp.asarray(core), (dst_h, dst_w, core.shape[-1]), 'bilinear')
    core = np.asarray(core).reshape(dst_h * dst_w, -1)
    if extra is not None:
        core = np.concatenate([core, extra], axis=0)
    return core


class RelPosBias(nnx.Module):
    """Swin-V1 style learned relative position bias
    (reference pos_embed_rel.py:272-331)."""

    def __init__(
            self,
            window_size: Tuple[int, int],
            num_heads: int,
            prefix_tokens: int = 0,
            *,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert prefix_tokens <= 1
        self.window_size = window_size
        self.window_area = window_size[0] * window_size[1]
        self.num_heads = num_heads
        self.prefix_tokens = prefix_tokens
        self.bias_shape = (self.window_area + prefix_tokens,) * 2 + (num_heads,)
        num_rel_dist = (2 * window_size[0] - 1) * (2 * window_size[1] - 1) + 3 * prefix_tokens
        self.relative_position_bias_table = nnx.Param(
            trunc_normal_(std=0.02)(rngs.params(), (num_rel_dist, num_heads), param_dtype))
        # nnx.Variable: raw array attrs break nnx graph traversal on older flax
        self._index = nnx.Variable(jnp.asarray(gen_relative_position_index(
            window_size, class_token=prefix_tokens > 0).reshape(-1)))

    def get_bias(self) -> jax.Array:
        bias = self.relative_position_bias_table[...][self._index[...]]
        bias = bias.reshape(self.bias_shape).transpose(2, 0, 1)  # (H, N, N)
        return bias[None]

    def __call__(self, attn, shared_rel_pos=None):
        return attn + self.get_bias().astype(attn.dtype)


class RelPosBiasTf(nnx.Module):
    """TF-MaxViT-compatible relative position bias: a (heads, 2H-1, 2W-1)
    table indexed by decomposed row/col offsets (reference
    pos_embed_rel.py:467-527). The reference materialises one-hot lookup
    tensors and einsums; here the gather indices are trace-time numpy
    constants so the bias is two static takes."""

    def __init__(
            self,
            window_size: Tuple[int, int],
            num_heads: int,
            prefix_tokens: int = 0,
            *,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert prefix_tokens <= 1
        self.window_size = window_size
        self.window_area = window_size[0] * window_size[1]
        self.num_heads = num_heads
        h, w = window_size
        self.bias_shape = (num_heads, 2 * h - 1, 2 * w - 1)
        self.relative_position_bias_table = nnx.Param(
            jax.random.normal(rngs.params(), self.bias_shape, param_dtype) * 0.02)
        idx_h = np.arange(h)[:, None] - np.arange(h)[None, :] + (h - 1)  # (qh, kh)
        idx_w = np.arange(w)[:, None] - np.arange(w)[None, :] + (w - 1)  # (qw, kw)
        self._idx_h = nnx.Variable(jnp.asarray(idx_h))
        self._idx_w = nnx.Variable(jnp.asarray(idx_w))

    def get_bias(self) -> jax.Array:
        h, w = self.window_size
        table = self.relative_position_bias_table[...]
        bias = table[:, self._idx_h[...]]            # (nh, qh, kh, 2w-1)
        bias = bias[..., self._idx_w[...]]           # (nh, qh, kh, qw, kw)
        bias = bias.transpose(0, 1, 3, 2, 4)    # (nh, qh, qw, kh, kw)
        bias = bias.reshape(self.num_heads, self.window_area, self.window_area)
        return bias[None]

    def __call__(self, attn, shared_rel_pos=None):
        return attn + self.get_bias().astype(attn.dtype)


def gen_relative_log_coords(
        win_size: Tuple[int, int],
        pretrained_win_size: Tuple[int, int] = (0, 0),
        mode: str = 'swin',
) -> np.ndarray:
    """Log-spaced relative coordinate grid for MLP-CPB
    (reference pos_embed_rel.py:334-363; Swin-V2 §: log-CPB)."""
    assert mode in ('swin', 'cr')
    h, w = win_size
    rel_h = np.arange(-(h - 1), h, dtype=np.float32)
    rel_w = np.arange(-(w - 1), w, dtype=np.float32)
    coords = np.stack(np.meshgrid(rel_h, rel_w, indexing='ij'), axis=-1)  # (2h-1, 2w-1, 2)
    if mode == 'swin':
        if pretrained_win_size[0] > 0:
            coords[:, :, 0] /= pretrained_win_size[0] - 1
            coords[:, :, 1] /= pretrained_win_size[1] - 1
        else:
            coords[:, :, 0] /= h - 1
            coords[:, :, 1] /= w - 1
        coords *= 8  # normalize to -8..8
        coords = np.sign(coords) * np.log2(1.0 + np.abs(coords)) / np.log2(8)
    else:  # swin-v2-cr: unscaled natural log
        coords = np.sign(coords) * np.log(1.0 + np.abs(coords))
    return coords


class RelPosMlp(nnx.Module):
    """MLP-based continuous relative position bias (Swin-V2 log-CPB;
    reference pos_embed_rel.py:365-465)."""

    def __init__(
            self,
            window_size: Tuple[int, int],
            num_heads: int = 8,
            hidden_dim: int = 128,
            prefix_tokens: int = 0,
            mode: str = 'cr',
            pretrained_window_size: Tuple[int, int] = (0, 0),
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.window_size = window_size
        self.window_area = window_size[0] * window_size[1]
        self.prefix_tokens = prefix_tokens
        self.num_heads = num_heads
        self.bias_shape = (self.window_area,) * 2 + (num_heads,)
        if mode == 'swin':
            self.bias_act = 'sigmoid'
            self.bias_gain = 16.0
            mlp_bias = (True, False)
        else:
            self.bias_act = None
            self.bias_gain = None
            mlp_bias = True
        self.mlp = Mlp(
            2, hidden_features=hidden_dim, out_features=num_heads, act_layer='relu',
            bias=mlp_bias, drop=(0.125, 0.0), dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        # nnx.Variable: raw array attrs break nnx graph traversal on older flax
        self._index = nnx.Variable(jnp.asarray(gen_relative_position_index(window_size).reshape(-1)))
        self._log_coords = nnx.Variable(jnp.asarray(gen_relative_log_coords(
            window_size, pretrained_window_size, mode=mode)))

    def get_bias(self) -> jax.Array:
        bias = self.mlp(self._log_coords[...])  # (2h-1, 2w-1, heads)
        bias = bias.reshape(-1, self.num_heads)[self._index[...]]
        bias = bias.reshape(self.bias_shape).transpose(2, 0, 1)
        if self.bias_act == 'sigmoid':
            bias = jax.nn.sigmoid(bias)
        if self.bias_gain is not None:
            bias = self.bias_gain * bias
        if self.prefix_tokens:
            bias = jnp.pad(bias, ((0, 0), (self.prefix_tokens, 0), (self.prefix_tokens, 0)))
        return bias[None]

    def __call__(self, attn, shared_rel_pos=None):
        return attn + self.get_bias().astype(attn.dtype)
