"""Non-Local and Bilinear-Attention-Transform (BAT) attention
(reference: timm/layers/non_local_attn.py:1-189, Chi et al. CVPR 2020).

NHWC throughout: the non-local attention is one einsum-softmax-einsum over
flattened spatial positions; the BAT bilinear transform is two batched
matmuls with block-structured row/column mixing matrices, both of which XLA
maps straight onto the MXU.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import nnx

from .create_conv2d import ConvNormAct, create_conv2d
from .drop import Dropout
from .helpers import make_divisible

__all__ = ['NonLocalAttn', 'BilinearAttnTransform', 'BatNonLocalAttn']


class NonLocalAttn(nnx.Module):
    """Classic spatial non-local block (reference non_local_attn.py:19-84)."""

    def __init__(self, in_channels, use_scale: bool = True, rd_ratio: float = 1 / 8,
                 rd_channels: Optional[int] = None, rd_divisor: int = 8,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs, **_):
        from .norm import BatchNorm2d
        if rd_channels is None:
            rd_channels = make_divisible(in_channels * rd_ratio, divisor=rd_divisor)
        self.scale = in_channels ** -0.5 if use_scale else 1.0
        conv = lambda ci, co: create_conv2d(
            ci, co, 1, bias=True, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.t = conv(in_channels, rd_channels)
        self.p = conv(in_channels, rd_channels)
        self.g = conv(in_channels, rd_channels)
        self.z = conv(rd_channels, in_channels)
        self.norm = BatchNorm2d(in_channels, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        # zero-init the BN scale so the block starts as identity (reference
        # reset_parameters zeroes the norm weight)
        if self.norm.scale is not None:
            self.norm.scale[...] = jnp.zeros_like(self.norm.scale[...])

    def __call__(self, x):
        shortcut = x
        B, H, W, _ = x.shape
        t = self.t(x).reshape(B, H * W, -1)
        p = self.p(x).reshape(B, H * W, -1)
        g = self.g(x).reshape(B, H * W, -1)
        att = jnp.einsum('bnc,bmc->bnm', t, p) * self.scale
        att = jax.nn.softmax(att, axis=2)
        y = jnp.einsum('bnm,bmc->bnc', att, g).reshape(B, H, W, -1)
        y = self.z(y)
        return self.norm(y) + shortcut


def _kron_identity(x, t: int):
    """kron(x, I_t) on trailing (bs, bs) matrices — the reference's
    `resize_mat` (non_local_attn.py:110-120) without the split/cat dance."""
    if t <= 1:
        return x
    *lead, bs, bs2 = x.shape
    assert bs == bs2
    eye = jnp.eye(t, dtype=x.dtype)
    out = x[..., :, None, :, None] * eye[None, :, None, :]
    return out.reshape(*lead, bs * t, bs * t)


class BilinearAttnTransform(nnx.Module):
    """Grouped bilinear attentional transform (reference non_local_attn.py:87)."""

    def __init__(self, in_channels: int, block_size: int, groups: int,
                 act_layer='relu', norm_layer=None,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv1 = ConvNormAct(in_channels, groups, 1, act_layer=act_layer,
                                 norm_layer=norm_layer, **dd)
        self.conv_p = create_conv2d(groups, block_size * block_size * groups,
                                    (block_size, 1), padding='valid', bias=True, **dd)
        self.conv_q = create_conv2d(groups, block_size * block_size * groups,
                                    (1, block_size), padding='valid', bias=True, **dd)
        self.conv2 = ConvNormAct(in_channels, in_channels, 1, act_layer=act_layer,
                                 norm_layer=norm_layer, **dd)
        self.block_size = block_size
        self.groups = groups
        self.in_channels = in_channels

    def __call__(self, x):
        B, H, W, C = x.shape
        assert H % self.block_size == 0 and W % self.block_size == 0
        bs, G = self.block_size, self.groups
        out = self.conv1(x)  # (B, H, W, G)
        # adaptive max pool to (bs, 1) rows / (1, bs) cols — H/W divisible by bs
        rp = out.reshape(B, bs, H // bs, W, G).max(axis=(2, 3), keepdims=False)[:, :, None]  # (B, bs, 1, G)
        cp = out.reshape(B, H, bs, W // bs, G).max(axis=(1, 3), keepdims=False)[:, None]     # (B, 1, bs, G)
        p = jax.nn.sigmoid(self.conv_p(rp).reshape(B, G, bs, bs))
        q = jax.nn.sigmoid(self.conv_q(cp).reshape(B, G, bs, bs))
        p = p / p.sum(axis=3, keepdims=True)
        q = q / q.sum(axis=2, keepdims=True)
        # expand per-group matrices to all channels of the group
        cpg = C // G
        p = jnp.broadcast_to(p[:, :, None], (B, G, cpg, bs, bs)).reshape(B, C, bs, bs)
        q = jnp.broadcast_to(q[:, :, None], (B, G, cpg, bs, bs)).reshape(B, C, bs, bs)
        p = _kron_identity(p, H // bs)  # (B, C, H, H)
        q = _kron_identity(q, W // bs)  # (B, C, W, W)
        # y = p @ x @ q with NHWC x
        y = jnp.einsum('bchk,bkwc->bhwc', p, x)
        y = jnp.einsum('bhkc,bckw->bhwc', y, q)
        return self.conv2(y)


class BatNonLocalAttn(nnx.Module):
    """BAT block: reduce 1x1 → bilinear transform → expand 1x1 + residual
    (reference non_local_attn.py:148-189)."""

    def __init__(self, in_channels: int, block_size: int = 7, groups: int = 2,
                 rd_ratio: float = 0.25, rd_channels: Optional[int] = None,
                 rd_divisor: int = 8, drop_rate: float = 0.2,
                 act_layer='relu', norm_layer=None,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs, **_):
        if rd_channels is None:
            rd_channels = make_divisible(in_channels * rd_ratio, divisor=rd_divisor)
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv1 = ConvNormAct(in_channels, rd_channels, 1, act_layer=act_layer,
                                 norm_layer=norm_layer, **dd)
        self.ba = BilinearAttnTransform(rd_channels, block_size, groups,
                                        act_layer=act_layer, norm_layer=norm_layer, **dd)
        self.conv2 = ConvNormAct(rd_channels, in_channels, 1, act_layer=act_layer,
                                 norm_layer=norm_layer, **dd)
        # channel-wise (2d) dropout: drop whole feature maps, like nn.Dropout2d
        self.dropout = Dropout(drop_rate, broadcast_dims=(1, 2), rngs=rngs)

    def __call__(self, x):
        xl = self.conv1(x)
        y = self.ba(xl)
        y = self.conv2(y)
        y = self.dropout(y)
        return y + x
