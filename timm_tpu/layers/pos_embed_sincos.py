"""Sin-cos / Fourier / rotary position embeddings
(reference: timm/layers/pos_embed_sincos.py:1-1357).

Everything here is pure-functional and shape-static: tables are built at trace
time from python ints, so they constant-fold under jit.
"""
from __future__ import annotations

import math
from typing import List, Optional, Tuple

import jax.numpy as jnp
from flax import nnx

__all__ = [
    'build_sincos2d_pos_embed', 'build_fourier_pos_embed', 'build_rotary_pos_embed',
    'RotaryEmbeddingCat', 'RotaryEmbeddingMixed', 'RotaryEmbeddingDinoV3',
    'create_rope_embed', 'freq_bands', 'pixel_freq_bands',
]


def freq_bands(num_bands: int, temperature: float = 10000.0, step: int = 2) -> jnp.ndarray:
    exp = jnp.arange(0, num_bands, step, dtype=jnp.float32) / num_bands
    return 1.0 / (temperature ** exp)


def pixel_freq_bands(num_bands: int, max_freq: float = 224.0, linear_bands: bool = True) -> jnp.ndarray:
    if linear_bands:
        bands = jnp.linspace(1.0, max_freq / 2, num_bands, dtype=jnp.float32)
    else:
        bands = 2.0 ** jnp.linspace(0, math.log2(max_freq / 2), num_bands, dtype=jnp.float32)
    return bands * jnp.pi


def build_sincos2d_pos_embed(
        feat_shape: Tuple[int, int],
        dim: int = 64,
        temperature: float = 10000.0,
        reverse_coord: bool = False,
        interleave_sin_cos: bool = False,
        dtype=jnp.float32,
) -> jnp.ndarray:
    """Fixed 2D sin-cos position embedding, (H*W, dim)."""
    assert dim % 4 == 0, 'Embed dim must be divisible by 4 for sin-cos 2d pos embed'
    h, w = feat_shape
    grid_y, grid_x = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32), jnp.arange(w, dtype=jnp.float32), indexing='ij')
    if reverse_coord:
        grid_y, grid_x = grid_x, grid_y
    pos_dim = dim // 4
    omega = freq_bands(pos_dim * 2, temperature=temperature, step=2)
    out_x = grid_x.reshape(-1, 1) * omega[None, :]
    out_y = grid_y.reshape(-1, 1) * omega[None, :]
    if interleave_sin_cos:
        emb = jnp.stack([jnp.sin(out_x), jnp.cos(out_x), jnp.sin(out_y), jnp.cos(out_y)], axis=-1).reshape(h * w, -1)
    else:
        emb = jnp.concatenate([jnp.sin(out_x), jnp.cos(out_x), jnp.sin(out_y), jnp.cos(out_y)], axis=1)
    return emb.astype(dtype)


def build_fourier_pos_embed(
        feat_shape: Tuple[int, ...],
        bands: Optional[jnp.ndarray] = None,
        num_bands: int = 64,
        max_res: int = 224,
        temperature: float = 10000.0,
        linear_bands: bool = False,
        include_grid: bool = False,
        in_pixels: bool = True,
        ref_feat_shape: Optional[Tuple[int, ...]] = None,
        grid_offset: float = 0.0,
        grid_indexing: str = 'ij',
        dtype=jnp.float32,
) -> List[jnp.ndarray]:
    if bands is None:
        if in_pixels:
            bands = pixel_freq_bands(num_bands, float(max_res), linear_bands=linear_bands)
        else:
            bands = freq_bands(num_bands, temperature=temperature, step=1)
    if in_pixels:
        t = [jnp.linspace(-1.0, 1.0, s, dtype=jnp.float32) for s in feat_shape]
    else:
        t = [jnp.arange(s, dtype=jnp.float32) + grid_offset for s in feat_shape]
        if ref_feat_shape is not None:
            t = [x / s * r for x, s, r in zip(t, feat_shape, ref_feat_shape)]
    grid = jnp.stack(jnp.meshgrid(*t, indexing=grid_indexing), axis=-1)
    grid = grid[..., None]
    pos = grid * bands
    pos_sin, pos_cos = jnp.sin(pos).astype(dtype), jnp.cos(pos).astype(dtype)
    out = [grid, pos_sin, pos_cos] if include_grid else [pos_sin, pos_cos]
    return out


def build_rotary_pos_embed(
        feat_shape: Tuple[int, ...],
        bands: Optional[jnp.ndarray] = None,
        dim: int = 64,
        max_res: int = 224,
        temperature: float = 10000.0,
        linear_bands: bool = False,
        in_pixels: bool = True,
        ref_feat_shape: Optional[Tuple[int, ...]] = None,
        grid_offset: float = 0.0,
        grid_indexing: str = 'ij',
        dtype=jnp.float32,
):
    """Returns (sin_emb, cos_emb), each (num_tokens, dim) for 2D rotary."""
    sin_emb, cos_emb = build_fourier_pos_embed(
        feat_shape,
        bands=bands,
        num_bands=dim // 4,
        max_res=max_res,
        temperature=temperature,
        linear_bands=linear_bands,
        in_pixels=in_pixels,
        ref_feat_shape=ref_feat_shape,
        grid_offset=grid_offset,
        grid_indexing=grid_indexing,
        dtype=dtype,
    )
    num_spatial_dim = 1
    for x in feat_shape:
        num_spatial_dim *= x
    sin_emb = sin_emb.reshape(num_spatial_dim, -1)
    sin_emb = jnp.repeat(sin_emb, 2, axis=-1)
    cos_emb = cos_emb.reshape(num_spatial_dim, -1)
    cos_emb = jnp.repeat(cos_emb, 2, axis=-1)
    return sin_emb, cos_emb


class RotaryEmbeddingCat(nnx.Module):
    """2D ROPE producing a concatenated (sin, cos) table
    (reference pos_embed_sincos.py RotaryEmbeddingCat)."""

    def __init__(
            self,
            dim: int,
            max_res: int = 224,
            temperature: float = 10000.0,
            in_pixels: bool = True,
            linear_bands: bool = False,
            feat_shape: Optional[Tuple[int, int]] = None,
            ref_feat_shape: Optional[Tuple[int, int]] = None,
            grid_offset: float = 0.0,
            grid_indexing: str = 'ij',
            *,
            rngs: nnx.Rngs = None,
    ):
        self.dim = dim
        self.max_res = max_res
        self.temperature = temperature
        self.in_pixels = in_pixels
        self.linear_bands = linear_bands
        self.feat_shape = feat_shape
        self.ref_feat_shape = ref_feat_shape
        self.grid_offset = grid_offset
        self.grid_indexing = grid_indexing

    def get_embed(self, shape: Optional[Tuple[int, int]] = None):
        shape = shape if shape is not None else self.feat_shape
        assert shape is not None
        sin_emb, cos_emb = build_rotary_pos_embed(
            shape,
            dim=self.dim,
            max_res=self.max_res,
            temperature=self.temperature,
            linear_bands=self.linear_bands,
            in_pixels=self.in_pixels,
            ref_feat_shape=self.ref_feat_shape,
            grid_offset=self.grid_offset,
            grid_indexing=self.grid_indexing,
        )
        return jnp.concatenate([sin_emb, cos_emb], axis=-1)


def _swap_shape_xy(shape):
    return (shape[1], shape[0]) if len(shape) >= 2 else shape


def init_random_2d_freqs(key, head_dim: int, depth: int, num_heads: int,
                         temperature: float = 10.0, rotate: bool = True) -> jnp.ndarray:
    """Per-depth/per-head randomly-rotated 2D rope frequencies for mixed-mode
    rope (reference pos_embed_sincos.py:721-752). Returns (2, depth, num_heads,
    head_dim//2)."""
    import jax
    mag = 1.0 / (temperature ** (jnp.arange(0, head_dim, 4, dtype=jnp.float32) / head_dim))
    mag = mag[None, None, :]
    if rotate:
        angles = jax.random.uniform(key, (depth, num_heads, 1), jnp.float32) * 2 * math.pi
    else:
        angles = jnp.zeros((depth, num_heads, 1), jnp.float32)
    fx = jnp.concatenate([mag * jnp.cos(angles), mag * jnp.cos(angles + math.pi / 2)], axis=-1)
    fy = jnp.concatenate([mag * jnp.sin(angles), mag * jnp.sin(angles + math.pi / 2)], axis=-1)
    return jnp.stack([fx, fy], axis=0)


class RotaryEmbeddingMixed(nnx.Module):
    """Learnable depth/head-dependent rope frequencies — naver rope-vit
    'mixed' mode (reference pos_embed_sincos.py:873-1056). ``get_embed``
    returns a (depth, num_heads, H*W, head_dim) cat(sin, cos) table; the model
    indexes depth per block."""

    def __init__(
            self,
            dim: int,
            depth: int,
            num_heads: int,
            temperature: float = 10.0,
            feat_shape: Optional[Tuple[int, int]] = None,
            grid_indexing: str = 'xy',
            *,
            rngs: nnx.Rngs = None,
    ):
        self.dim = dim
        self.depth = depth
        self.num_heads = num_heads
        self.temperature = temperature
        self.feat_shape = feat_shape
        self.grid_indexing = grid_indexing
        head_dim = dim // num_heads
        assert head_dim % 4 == 0, f'head_dim must be divisible by 4, got {head_dim}'
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.freqs = nnx.Param(init_random_2d_freqs(
            rngs.params(), head_dim, depth, num_heads, temperature=temperature, rotate=True))

    def _grid(self, shape):
        if self.grid_indexing == 'xy':
            shape = _swap_shape_xy(shape)
        xs = jnp.arange(shape[0], dtype=jnp.float32)
        ys = jnp.arange(shape[1], dtype=jnp.float32)
        x_pos, y_pos = jnp.meshgrid(xs, ys, indexing=self.grid_indexing if self.grid_indexing in ('ij', 'xy') else 'ij')
        return x_pos.reshape(-1), y_pos.reshape(-1)

    def get_embed(self, shape: Optional[Tuple[int, int]] = None):
        shape = shape if shape is not None else self.feat_shape
        assert shape is not None
        t_x, t_y = self._grid(shape)
        freqs = self.freqs[...].astype(jnp.float32)
        freqs_x = t_x[:, None] @ freqs[0][..., None, :]   # (depth, nH, N, hd//4... broadcast)
        freqs_y = t_y[:, None] @ freqs[1][..., None, :]
        combined = freqs_x + freqs_y                      # (depth, num_heads, N, head_dim//2)
        sin_emb = jnp.repeat(jnp.sin(combined), 2, axis=-1)
        cos_emb = jnp.repeat(jnp.cos(combined), 2, axis=-1)
        return jnp.concatenate([sin_emb, cos_emb], axis=-1)


def make_coords_dinov3(height: int, width: int, normalize_coords: str = 'separate',
                       grid_indexing: str = 'ij', grid_offset: float = 0.0) -> jnp.ndarray:
    """DINOv3 coordinate grid: 0.5-centered, normalized, mapped to [-1, 1]
    (reference pos_embed_sincos.py:1059-1105). Returns (H*W, 2)."""
    coords_h = jnp.arange(0.5, height, dtype=jnp.float32) + grid_offset
    coords_w = jnp.arange(0.5, width, dtype=jnp.float32) + grid_offset
    if normalize_coords == 'max':
        h_denom = w_denom = float(max(height, width))
    elif normalize_coords == 'min':
        h_denom = w_denom = float(min(height, width))
    elif normalize_coords == 'separate':
        h_denom, w_denom = float(height), float(width)
    else:
        raise ValueError(f'Unknown normalize_coords: {normalize_coords}')
    coords_h = coords_h / h_denom
    coords_w = coords_w / w_denom
    if grid_indexing == 'xy':
        grid_w, grid_h = jnp.meshgrid(coords_w, coords_h, indexing='xy')
        coords = jnp.stack([grid_h, grid_w], axis=-1)
    else:
        gh, gw = jnp.meshgrid(coords_h, coords_w, indexing='ij')
        coords = jnp.stack([gh, gw], axis=-1)
    return 2.0 * coords.reshape(-1, 2) - 1.0


class RotaryEmbeddingDinoV3(nnx.Module):
    """DINOv3-numerics rope: 0.5-centered normalized coords in [-1, 1], a
    geometric period schedule, and (by default) the 'half' rotation layout
    (reference pos_embed_sincos.py:1107-1313). ``get_embed`` returns
    (H*W, 2 * dim) cat(sin, cos); consume with apply_rot_embed_cat(half=True).

    The reference's train-time coordinate augmentations (shift/jitter/rescale)
    are accepted for interface parity but not implemented — no released model
    cfg enables them at inference, and training augs belong in the data
    pipeline here.
    """

    def __init__(
            self,
            dim: int,
            temperature: Optional[float] = 100.0,
            min_period: Optional[float] = None,
            max_period: Optional[float] = None,
            feat_shape: Optional[Tuple[int, int]] = None,
            normalize_coords: str = 'separate',
            grid_offset: float = 0.0,
            grid_indexing: str = 'ij',
            rotate_half: bool = True,
            shift_coords: Optional[float] = None,
            jitter_coords: Optional[float] = None,
            rescale_coords: Optional[float] = None,
            *,
            rngs: nnx.Rngs = None,
    ):
        if any(a is not None for a in (shift_coords, jitter_coords, rescale_coords)):
            raise NotImplementedError('DINOv3 rope train-time coord augs not implemented')
        self.dim = dim
        self.rotate_half = rotate_half
        self.temperature = float(temperature) if temperature is not None else None
        self.min_period = min_period
        self.max_period = max_period
        self.normalize_coords = normalize_coords
        self.feat_shape = feat_shape
        self.grid_offset = grid_offset
        self.grid_indexing = grid_indexing

    def _periods(self) -> jnp.ndarray:
        d = self.dim // 4
        if self.min_period is not None and self.max_period is not None:
            exponents = jnp.linspace(0.0, 1.0, d)
            return self.min_period * ((self.max_period / self.min_period) ** exponents)
        if self.temperature is None:
            raise ValueError('Provide either min/max periods or `temperature`.')
        exponents = 2.0 * jnp.arange(d, dtype=jnp.float32) / (self.dim // 2)
        return self.temperature ** exponents

    def get_embed(self, shape: Optional[Tuple[int, int]] = None):
        shape = shape if shape is not None else self.feat_shape
        assert shape is not None
        coords = make_coords_dinov3(
            shape[0], shape[1], normalize_coords=self.normalize_coords,
            grid_indexing=self.grid_indexing, grid_offset=self.grid_offset)  # (HW, 2)
        periods = self._periods()
        angles = 2 * math.pi * coords[:, :, None] / periods[None, None, :]
        angles = angles.reshape(angles.shape[0], -1)  # (HW, dim//2)
        if self.rotate_half:
            angles = jnp.tile(angles, (1, 2))
        else:
            angles = jnp.repeat(angles, 2, axis=-1)
        return jnp.concatenate([jnp.sin(angles), jnp.cos(angles)], axis=-1)


def create_rope_embed(rope_type: str = 'cat', dim: int = 768, num_heads: int = 12,
                      *, rngs: nnx.Rngs = None, **kwargs):
    """Rope factory matching reference pos_embed_sincos.py:1315-1357 ('cat',
    'mixed', 'dinov3' supported here)."""
    if rope_type == 'cat':
        kwargs.pop('rotate_half', None)
        return RotaryEmbeddingCat(dim=dim // num_heads, rngs=rngs, **kwargs)
    if rope_type == 'mixed':
        kwargs.pop('in_pixels', None)
        kwargs.pop('ref_feat_shape', None)
        kwargs.pop('rotate_half', None)
        kwargs.pop('grid_offset', None)
        return RotaryEmbeddingMixed(dim=dim, num_heads=num_heads, rngs=rngs, **kwargs)
    if rope_type == 'dinov3':
        kwargs.pop('in_pixels', None)
        kwargs.pop('ref_feat_shape', None)
        return RotaryEmbeddingDinoV3(dim=dim // num_heads, rngs=rngs, **kwargs)
    raise ValueError(f'Unknown RoPE type: {rope_type}')
