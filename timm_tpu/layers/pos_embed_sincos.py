"""Sin-cos / Fourier / rotary position embeddings
(reference: timm/layers/pos_embed_sincos.py:1-1357).

Everything here is pure-functional and shape-static: tables are built at trace
time from python ints, so they constant-fold under jit.
"""
from __future__ import annotations

import math
from typing import List, Optional, Tuple

import jax.numpy as jnp
from flax import nnx

__all__ = [
    'build_sincos2d_pos_embed', 'build_fourier_pos_embed', 'build_rotary_pos_embed',
    'RotaryEmbeddingCat', 'freq_bands', 'pixel_freq_bands',
]


def freq_bands(num_bands: int, temperature: float = 10000.0, step: int = 2) -> jnp.ndarray:
    exp = jnp.arange(0, num_bands, step, dtype=jnp.float32) / num_bands
    return 1.0 / (temperature ** exp)


def pixel_freq_bands(num_bands: int, max_freq: float = 224.0, linear_bands: bool = True) -> jnp.ndarray:
    if linear_bands:
        bands = jnp.linspace(1.0, max_freq / 2, num_bands, dtype=jnp.float32)
    else:
        bands = 2.0 ** jnp.linspace(0, math.log2(max_freq / 2), num_bands, dtype=jnp.float32)
    return bands * jnp.pi


def build_sincos2d_pos_embed(
        feat_shape: Tuple[int, int],
        dim: int = 64,
        temperature: float = 10000.0,
        reverse_coord: bool = False,
        interleave_sin_cos: bool = False,
        dtype=jnp.float32,
) -> jnp.ndarray:
    """Fixed 2D sin-cos position embedding, (H*W, dim)."""
    assert dim % 4 == 0, 'Embed dim must be divisible by 4 for sin-cos 2d pos embed'
    h, w = feat_shape
    grid_y, grid_x = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32), jnp.arange(w, dtype=jnp.float32), indexing='ij')
    if reverse_coord:
        grid_y, grid_x = grid_x, grid_y
    pos_dim = dim // 4
    omega = freq_bands(pos_dim * 2, temperature=temperature, step=2)
    out_x = grid_x.reshape(-1, 1) * omega[None, :]
    out_y = grid_y.reshape(-1, 1) * omega[None, :]
    if interleave_sin_cos:
        emb = jnp.stack([jnp.sin(out_x), jnp.cos(out_x), jnp.sin(out_y), jnp.cos(out_y)], axis=-1).reshape(h * w, -1)
    else:
        emb = jnp.concatenate([jnp.sin(out_x), jnp.cos(out_x), jnp.sin(out_y), jnp.cos(out_y)], axis=1)
    return emb.astype(dtype)


def build_fourier_pos_embed(
        feat_shape: Tuple[int, ...],
        bands: Optional[jnp.ndarray] = None,
        num_bands: int = 64,
        max_res: int = 224,
        temperature: float = 10000.0,
        linear_bands: bool = False,
        include_grid: bool = False,
        in_pixels: bool = True,
        ref_feat_shape: Optional[Tuple[int, ...]] = None,
        grid_offset: float = 0.0,
        grid_indexing: str = 'ij',
        dtype=jnp.float32,
) -> List[jnp.ndarray]:
    if bands is None:
        if in_pixels:
            bands = pixel_freq_bands(num_bands, float(max_res), linear_bands=linear_bands)
        else:
            bands = freq_bands(num_bands, temperature=temperature, step=1)
    if in_pixels:
        t = [jnp.linspace(-1.0, 1.0, s, dtype=jnp.float32) for s in feat_shape]
    else:
        t = [jnp.arange(s, dtype=jnp.float32) + grid_offset for s in feat_shape]
        if ref_feat_shape is not None:
            t = [x / s * r for x, s, r in zip(t, feat_shape, ref_feat_shape)]
    grid = jnp.stack(jnp.meshgrid(*t, indexing=grid_indexing), axis=-1)
    grid = grid[..., None]
    pos = grid * bands
    pos_sin, pos_cos = jnp.sin(pos).astype(dtype), jnp.cos(pos).astype(dtype)
    out = [grid, pos_sin, pos_cos] if include_grid else [pos_sin, pos_cos]
    return out


def build_rotary_pos_embed(
        feat_shape: Tuple[int, ...],
        bands: Optional[jnp.ndarray] = None,
        dim: int = 64,
        max_res: int = 224,
        temperature: float = 10000.0,
        linear_bands: bool = False,
        in_pixels: bool = True,
        ref_feat_shape: Optional[Tuple[int, ...]] = None,
        grid_offset: float = 0.0,
        grid_indexing: str = 'ij',
        dtype=jnp.float32,
):
    """Returns (sin_emb, cos_emb), each (num_tokens, dim) for 2D rotary."""
    sin_emb, cos_emb = build_fourier_pos_embed(
        feat_shape,
        bands=bands,
        num_bands=dim // 4,
        max_res=max_res,
        temperature=temperature,
        linear_bands=linear_bands,
        in_pixels=in_pixels,
        ref_feat_shape=ref_feat_shape,
        grid_offset=grid_offset,
        grid_indexing=grid_indexing,
        dtype=dtype,
    )
    num_spatial_dim = 1
    for x in feat_shape:
        num_spatial_dim *= x
    sin_emb = sin_emb.reshape(num_spatial_dim, -1)
    sin_emb = jnp.repeat(sin_emb, 2, axis=-1)
    cos_emb = cos_emb.reshape(num_spatial_dim, -1)
    cos_emb = jnp.repeat(cos_emb, 2, axis=-1)
    return sin_emb, cos_emb


class RotaryEmbeddingCat(nnx.Module):
    """2D ROPE producing a concatenated (sin, cos) table
    (reference pos_embed_sincos.py RotaryEmbeddingCat)."""

    def __init__(
            self,
            dim: int,
            max_res: int = 224,
            temperature: float = 10000.0,
            in_pixels: bool = True,
            linear_bands: bool = False,
            feat_shape: Optional[Tuple[int, int]] = None,
            ref_feat_shape: Optional[Tuple[int, int]] = None,
            grid_offset: float = 0.0,
            grid_indexing: str = 'ij',
            *,
            rngs: nnx.Rngs = None,
    ):
        self.dim = dim
        self.max_res = max_res
        self.temperature = temperature
        self.in_pixels = in_pixels
        self.linear_bands = linear_bands
        self.feat_shape = feat_shape
        self.ref_feat_shape = ref_feat_shape
        self.grid_offset = grid_offset
        self.grid_indexing = grid_indexing

    def get_embed(self, shape: Optional[Tuple[int, int]] = None):
        shape = shape if shape is not None else self.feat_shape
        assert shape is not None
        sin_emb, cos_emb = build_rotary_pos_embed(
            shape,
            dim=self.dim,
            max_res=self.max_res,
            temperature=self.temperature,
            linear_bands=self.linear_bands,
            in_pixels=self.in_pixels,
            ref_feat_shape=self.ref_feat_shape,
            grid_offset=self.grid_offset,
            grid_indexing=self.grid_indexing,
        )
        return jnp.concatenate([sin_emb, cos_emb], axis=-1)
