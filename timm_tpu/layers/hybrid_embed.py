"""CNN-backbone patch embedding for hybrid ViTs (NHWC).

Wraps an arbitrary CNN backbone, takes its (last) feature map, and projects
patches of it to the transformer embedding dim. Mirrors the behavior of
reference timm/layers/hybrid_embed.py:32-199 (HybridEmbed): when
``feature_size`` is not given it is discovered by running the backbone once
on a zero image — the most reliable way to handle arbitrary backbones — and
the projection is a ``patch_size``-strided conv over the feature map.

TPU notes: the discovery forward runs eagerly at construction (outside jit),
so it costs one CPU/TPU eager pass at build time and nothing afterwards; the
runtime path is a single static-shape conv + reshape that XLA fuses.
"""
from typing import Callable, Optional, Tuple, Union

import jax.numpy as jnp
from flax import nnx

from .helpers import to_2tuple
from .weight_init import lecun_normal_, zeros_

__all__ = ['HybridEmbed']


def _is_training(mod) -> bool:
    """Infer a module tree's train/eval mode from its first stateful-mode
    submodule (BatchNorm use_running_average / Dropout deterministic).
    Freshly-built nnx modules default to train; returns True when no
    mode-carrying module exists (mode is then irrelevant)."""
    stack, seen = [mod], set()
    while stack:
        m = stack.pop()
        if id(m) in seen:
            continue
        seen.add(id(m))
        ura = getattr(m, 'use_running_average', None)
        if isinstance(ura, bool):
            return not ura
        det = getattr(m, 'deterministic', None)
        if isinstance(det, bool):
            return not det
        for v in vars(m).values():
            if isinstance(v, nnx.Module):
                stack.append(v)
            elif isinstance(v, (list, tuple, nnx.List)):
                stack.extend(c for c in v if isinstance(c, nnx.Module))
    return True


class HybridEmbed(nnx.Module):
    """Extract feature map from a CNN, flatten, project to embedding dim.

    Reference: timm/layers/hybrid_embed.py:32 (HybridEmbed).
    """

    def __init__(
            self,
            backbone: nnx.Module,
            img_size: Union[int, Tuple[int, int]] = 224,
            patch_size: Union[int, Tuple[int, int]] = 1,
            feature_size: Optional[Union[int, Tuple[int, int]]] = None,
            feature_ratio: Optional[Union[int, Tuple[int, int]]] = None,
            in_chans: int = 3,
            embed_dim: int = 768,
            bias: bool = True,
            proj: bool = True,
            flatten: bool = True,
            strict_img_size: bool = True,
            dynamic_img_pad: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.backbone = backbone
        self.in_chans = in_chans
        self.img_size = to_2tuple(img_size)
        self.patch_size = to_2tuple(patch_size)
        if feature_size is None:
            # Run the backbone once on zeros to discover the feature map shape
            # (reference hybrid_embed.py:103-116 does the same with torch).
            # Eval mode so BatchNorm running stats aren't polluted by the
            # zero-image pass; the prior train/eval mode is restored after.
            was_training = _is_training(backbone)
            if hasattr(backbone, 'eval'):
                backbone.eval()
            o = self._backbone_fwd(jnp.zeros((1, *self.img_size, in_chans), jnp.float32))
            if was_training and hasattr(backbone, 'train'):
                backbone.train()
            feature_size = o.shape[1:3]
            feature_dim = o.shape[-1]
        else:
            feature_size = to_2tuple(feature_size)
            if feature_ratio is None:
                feature_ratio = tuple(i // f for i, f in zip(self.img_size, feature_size))
            if hasattr(backbone, 'feature_info'):
                feature_dim = backbone.feature_info[-1]['num_chs']
            else:
                feature_dim = getattr(backbone, 'num_features')
        self.feature_size = feature_size
        self.feature_ratio = to_2tuple(feature_ratio) if feature_ratio is not None else \
            tuple(i // f for i, f in zip(self.img_size, feature_size))
        self.feature_dim = feature_dim
        if not dynamic_img_pad:
            assert feature_size[0] % self.patch_size[0] == 0 and feature_size[1] % self.patch_size[1] == 0
        self.grid_size = tuple(f // p for f, p in zip(feature_size, self.patch_size))
        self.num_patches = self.grid_size[0] * self.grid_size[1]
        self.flatten = flatten
        self.strict_img_size = strict_img_size
        self.dynamic_img_pad = dynamic_img_pad

        if proj:
            self.proj = nnx.Conv(
                feature_dim, embed_dim,
                kernel_size=self.patch_size, strides=self.patch_size, padding='VALID',
                use_bias=bias, dtype=dtype, param_dtype=param_dtype,
                kernel_init=lecun_normal_(), bias_init=zeros_, rngs=rngs)
        else:
            assert feature_dim == embed_dim, \
                f'feature dim ({feature_dim}) must match embed dim ({embed_dim}) with proj disabled'
            self.proj = None

    def _backbone_fwd(self, x):
        if hasattr(self.backbone, 'forward_features'):
            out = self.backbone.forward_features(x)
        else:
            out = self.backbone(x)
        if isinstance(out, (list, tuple)):
            out = out[-1]  # last feature if backbone outputs a pyramid
        return out

    def feat_ratio(self, as_scalar: bool = True):
        """Total input→token reduction: backbone stride x patch size
        (reference hybrid_embed.py:166-171)."""
        total = tuple(r * p for r, p in zip(self.feature_ratio, self.patch_size))
        return max(total) if as_scalar else total

    def dynamic_feat_size(self, img_size: Tuple[int, int]) -> Tuple[int, int]:
        """Expected grid (feature) size for a given image size."""
        feat = tuple(i // r for i, r in zip(img_size, self.feature_ratio))
        if self.dynamic_img_pad:
            return tuple(-(-f // p) for f, p in zip(feat, self.patch_size))
        return tuple(f // p for f, p in zip(feat, self.patch_size))

    def __call__(self, x):
        x = self._backbone_fwd(x)  # (B, H', W', C)
        if self.dynamic_img_pad:
            ph, pw = self.patch_size
            pad_h = (ph - x.shape[1] % ph) % ph
            pad_w = (pw - x.shape[2] % pw) % pw
            x = jnp.pad(x, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)))
        if self.proj is not None:
            x = self.proj(x)
        if self.flatten:
            x = x.reshape(x.shape[0], -1, x.shape[-1])  # (B, N, C)
        return x
