"""Selective-kernel convolution (SKNet) over NHWC features
(reference: timm/layers/selective_kernel.py:24-160).
"""
from __future__ import annotations

from typing import List, Optional, Union

import jax
import jax.numpy as jnp
from flax import nnx

from .create_act import get_act_fn
from .create_conv2d import ConvNormAct, create_conv2d
from .helpers import make_divisible
from .norm_act import BatchNormAct2d

__all__ = ['SelectiveKernelAttn', 'SelectiveKernel']


def _kernel_valid(k):
    if isinstance(k, (list, tuple)):
        for ki in k:
            _kernel_valid(ki)
        return
    assert k >= 3 and k % 2


class SelectiveKernelAttn(nnx.Module):
    """Per-path channel attention: softmax over paths (reference :24-59)."""

    def __init__(
            self,
            channels: int,
            num_paths: int = 2,
            attn_channels: int = 32,
            act_layer='relu',
            norm_layer=None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.num_paths = num_paths
        conv_kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.fc_reduce = create_conv2d(channels, attn_channels, 1, bias=False, **conv_kw)
        norm_layer = norm_layer or BatchNormAct2d
        self.bn = norm_layer(attn_channels, apply_act=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.act = get_act_fn(act_layer)
        self.fc_select = create_conv2d(attn_channels, channels * num_paths, 1, bias=False, **conv_kw)

    def __call__(self, x):
        # x: (B, P, H, W, C)
        assert x.shape[1] == self.num_paths
        s = x.sum(axis=1).mean(axis=(1, 2), keepdims=True)  # (B, 1, 1, C)
        s = self.act(self.bn(self.fc_reduce(s)))
        s = self.fc_select(s)  # (B, 1, 1, C*P)
        B = s.shape[0]
        s = s.reshape(B, 1, 1, self.num_paths, -1).transpose(0, 3, 1, 2, 4)  # (B, P, 1, 1, C)
        return jax.nn.softmax(s, axis=1)


class SelectiveKernel(nnx.Module):
    """Multi-kernel-size conv paths merged by learned attention
    (reference :61-160; 5x5 becomes dilated 3x3 with keep_3x3)."""

    def __init__(
            self,
            in_channels: int,
            out_channels: Optional[int] = None,
            kernel_size: Optional[Union[int, List[int]]] = None,
            stride: int = 1,
            dilation: int = 1,
            groups: int = 1,
            rd_ratio: float = 1. / 16,
            rd_channels: Optional[int] = None,
            rd_divisor: int = 8,
            keep_3x3: bool = True,
            split_input: bool = True,
            act_layer='relu',
            norm_layer=None,
            aa_layer=None,
            drop_layer=None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        out_channels = out_channels or in_channels
        kernel_size = kernel_size or [3, 5]
        _kernel_valid(kernel_size)
        if not isinstance(kernel_size, list):
            kernel_size = [kernel_size] * 2
        if keep_3x3:
            dilation = [dilation * (k - 1) // 2 for k in kernel_size]
            kernel_size = [3] * len(kernel_size)
        else:
            dilation = [dilation] * len(kernel_size)
        self.num_paths = len(kernel_size)
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.split_input = split_input
        if self.split_input:
            assert in_channels % self.num_paths == 0
            in_channels = in_channels // self.num_paths
        groups = min(out_channels, groups)

        self.paths = nnx.List([
            ConvNormAct(
                in_channels, out_channels, kernel_size=k, stride=stride, dilation=d,
                groups=groups, act_layer=act_layer, norm_layer=norm_layer,
                aa_layer=aa_layer, drop_layer=drop_layer,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            for k, d in zip(kernel_size, dilation)])

        attn_channels = rd_channels or make_divisible(out_channels * rd_ratio, divisor=rd_divisor)
        self.attn = SelectiveKernelAttn(
            out_channels, self.num_paths, attn_channels,
            act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        if self.split_input:
            splits = jnp.split(x, self.num_paths, axis=-1)
            x_paths = [op(splits[i]) for i, op in enumerate(self.paths)]
        else:
            x_paths = [op(x) for op in self.paths]
        x = jnp.stack(x_paths, axis=1)  # (B, P, H, W, C)
        x = x * self.attn(x)
        return x.sum(axis=1)
