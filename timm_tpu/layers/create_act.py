"""Activation factory (reference: timm/layers/create_act.py + activations.py).

Activations are pure functions here (not Modules) — XLA fuses them into the
surrounding matmuls, so the reference's memory-efficient custom-grad variants
(activations_me.py) are unnecessary on TPU.
"""
from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

__all__ = ['get_act_fn', 'get_act_layer', 'create_act_layer']


def gelu(x):
    """Exact (erf) GELU via `lax.erf` directly.

    `jax.nn.gelu(approximate=False)` rewrites to `erfc(-x/sqrt2)`, whose TPU
    lowering is a long branchy f32 polynomial that dominates the MLP fusion
    (measured: ViT-B/16 train 875 -> 914 img/s/chip from this change alone).
    The direct erf form matches it to ~1e-6 abs and lowers to the cheap
    single-polynomial erf.
    """
    xf = x.astype(jnp.float32)
    out = 0.5 * xf * (1.0 + jax.lax.erf(xf * 0.7071067811865476))
    return out.astype(x.dtype)


def gelu_tanh(x):
    return jax.nn.gelu(x, approximate=True)


def quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


def hard_sigmoid(x):
    return jax.nn.relu6(x + 3.0) / 6.0


def hard_swish(x):
    return x * hard_sigmoid(x)


def hard_mish(x):
    return 0.5 * x * jnp.clip(x + 2.0, 0.0, 2.0)


def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def swish(x):
    return jax.nn.silu(x)


def prelu_like(x):  # placeholder; PReLU with learned slope is a module concern
    return jax.nn.leaky_relu(x, 0.25)


_ACT_FNS = {
    '': None,
    'none': None,
    'identity': lambda x: x,
    'relu': jax.nn.relu,
    'relu6': jax.nn.relu6,
    'leaky_relu': jax.nn.leaky_relu,
    'elu': jax.nn.elu,
    'celu': jax.nn.celu,
    'selu': jax.nn.selu,
    'gelu': gelu,
    'gelu_tanh': gelu_tanh,
    'gelu_erf': gelu,
    'quick_gelu': quick_gelu,
    'sigmoid': jax.nn.sigmoid,
    'tanh': jnp.tanh,
    'silu': jax.nn.silu,
    'swish': swish,
    'mish': mish,
    'hard_sigmoid': hard_sigmoid,
    'hard_swish': hard_swish,
    'hard_mish': hard_mish,
    'softplus': jax.nn.softplus,
    'hardswish': hard_swish,
    'hardsigmoid': hard_sigmoid,
}


def get_act_fn(name: Union[str, Callable, None] = 'relu') -> Optional[Callable]:
    if name is None:
        return None
    if callable(name):
        return name
    name = name.lower()
    if name not in _ACT_FNS:
        raise ValueError(f'Unknown activation: {name}')
    return _ACT_FNS[name]


# In this framework activations are functions; layer == fn.
get_act_layer = get_act_fn


def create_act_layer(name, inplace=None, **kwargs):
    fn = get_act_fn(name)
    return fn if fn is not None else (lambda x: x)
