"""Stochastic-depth / dropout regularizers (reference: timm/layers/drop.py).

RNG is explicit: modules own an `nnx.Rngs` stream; `model.eval()` flips the
standard `deterministic` flag the same way flax dropout does.
"""
from __future__ import annotations

from typing import List, Optional, Union

import jax
import jax.numpy as jnp
from flax import nnx

__all__ = ['DropPath', 'Dropout', 'calculate_drop_path_rates', 'drop_path']


def drop_path(x, key, drop_prob: float = 0.0, scale_by_keep: bool = True):
    """Per-sample stochastic depth (reference drop.py:~140)."""
    if drop_prob == 0.0:
        return x
    keep_prob = 1.0 - drop_prob
    shape = (x.shape[0],) + (1,) * (x.ndim - 1)
    mask = jax.random.bernoulli(key, keep_prob, shape)
    if scale_by_keep:
        return jnp.where(mask, x / keep_prob, jnp.zeros((), x.dtype))
    return jnp.where(mask, x, jnp.zeros((), x.dtype))


class DropPath(nnx.Module):
    """Drop residual-branch output per sample (stochastic depth)."""

    def __init__(self, drop_prob: float = 0.0, scale_by_keep: bool = True, *, rngs: Optional[nnx.Rngs] = None):
        self.drop_prob = float(drop_prob)
        self.scale_by_keep = scale_by_keep
        self.deterministic = False
        self.rngs = rngs.fork() if rngs is not None and self.drop_prob > 0.0 else None

    def __call__(self, x):
        if self.deterministic or self.drop_prob == 0.0 or self.rngs is None:
            return x
        return drop_path(x, self.rngs.dropout(), self.drop_prob, self.scale_by_keep)


class Dropout(nnx.Dropout):
    """nnx Dropout with a torch-ish positional-rate constructor."""

    def __init__(self, rate: float = 0.0, *, rngs: Optional[nnx.Rngs] = None):
        super().__init__(rate=rate, rngs=rngs if rate > 0.0 else None)


def dropout_rng_key(drop) -> Optional[jax.Array]:
    """Draw a key from a Dropout module's stream (nnx stores an RngStream or
    an Rngs depending on construction), or None if it has no stream."""
    r = getattr(drop, 'rngs', None)
    if r is None:
        return None
    if hasattr(r, 'dropout'):
        return r.dropout()
    return r()


def calculate_drop_path_rates(
        drop_path_rate: float,
        depths: Union[int, List[int]],
        stagewise: bool = False,
) -> Union[List[float], List[List[float]]]:
    """Linearly-increasing per-block drop-path rates (reference drop.py:~190).

    Returns a flat per-block list; `stagewise=True` (requires list depths)
    groups the flat rates per stage instead.
    """
    if isinstance(depths, int):
        if stagewise:
            raise ValueError('stagewise=True requires a list of per-stage depths')
        depths = [depths]
    total = sum(depths)
    rates = [drop_path_rate * i / max(total - 1, 1) for i in range(total)]
    if not stagewise:
        return rates
    out, idx = [], 0
    for d in depths:
        out.append(rates[idx:idx + d])
        idx += d
    return out
