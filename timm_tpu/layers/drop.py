"""Stochastic-depth / dropout regularizers (reference: timm/layers/drop.py).

RNG is explicit: modules own an `nnx.Rngs` stream; `model.eval()` flips the
standard `deterministic` flag the same way flax dropout does.
"""
from __future__ import annotations

from typing import List, Optional, Union

import jax
import jax.numpy as jnp
from flax import nnx

__all__ = ['DropPath', 'Dropout', 'DropBlock2d', 'calculate_drop_path_rates', 'drop_path',
           'apply_drop_path', 'drop_block_2d']


def drop_path(x, key, drop_prob=0.0, scale_by_keep: bool = True):
    """Per-sample stochastic depth (reference drop.py:~140).

    `drop_prob` may be a traced scalar: scan-over-layers threads the per-layer
    rate as data (`_manipulate.drop_path_scan_inputs`), where the zero-rate
    early-out can't apply — a traced rate of 0 still reduces to the identity
    (keep mask all-True, scale 1).
    """
    static = isinstance(drop_prob, (int, float))
    if static and drop_prob == 0.0:
        return x
    keep_prob = 1.0 - drop_prob
    shape = (x.shape[0],) + (1,) * (x.ndim - 1)
    mask = jax.random.bernoulli(
        key, keep_prob if static else jnp.asarray(keep_prob, jnp.float32), shape)
    if scale_by_keep:
        denom = keep_prob if static else jnp.asarray(keep_prob, x.dtype)
        return jnp.where(mask, x / denom, jnp.zeros((), x.dtype))
    return jnp.where(mask, x, jnp.zeros((), x.dtype))


def apply_drop_path(y, module: 'DropPath', override, site: int):
    """Run a DropPath site: the module itself in loop mode, or the functional
    form with the scanned per-layer ``(rates[S], keys[S])`` override in scan
    mode (the merged block's DropPath modules are structural no-ops there)."""
    if override is None:
        return module(y)
    rates, keys = override
    return drop_path(y, keys[site], rates[site], module.scale_by_keep)


class DropPath(nnx.Module):
    """Drop residual-branch output per sample (stochastic depth)."""

    def __init__(self, drop_prob: float = 0.0, scale_by_keep: bool = True, *, rngs: Optional[nnx.Rngs] = None):
        self.drop_prob = float(drop_prob)
        self.scale_by_keep = scale_by_keep
        self.deterministic = False
        self.rngs = rngs.fork() if rngs is not None and self.drop_prob > 0.0 else None

    def __call__(self, x):
        # scan mode (models/_manipulate.scan_stage_stack): the merged block's
        # DropPath is a structural no-op (rate/rngs neutralized before the
        # split) and the per-layer (rate, key) ride the scanned inputs — the
        # scan body pins them here because stage blocks, unlike ViT blocks,
        # take no drop_path_override argument.
        ov = getattr(self, '_scan_override', None)
        if ov is not None:
            rate, key = ov
            return drop_path(x, key, rate, self.scale_by_keep)
        if self.deterministic or self.drop_prob == 0.0 or self.rngs is None:
            return x
        return drop_path(x, self.rngs.dropout(), self.drop_prob, self.scale_by_keep)


class Dropout(nnx.Dropout):
    """nnx Dropout with a torch-ish positional-rate constructor.

    `broadcast_dims=(1, 2)` on NHWC input gives nn.Dropout2d semantics
    (whole feature maps dropped together).
    """

    def __init__(self, rate: float = 0.0, broadcast_dims=(), *, rngs: Optional[nnx.Rngs] = None):
        super().__init__(rate=rate, broadcast_dims=broadcast_dims,
                         rngs=rngs if rate > 0.0 else None)


def dropout_rng_key(drop) -> Optional[jax.Array]:
    """Draw a key from a Dropout module's stream (nnx stores an RngStream or
    an Rngs depending on construction), or None if it has no stream."""
    r = getattr(drop, 'rngs', None)
    if r is None:
        return None
    if hasattr(r, 'dropout'):
        return r.dropout()
    return r()


def calculate_drop_path_rates(
        drop_path_rate: float,
        depths: Union[int, List[int]],
        stagewise: bool = False,
) -> Union[List[float], List[List[float]]]:
    """Linearly-increasing per-block drop-path rates (reference drop.py:~190).

    Returns a flat per-block list; `stagewise=True` (requires list depths)
    groups the flat rates per stage instead.
    """
    if isinstance(depths, int):
        if stagewise:
            raise ValueError('stagewise=True requires a list of per-stage depths')
        depths = [depths]
    total = sum(depths)
    rates = [drop_path_rate * i / max(total - 1, 1) for i in range(total)]
    if not stagewise:
        return rates
    out, idx = [], 0
    for d in depths:
        out.append(rates[idx:idx + d])
        idx += d
    return out


def drop_block_2d(
        x, key,
        drop_prob: float = 0.1,
        block_size: int = 7,
        gamma_scale: float = 1.0,
        with_noise: bool = False,
        couple_channels: bool = True,
        scale_by_keep: bool = True,
):
    """DropBlock on NHWC features (reference drop.py:24-100, arXiv:1810.12890).
    Block centres drawn at rate gamma; a stride-1 max-pool dilates them to
    kh x kw blocks."""
    B, H, W, C = x.shape
    kh, kw = min(block_size, H), min(block_size, W)
    gamma = float(gamma_scale * drop_prob * H * W) / float(kh * kw) / float((H - kh + 1) * (W - kw + 1))

    noise_shape = (B, H, W, 1 if couple_channels else C)
    k1, k2 = jax.random.split(key)
    centers = jax.random.bernoulli(k1, gamma, noise_shape).astype(x.dtype)
    pad_h, pad_w = kh // 2, kw // 2
    block_mask = jax.lax.reduce_window(
        centers, -jnp.inf, jax.lax.max, (1, kh, kw, 1), (1, 1, 1, 1),
        [(0, 0), (pad_h, pad_h), (pad_w, pad_w), (0, 0)])
    if kh % 2 == 0 or kw % 2 == 0:
        block_mask = block_mask[:, (kh + 1) % 2:, (kw + 1) % 2:, :]
        block_mask = block_mask[:, :H, :W, :]
    keep_mask = 1.0 - block_mask

    if with_noise:
        noise = jax.random.normal(k2, keep_mask.shape, x.dtype) * block_mask
        return x * keep_mask + noise
    if scale_by_keep:
        scale = keep_mask.size / (keep_mask.astype(jnp.float32).sum() + 1e-7)
        keep_mask = keep_mask * scale.astype(x.dtype)
    return x * keep_mask


class DropBlock2d(nnx.Module):
    """DropBlock regularizer module (reference drop.py:~103)."""

    def __init__(
            self,
            drop_prob: float = 0.1,
            block_size: int = 7,
            gamma_scale: float = 1.0,
            with_noise: bool = False,
            inplace: bool = False,  # parity arg; jax arrays are immutable
            couple_channels: bool = True,
            scale_by_keep: bool = True,
            *,
            rngs: Optional[nnx.Rngs] = None,
    ):
        self.drop_prob = float(drop_prob)
        self.block_size = block_size
        self.gamma_scale = gamma_scale
        self.with_noise = with_noise
        self.couple_channels = couple_channels
        self.scale_by_keep = scale_by_keep
        self.deterministic = False
        self.rngs = rngs.fork() if rngs is not None and self.drop_prob > 0.0 else None

    def __call__(self, x):
        if self.deterministic or self.drop_prob == 0.0 or self.rngs is None:
            return x
        return drop_block_2d(
            x, self.rngs.dropout(), self.drop_prob, self.block_size, self.gamma_scale,
            self.with_noise, self.couple_channels, self.scale_by_keep)
