"""Split ('auxiliary') BatchNorm for aug-split training
(reference: timm/layers/split_batchnorm.py:19-87, AdvProp §4.2).

The batch is split into `num_splits` equal parts along the batch axis; the
first (clean) split flows through the primary BN statistics, the remaining
(augmented) splits each keep their own aux statistics. At eval time only the
primary statistics are used — so the aux layers can simply be dropped for
deployment.
"""
from __future__ import annotations

import jax.numpy as jnp
from flax import nnx

from .norm import BatchNorm2d
from .norm_act import BatchNormAct2d

__all__ = ['SplitBatchNorm2d', 'SplitBatchNormAct2d', 'convert_splitbn_model']


class SplitBatchNormAct2d(BatchNormAct2d):
    """BatchNormAct2d whose train-mode statistics are computed per batch split."""

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 apply_act=True, act_layer='relu', num_splits=2,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        assert num_splits > 1, 'Should have at least one aux BN layer (num_splits at least 2)'
        super().__init__(
            num_features, eps=eps, momentum=momentum, affine=affine,
            apply_act=apply_act, act_layer=act_layer,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.num_splits = num_splits
        self.aux_bn = nnx.List([
            BatchNorm2d(num_features, eps=eps, momentum=momentum, affine=affine,
                        dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            for _ in range(num_splits - 1)])

    def __call__(self, x):
        if not self.use_running_average:  # training: per-split statistics
            split = x.shape[0] // self.num_splits
            assert x.shape[0] == split * self.num_splits, \
                'batch size must be evenly divisible by num_splits'
            outs = [nnx.BatchNorm.__call__(self, x[:split])]
            for i, aux in enumerate(self.aux_bn):
                outs.append(aux(x[(i + 1) * split:(i + 2) * split]))
            x = jnp.concatenate(outs, axis=0)
        else:
            x = nnx.BatchNorm.__call__(self, x)
        if self.drop is not None:
            x = self.drop(x)
        if self.act is not None:
            x = self.act(x)
        return x


class SplitBatchNorm2d(SplitBatchNormAct2d):
    """Plain split BN — no activation, matching the reference class of this
    name (split_batchnorm.py:19)."""

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 num_splits=2, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        super().__init__(
            num_features, eps=eps, momentum=momentum, affine=affine,
            apply_act=False, num_splits=num_splits,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)


def convert_splitbn_model(module: nnx.Module, num_splits: int = 2) -> nnx.Module:
    """Recursively replace BatchNorm(Act)2d with SplitBatchNormAct2d,
    copying affine params + running stats into the primary and every aux BN
    (reference split_batchnorm.py:54-87). In-place on the module tree."""

    def _convert_one(bn):
        new = SplitBatchNormAct2d(
            bn.num_features, eps=bn.epsilon, momentum=1.0 - bn.momentum,
            num_splits=num_splits, rngs=nnx.Rngs(0))
        new.act = getattr(bn, 'act', None)
        new.drop = getattr(bn, 'drop', None)
        for tgt in [new] + list(new.aux_bn):
            if bn.scale is not None and tgt.scale is not None:
                tgt.scale[...] = bn.scale[...]
                tgt.bias[...] = bn.bias[...]
            tgt.mean[...] = bn.mean[...]
            tgt.var[...] = bn.var[...]
        new.use_running_average = bn.use_running_average
        return new

    def _walk(m):
        for name, child in list(vars(m).items()):
            if isinstance(child, SplitBatchNormAct2d):
                continue
            if isinstance(child, nnx.BatchNorm):
                setattr(m, name, _convert_one(child))
            elif isinstance(child, nnx.List):
                for i, item in enumerate(child):
                    if isinstance(item, SplitBatchNormAct2d):
                        continue
                    if isinstance(item, nnx.BatchNorm):
                        child[i] = _convert_one(item)
                    elif isinstance(item, nnx.Module):
                        _walk(item)
            elif isinstance(child, nnx.Module):
                _walk(child)
    _walk(module)
    return module
