"""Patch (token) dropout (reference: timm/layers/patch_dropout.py).

Keeps a fixed *count* of tokens per sample so shapes stay static under jit —
per-sample random subset selection via argsort of random keys.
"""
from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

__all__ = ['PatchDropout']


class PatchDropout(nnx.Module):
    def __init__(
            self,
            prob: float = 0.5,
            num_prefix_tokens: int = 1,
            ordered: bool = False,
            return_indices: bool = False,
            *,
            rngs: Optional[nnx.Rngs] = None,
    ):
        assert 0.0 <= prob < 1.0
        self.prob = prob
        self.num_prefix_tokens = num_prefix_tokens
        self.ordered = ordered
        self.return_indices = return_indices
        self.deterministic = False
        self.rngs = rngs.fork() if rngs is not None and prob > 0.0 else None

    def __call__(self, x):
        if self.deterministic or self.prob == 0.0 or self.rngs is None:
            return (x, None) if self.return_indices else x

        if self.num_prefix_tokens:
            prefix, x = x[:, :self.num_prefix_tokens], x[:, self.num_prefix_tokens:]
        else:
            prefix = None

        B, L = x.shape[:2]
        num_keep = max(1, int(L * (1.0 - self.prob)))
        rand = jax.random.uniform(self.rngs.dropout(), (B, L))
        keep_indices = jnp.argsort(rand, axis=-1)[:, :num_keep]
        if self.ordered:
            keep_indices = jnp.sort(keep_indices, axis=-1)
        x = jnp.take_along_axis(x, keep_indices[..., None], axis=1)

        if prefix is not None:
            x = jnp.concatenate([prefix, x], axis=1)
        return (x, keep_indices) if self.return_indices else x
