"""Weight-standardized convs (reference: timm/layers/std_conv.py:1-232).

`ScaledStdConv2d` is the NFNet building block: per-output-channel weight
standardization with a learned gain, applied at call time (the kernel itself
stays unstandardized, matching the reference's F.batch_norm trick).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import nnx

from .create_conv2d import _resolve_padding
from .helpers import to_2tuple
from .weight_init import variance_scaling_, zeros_

__all__ = ['StdConv2d', 'ScaledStdConv2d']


class StdConv2d(nnx.Conv):
    """Conv with weight standardization (BiT / pre-act ResNets)."""

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1, padding=None,
                 dilation=1, groups=1, bias=False, eps=1e-6,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kernel_size = to_2tuple(kernel_size)
        super().__init__(
            in_channels, out_channels, kernel_size=kernel_size, strides=to_2tuple(stride),
            padding=_resolve_padding(padding, kernel_size, stride, dilation),
            kernel_dilation=to_2tuple(dilation), feature_group_count=groups, use_bias=bias,
            dtype=dtype, param_dtype=param_dtype,
            kernel_init=variance_scaling_(2.0, 'fan_out', 'normal'), bias_init=zeros_, rngs=rngs)
        self.eps = eps

    def _std_kernel(self):
        w = self.kernel[...]
        axes = (0, 1, 2)  # HWI of HWIO
        mean = w.mean(axis=axes, keepdims=True)
        var = w.var(axis=axes, keepdims=True)
        return (w - mean) / jnp.sqrt(var + self.eps)

    def __call__(self, x):
        orig = self.kernel[...]
        self.kernel[...] = self._std_kernel()
        try:
            out = super().__call__(x)
        finally:
            self.kernel[...] = orig
        return out


class ScaledStdConv2d(nnx.Module):
    """NFNet scaled weight standardization w/ per-channel gain
    (reference std_conv.py ScaledStdConv2d)."""

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1, padding=None,
                 dilation=1, groups=1, bias=True, gamma=1.0, eps=1e-6, gain_init=1.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kernel_size = to_2tuple(kernel_size)
        self.conv = nnx.Conv(
            in_channels, out_channels, kernel_size=kernel_size, strides=to_2tuple(stride),
            padding=_resolve_padding(padding, kernel_size, stride, dilation),
            kernel_dilation=to_2tuple(dilation), feature_group_count=groups, use_bias=bias,
            dtype=dtype, param_dtype=param_dtype,
            kernel_init=variance_scaling_(2.0, 'fan_out', 'normal'), bias_init=zeros_, rngs=rngs)
        self.gain = nnx.Param(jnp.full((out_channels,), gain_init, param_dtype))
        fan_in = kernel_size[0] * kernel_size[1] * in_channels / groups
        self.scale = gamma * fan_in ** -0.5
        self.eps = eps

    def __call__(self, x):
        w = self.conv.kernel[...]
        axes = (0, 1, 2)  # HWI (per-output-channel stats over the fan-in)
        mean = w.mean(axis=axes, keepdims=True)
        var = w.var(axis=axes, keepdims=True)
        w_std = (self.scale * self.gain[...]).astype(w.dtype) * (w - mean) / jnp.sqrt(var + self.eps)
        orig = self.conv.kernel[...]
        self.conv.kernel[...] = w_std.astype(orig.dtype)
        try:
            out = self.conv(x)
        finally:
            self.conv.kernel[...] = orig
        return out
