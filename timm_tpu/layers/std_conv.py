"""Weight-standardized convs (reference: timm/layers/std_conv.py:1-232).

`ScaledStdConv2d` is the NFNet building block: per-output-channel weight
standardization with a learned gain. The kernel parameter itself stays
unstandardized (matching the reference's F.batch_norm trick); the
standardized weight is computed at call time and fed to the conv directly —
XLA folds the standardization into the conv's weight preprocessing, and for
inference the whole thing constant-folds when params are frozen.

Param names mirror the reference conv (`kernel`/`bias`/`gain` on the module
itself), so torch checkpoints remap without special cases.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import nnx

from .create_conv2d import _resolve_padding
from .helpers import to_2tuple
from .weight_init import variance_scaling_, zeros_

__all__ = ['StdConv2d', 'ScaledStdConv2d', 'ScaledStdConv2dSame']


def _bias_value(bias):
    # use_bias=False is Param(None) on older flax, plain None on newer
    if bias is None or bias.value is None:
        return None
    return bias[...]


def _conv_nhwc(x, kernel, bias, strides, padding, dilation, groups):
    out = jax.lax.conv_general_dilated(
        x, kernel.astype(x.dtype),
        window_strides=strides,
        padding=padding,
        rhs_dilation=dilation,
        feature_group_count=groups,
        dimension_numbers=('NHWC', 'HWIO', 'NHWC'))
    if bias is not None:
        out = out + bias.astype(out.dtype)
    return out


class StdConv2d(nnx.Conv):
    """Conv with weight standardization (BiT / pre-act ResNets)."""

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1, padding=None,
                 dilation=1, groups=1, bias=False, eps=1e-6,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kernel_size = to_2tuple(kernel_size)
        super().__init__(
            in_channels, out_channels, kernel_size=kernel_size, strides=to_2tuple(stride),
            padding=_resolve_padding(padding, kernel_size, stride, dilation),
            kernel_dilation=to_2tuple(dilation), feature_group_count=groups, use_bias=bias,
            dtype=dtype, param_dtype=param_dtype,
            kernel_init=variance_scaling_(2.0, 'fan_out', 'normal'), bias_init=zeros_, rngs=rngs)
        self.eps = eps

    def _std_kernel(self):
        w = self.kernel[...]
        axes = (0, 1, 2)  # HWI of HWIO → per-output-channel stats over fan-in
        mean = w.mean(axis=axes, keepdims=True)
        var = w.var(axis=axes, keepdims=True)
        return (w - mean) / jnp.sqrt(var + self.eps)

    def __call__(self, x):
        return _conv_nhwc(
            x, self._std_kernel(), _bias_value(self.bias),
            self.strides, self.padding, self.kernel_dilation, self.feature_group_count)


class ScaledStdConv2d(nnx.Conv):
    """NFNet scaled weight standardization w/ per-channel gain
    (reference std_conv.py:115-170 ScaledStdConv2d)."""

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1, padding=None,
                 dilation=1, groups=1, bias=True, gamma=1.0, eps=1e-6, gain_init=1.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kernel_size = to_2tuple(kernel_size)
        super().__init__(
            in_channels, out_channels, kernel_size=kernel_size, strides=to_2tuple(stride),
            padding=_resolve_padding(padding, kernel_size, stride, dilation),
            kernel_dilation=to_2tuple(dilation), feature_group_count=groups, use_bias=bias,
            dtype=dtype, param_dtype=param_dtype,
            kernel_init=variance_scaling_(2.0, 'fan_out', 'normal'), bias_init=zeros_, rngs=rngs)
        self.gain = nnx.Param(jnp.full((out_channels,), gain_init, param_dtype))
        fan_in = kernel_size[0] * kernel_size[1] * in_channels / groups
        self.scale = gamma * fan_in ** -0.5
        self.eps = eps

    def __call__(self, x):
        w = self.kernel[...]
        axes = (0, 1, 2)
        mean = w.mean(axis=axes, keepdims=True)
        var = w.var(axis=axes, keepdims=True)
        w_std = (self.scale * self.gain[...]).astype(w.dtype) * (w - mean) / jnp.sqrt(var + self.eps)
        return _conv_nhwc(
            x, w_std, _bias_value(self.bias),
            self.strides, self.padding, self.kernel_dilation, self.feature_group_count)


class ScaledStdConv2dSame(ScaledStdConv2d):
    """TF-SAME-padded variant (reference ScaledStdConv2dSame) used by the
    DeepMind-weight-compatible dm_nfnet models."""

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1, padding='same',
                 dilation=1, groups=1, bias=True, gamma=1.0, eps=1e-6, gain_init=1.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        super().__init__(
            in_channels, out_channels, kernel_size=kernel_size, stride=stride, padding='same',
            dilation=dilation, groups=groups, bias=bias, gamma=gamma, eps=eps,
            gain_init=gain_init, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
