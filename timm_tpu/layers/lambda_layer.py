"""Lambda layer (LambdaNetworks), TPU-native NHWC
(reference: timm/layers/lambda_layer.py:1-175; Bello 2021).

Content + position lambdas via einsums; the positional path's Conv3d
(r, r, 1) over (H, W, V) is expressed as a shared 2D conv applied per value
channel (fold V into batch) — same weights, no 3D conv lowering needed. The
relative-position variant gathers a static (M, M) index into the pos table.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import nnx

from .helpers import make_divisible, to_2tuple
from .norm import BatchNorm2d

__all__ = ['LambdaLayer']


def _rel_pos_indices(size):
    size = to_2tuple(size)
    pos = np.stack(np.meshgrid(np.arange(size[0]), np.arange(size[1]), indexing='ij')).reshape(2, -1)
    rel_pos = pos[:, None, :] - pos[:, :, None]
    rel_pos[0] += size[0] - 1
    rel_pos[1] += size[1] - 1
    return rel_pos  # (2, M, M)


class LambdaLayer(nnx.Module):
    """Lambda layer (reference lambda_layer.py:46-175)."""

    def __init__(
            self,
            dim: int,
            dim_out: Optional[int] = None,
            feat_size=None,
            stride: int = 1,
            num_heads: int = 4,
            dim_head: int = 16,
            r: Optional[int] = 9,
            qk_ratio: float = 1.0,
            qkv_bias: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        dim_out = dim_out or dim
        assert dim_out % num_heads == 0
        self.dim_qk = dim_head or make_divisible(dim_out * qk_ratio, divisor=8) // num_heads
        self.num_heads = num_heads
        self.dim_v = dim_out // num_heads
        self.stride = stride

        self.qkv = nnx.Conv(
            dim, num_heads * self.dim_qk + self.dim_qk + self.dim_v, kernel_size=(1, 1),
            use_bias=qkv_bias, kernel_init=nnx.initializers.truncated_normal(stddev=dim ** -0.5),
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm_q = BatchNorm2d(num_heads * self.dim_qk, rngs=rngs)
        self.norm_v = BatchNorm2d(self.dim_v, rngs=rngs)

        if r is not None:
            # local positional lambdas: shared (r, r) conv per value channel
            self.conv_lambda = nnx.Conv(
                1, self.dim_qk, kernel_size=(r, r), padding=[(r // 2, r // 2), (r // 2, r // 2)],
                kernel_init=nnx.initializers.truncated_normal(stddev=self.dim_qk ** -0.5),
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            self.pos_emb = None
            self._rel_pos_indices = None
        else:
            assert feat_size is not None
            feat_size = to_2tuple(feat_size)
            rel_size = [2 * s - 1 for s in feat_size]
            self.conv_lambda = None
            self.pos_emb = nnx.Param(
                jax.random.truncated_normal(
                    rngs.params(), -2, 2, (rel_size[0], rel_size[1], self.dim_qk), param_dtype) * 0.02)
            # nnx.Variable: raw array attrs break nnx graph traversal on older flax
            self._rel_pos_indices = nnx.Variable(jnp.asarray(_rel_pos_indices(feat_size)))

    def __call__(self, x):
        B, H, W, C = x.shape
        M = H * W
        qkv = self.qkv(x)  # (B, H, W, heads*K + K + V)
        q, k, v = jnp.split(
            qkv, [self.num_heads * self.dim_qk, self.num_heads * self.dim_qk + self.dim_qk], axis=-1)
        q = self.norm_q(q).reshape(B, M, self.num_heads, self.dim_qk).transpose(0, 2, 1, 3)  # B, h, M, K
        v = self.norm_v(v).reshape(B, M, self.dim_v)  # B, M, V
        k = jax.nn.softmax(k.reshape(B, M, self.dim_qk), axis=1)  # normalize over positions

        content_lam = jnp.einsum('bmk,bmv->bkv', k, v)
        content_out = jnp.einsum('bhmk,bkv->bhmv', q, content_lam)

        if self.pos_emb is None:
            # (B, H, W, V) → per-channel shared conv → (B, M, K, V)
            vs = v.reshape(B, H, W, self.dim_v).transpose(0, 3, 1, 2).reshape(B * self.dim_v, H, W, 1)
            pl = self.conv_lambda(vs)  # (B*V, H, W, K)
            position_lam = pl.reshape(B, self.dim_v, M, self.dim_qk).transpose(0, 2, 3, 1)  # B, M, K, V
        else:
            idx = self._rel_pos_indices[...]
            pos = self.pos_emb[...][idx[0], idx[1]]  # (M, M, K)
            position_lam = jnp.einsum('mnk,bnv->bmkv', pos.astype(v.dtype), v)
        position_out = jnp.einsum('bhmk,bmkv->bhmv', q, position_lam)

        out = (content_out + position_out).transpose(0, 2, 1, 3).reshape(B, H, W, -1)
        if self.stride == 2:
            # AvgPool2d(2, 2) floors odd maps: crop trailing row/col first
            out = out[:, :2 * (H // 2), :2 * (W // 2)]
            out = out.reshape(B, H // 2, 2, W // 2, 2, -1).mean(axis=(2, 4))
        return out
