"""Attention-module factory (reference: timm/layers/create_attn.py:1-98)."""
from __future__ import annotations


from functools import partial
from typing import Callable, Union

from .bottleneck_attn import BottleneckAttn
from .cbam import CbamModule, LightCbamModule
from .eca import CecaModule, EcaModule
from .halo_attn import HaloAttn
from .lambda_layer import LambdaLayer
from .gather_excite import GatherExcite
from .global_context import GlobalContext
from .non_local_attn import BatNonLocalAttn, NonLocalAttn
from .selective_kernel import SelectiveKernel
from .split_attn import SplitAttn
from .squeeze_excite import EffectiveSEModule, SEModule

__all__ = ['get_attn', 'create_attn']

_ATTN_MAP = dict(
    # self-attention spatial mixers (byoanet-style nets)
    bottleneck=BottleneckAttn,
    halo=HaloAttn,
    se=SEModule,
    ese=EffectiveSEModule,
    eca=EcaModule,
    ceca=CecaModule,
    cbam=CbamModule,
    lcbam=LightCbamModule,
    ge=GatherExcite,
    gc=GlobalContext,
    gca=partial(GlobalContext, fuse_add=True, fuse_scale=False),
    nl=NonLocalAttn,
    bat=BatNonLocalAttn,
    sk=SelectiveKernel,
    splat=SplitAttn,
)
_ATTN_MAP['lambda'] = LambdaLayer


def get_attn(attn_type: Union[str, Callable, None]):
    if attn_type is None or callable(attn_type):
        return attn_type
    name = attn_type.lower()
    if name not in _ATTN_MAP:
        raise ValueError(f'Unknown/unsupported attn module: {attn_type}')
    return _ATTN_MAP[name]


def create_attn(attn_type, channels: int, *, rngs, **kwargs):
    module_cls = get_attn(attn_type)
    if module_cls is None:
        return None
    return module_cls(channels, rngs=rngs, **kwargs)
