"""Norm factory (reference: timm/layers/create_norm.py).

All LayerNorm/RmsNorm/SimpleNorm variants created here honour the
compute-precision policy (`config.norm_internal_dtype()` / the
`TIMM_TPU_NORM_DTYPE` env var) for their statistics dtype; pass
`internal_dtype=` through `create_norm_layer` to pin one instance
(LayerNormFp32 is permanently pinned to fp32).
"""
from __future__ import annotations

import functools
import types
from typing import Callable, Optional, Union

from .norm import (
    BatchNorm2d, GroupNorm, GroupNorm1, LayerNorm, LayerNorm2d, LayerNormFp32,
    RmsNorm, RmsNorm2d, SimpleNorm, SimpleNorm2d,
)

__all__ = ['get_norm_layer', 'create_norm_layer']

_NORM_MAP = dict(
    batchnorm=BatchNorm2d,
    batchnorm2d=BatchNorm2d,
    batchnorm1d=BatchNorm2d,
    groupnorm=GroupNorm,
    groupnorm1=GroupNorm1,
    layernorm=LayerNorm,
    layernorm2d=LayerNorm2d,
    layernormfp32=LayerNormFp32,
    rmsnorm=RmsNorm,
    rmsnorm2d=RmsNorm2d,
    simplenorm=SimpleNorm,
    simplenorm2d=SimpleNorm2d,
)


def get_norm_layer(norm_layer: Union[str, Callable, None]):
    if norm_layer is None:
        return None
    if not isinstance(norm_layer, str):
        return norm_layer
    name = norm_layer.replace('_', '').lower()
    if name not in _NORM_MAP:
        raise ValueError(f'Unknown norm layer {norm_layer}')
    return _NORM_MAP[name]


def create_norm_layer(norm_layer, num_features, *, rngs, **kwargs):
    cls = get_norm_layer(norm_layer)
    return cls(num_features, rngs=rngs, **kwargs)
