"""Lower real programs, extract hardware-independent perf metrics.

One :class:`ProbeConfig` = one budgeted entry in perf_budgets.json. The probe
builds the REAL `TrainingTask` jitted step (or the real serve engine bucket
programs) on a {model x fsdp x tp x block_scan x grad_accum} point and
extracts everything XLA will tell us without a TPU:

  * ``trace_ms`` / ``jaxpr_eqns``  — trace cost and equation count of the
    closed jaxpr (the O(1)-in-depth / O(1)-in-accum contracts);
  * ``flops`` / ``bytes_accessed`` — `compiled.cost_analysis()` of the AOT-
    compiled step (XLA's own per-execution estimate; deterministic);
  * ``param_bytes_*`` / ``opt_bytes_per_device`` / ``activation_bytes_*`` —
    per-device state footprint via the parallel/sharding.py calculators and
    the actual on-device shard sizes;
  * ``donation_aliases`` / ``donation_ok`` — the compiled HLO header's
    ``input_output_alias`` table: donated state buffers must actually alias
    (the train step's donation is usable — params/opt/EMA outputs match their
    inputs — so a missing table means donation silently died);
  * ``no_replicated_residual``     — the tp forward HLO carries the
    per-device residual shape and never materializes the full one
    (involuntary-remat regression gate, mirrors test_sharding);
  * ``serve_programs`` / ``serve_donation_declared`` — every declared bucket
    has an AOT executable and its input donation provably reached lowering
    (`InferenceEngine.donation_report`).

Collect modes trim tier-1 cost: ``trace`` never compiles, ``full`` compiles
the train step, ``fwd`` compiles a forward-only program (the tp residual
check — same program test_sharding compiles, so the persistent cache is
shared), ``serve`` drives the engine prewarm path, ``augment`` compiles the
on-device data-path programs (fused image augment + donated naflex augment),
``naflex`` compiles the packed variable-resolution train step at one bucket
shape, ``kernels`` lowers every registered Pallas kernel against its XLA
reference at the declared dry regime shapes (kernels/harness.py) and budgets
jaxpr eqns + the bytes story per kernel: analytic one-pass ``*_io_bytes``
for the kernel arm (interpret-mode cost_analysis is emulation noise) vs the
compiled reference's ``*_ref_bytes_accessed``, plus the ``*_wins_bytes``
bool the win-or-delete verdict machinery keys on.
"""
from __future__ import annotations

import contextlib
import dataclasses
import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

_logger = logging.getLogger(__name__)

__all__ = ['ProbeConfig', 'DEFAULT_MATRIX', 'probe_config', 'run_matrix',
           'donation_evidence', 'capture_programs']


@dataclasses.dataclass(frozen=True)
class ProbeConfig:
    name: str
    model: str = 'test_vit'
    model_kwargs: Tuple[Tuple[str, object], ...] = ()
    batch_size: int = 8
    fsdp: int = 1
    tp: int = 1
    block_scan: Optional[bool] = None     # None = model default
    grad_accum: int = 1
    opt: str = 'adamw'
    collect: str = 'full'   # 'trace' | 'full' | 'fwd' | 'serve' | 'quant' | 'augment' | 'naflex' | 'kernels' | 'elastic' | 'autotune'
    buckets: Tuple[int, ...] = (2, 4)     # serve only
    seq_len: int = 25                     # naflex packed probe only
    fused_update: bool = False            # route the step through fused_adamw
    # batch spatial size when it is NOT a ctor kwarg (conv models size from
    # the data; their ctors reject img_size) — falls back to model_kwargs
    img_size: Optional[int] = None
    # tp 'fwd' residual-shape gate (config-specific HLO shape strings)
    fwd_expect_shard: str = ''
    fwd_forbid_full: str = ''

    def kwargs(self) -> Dict:
        return dict(self.model_kwargs)

    def img(self, default: int = 224) -> int:
        return int(self.img_size or self.kwargs().get('img_size', default))


# The tier-1 matrix: one config per proven perf property, trimmed so the
# whole suite stays within its <=60s warm budget (trace-only where a compile
# adds nothing; compiles ride the persistent disk cache).
DEFAULT_MATRIX: Tuple[ProbeConfig, ...] = (
    # the canonical data-mesh step: FLOPs/bytes/donation baseline
    ProbeConfig(name='base', model='test_vit',
                model_kwargs=(('num_classes', 10), ('img_size', 32)),
                batch_size=8, collect='full'),
    # depth-12 scanned stack: the block-scan O(1)-in-depth contract; the
    # injected-regression test re-probes this with block_scan=False
    ProbeConfig(name='scan_depth12', model='vit_tiny_patch16_224',
                model_kwargs=(('img_size', 64),),
                batch_size=8, block_scan=True, collect='full'),
    # fsdp=4: sharded param/opt bytes + donation must stay aliased
    ProbeConfig(name='fsdp4', model='test_vit',
                model_kwargs=(('num_classes', 10), ('img_size', 32)),
                batch_size=8, fsdp=4, collect='full'),
    # fsdp x tp = (2,2): residual stays sharded inside the scanned body
    # (same forward program test_sharding compiles — disk cache shared)
    ProbeConfig(name='tp22', model='vit_tiny_patch16_224',
                model_kwargs=(('img_size', 64),),
                batch_size=8, fsdp=2, tp=2, block_scan=True, collect='fwd',
                fwd_expect_shard='f32[2,17,96]', fwd_forbid_full='f32[8,17,192]'),
    # scanned grad accumulation: trace cost O(1) in accum steps (trace-only)
    ProbeConfig(name='accum4', model='test_vit',
                model_kwargs=(('num_classes', 10), ('img_size', 32)),
                batch_size=8, grad_accum=4, collect='trace'),
    # serve engine: every bucket AOT-compiled, input donation reaches lowering
    ProbeConfig(name='serve_test_vit', model='test_vit',
                model_kwargs=(('num_classes', 10), ('img_size', 32)),
                collect='serve', buckets=(2, 4)),
    # int8 serve path: quantized program bytes-accessed + per-device param
    # bytes at <=0.55x fp32, donation declared, scale sharding legal on
    # (fsdp=2, tp=2) — the ROADMAP-3a claim, provable without hardware
    ProbeConfig(name='quant_serve_int8', model='test_vit',
                model_kwargs=(('num_classes', 10), ('img_size', 32)),
                collect='quant', buckets=(2, 4)),
    # on-device augment programs: the fused uint8->erase->mixup->normalize
    # image program stays tiny (eqns/flops/bytes), and the naflex variant's
    # f32 patches donation provably reaches lowering (must-alias in the HLO)
    ProbeConfig(name='device_augment',
                model_kwargs=(('num_classes', 10), ('img_size', 32)),
                batch_size=8, collect='augment'),
    # NaFlex packed train step: dict-batch program the bucket ladder reuses
    # per seq_len — eqn/FLOP/donation baseline for one bucket shape
    ProbeConfig(name='naflex_packed', model='test_naflexvit',
                model_kwargs=(('num_classes', 10),),
                batch_size=8, collect='naflex', seq_len=25),
    # kernel portfolio: per registered Pallas kernel, jaxpr eqns of both arms
    # + analytic one-pass io bytes vs the compiled XLA reference's bytes-
    # accessed at the declared dry regime shapes (kernels/harness.py)
    ProbeConfig(name='kernels', collect='kernels'),
    # the fused AdamW+EMA train step: same test_vit step as 'base' but routed
    # through the one-pass kernel — donation must survive (donation_ok) and
    # the step must still lower/compile with the opt_state shardings intact
    ProbeConfig(name='fused_update', model='test_vit',
                model_kwargs=(('num_classes', 10), ('img_size', 32)),
                batch_size=8, collect='full', fused_update=True),
    # elastic resize: state saved on an 8-device (2,4) mesh re-places on the
    # 4-device post-resize mesh (fsdp clamped by resolve_elastic_axes), the
    # rescale solver holds the global batch, and the RE-PLACED train step
    # still lowers with donation intact (resilience/elastic.py)
    ProbeConfig(name='elastic_resize', model='test_vit',
                model_kwargs=(('num_classes', 10), ('img_size', 32)),
                batch_size=8, fsdp=4, collect='elastic'),
    # autotune solver-output legality: the analytic tier enumerates the full
    # {fsdp x tp x batch x accum x scan x remat} space for global batch
    # batch_size*grad_accum (deterministic candidate/rejection counts and a
    # deterministic winner), then the WINNING config's real train step is
    # lowered once — its donation + sharding ride the same 'full'-collect
    # machinery every other train probe budgets
    ProbeConfig(name='autotune', model='test_vit',
                model_kwargs=(('num_classes', 10), ('img_size', 32)),
                batch_size=8, grad_accum=8, collect='autotune'),
    # hierarchical stage scan (ISSUE-20): the conv family baseline — convnext
    # sizes from the data (ctor takes no img_size; the new img_size field
    # sizes the batch), stages scanned via the set_block_scan alias
    ProbeConfig(name='stage_scan_convnext', model='test_convnext',
                model_kwargs=(('num_classes', 10),), img_size=64,
                batch_size=8, block_scan=True, collect='full'),
    # ...and the windowed-attention baseline at swin's native test size
    # (relative-position tables are resolution-bound)
    ProbeConfig(name='stage_scan_swin', model='test_swin',
                model_kwargs=(('num_classes', 10),), img_size=96,
                batch_size=8, block_scan=True, collect='full'),
)


# ---- program capture (timm_tpu.analysis Tier B/C hook) ----------------------
#
# The probes are the one place the repo lowers its REAL programs; the
# analysis suite's jaxpr/HLO passes audit those exact artifacts instead of
# re-lowering. Inside `capture_programs()`, every probe records the jaxprs
# and compiled executables it produces, tagged with the invariant each one
# is expected to uphold (donation via alias table vs declared-at-lowering,
# residual-sharding shape strings).

_CAPTURE: Optional[List[Dict]] = None


@contextlib.contextmanager
def capture_programs():
    """Collect {'config','name','kind','jaxpr','compiled','expect'} records
    for every program the probes lower while the context is active."""
    global _CAPTURE
    prev, _CAPTURE = _CAPTURE, []
    try:
        yield _CAPTURE
    finally:
        _CAPTURE = prev


def _capture(config: str, name: str, kind: str, *,
             jaxpr=None, compiled=None, **expect) -> None:
    if _CAPTURE is not None:
        _CAPTURE.append(dict(config=config, name=name, kind=kind,
                             jaxpr=jaxpr, compiled=compiled, expect=expect))


# configs whose cost_analysis() already raised once this process — the
# warning fires once per config, not once per retry/rerank.
_COST_WARNED: set = set()


def _cost_analysis(compiled, name: str = '') -> Dict[str, float]:
    """Normalize `compiled.cost_analysis()` across jax versions (dict or
    [dict]); returns {} when the backend reports nothing. A raising backend
    is logged once per config name — an autotune/budget consumer ranking on
    partially-missing costs must be able to see WHY in the log."""
    try:
        ca = compiled.cost_analysis()
    except Exception as e:
        if name not in _COST_WARNED:
            _COST_WARNED.add(name)
            _logger.warning(
                'perfbudget: cost_analysis() raised for config %r '
                '(%s: %s) — flops/bytes_accessed will be missing',
                name or '<unnamed>', type(e).__name__, e)
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def donation_evidence(compiled) -> Dict[str, object]:
    """Alias evidence from a compiled executable's HLO header: number of
    `may-alias`/`must-alias` entries in its ``input_output_alias`` table."""
    header = compiled.as_text().splitlines()[0] if hasattr(compiled, 'as_text') else ''
    aliases = (header.count('may-alias') + header.count('must-alias')
               if 'input_output_alias' in header else 0)
    return {'aliases': int(aliases), 'header': header}


def _device_state_bytes(tree) -> int:
    """Exact per-device bytes of a placed pytree: one addressable shard per
    leaf (correct for both replicated and sharded placements)."""
    import jax
    total = 0
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, 'addressable_shards', None)
        if shards:
            total += int(shards[0].data.nbytes)
    return total


def _model_dims(model) -> Optional[Tuple[int, int, int]]:
    """(seq_len, width, depth) for the activation calculator, read off the
    live model; None for models without a pos_embed/blocks ViT shape."""
    pos = getattr(model, 'pos_embed', None)
    blocks = getattr(model, 'blocks', None)
    if pos is None or blocks is None:
        return None
    shape = getattr(getattr(pos, 'value', pos), 'shape', None)
    if not shape or len(shape) != 3:
        return None
    try:
        depth = len(blocks)
    except TypeError:
        return None
    return int(shape[1]), int(shape[2]), int(depth)


def _probe_train(cfg: ProbeConfig) -> Dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax import nnx

    import timm_tpu
    from ..loss import LabelSmoothingCrossEntropy
    from ..optim import create_optimizer_v2
    from ..parallel import (
        activation_bytes_per_device, build_param_shardings, create_mesh,
        param_bytes_per_device, set_global_mesh, shard_batch,
    )
    from ..task import ClassificationTask
    from ..utils.compile_cache import count_jaxpr_eqns

    mesh = create_mesh(fsdp=cfg.fsdp, tp=cfg.tp)
    # the models' activation sharding constraints resolve against the GLOBAL
    # mesh at trace time — it must match the probe mesh or GSPMD degenerates
    # into the involuntary-remat regime this probe exists to detect
    set_global_mesh(mesh)
    model = timm_tpu.create_model(cfg.model, **cfg.kwargs())
    if cfg.block_scan is not None and hasattr(model, 'set_block_scan'):
        model.set_block_scan(cfg.block_scan)
    dims = _model_dims(model)

    rng = np.random.RandomState(0)
    s = cfg.img(224)
    num_classes = int(cfg.kwargs().get('num_classes', 1000))
    batch = {'input': jnp.asarray(rng.rand(cfg.batch_size, s, s, 3), jnp.float32),
             'target': jnp.asarray(rng.randint(0, num_classes, cfg.batch_size))}

    metrics: Dict = {}
    if cfg.collect == 'fwd':
        # forward-only program (the tp residual-sharding gate): mirrors
        # test_tp_constraint_in_scan_body_and_no_involuntary_remat
        model.eval()
        graphdef, state = nnx.split(model)
        state = jax.device_put(state, build_param_shardings(state, mesh))

        def fwd(state, x):
            return nnx.merge(graphdef, state)(x)

        x = shard_batch(batch['input'], mesh)
        t0 = time.perf_counter()
        closed = jax.make_jaxpr(fwd)(state, x)
        metrics['trace_ms'] = round((time.perf_counter() - t0) * 1e3, 3)
        metrics['jaxpr_eqns'] = count_jaxpr_eqns(closed)
        compiled = jax.jit(fwd).lower(state, x).compile()
        ca = _cost_analysis(compiled, cfg.name)
        if 'flops' in ca:
            metrics['flops'] = float(ca['flops'])
        if 'bytes accessed' in ca:
            metrics['bytes_accessed'] = float(ca['bytes accessed'])
        _capture(cfg.name, f'{cfg.name}/fwd', 'fwd',
                 jaxpr=closed, compiled=compiled,
                 expect_shard=cfg.fwd_expect_shard or None,
                 forbid_full=cfg.fwd_forbid_full or None)
        if cfg.fwd_expect_shard:
            hlo = compiled.as_text()
            metrics['no_replicated_residual'] = bool(
                cfg.fwd_expect_shard in hlo
                and (not cfg.fwd_forbid_full or cfg.fwd_forbid_full not in hlo))
        rep, shard = param_bytes_per_device(nnx.state(model, nnx.Param), mesh)
        metrics['param_bytes_replicated'] = int(rep)
        metrics['param_bytes_sharded'] = int(shard)
        return metrics

    def build_task():
        return ClassificationTask(model,
                                  optimizer=create_optimizer_v2(model, opt=cfg.opt, lr=0.1),
                                  mesh=mesh, grad_accum_steps=cfg.grad_accum,
                                  train_loss_fn=LabelSmoothingCrossEntropy(0.1),
                                  fused_update=cfg.fused_update)

    task = build_task()
    batch = shard_batch(batch, mesh)

    # trace_ms = min over two FRESH tasks (a task's jit caches its first
    # trace, so re-timing needs a new step fn). Load spikes only ever inflate
    # a trace measurement, so the min tracks the true cost closely (~±5% here
    # vs ±15% single-shot) — tight enough that the 1.3x upper tolerance
    # separates block_scan=False (~1.45x) from noise without flaking tier-1.
    trace_times = []
    for t in (task, build_task()):
        t0 = time.perf_counter()
        jaxpr = t.trace_train_step(batch, lr=0.1)
        trace_times.append((time.perf_counter() - t0) * 1e3)
    metrics['trace_ms'] = round(min(trace_times), 3)
    metrics['jaxpr_eqns'] = count_jaxpr_eqns(jaxpr)

    params = nnx.state(task.model, nnx.Param)
    rep, shard = param_bytes_per_device(params, mesh, task.partition_rules)
    metrics['param_bytes_replicated'] = int(rep)
    metrics['param_bytes_sharded'] = int(shard)
    metrics['opt_bytes_per_device'] = _device_state_bytes(task.opt_state)
    if dims is not None:
        seq_len, width, depth = dims
        unc, con = activation_bytes_per_device(
            mesh, batch_size=cfg.batch_size, seq_len=seq_len, width=width, depth=depth)
        metrics['activation_bytes_unconstrained'] = int(unc)
        metrics['activation_bytes_constrained'] = int(con)

    if cfg.collect == 'full':
        compiled = task.lower_train_step(batch, lr=0.1)
        ca = _cost_analysis(compiled, cfg.name)
        if 'flops' in ca:
            metrics['flops'] = float(ca['flops'])
        if 'bytes accessed' in ca:
            metrics['bytes_accessed'] = float(ca['bytes accessed'])
        ev = donation_evidence(compiled)
        metrics['donation_aliases'] = ev['aliases']
        # the train step's donation is always usable (state outputs match
        # their donated inputs leaf-for-leaf): zero aliases = donation died
        metrics['donation_ok'] = ev['aliases'] > 0
        _capture(cfg.name, f'{cfg.name}/train_step', 'train_step',
                 jaxpr=jaxpr, compiled=compiled, donation='alias')
    else:
        _capture(cfg.name, f'{cfg.name}/train_step', 'train_step',
                 jaxpr=jaxpr)
    return metrics


def _probe_augment(cfg: ProbeConfig) -> Dict:
    """The on-device data-path programs (data/device_augment.py). Two pieces
    of evidence: the fused image program (uint8 -> erase -> mixup -> normalize
    -> soft targets) stays a small fixed-size jaxpr with bytes dominated by
    the batch itself, and the NaFlex variant's float32 patches buffer donation
    survives to the compiled HLO as a real alias (the uint8 image input can
    never alias its float output, so the naflex program is where donation is
    provable)."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..data.device_augment import augment_image_batch, augment_naflex_batch
    from ..parallel import create_mesh, set_global_mesh, shard_batch
    from ..utils.compile_cache import count_jaxpr_eqns

    mesh = create_mesh(fsdp=cfg.fsdp, tp=cfg.tp)
    set_global_mesh(mesh)
    rng = np.random.RandomState(0)
    B = cfg.batch_size
    s = cfg.img(32)
    num_classes = int(cfg.kwargs().get('num_classes', 10))
    raw = shard_batch({
        'image': jnp.asarray(rng.randint(0, 256, (B, s, s, 3)), jnp.uint8),
        'target': jnp.asarray(rng.randint(0, num_classes, B)),
        'lam': jnp.asarray(rng.beta(0.8, 0.8, B), jnp.float32),
        'use_cutmix': jnp.zeros((B,), bool),
        'bbox': jnp.zeros((B, 4), jnp.int32),
        'erase_box': jnp.zeros((B, 1, 4), jnp.int32),
    }, mesh)
    fn = functools.partial(augment_image_batch, mean=(0.5,) * 3, std=(0.5,) * 3,
                           num_classes=num_classes, smoothing=0.1)

    metrics: Dict = {}
    t0 = time.perf_counter()
    closed = jax.make_jaxpr(fn)(raw)
    metrics['trace_ms'] = round((time.perf_counter() - t0) * 1e3, 3)
    metrics['jaxpr_eqns'] = count_jaxpr_eqns(closed)
    compiled = jax.jit(fn).lower(raw).compile()
    ca = _cost_analysis(compiled, cfg.name)
    if 'flops' in ca:
        metrics['flops'] = float(ca['flops'])
    if 'bytes accessed' in ca:
        metrics['bytes_accessed'] = float(ca['bytes accessed'])

    L, pd = 25, 4 * 4 * 3
    nf = shard_batch({
        'patches': jnp.asarray(rng.rand(B, L, pd), jnp.float32),
        'patch_coord': jnp.asarray(rng.randint(0, 5, (B, L, 2)), jnp.int32),
        'patch_valid': jnp.ones((B, L), bool),
        'target': jnp.asarray(rng.randint(0, num_classes, B)),
        'erase_mask': jnp.zeros((B, L), bool),
    }, mesh)
    _capture(cfg.name, f'{cfg.name}/image_augment', 'augment',
             jaxpr=closed, compiled=compiled)
    nf_fn = functools.partial(augment_naflex_batch, mean=(0.5,) * 3, std=(0.5,) * 3)
    nf_compiled = jax.jit(nf_fn, donate_argnums=(0,)).lower(nf).compile()
    _capture(cfg.name, f'{cfg.name}/naflex_augment', 'augment',
             compiled=nf_compiled, donation='alias')
    ev = donation_evidence(nf_compiled)
    metrics['naflex_donation_aliases'] = ev['aliases']
    # the (B, L, D) float patches round-trip f32 -> f32 at unchanged shape:
    # the donation MUST alias; zero aliases means it silently died
    metrics['naflex_donation_ok'] = ev['aliases'] > 0
    return metrics


def _probe_naflex(cfg: ProbeConfig) -> Dict:
    """The packed variable-resolution train step (NaFlexClassificationTask on
    a {patches, patch_coord, patch_valid, target} dict batch) at one bucket
    shape: trace/eqn cost, XLA flops/bytes, and state donation — the program
    every bucket in the seq-len ladder re-instantiates per shape."""
    import jax.numpy as jnp
    import numpy as np
    from flax import nnx

    import timm_tpu
    from ..optim import create_optimizer_v2
    from ..parallel import (
        create_mesh, param_bytes_per_device, set_global_mesh, shard_batch,
    )
    from ..task import NaFlexClassificationTask
    from ..utils.compile_cache import count_jaxpr_eqns

    mesh = create_mesh(fsdp=cfg.fsdp, tp=cfg.tp)
    set_global_mesh(mesh)
    model = timm_tpu.create_model(cfg.model, **cfg.kwargs())
    model.train()
    p = getattr(model.embeds, 'patch_size', 16)
    num_classes = int(cfg.kwargs().get('num_classes', 1000))

    rng = np.random.RandomState(0)
    B, L = cfg.batch_size, cfg.seq_len
    batch = shard_batch({
        'patches': jnp.asarray(rng.rand(B, L, p * p * 3), jnp.float32),
        'patch_coord': jnp.asarray(rng.randint(0, 5, (B, L, 2)), jnp.int32),
        'patch_valid': jnp.asarray(np.arange(L)[None, :]
                                   < rng.randint(L // 2, L + 1, (B, 1))),
        'target': jnp.asarray(rng.randint(0, num_classes, B)),
    }, mesh)

    def build_task():
        return NaFlexClassificationTask(
            model, optimizer=create_optimizer_v2(model, opt=cfg.opt, lr=0.1),
            mesh=mesh, grad_accum_steps=cfg.grad_accum)

    task = build_task()
    metrics: Dict = {}
    trace_times = []
    for t in (task, build_task()):
        t0 = time.perf_counter()
        jaxpr = t.trace_train_step(batch, lr=0.1)
        trace_times.append((time.perf_counter() - t0) * 1e3)
    metrics['trace_ms'] = round(min(trace_times), 3)
    metrics['jaxpr_eqns'] = count_jaxpr_eqns(jaxpr)

    rep, shard = param_bytes_per_device(
        nnx.state(task.model, nnx.Param), mesh, task.partition_rules)
    metrics['param_bytes_replicated'] = int(rep)
    metrics['param_bytes_sharded'] = int(shard)

    compiled = task.lower_train_step(batch, lr=0.1)
    ca = _cost_analysis(compiled, cfg.name)
    if 'flops' in ca:
        metrics['flops'] = float(ca['flops'])
    if 'bytes accessed' in ca:
        metrics['bytes_accessed'] = float(ca['bytes accessed'])
    ev = donation_evidence(compiled)
    metrics['donation_aliases'] = ev['aliases']
    metrics['donation_ok'] = ev['aliases'] > 0
    _capture(cfg.name, f'{cfg.name}/train_step', 'train_step',
             jaxpr=jaxpr, compiled=compiled, donation='alias')
    return metrics


def _probe_serve(cfg: ProbeConfig) -> Dict:
    from ..serve import InferenceEngine

    eng = InferenceEngine(buckets=cfg.buckets)
    eng.add_model(cfg.model, **cfg.kwargs())
    exes = eng.aot_executables(cfg.model)
    metrics: Dict = {
        'serve_programs': set(exes) == set(cfg.buckets),
    }
    flops = 0.0
    have_flops = False
    for bucket in sorted(exes):
        ca = _cost_analysis(exes[bucket], f'{cfg.name}/bucket{bucket}')
        if 'flops' in ca:
            flops += float(ca['flops'])
            have_flops = True
    if have_flops:
        metrics['flops'] = flops
    report = eng.donation_report(cfg.model)
    metrics['serve_donation_declared'] = bool(report) and all(
        r['declared'] for r in report.values())
    for bucket in sorted(exes):
        _capture(cfg.name, f'{cfg.name}/bucket{bucket}', 'serve_bucket',
                 compiled=exes[bucket], donation='declared',
                 declared=bool(report.get(bucket, {}).get('declared')))
    return metrics


def _probe_quant(cfg: ProbeConfig) -> Dict:
    """Int8 serve-path budgets: the quantized serve program vs its fp32 twin.

    The acceptance claim is hardware-independent — per-device param bytes AND
    the compiled program's HBM bytes-accessed must land at <= 0.55x the fp32
    baseline (``quant_halves_hbm``), with the input-batch donation still
    declared on every bucket program and the int8 pytree placeable under a
    real (fsdp=2, tp=2) mesh where every scale rides its kernel's spec
    (``quant_sharding_ok``).

    Two bytes-accessed measures are reported because they answer different
    questions:

      * ``bytes_accessed*`` — XLA's aggregate ``cost_analysis()`` estimate.
        Informative only: the pre-fusion cost model charges the dequantized
        fp32 weights as a materialized intermediate, so this aggregate does
        NOT drop under int8 even though on real hardware the dequant is a
        fusion transient (cache/VMEM resident, never HBM round-trip traffic).
      * ``hbm_bytes_accessed*`` — from each COMPILED executable's
        ``memory_analysis()``: the program's argument-buffer bytes, summed
        over the AOT serve programs plus a directly-lowered quantized
        forward. Every argument buffer is streamed from device memory exactly
        once per execution, so this is the per-step HBM read traffic the
        weights actually cost — and it is provably int8-sized for the
        quantized programs. This is the measure the 0.55x gate uses."""
    import jax
    import jax.numpy as jnp
    from flax import nnx

    import timm_tpu
    from ..parallel import build_quant_shardings, create_mesh, quant_path_specs
    from ..parallel.sharding import _kp_str
    from ..quantize import dequantize_tree, quantize_tree
    from ..serve import InferenceEngine

    metrics: Dict = {}

    # A/B engines on the default single-device serving mesh
    eng_fp = InferenceEngine(buckets=cfg.buckets)
    eng_fp.add_model(cfg.model, **cfg.kwargs())
    eng_q = InferenceEngine(buckets=cfg.buckets)
    eng_q.add_model(cfg.model, quantize='int8', **cfg.kwargs())

    fp_bytes = eng_fp.pool.acquire(cfg.model).param_bytes
    q_bytes = eng_q.pool.acquire(cfg.model).param_bytes
    metrics['param_bytes_fp32'] = int(fp_bytes)
    metrics['param_bytes_int8'] = int(q_bytes)
    metrics['quant_param_bytes_ratio'] = round(q_bytes / max(fp_bytes, 1), 4)

    def _exe_stats(exe):
        """(cost-model bytes-accessed | None, flops, compiled argument bytes)."""
        ca = _cost_analysis(exe, cfg.name)
        accessed = float(ca['bytes accessed']) if 'bytes accessed' in ca else None
        flops = float(ca.get('flops', 0.0))
        try:
            arg_bytes = int(exe.memory_analysis().argument_size_in_bytes)
        except Exception:
            arg_bytes = 0
        return accessed, flops, arg_bytes

    def _engine_stats(eng):
        total, have, flops, args = 0.0, False, 0.0, 0
        for bucket, exe in sorted(eng.aot_executables(cfg.model).items()):
            accessed, f, a = _exe_stats(exe)
            if accessed is not None:
                total, have = total + accessed, True
            flops += f
            args += a
        return (total if have else None), flops, args

    fp_accessed, _fp_flops, fp_args = _engine_stats(eng_fp)
    q_accessed, q_flops, q_args = _engine_stats(eng_q)
    if q_flops:
        metrics['flops'] = q_flops
    if fp_accessed is not None and q_accessed is not None:
        metrics['bytes_accessed_fp32'] = fp_accessed
        metrics['bytes_accessed'] = q_accessed
    metrics['serve_programs'] = (
        set(eng_fp.aot_executables(cfg.model)) == set(cfg.buckets)
        and set(eng_q.aot_executables(cfg.model)) == set(cfg.buckets))
    report = eng_q.donation_report(cfg.model)
    metrics['serve_donation_declared'] = bool(report) and all(
        r['declared'] for r in report.values())

    # the "quantized forward" twin pair: the same model lowered directly
    # (no engine plumbing) at the smallest bucket's batch shape
    model = timm_tpu.create_model(cfg.model, **cfg.kwargs())
    model.eval()
    graphdef, state = nnx.split(model)
    qstate = quantize_tree(state)
    img = cfg.img(224)
    x = jnp.zeros((min(cfg.buckets), img, img, 3), jnp.float32)

    def fwd_fp(s, xx):
        return nnx.merge(graphdef, s)(xx)

    def fwd_q(qs, xx):
        return nnx.merge(graphdef, dequantize_tree(qs))(xx)

    for bucket, exe in sorted(eng_q.aot_executables(cfg.model).items()):
        _capture(cfg.name, f'{cfg.name}/bucket{bucket}', 'serve_bucket',
                 compiled=exe, donation='declared',
                 declared=bool(report.get(bucket, {}).get('declared')))

    fp_fwd_compiled = jax.jit(fwd_fp).lower(state, x).compile()
    q_fwd_compiled = jax.jit(fwd_q).lower(qstate, x).compile()
    _capture(cfg.name, f'{cfg.name}/fwd_int8', 'fwd', compiled=q_fwd_compiled)
    _, _, fp_fwd_args = _exe_stats(fp_fwd_compiled)
    _, _, q_fwd_args = _exe_stats(q_fwd_compiled)

    hbm_fp = fp_args + fp_fwd_args
    hbm_q = q_args + q_fwd_args
    metrics['hbm_bytes_accessed_fp32'] = int(hbm_fp)
    metrics['hbm_bytes_accessed_int8'] = int(hbm_q)
    metrics['quant_bytes_accessed_ratio'] = round(hbm_q / max(hbm_fp, 1), 4)
    metrics['quant_halves_hbm'] = bool(
        metrics['quant_param_bytes_ratio'] <= 0.55
        and metrics['quant_bytes_accessed_ratio'] <= 0.55)

    # sharding legality on a real 3-axis mesh: place the int8 pytree under
    # build_quant_shardings and verify, from the PLACED arrays, that every
    # leaf landed on its resolved spec (qvalues through the unchanged rule
    # table, scales inheriting their kernel's last axis)
    mesh = create_mesh(fsdp=2, tp=2)
    specs = quant_path_specs(qstate, mesh)
    placed = jax.device_put(qstate, build_quant_shardings(qstate, mesh))
    flat, _ = jax.tree_util.tree_flatten_with_path(placed)
    placement_ok = len(qstate['scales']) > 0
    scales_sharded = 0
    for kp, leaf in flat:
        path = _kp_str(kp)
        spec = getattr(leaf.sharding, 'spec', None)
        placement_ok = placement_ok and tuple(spec or ()) == tuple(specs[path])
        if path.startswith('scales.') and tuple(spec or ()):
            scales_sharded += 1
    metrics['quant_sharding_ok'] = bool(placement_ok)
    # at least the tp column-parallel kernels' scales must actually shard —
    # inheritance degenerating to replicate-everything would silently pass
    # a pure equality check
    metrics['quant_scales_sharded'] = int(scales_sharded)
    return metrics


def _probe_elastic(cfg: ProbeConfig) -> Dict:
    """Elastic-resize legality (resilience/elastic.py): checkpoint state
    captured under the pre-resize mesh (all devices, fsdp=cfg.fsdp) re-places
    under the post-resize half-pod mesh with the fsdp axis clamped the way
    ``plan_elastic_resume`` would clamp it, and the re-placed task's train
    step still lowers with its state donation aliased.

      * ``elastic_resharding_ok``   — every re-placed param landed on the NEW
        mesh with at least one leaf actually sharded over 'fsdp', and the
        values round-tripped bit-exactly through the host snapshot;
      * ``elastic_global_batch_ok`` — the rescale solver returns a
        (batch, accum) pair that preserves the global batch and shards evenly
        on the post-resize mesh;
      * ``donation_aliases`` / ``donation_ok`` — the usual HLO alias-table
        evidence, for the step compiled AFTER the resize re-placement.

    No trace_ms: this probe pins legality, not trace cost."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax import nnx

    import timm_tpu
    from ..loss import LabelSmoothingCrossEntropy
    from ..optim import create_optimizer_v2
    from ..parallel import create_mesh, resolve_elastic_axes, set_global_mesh, shard_batch
    from ..resilience import rescale_for_devices, snapshot_to_host
    from ..task import ClassificationTask

    def build(mesh):
        model = timm_tpu.create_model(cfg.model, **cfg.kwargs())
        return ClassificationTask(
            model, optimizer=create_optimizer_v2(model, opt=cfg.opt, lr=0.1),
            mesh=mesh, train_loss_fn=LabelSmoothingCrossEntropy(0.1))

    # pre-resize: the dead run's full-pod mesh
    mesh_from = create_mesh(fsdp=cfg.fsdp)
    set_global_mesh(mesh_from)
    state = snapshot_to_host(build(mesh_from).get_checkpoint_state())

    # post-resize: half the devices survive; clamp the axes as the planner does
    devices = jax.devices()
    n_to = max(1, len(devices) // 2)
    fsdp_to, tp_to = resolve_elastic_axes(n_to, fsdp=cfg.fsdp, tp=cfg.tp)
    mesh_to = create_mesh(devices=devices[:n_to], fsdp=fsdp_to, tp=tp_to)
    set_global_mesh(mesh_to)
    task_to = build(mesh_to)
    task_to.load_checkpoint_state(state)

    metrics: Dict = {'elastic_devices_from': len(devices), 'elastic_devices_to': n_to}
    params = nnx.state(task_to.model, nnx.Param)
    on_new_mesh, fsdp_sharded = True, False
    for leaf in jax.tree.leaves(params):
        sharding = getattr(getattr(leaf, 'value', leaf), 'sharding', None)
        on_new_mesh = on_new_mesh and getattr(sharding, 'mesh', None) == mesh_to
        fsdp_sharded = fsdp_sharded or 'fsdp' in tuple(getattr(sharding, 'spec', ()) or ())
    # bit-exact round trip through the host snapshot for one witness leaf
    key = next(k for k in state if k.startswith('state_dict.'))
    reloaded = snapshot_to_host(task_to.get_checkpoint_state())
    values_ok = np.array_equal(state[key], reloaded[key])
    metrics['elastic_resharding_ok'] = bool(on_new_mesh and fsdp_sharded and values_ok)

    global_batch = cfg.batch_size * cfg.grad_accum
    bs, accum = rescale_for_devices(global_batch, mesh_to.size,
                                    prefer_batch_size=cfg.batch_size)
    metrics['elastic_global_batch_ok'] = bool(
        bs * accum == global_batch and bs % mesh_to.size == 0)

    rng = np.random.RandomState(0)
    s = cfg.img(224)
    num_classes = int(cfg.kwargs().get('num_classes', 1000))
    batch = shard_batch({'input': jnp.asarray(rng.rand(bs, s, s, 3), jnp.float32),
                         'target': jnp.asarray(rng.randint(0, num_classes, bs))},
                        mesh_to)
    compiled = task_to.lower_train_step(batch, lr=0.1)
    _capture(cfg.name, f'{cfg.name}/train_step_postresize', 'train_step',
             compiled=compiled, donation='alias')
    ev = donation_evidence(compiled)
    metrics['donation_aliases'] = ev['aliases']
    metrics['donation_ok'] = ev['aliases'] > 0
    return metrics


def _probe_kernels(cfg: ProbeConfig) -> Dict:
    """Per-kernel lowering A/B over the registry (kernels/harness.py): one
    budget anchor per kernel (its first declared regime case, dry arm).
    ``<k>_io_bytes`` is the kernel's analytic one-pass HBM contract and
    ``<k>_ref_bytes_accessed`` the compiled XLA reference's cost-model bytes;
    fused_adamw's reference IS the unfused optax update+EMA chain, so its
    ``fused_adamw_wins_bytes`` bool is exactly the ISSUE-12 one-pass-
    reduction acceptance gate. ``kernels_registered`` pins the portfolio
    size so a silently dropped registration fails the budget diff."""
    from ..kernels.harness import kernel_metrics

    return dict(kernel_metrics())


def _probe_autotune(cfg: ProbeConfig) -> Dict:
    """Pin the autotune solver's output legality: enumerate + rank the full
    space analytically (no lowering) for global batch ``batch_size *
    grad_accum``, then probe the WINNER's real train step through
    `_probe_train` so its flops/bytes/donation land in the same budget file
    every other train config uses."""
    from ..autotune import autotune

    result = autotune(cfg.model, cfg.kwargs(),
                      global_batch=cfg.batch_size * cfg.grad_accum,
                      probe_anchor=False, correction=1.0)
    w = result.winner
    metrics: Dict = {
        'autotune_candidates': len(result.ranked),
        'autotune_rejections': len(result.rejections),
        'autotune_winner_fsdp': int(w.fsdp),
        'autotune_winner_tp': int(w.tp),
        'autotune_winner_batch_size': int(w.batch_size),
        'autotune_winner_grad_accum': int(w.grad_accum),
        'autotune_winner_global_batch_ok':
            w.global_batch == cfg.batch_size * cfg.grad_accum,
    }
    winner_metrics = _probe_train(dataclasses.replace(
        cfg, batch_size=w.batch_size, fsdp=w.fsdp, tp=w.tp,
        grad_accum=w.grad_accum, block_scan=w.block_scan, collect='full'))
    metrics.update(winner_metrics)
    # the winner must be a config we can actually run: its real step lowered,
    # compiled, and kept donation alive
    metrics['autotune_winner_legal'] = bool(winner_metrics.get('donation_ok'))
    return metrics


def probe_config(cfg: ProbeConfig) -> Dict:
    """Probe one config; global mesh is saved/restored so probes compose with
    whatever mesh the calling process (tests, bench) had active."""
    from ..parallel import mesh as mesh_mod

    saved = mesh_mod.peek_global_mesh()
    try:
        if cfg.collect == 'autotune':
            return _probe_autotune(cfg)
        if cfg.collect == 'serve':
            return _probe_serve(cfg)
        if cfg.collect == 'quant':
            return _probe_quant(cfg)
        if cfg.collect == 'augment':
            return _probe_augment(cfg)
        if cfg.collect == 'naflex':
            return _probe_naflex(cfg)
        if cfg.collect == 'kernels':
            return _probe_kernels(cfg)
        if cfg.collect == 'elastic':
            return _probe_elastic(cfg)
        return _probe_train(cfg)
    finally:
        mesh_mod._GLOBAL_MESH = saved


def run_matrix(configs: Optional[Sequence[ProbeConfig]] = None,
               names: Optional[Sequence[str]] = None,
               log=None) -> Dict[str, Dict]:
    """Probe the matrix (default: DEFAULT_MATRIX, optionally filtered by
    `names`) -> {config_name: metrics}."""
    configs = list(configs) if configs is not None else list(DEFAULT_MATRIX)
    if names is not None:
        wanted = set(names)
        unknown = wanted - {c.name for c in configs}
        if unknown:
            raise ValueError(f'unknown probe config(s): {sorted(unknown)}')
        configs = [c for c in configs if c.name in wanted]
    out: Dict[str, Dict] = {}
    for cfg in configs:
        t0 = time.perf_counter()
        out[cfg.name] = probe_config(cfg)
        if log is not None:
            log(f'perfbudget probe {cfg.name} [{cfg.collect}] '
                f'({time.perf_counter() - t0:.1f}s): '
                f'{len(out[cfg.name])} metrics')
    return out
