"""Budget storage, tolerance policy, and the ONE comparison helper family.

Every hardware-independent perf property this repo has proven (compiled
FLOPs, jaxpr equation counts, trace time, per-device state bytes, donation/
sharding legality) is pinned here against `tests/fixtures/perf_budgets.json`.
The policy is deliberately two-sided for deterministic metrics:

  * **regression** — a measured value worse than budget * (1 + tol) fails;
  * **silent improvement** — a measured value better than budget * (1 - tol)
    ALSO fails. An improvement is real information: it must be re-baselined
    explicitly (``python -m timm_tpu.perfbudget --update-budgets``) so the
    budget keeps teeth. Without this, one accidental improvement (or a probe
    bug measuring the wrong thing) silently widens the band forever.

Timing metrics (trace_ms) are upper-bound only; the probe measures the min
over two fresh traces (load spikes only ever inflate a trace, so the min is
stable) and the tolerance gives 1.3x headroom — wall-clock noise must not
flake tier-1, but a block-scan-off regression (~1.45x trace, ~1.4x eqns)
must still trip. Legality metrics (donation_ok, no_replicated_residual) are
exact booleans.

The ``check_*`` helpers at the bottom are the shared comparison policy for
the ad-hoc ratio/counter assertions that used to be scattered across
test_block_scan.py / test_serve.py / test_sharding.py — one message format,
one failure type, one place to tune.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Sequence

SCHEMA = 'perf_budgets/v1'

# default checked-in budget file (env-overridable for scratch baselines)
_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))
BUDGETS_PATH = os.environ.get(
    'TIMM_TPU_PERF_BUDGETS',
    os.path.join(_REPO_ROOT, 'tests', 'fixtures', 'perf_budgets.json'))

# metric -> (kind, tolerance). kinds:
#   band  : fail above budget*(1+tol) [regression] AND below budget*(1-tol)
#           [improvement refused until --update-budgets]
#   upper : fail above budget*(1+tol) only (timing: noise-tolerant)
#   lower : fail below budget*(1-tol) only (counts that may only grow)
#   bool  : must equal the budget exactly (legality flags)
TOLERANCES: Dict[str, tuple] = {
    'jaxpr_eqns': ('band', 0.10),
    'flops': ('band', 0.05),
    'bytes_accessed': ('band', 0.50),          # XLA:CPU pre-fusion estimate
    'param_bytes_replicated': ('band', 0.02),
    'param_bytes_sharded': ('band', 0.02),
    'opt_bytes_per_device': ('band', 0.02),
    'activation_bytes_unconstrained': ('band', 0.02),
    'activation_bytes_constrained': ('band', 0.02),
    'trace_ms': ('upper', 0.30),               # probe takes min-of-2 fresh
                                               # traces (spikes only inflate),
                                               # so 1.3x catches scan-off
                                               # (~1.45x) without flaking
    'donation_aliases': ('lower', 0.10),
    'donation_ok': ('bool', 0.0),
    'naflex_donation_aliases': ('lower', 0.10),
    'naflex_donation_ok': ('bool', 0.0),
    'no_replicated_residual': ('bool', 0.0),
    'serve_programs': ('bool', 0.0),
    'serve_donation_declared': ('bool', 0.0),
    # int8 serve-path quantization (the quant probe): per-device bytes and
    # compiled argument-buffer bytes are deterministic shape/dtype sums
    # (tight band); the cost-model aggregates keep the loose estimate band.
    # `quant_bytes_accessed_ratio` divides the compiled int8 programs'
    # argument bytes by the fp32 twins' — the per-step HBM weight-read
    # traffic — and must sit well under the 0.55x gate (quant_halves_hbm)
    'param_bytes_fp32': ('band', 0.02),
    'param_bytes_int8': ('band', 0.02),
    'quant_param_bytes_ratio': ('band', 0.02),
    'bytes_accessed_fp32': ('band', 0.50),
    'hbm_bytes_accessed_fp32': ('band', 0.02),
    'hbm_bytes_accessed_int8': ('band', 0.02),
    'quant_bytes_accessed_ratio': ('band', 0.02),
    'quant_halves_hbm': ('bool', 0.0),         # both ratios <= 0.55x fp32
    'quant_sharding_ok': ('bool', 0.0),
    'quant_scales_sharded': ('lower', 0.10),
    # kernel portfolio (the `kernels` probe, kernels/harness.py): per kernel,
    # jaxpr eqn counts of both arms band-pinned; `<k>_io_bytes` is an exact
    # shape/dtype sum (tight band) while `<k>_ref_bytes_accessed` is the
    # XLA:CPU cost-model estimate (loose band, like bytes_accessed above);
    # `<k>_wins_bytes` is the one-pass-beats-reference bool the win-or-delete
    # verdict rests on. `kernels_registered` pins the portfolio size (band
    # with zero tolerance = exact count) so a dropped registration cannot
    # pass silently.
    # elastic resize probe (resilience/elastic.py): pure legality — the
    # re-placed-after-resize state must land sharded on the new mesh and the
    # rescale solver must hold the global batch; device counts pinned exactly
    'elastic_resharding_ok': ('bool', 0.0),
    'elastic_global_batch_ok': ('bool', 0.0),
    'elastic_devices_from': ('band', 0.0),
    'elastic_devices_to': ('band', 0.0),
    # autotune probe (autotune/solver.py): solver-output legality. The
    # enumeration is deterministic given the 8-device topology and model, so
    # candidate/rejection counts and the winning config's axes pin exactly
    # (band 0.0); the winner's own compiled step rides the shared
    # flops/bytes/donation tolerances above.
    'autotune_candidates': ('band', 0.0),
    'autotune_rejections': ('band', 0.0),
    'autotune_winner_fsdp': ('band', 0.0),
    'autotune_winner_tp': ('band', 0.0),
    'autotune_winner_batch_size': ('band', 0.0),
    'autotune_winner_grad_accum': ('band', 0.0),
    'autotune_winner_global_batch_ok': ('bool', 0.0),
    'autotune_winner_legal': ('bool', 0.0),
    'kernels_registered': ('band', 0.0),
    'fused_adamw_eqns': ('band', 0.10),
    'fused_adamw_ref_eqns': ('band', 0.10),
    'fused_adamw_io_bytes': ('band', 0.02),
    'fused_adamw_ref_bytes_accessed': ('band', 0.50),
    'fused_adamw_wins_bytes': ('bool', 0.0),
    'flash_attention_eqns': ('band', 0.10),
    'flash_attention_ref_eqns': ('band', 0.10),
    'flash_attention_io_bytes': ('band', 0.02),
    'flash_attention_ref_bytes_accessed': ('band', 0.50),
    'flash_attention_wins_bytes': ('bool', 0.0),
    'augment_epilogue_eqns': ('band', 0.10),
    'augment_epilogue_ref_eqns': ('band', 0.10),
    'augment_epilogue_io_bytes': ('band', 0.02),
    'augment_epilogue_ref_bytes_accessed': ('band', 0.50),
    'augment_epilogue_wins_bytes': ('bool', 0.0),
}
_DEFAULT_TOL = ('band', 0.10)


def tolerance_for(metric: str) -> tuple:
    return TOLERANCES.get(metric, _DEFAULT_TOL)


def load_budgets(path: Optional[str] = None) -> Dict:
    path = path or BUDGETS_PATH
    with open(path) as f:
        doc = json.load(f)
    if doc.get('schema') != SCHEMA:
        raise ValueError(f'{path}: unexpected budget schema {doc.get("schema")!r} '
                         f'(want {SCHEMA!r})')
    return doc


def update_budgets(measured: Dict[str, Dict], path: Optional[str] = None,
                   note: str = '') -> Dict:
    """Re-baseline: write `measured` ({config: {metric: value}}) as the new
    budget file. This is the ONLY sanctioned way to accept an improvement."""
    path = path or BUDGETS_PATH
    doc = {
        'schema': SCHEMA,
        'generated_at': time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime()),
        'note': note or 'seed budgets; re-baseline via '
                        'python -m timm_tpu.perfbudget --update-budgets',
        'tolerances': {m: {'kind': k, 'tol': t} for m, (k, t) in TOLERANCES.items()},
        'configs': {name: dict(sorted(metrics.items()))
                    for name, metrics in sorted(measured.items())},
    }
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(doc, f, indent=1, sort_keys=False)
        f.write('\n')
    os.replace(tmp, path)
    return doc


def compare_config(measured: Dict, budget: Dict, config: str = '',
                   metrics: Optional[Sequence[str]] = None) -> List[Dict]:
    """Compare one config's measured metrics against its budget entry.

    Returns a list of violation dicts. `metrics` restricts the comparison
    (partial probes — e.g. trace-only); default compares every budgeted
    metric and flags budgeted-but-unmeasured metrics as 'missing' so a probe
    that silently stops collecting a metric cannot pass."""
    out: List[Dict] = []
    names = list(metrics) if metrics is not None else sorted(budget)
    for metric in names:
        if metric not in budget:
            continue
        b = budget[metric]
        kind, tol = tolerance_for(metric)

        def viol(direction, detail, measured_v=None):
            out.append({'config': config, 'metric': metric, 'kind': kind,
                        'measured': measured_v, 'budget': b,
                        'direction': direction, 'detail': detail})

        if metric not in measured:
            viol('missing', 'metric budgeted but not measured')
            continue
        v = measured[metric]
        if kind == 'bool':
            if bool(v) != bool(b):
                viol('mismatch', f'expected {b!r}, measured {v!r}', v)
            continue
        hi, lo = float(b) * (1.0 + tol), float(b) * (1.0 - tol)
        if kind in ('band', 'upper') and float(v) > hi:
            viol('regression',
                 f'{v:.6g} > {b:.6g} * (1+{tol:g}) = {hi:.6g}', v)
        if kind in ('band', 'lower') and float(v) < lo:
            direction = 'improvement' if kind == 'band' else 'regression'
            what = ('improved past the tolerance band — re-baseline explicitly '
                    'with --update-budgets' if direction == 'improvement'
                    else 'fell below the budgeted floor')
            viol(direction, f'{v:.6g} < {b:.6g} * (1-{tol:g}) = {lo:.6g} ({what})', v)
    return out


def compare_budgets(measured_all: Dict[str, Dict], budgets: Dict,
                    configs: Optional[Sequence[str]] = None) -> List[Dict]:
    """Compare a {config: metrics} result set against a loaded budget doc."""
    entries = budgets.get('configs', budgets)
    out: List[Dict] = []
    for name in (configs if configs is not None else sorted(entries)):
        if name not in entries:
            continue
        if name not in measured_all:
            out.append({'config': name, 'metric': '*', 'kind': 'config',
                        'measured': None, 'budget': None, 'direction': 'missing',
                        'detail': 'budgeted config not probed'})
            continue
        out.extend(compare_config(measured_all[name], entries[name], config=name))
    return out


def format_violations(violations: Sequence[Dict]) -> str:
    if not violations:
        return 'perfbudget: all metrics within budget'
    lines = [f'perfbudget: {len(violations)} budget violation(s):']
    for v in violations:
        lines.append(
            f"  [{v['direction']}] {v['config']}.{v['metric']} "
            f"({v['kind']}): {v['detail']}")
    return '\n'.join(lines)


def assert_within(measured_all: Dict[str, Dict], budgets: Dict,
                  configs: Optional[Sequence[str]] = None) -> None:
    violations = compare_budgets(measured_all, budgets, configs=configs)
    if violations:
        raise AssertionError(format_violations(violations))


# ---- shared ad-hoc comparison policy (the single tolerance authority for
# ---- the compile-time / cache-count assertions in the test suite) -----------

def check_counter(name: str, actual, expected) -> None:
    """Exact counter equality (cache hits, fresh compiles, program counts)."""
    if int(actual) != int(expected):
        raise AssertionError(
            f'perfbudget counter {name!r}: measured {actual}, expected exactly '
            f'{expected}')


def check_counter_min(name: str, actual, minimum) -> None:
    """Counter floor (e.g. disk-cache hits must at least cover the programs)."""
    if int(actual) < int(minimum):
        raise AssertionError(
            f'perfbudget counter {name!r}: measured {actual}, expected >= {minimum}')


def check_ratio_max(name: str, value, baseline, max_ratio: float) -> None:
    """`value` must stay under `max_ratio` x `baseline` — the O(1)-cost
    contracts (scanned depth-12 jaxpr < 2x depth-2, accum=8 < 2x accum=2)."""
    if float(value) >= float(max_ratio) * float(baseline):
        raise AssertionError(
            f'perfbudget ratio {name!r}: {value} >= {max_ratio:g} x baseline '
            f'{baseline} (ratio {float(value) / max(float(baseline), 1e-12):.2f})')


def check_ratio_min(name: str, value, baseline, min_ratio: float) -> None:
    """`value` must exceed `min_ratio` x `baseline` — sanity direction checks
    (the unrolled/loop jaxpr must dwarf the scanned one, or the scanned
    measurement itself is broken)."""
    if float(value) <= float(min_ratio) * float(baseline):
        raise AssertionError(
            f'perfbudget ratio {name!r}: {value} <= {min_ratio:g} x baseline '
            f'{baseline} (ratio {float(value) / max(float(baseline), 1e-12):.2f})')


def check_upper(name: str, value, limit, *, unit: str = '') -> None:
    """Plain upper bound with the shared message format (timing budgets)."""
    if float(value) > float(limit):
        raise AssertionError(
            f'perfbudget bound {name!r}: measured {value}{unit} > budget '
            f'{limit}{unit}')
