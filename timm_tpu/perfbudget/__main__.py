"""CLI: probe the matrix and compare (or re-baseline) the budgets.

    python -m timm_tpu.perfbudget                     # compare vs checked-in budgets
    python -m timm_tpu.perfbudget --update-budgets    # re-baseline (the ONLY way
                                                      # to accept an improvement)
    python -m timm_tpu.perfbudget --configs base,fsdp4 --json

The probe matrix needs the forced 8-virtual-CPU-device topology
(`XLA_FLAGS=--xla_force_host_platform_device_count=8`), which MUST be set
before jax is imported — but `python -m timm_tpu.perfbudget` imports the
timm_tpu package (and therefore jax) before this module runs. When the
device count is short, this module re-execs itself once in a subprocess
with the flag exported (guarded by TIMM_TPU_PERFBUDGET_REEXEC so a topology
that still comes up short fails loudly instead of looping).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_REQUIRED_DEVICES = 8
_REEXEC_GUARD = 'TIMM_TPU_PERFBUDGET_REEXEC'


def _maybe_reexec(argv) -> None:
    import jax
    if jax.device_count() >= _REQUIRED_DEVICES or os.environ.get(_REEXEC_GUARD):
        return
    env = dict(os.environ)
    flags = env.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in flags:
        env['XLA_FLAGS'] = (
            flags + f' --xla_force_host_platform_device_count={_REQUIRED_DEVICES}').strip()
    env.setdefault('JAX_PLATFORMS', 'cpu')  # the probe metrics are CPU-provable
    env[_REEXEC_GUARD] = '1'
    raise SystemExit(subprocess.call(
        [sys.executable, '-m', 'timm_tpu.perfbudget'] + list(argv), env=env))


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = argparse.ArgumentParser(prog='python -m timm_tpu.perfbudget')
    parser.add_argument('--update-budgets', action='store_true',
                        help='re-baseline: write the measured metrics as the new '
                             'budget file instead of comparing')
    parser.add_argument('--budgets', default=None, metavar='PATH',
                        help='budget file (default: tests/fixtures/perf_budgets.json, '
                             'env TIMM_TPU_PERF_BUDGETS)')
    parser.add_argument('--configs', default='', metavar='A,B',
                        help='comma-separated subset of the probe matrix')
    parser.add_argument('--json', action='store_true',
                        help='print measured metrics + violations as JSON')
    parser.add_argument('--note', default='', help='note recorded on --update-budgets')
    args = parser.parse_args(argv)

    _maybe_reexec(argv)

    from . import budgets as B
    from .probe import run_matrix

    names = [n.strip() for n in args.configs.split(',') if n.strip()] or None
    measured = run_matrix(names=names,
                          log=lambda m: print(m, file=sys.stderr, flush=True))

    if args.update_budgets:
        doc = B.update_budgets(measured, path=args.budgets, note=args.note)
        path = args.budgets or B.BUDGETS_PATH
        print(f'perfbudget: re-baselined {len(doc["configs"])} config(s) -> {path}')
        if args.json:
            print(json.dumps(doc, indent=1))
        return 0

    budgets = B.load_budgets(args.budgets)
    violations = B.compare_budgets(measured, budgets, configs=names)
    if args.json:
        print(json.dumps({'measured': measured, 'violations': violations}, indent=1))
    print(B.format_violations(violations))
    return 1 if violations else 0


if __name__ == '__main__':
    raise SystemExit(main())
