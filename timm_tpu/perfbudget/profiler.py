"""`jax.profiler.trace` harness + self-parsed op-category summary.

PERF.md checklist item 6 ("capture one profiler trace per config, check MXU
utilization") previously needed a human with TensorBoard. This module makes
it unattended: wrap one step in :func:`profile_step`, which dumps the
standard trace directory (still TensorBoard/XProf-loadable for the human
deep-dive later) AND parses the perfetto trace itself into a compact
summary: MXU-class time (dot/conv ops) vs everything else, top ops by
self-time, total event count.

Parsing notes (verified against this jax version's CPU traces; the format
is the device-agnostic perfetto JSON):
  * XLA op execution events land on device tracks whose thread name carries
    the backend marker (``tf_XLAEigen/...`` on CPU, TPU op tracks on
    device); python frames land on a thread literally named ``python``;
    compile/codegen events (``backend_compile``, ``TfrtCpuClient::Compile``)
    land on client threads.
  * Op events are complete events (``ph == 'X'``) with microsecond ``dur``
    and HLO-shaped names (``dot.3``, ``fusion.12``). We keep only
    op-shaped names on non-python threads, preferring recognized device
    tracks when present, so compile noise never pollutes the op summary.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import time
from typing import Dict, List, Optional, Sequence

__all__ = ['profile_step', 'parse_trace', 'summarize_events', 'latest_trace_file']

# HLO op prefixes that execute on the MXU (matrix unit) — the utilization
# question the checklist item actually asks
_MXU_PREFIXES = ('dot', 'conv', 'cudnn-conv', 'custom-call-conv')
# lowercase-but-not-an-op event names seen on non-device threads
_NAME_DENYLIST = ('backend_compile', 'compile', 'codegen', 'thread_name',
                  'process_name', 'program_interpreter')


def latest_trace_file(trace_dir: str) -> Optional[str]:
    """Newest perfetto trace under a `jax.profiler.trace` output dir."""
    pats = (os.path.join(trace_dir, 'plugins', 'profile', '*', '*.trace.json.gz'),
            os.path.join(trace_dir, '**', '*.trace.json.gz'))
    hits: List[str] = []
    for p in pats:
        hits = glob.glob(p, recursive=True)
        if hits:
            break
    return max(hits, key=os.path.getmtime) if hits else None


def _is_op_name(name: str) -> bool:
    if not name or name in _NAME_DENYLIST:
        return False
    if name != name.lower():
        return False
    return not any(ch in name for ch in ('::', '(', ' ', '\n'))


def parse_trace(path: str) -> List[Dict]:
    """Perfetto JSON(.gz) -> [{'name', 'dur_us', 'thread'}] op-event list."""
    opener = gzip.open if path.endswith('.gz') else open
    with opener(path, 'rt') as f:
        doc = json.load(f)
    events = doc.get('traceEvents', doc if isinstance(doc, list) else [])

    threads: Dict[tuple, str] = {}
    for ev in events:
        if ev.get('ph') == 'M' and ev.get('name') == 'thread_name':
            threads[(ev.get('pid'), ev.get('tid'))] = ev.get('args', {}).get('name', '')

    def collect(device_only: bool) -> List[Dict]:
        out = []
        for ev in events:
            if ev.get('ph') != 'X' or 'dur' not in ev:
                continue
            tname = threads.get((ev.get('pid'), ev.get('tid')), '')
            if tname == 'python':
                continue
            if device_only and not any(m in tname for m in ('XLA', 'TPU', 'GPU')):
                continue
            name = ev.get('name', '')
            if not _is_op_name(name):
                continue
            out.append({'name': name, 'dur_us': float(ev['dur']), 'thread': tname})
        return out

    ops = collect(device_only=True)
    # trace format without recognizable device-track names: fall back to the
    # op-name shape filter alone rather than reporting an empty profile
    return ops if ops else collect(device_only=False)


def summarize_events(ops: Sequence[Dict], top_n: int = 10) -> Dict:
    """Op events -> {'mxu_us', 'non_mxu_us', 'mxu_frac', 'top_ops', ...}."""
    mxu = non_mxu = 0.0
    by_op: Dict[str, float] = {}
    for ev in ops:
        base = ev['name'].split('.')[0]
        if base.startswith(_MXU_PREFIXES):
            mxu += ev['dur_us']
        else:
            non_mxu += ev['dur_us']
        by_op[base] = by_op.get(base, 0.0) + ev['dur_us']
    total = mxu + non_mxu
    top = sorted(by_op.items(), key=lambda kv: -kv[1])[:top_n]
    return {
        'total_events': len(ops),
        'mxu_us': round(mxu, 1),
        'non_mxu_us': round(non_mxu, 1),
        'mxu_frac': round(mxu / total, 4) if total else 0.0,
        'top_ops': [{'op': k, 'us': round(v, 1)} for k, v in top],
    }


def profile_step(fn, trace_dir: str, *, steps: int = 1, label: str = 'step') -> Dict:
    """Run `fn()` `steps` times under `jax.profiler.trace` and self-parse the
    resulting perfetto trace. Returns the op-category summary plus where the
    full trace lives (for the TensorBoard deep-dive)."""
    import jax

    os.makedirs(trace_dir, exist_ok=True)
    t0 = time.perf_counter()
    with jax.profiler.trace(trace_dir, create_perfetto_trace=True):
        for _ in range(steps):
            out = fn()
            jax.block_until_ready(out)
    wall_s = time.perf_counter() - t0

    summary: Dict = {'label': label, 'steps': steps,
                     'wall_s': round(wall_s, 3), 'trace_dir': trace_dir}
    path = latest_trace_file(trace_dir)
    if path is None:
        summary.update({'error': 'no perfetto trace produced', 'total_events': 0})
        return summary
    summary['trace_file'] = os.path.relpath(path, trace_dir)
    summary.update(summarize_events(parse_trace(path)))
    return summary
