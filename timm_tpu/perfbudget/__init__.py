"""Hardware-independent perf-regression suite + profiler harness + replay.

Four pieces (see each module's docstring):
  * probe    — lower the real train/serve programs, extract XLA cost
               analysis, jaxpr size, per-device bytes, donation/sharding
               legality for a config matrix;
  * budgets  — checked-in seed budgets + the one tolerance policy (fails on
               regression AND on silent improvement; re-baseline via
               ``python -m timm_tpu.perfbudget --update-budgets``);
  * profiler — `jax.profiler.trace` harness with a self-parsed MXU vs
               non-MXU op summary (`bench.py --profile`);
  * replay   — the PERF.md on-device checklist as one scripted sequence
               writing BENCH_SELF.json (`bench.py --replay [--dry-run]`).

Top-level imports stay lazy-safe: importing this package does not import
jax (bench.py's abort paths use the replay writers pre-jax-setup).
"""
from .budgets import (
    BUDGETS_PATH, TOLERANCES, assert_within, check_counter, check_counter_min,
    check_ratio_max, check_ratio_min, check_upper, compare_budgets, compare_config,
    format_violations, load_budgets, tolerance_for, update_budgets,
)
from .probe import DEFAULT_MATRIX, ProbeConfig, donation_evidence, probe_config, run_matrix
from .profiler import latest_trace_file, parse_trace, profile_step, summarize_events
from .replay import (
    REPLAY_STEPS, SELF_SCHEMA, load_self_doc, record_abort, record_result,
    run_replay, save_self_doc, validate_self_result,
)

__all__ = [
    'BUDGETS_PATH', 'TOLERANCES', 'assert_within', 'check_counter',
    'check_counter_min', 'check_ratio_max', 'check_ratio_min', 'check_upper',
    'compare_budgets', 'compare_config', 'format_violations', 'load_budgets',
    'tolerance_for', 'update_budgets',
    'DEFAULT_MATRIX', 'ProbeConfig', 'donation_evidence', 'probe_config', 'run_matrix',
    'latest_trace_file', 'parse_trace', 'profile_step', 'summarize_events',
    'REPLAY_STEPS', 'SELF_SCHEMA', 'load_self_doc', 'record_abort', 'record_result',
    'run_replay', 'save_self_doc', 'validate_self_result',
]
