"""Unattended replay of the PERF.md "next-round on-device checklist".

Five bench rounds in a row aborted with zero on-device numbers because the
checklist needed a human to type seven command families in order during a
relay window. This module turns the whole queue into ONE scripted sequence:

    python bench.py --replay [--dry-run] [--save-self]

Every step is a REPLAY_STEPS entry with a `dry` spec (tiny models, CPU,
tier-1-smoked every run) and a `live` spec (the real on-device A/B). The two
specs run the IDENTICAL code path — only model size, batch, and step count
differ — so the first live relay window executes a sequence that tier-1 has
already proven end to end. Results stream into BENCH_SELF.json (schema
``bench_self/v2``) after EVERY step, so a relay that dies mid-checklist
still leaves everything measured so far on disk.

This module also owns the BENCH_SELF.json v2 document helpers shared with
bench.py: the v2 file keeps the last good `result` (what `--save-self`
records and the replay fallback reads), a bounded `aborts` history (the
satellite fix: an aborted TPU probe now leaves a structured record instead
of an empty round file), and the latest `replay` run. Top-level imports are
stdlib-only so bench.py's abort paths can use the writers without paying a
jax import.
"""
from __future__ import annotations

import contextlib
import json
import os
import tempfile
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ['REPLAY_STEPS', 'run_replay', 'load_self_doc', 'save_self_doc',
           'record_result', 'record_abort', 'validate_self_result',
           'SELF_SCHEMA']

SELF_SCHEMA = 'bench_self/v2'
_MAX_ABORTS = 20


# ---- BENCH_SELF.json v2 document ------------------------------------------

def load_self_doc(path: str) -> Dict:
    """Load (and, for pre-v2 files, upgrade) the BENCH_SELF document. A
    missing/corrupt file yields a fresh empty document — the abort recorder
    must never itself abort."""
    doc: Dict = {}
    try:
        with open(path) as f:
            doc = json.load(f)
    except Exception:
        doc = {}
    if not isinstance(doc, dict):
        doc = {}
    if doc.get('schema') != SELF_SCHEMA:
        # v1 shape was {'measured_at', 'result'}; carry both forward
        doc = {'schema': SELF_SCHEMA,
               'measured_at': doc.get('measured_at'),
               'result': doc.get('result'),
               'aborts': []}
    doc.setdefault('aborts', [])
    doc.setdefault('result', None)
    return doc


def save_self_doc(path: str, doc: Dict) -> None:
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(doc, f, indent=1)
        f.write('\n')
    os.replace(tmp, path)


def _now() -> str:
    return time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())


def record_result(path: str, result: Dict) -> Dict:
    """`--save-self` success path: record the live measurement, preserving
    abort history and the last replay run."""
    doc = load_self_doc(path)
    doc['measured_at'] = _now()
    doc['result'] = result
    save_self_doc(path, doc)
    return doc


def record_abort(path: str, reason: str, context: Optional[Dict] = None) -> Dict:
    """Satellite fix: an aborted probe/bench appends a structured record
    instead of leaving the round file empty; the last good `result` (if any)
    survives for the replay fallback."""
    doc = load_self_doc(path)
    rec = {'at': _now(), 'reason': reason}
    if context:
        rec.update(context)
    doc['aborts'] = (doc['aborts'] + [rec])[-_MAX_ABORTS:]
    save_self_doc(path, doc)
    return doc


def validate_self_result(doc: Dict) -> List[str]:
    """Schema check for a v2 document; returns a list of problems (empty =
    valid). Used by the tier-1 dry-run smoke so a malformed writer can't
    silently produce an unparseable round file."""
    errs: List[str] = []
    if not isinstance(doc, dict):
        return ['document is not a JSON object']
    if doc.get('schema') != SELF_SCHEMA:
        errs.append(f"schema != {SELF_SCHEMA!r}: {doc.get('schema')!r}")
    if not isinstance(doc.get('aborts', []), list):
        errs.append('aborts is not a list')
    else:
        for i, a in enumerate(doc.get('aborts', [])):
            if not isinstance(a, dict) or 'at' not in a or 'reason' not in a:
                errs.append(f'aborts[{i}] missing at/reason')
    result = doc.get('result')
    if result is not None and (not isinstance(result, dict) or 'value' not in result):
        errs.append('result present but not a bench result object')
    rep = doc.get('replay')
    if rep is not None:
        if not isinstance(rep, dict):
            errs.append('replay is not an object')
        else:
            for key in ('dry_run', 'steps', 'total', 'completed', 'failed'):
                if key not in rep:
                    errs.append(f'replay missing {key!r}')
            for i, s in enumerate(rep.get('steps', []) or []):
                if not isinstance(s, dict) or 'id' not in s or 'status' not in s:
                    errs.append(f'replay.steps[{i}] missing id/status')
                elif s['status'] not in ('ok', 'failed', 'skipped'):
                    errs.append(f"replay.steps[{i}] bad status {s['status']!r}")
    return errs


# ---- the checklist ----------------------------------------------------------
# One entry per PERF.md "next-round on-device checklist" family (`item` is
# the checklist number). `dry` and `live` feed the same runner.

_TINY = {'model': 'test_vit', 'img_size': 32, 'batch': 8,
         'model_kwargs': {'num_classes': 10}}
_VITB = {'model': 'vit_base_patch16_224', 'img_size': 224, 'batch': 128}

REPLAY_STEPS: Tuple[Dict, ...] = (
    dict(id='analysis', item=None, kind='analysis',
         title='static-analysis gate: source/jaxpr/HLO rules + zoo abstract-trace '
               '(a bench round never measures a repo the analyzers reject)',
         dry=dict(tiers=('A',), zoo='smoke'), live=dict()),
    dict(id='family_sweep', item=None, kind='family_sweep',
         title='family coverage sweep: re-derive the checked-in coverage matrix '
               '(abstract trace, stage/block scan, sharded donated step, serve '
               'AOT, device prefetch) and fail on any family that lost a '
               'capability (dry = the tier-1 smoke subset; live = every '
               'deep-eligible family)',
         dry=dict(families='smoke'), live=dict(families='all')),
    dict(id='baseline', item=1, kind='train',
         title='baseline train-step throughput (the --save-self measurement)',
         dry=dict(_TINY), live=dict(_VITB)),
    dict(id='donate_off', item=2, kind='train',
         title='donation A/B: --no-donate arm vs the baseline',
         dry=dict(_TINY, no_donate=True), live=dict(_VITB, no_donate=True)),
    dict(id='pad_auto', item=3, kind='train',
         title='token padding A/B: pad_tokens=auto (next sublane multiple)',
         dry=dict(_TINY, pad_tokens='auto'), live=dict(_VITB, pad_tokens='auto')),
    dict(id='pad_fixed', item=3, kind='train',
         title='token padding A/B: fixed pad (8 dry / 256 live) + masked softmax',
         dry=dict(_TINY, pad_tokens=8), live=dict(_VITB, pad_tokens=256)),
    dict(id='bf16_softmax', item=4, kind='train',
         title='bf16 softmax internals A/B',
         dry=dict(_TINY, softmax_dtype='bfloat16'),
         live=dict(_VITB, softmax_dtype='bfloat16')),
    dict(id='bf16_norm', item=4, kind='train',
         title='bf16 norm statistics A/B',
         dry=dict(_TINY, norm_dtype='bfloat16'),
         live=dict(_VITB, norm_dtype='bfloat16')),
    dict(id='bf16_mu', item=4, kind='train',
         title='bf16 optimizer first-moment A/B',
         dry=dict(_TINY, mu_dtype='bfloat16'), live=dict(_VITB, mu_dtype='bfloat16')),
    dict(id='bf16_all', item=4, kind='train',
         title='all three bf16 compute levers together',
         dry=dict(_TINY, softmax_dtype='bfloat16', norm_dtype='bfloat16',
                  mu_dtype='bfloat16'),
         live=dict(_VITB, softmax_dtype='bfloat16', norm_dtype='bfloat16',
                   mu_dtype='bfloat16')),
    dict(id='flash_gate', item=5, kind='flash',
         title='flash-attention masked-N gate: masked softmax path + kernel '
               'availability (win-at-N>=576-or-delete needs live hardware)',
         dry=dict(model='vit_tiny_patch16_224', img_size=64, batch=2,
                  pad_tokens=256),
         live=dict(model='naflexvit_base_patch16_gap', img_size=224, batch=32,
                   pad_tokens=784, pallas=True)),
    dict(id='profile', item=6, kind='profile',
         title='jax.profiler trace of the train step + MXU/non-MXU op summary',
         dry=dict(_TINY, steps=2), live=dict(_VITB, steps=3)),
    dict(id='grid_8x1', item=7, kind='train',
         title='fsdp x tp grid: (8,1)',
         dry=dict(_TINY, fsdp=8), live=dict(_VITB, batch=1024, fsdp=8)),
    dict(id='grid_4x2', item=7, kind='train',
         title='fsdp x tp grid: (4,2)',
         dry=dict(_TINY, fsdp=4, tp=2), live=dict(_VITB, batch=1024, fsdp=4, tp=2)),
    dict(id='grid_2x4', item=7, kind='train',
         title='fsdp x tp grid: (2,4)',
         dry=dict(_TINY, fsdp=2, tp=4), live=dict(_VITB, batch=1024, fsdp=2, tp=4)),
    dict(id='serve_drill', item=None, kind='serve',
         title='serving drill: continuous batching vs per-request at equal load',
         dry=dict(num_requests=128), live=dict(num_requests=1024)),
    dict(id='quant_serve', item=None, kind='quant_serve',
         title='int8 residency A/B: fp32 vs weight-only int8 under the same '
               'one-model HBM budget (int8 must hold both models, zero evictions)',
         dry=dict(num_requests=96), live=dict(num_requests=1024)),
    dict(id='device_augment', item=None, kind='train',
         title='on-device data path A/B: raw uint8 batch + jitted augment program '
               'fused into the step vs host-prepped floats (baseline step)',
         dry=dict(_TINY, device_augment=True),
         live=dict(_VITB, device_augment=True)),
    dict(id='kernels', item=5, kind='kernels',
         title='kernel portfolio win-or-delete A/B: every registered Pallas '
               'kernel vs its XLA reference at the declared regime shapes '
               '(dry = parity + pending gates on CPU; live = timed verdicts)',
         dry=dict(steps=3), live=dict(steps=20)),
    dict(id='naflex_bucketed', item=5, kind='naflex',
         title='NaFlex packed variable-resolution batches: zero fresh compiles over '
               'the seq-len bucket ladder after warmup (the flash masked-N>=576 '
               'experiment rides the same bucketed shapes)',
         dry=dict(model='test_naflexvit', seq_lens=(16, 25, 36), batch=4),
         live=dict(model='naflexvit_base_patch16_gap', seq_lens=(576, 784, 1024),
                   batch=16, pallas=True)),
    dict(id='autotune', item=None, kind='autotune',
         title='autotune top-K verification: rank the config space analytically, '
               'time the top-K predicted configs\' real steps, and fit the '
               'predicted->measured correction factor (live runs persist it to '
               'BENCH_SELF.json, where autotune.load_correction picks it up)',
         dry=dict(_TINY, global_batch=64, top_k=2, steps=2),
         live=dict(_VITB, global_batch=1024, top_k=3, steps=10)),
    dict(id='multihost', item=None, kind='multihost',
         title='multi-process pod drill: 2-process CPU cluster over '
               'jax.distributed, SIGKILL one host mid-epoch — survivor '
               'consensus + crash-safe manifest commit (dry = kill leg only; '
               'live adds the baseline-parity and elastic-resume legs)',
         dry=dict(processes=2, kill_update=4, compare=False, resume=False,
                  timeout=240),
         live=dict(processes=2, kill_update=4, compare=True, resume=True,
                   timeout=600)),
)


# ---- step runners -----------------------------------------------------------

def _build_tiny_step(spec: Dict):
    """Build a donated (unless no_donate) jitted train step for the spec's
    model/mesh, mirroring bench.py's measurement program. Returns
    (run_one_step, batch_size, meta) where run_one_step() advances the
    carried state and returns the loss."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from flax import nnx

    import timm_tpu
    from ..loss import cross_entropy
    from ..optim import create_optimizer_v2
    from ..parallel import (
        build_opt_shardings, build_param_shardings, create_mesh, set_global_mesh,
        shard_batch,
    )

    fsdp, tp = int(spec.get('fsdp', 0)), int(spec.get('tp', 0))
    if fsdp or tp:
        mesh = create_mesh(fsdp=fsdp or None, tp=tp or None)
    else:
        mesh = create_mesh(devices=jax.devices()[:1])
    set_global_mesh(mesh)

    model_kwargs = dict(spec.get('model_kwargs', {}))
    if spec.get('pad_tokens') is not None:
        model_kwargs['pad_tokens_to'] = spec['pad_tokens']
    model = timm_tpu.create_model(spec['model'], img_size=spec['img_size'],
                                  **model_kwargs)
    if hasattr(model, 'set_block_scan'):
        model.set_block_scan(True)
    model.train()
    opt_kwargs = {'mu_dtype': spec['mu_dtype']} if spec.get('mu_dtype') else {}
    opt = create_optimizer_v2(model, opt='adamw', lr=1e-3, weight_decay=0.05,
                              **opt_kwargs)
    graphdef, params, rest = nnx.split(model, nnx.Param, ...)
    param_sh = build_param_shardings(params, mesh)
    opt_sh, _ = build_opt_shardings(opt, params, mesh)
    params = jax.device_put(params, param_sh)
    opt_state = jax.jit(opt.init, out_shardings=opt_sh)(params)  # no-donate: init

    rng = np.random.RandomState(0)
    n = max(int(spec['batch']), mesh.size)
    s = spec['img_size']
    if spec.get('device_augment'):
        # on-device data path: raw uint8 batch + host-sampled params; the
        # jitted augment program runs fused inside the train step so its
        # per-step cost rides the A/B measurement
        import functools

        from ..data.device_augment import augment_image_batch
        raw = shard_batch({
            'image': jnp.asarray((rng.rand(n, s, s, 3) * 255).astype(np.uint8)),
            'target': jnp.asarray(rng.randint(0, model.num_classes, n)),
            'lam': jnp.asarray(rng.beta(0.8, 0.8, n), jnp.float32),
            'use_cutmix': jnp.zeros((n,), bool),
            'bbox': jnp.zeros((n, 4), jnp.int32)}, mesh)
        aug = functools.partial(augment_image_batch, mean=(0.5,) * 3, std=(0.5,) * 3,
                                num_classes=model.num_classes, smoothing=0.1)

        def batch_loss(m):
            xf, y = aug(raw)
            return -(y * jax.nn.log_softmax(m(xf))).sum(-1).mean()
    else:
        batch = shard_batch(
            {'x': jnp.asarray(rng.rand(n, s, s, 3), jnp.float32),
             't': jnp.asarray(rng.randint(0, model.num_classes, n))}, mesh)
        x, t = batch['x'], batch['t']

        def batch_loss(m):
            return cross_entropy(m(x), t)

    def train_step(p, o):
        def loss_fn(p):
            m = nnx.merge(graphdef, p, rest)
            return batch_loss(m)
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = opt.update(grads, o, p, lr=1e-3)
        return optax.apply_updates(p, updates), o, loss

    donate = () if spec.get('no_donate') else (0, 1)
    jitted = jax.jit(train_step, donate_argnums=donate,
                     in_shardings=(param_sh, opt_sh),
                     out_shardings=(param_sh, opt_sh, None))

    state = {'p': params, 'o': opt_state}

    def run_one_step():
        state['p'], state['o'], loss = jitted(state['p'], state['o'])
        return loss

    meta = {'model': spec['model'], 'batch': n,
            'mesh': 'x'.join(str(mesh.shape[a]) for a in mesh.axis_names),
            'donate': not spec.get('no_donate', False)}
    if spec.get('device_augment'):
        meta['device_augment'] = True
    for knob in ('pad_tokens', 'softmax_dtype', 'norm_dtype', 'mu_dtype'):
        if spec.get(knob) is not None:
            meta[knob] = spec[knob]
    return run_one_step, n, meta


@contextlib.contextmanager
def _precision_context(spec: Dict):
    """softmax/norm dtype policies are process-wide; the `with` form of the
    setters restores the previous value so arms can't leak into each other."""
    from ..layers import set_norm_internal_dtype, set_softmax_dtype
    with contextlib.ExitStack() as stack:
        if spec.get('softmax_dtype'):
            stack.enter_context(set_softmax_dtype(spec['softmax_dtype']))
        if spec.get('norm_dtype'):
            stack.enter_context(set_norm_internal_dtype(spec['norm_dtype']))
        yield


def _run_train(spec: Dict) -> Dict:
    import jax

    need = max(1, int(spec.get('fsdp', 0) or 1) * int(spec.get('tp', 0) or 1))
    if jax.device_count() < need:
        return {'status': 'skipped',
                'reason': f'needs {need} devices, have {jax.device_count()}'}
    with _precision_context(spec):
        run_one_step, n, meta = _build_tiny_step(spec)
        loss = run_one_step()  # warmup: compile + first step
        jax.block_until_ready(loss)
        steps = int(spec.get('steps', 2))
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = run_one_step()
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
    import math
    finite = math.isfinite(float(loss))
    out = dict(meta)
    out.update({'status': 'ok' if finite else 'failed',
                'img_per_s': round(n * steps / dt, 1),
                'steps': steps, 'loss_finite': finite})
    return out


def _run_flash(spec: Dict) -> Dict:
    """Checklist item 5 prerequisite drill: the masked-softmax path the
    N>=576 experiment rides (pad_tokens forces a key-padding mask through
    every attention) runs and stays finite; records whether the opt-in
    Pallas kernel is importable and whether its env gate is set. The
    win-or-delete decision itself needs live hardware."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax import nnx

    import timm_tpu
    from ..parallel import create_mesh, set_global_mesh

    set_global_mesh(create_mesh(devices=jax.devices()[:1]))
    model = timm_tpu.create_model(spec['model'], img_size=spec['img_size'],
                                  pad_tokens_to=spec['pad_tokens'])
    model.eval()
    graphdef, state = nnx.split(model)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(spec['batch'], spec['img_size'], spec['img_size'], 3),
                    jnp.float32)
    out = jax.jit(lambda s, xx: nnx.merge(graphdef, s)(xx))(state, x)
    finite = bool(jnp.isfinite(out).all())
    try:
        from ..kernels import flash_attention  # noqa: F401
        kernel_available = True
    except Exception:
        kernel_available = False
    return {'status': 'ok' if finite else 'failed',
            'model': spec['model'], 'masked_n': spec['pad_tokens'],
            'logits_finite': finite, 'pallas_kernel_importable': kernel_available,
            'pallas_env_gate': os.environ.get('TIMM_TPU_PALLAS_ATTN', ''),
            'live_needs': 'TIMM_TPU_PALLAS_ATTN=1 at masked N in {576, 784, 1024}'}


def _run_profile(spec: Dict, trace_dir: Optional[str]) -> Dict:
    import jax

    from .profiler import profile_step

    run_one_step, _n, meta = _build_tiny_step(spec)
    loss = run_one_step()  # compile outside the trace window
    jax.block_until_ready(loss)
    trace_dir = trace_dir or tempfile.mkdtemp(prefix='timm_tpu_replay_trace_')
    summary = profile_step(run_one_step, trace_dir,
                           steps=int(spec.get('steps', 2)),
                           label=f"train:{spec['model']}")
    summary.update(meta)
    summary['status'] = 'ok' if summary.get('total_events', 0) > 0 else 'failed'
    return summary


def _run_naflex(spec: Dict) -> Dict:
    """ISSUE-10 acceptance drill: donated NaFlex train steps over the declared
    seq-len bucket ladder, with the on-device augment program (normalize +
    token erase) ahead of each step. Epoch 1 warms one program per
    bucket; epoch 2 re-runs every bucket under compile-cache event collection
    and must observe ZERO fresh XLA compiles. The live spec additionally
    records the Pallas flash-attention gate state, since the masked-N>=576
    win-or-delete decision rides these same bucketed shapes."""
    import functools
    import math

    import jax
    import jax.numpy as jnp
    import numpy as np

    import timm_tpu
    from ..data.device_augment import augment_naflex_batch, batch_donate_argnums
    from ..optim import create_optimizer_v2
    from ..parallel import create_mesh, set_global_mesh
    from ..task import NaFlexClassificationTask
    from ..utils.compile_cache import cache_event_total, collect_cache_events

    set_global_mesh(create_mesh(devices=jax.devices()[:1]))
    model = timm_tpu.create_model(spec['model'], **spec.get('model_kwargs', {}))
    p = getattr(model.embeds, 'patch_size', 16)
    model.train()
    opt = create_optimizer_v2(model, opt='adamw', lr=1e-3, weight_decay=0.05)
    task = NaFlexClassificationTask(model, optimizer=opt)

    B = int(spec['batch'])
    buckets = tuple(spec['seq_lens'])
    # batch_donate_argnums: donated on accelerators, not on CPU — a donated
    # augment program deserialized from the persistent compile cache returns
    # corrupted buffers on XLA:CPU (fresh compiles are fine, so the poison
    # only bites the SECOND warm-cache process).
    aug = jax.jit(functools.partial(augment_naflex_batch, mean=(0.5,) * 3,
                                    std=(0.5,) * 3, re_mode='const'),
                  donate_argnums=batch_donate_argnums())

    def make_batch(seq_len, step):
        rng = np.random.RandomState(1000 * seq_len + step)
        gw = max(1, int(math.isqrt(seq_len)))
        gh = seq_len // gw
        n_tok = gh * gw  # natural grid <= bucket: padded slots stay invalid
        yy, xx = np.meshgrid(np.arange(gh), np.arange(gw), indexing='ij')
        patches = np.zeros((B, seq_len, p * p * 3), np.float32)
        coord = np.zeros((B, seq_len, 2), np.int32)
        valid = np.zeros((B, seq_len), bool)
        patches[:, :n_tok] = rng.rand(B, n_tok, p * p * 3)
        coord[:, :n_tok] = np.stack([yy, xx], -1).reshape(n_tok, 2)
        valid[:, :n_tok] = True
        erase = np.zeros((B, seq_len), bool)
        erase[:, :max(1, n_tok // 8)] = True
        return aug({'patches': jnp.asarray(patches),
                    'patch_coord': jnp.asarray(coord),
                    'patch_valid': jnp.asarray(valid),
                    'target': jnp.asarray(rng.randint(0, model.num_classes, B)),
                    'erase_mask': jnp.asarray(erase)})

    losses = []

    def run_epoch():
        for sl in buckets:
            metrics = task.train_step(make_batch(sl, len(losses)), lr=1e-3)
            losses.append(float(metrics['loss']))

    run_epoch()  # warmup epoch: one augment + one step program per bucket
    t0 = time.perf_counter()
    with collect_cache_events() as counts:
        run_epoch()
    dt = time.perf_counter() - t0
    misses = cache_event_total(counts, 'cache_misses')
    hits = cache_event_total(counts, 'cache_hits')
    finite = all(math.isfinite(v) for v in losses)
    out = {'status': 'ok' if (finite and misses == 0) else 'failed',
           'buckets': list(buckets), 'batch': B, 'patch_size': p,
           'warm_epoch_cache_misses': misses, 'warm_epoch_cache_hits': hits,
           'zero_recompile': misses == 0, 'loss_finite': finite,
           'warm_epoch_s': round(dt, 3)}
    if spec.get('pallas'):
        try:
            from ..kernels import flash_attention  # noqa: F401
            out['pallas_kernel_importable'] = True
        except Exception:
            out['pallas_kernel_importable'] = False
        out['pallas_env_gate'] = os.environ.get('TIMM_TPU_PALLAS_ATTN', '')
        out['live_needs'] = 'TIMM_TPU_PALLAS_ATTN=1 at masked N in {576, 784, 1024}'
    return out


def _run_serve(spec: Dict) -> Dict:
    import jax

    from ..parallel import create_mesh, set_global_mesh
    from ..serve import canonical_drill

    # the drill's engines run on a single-device mesh, and activation sharding
    # constraints resolve against the GLOBAL mesh — a leftover (fsdp, tp) mesh
    # from a grid step would poison every bucket program
    set_global_mesh(create_mesh(devices=jax.devices()[:1]))
    try:
        ab = canonical_drill(num_requests=int(spec['num_requests']),
                             persist_all_programs=True)
    except AssertionError as e:
        return {'status': 'failed', 'error': f'drill assertion: {e}'}
    c, b = ab['continuous'], ab['per_request']
    return {'status': 'ok', 'speedup': ab['speedup'],
            'continuous_img_per_s': c['img_per_s'], 'per_request_img_per_s': b['img_per_s'],
            'p50_ms': c['p50_ms'], 'p99_ms': c['p99_ms'],
            'evictions': c['evictions'], 'num_requests': c['num_requests']}


def _run_quant_serve(spec: Dict) -> Dict:
    import jax

    from ..parallel import create_mesh, set_global_mesh
    from ..serve import quant_residency_drill

    set_global_mesh(create_mesh(devices=jax.devices()[:1]))
    try:
        ab = quant_residency_drill(num_requests=int(spec['num_requests']),
                                   persist_all_programs=True)
    except AssertionError as e:
        return {'status': 'failed', 'error': f'drill assertion: {e}'}
    fp32, int8 = ab['fp32'], ab['int8']
    # the acceptance claim, asserted (not just recorded): under a budget that
    # holds ~1.25 fp32 models, the fp32 arm thrashed (3 LRU evictions for the
    # phase-split schedule) while the int8 arm held BOTH models resident with
    # zero evictions and zero failed requests — 2x residency, same budget
    if fp32['evictions'] < 3:
        return {'status': 'failed',
                'error': f"fp32 arm expected >=3 LRU evictions, saw {fp32['evictions']}"}
    return {'status': 'ok',
            'hbm_budget_bytes': ab['hbm_budget_bytes'],
            'fp32_evictions': fp32['evictions'],
            'int8_evictions': int8['evictions'],
            'int8_resident_models': ab['int8_resident'],
            'fp32_img_per_s': fp32['img_per_s'], 'int8_img_per_s': int8['img_per_s'],
            'int8_p99_ms': int8['p99_ms'], 'num_requests': int8['num_requests']}


def _run_kernels(spec: Dict, live: bool) -> Dict:
    """Kernel-portfolio win-or-delete A/B over the registry
    (kernels/harness.py). Parity always runs; on hardware a kernel did not
    claim (dry CPU arm for the TPU-only portfolio) its verdict is 'pending'
    — the gate settles on the first live relay window. A 'delete' verdict
    (parity failure, or a timed loss on claimed hardware) fails the step:
    the checklist refuses to carry a losing kernel forward."""
    from ..kernels.harness import format_verdict_line, run_kernel_ab

    verdicts = run_kernel_ab(live=live, steps=int(spec.get('steps', 5)))
    deletes = [r['kernel'] for r in verdicts if r['verdict'] == 'delete']
    return {'status': 'failed' if deletes else 'ok',
            'kernels': len(verdicts), 'delete': deletes,
            'verdicts': verdicts,
            'verdict_lines': [format_verdict_line(r) for r in verdicts]}


def _run_analysis(spec: Dict) -> Dict:
    """Static-analysis gate (timm_tpu/analysis) as a checklist step. The dry
    arm runs the Tier A source rules plus the zoo smoke subset (cheap, no
    probe lowering — tier-1 smokes it every run); the live arm runs EVERY
    rule, including the jaxpr/HLO passes over the freshly lowered probe
    programs. Any violation or analyzer error fails the step: the checklist
    refuses to measure a repo the analyzers reject."""
    from ..analysis import AnalysisContext, get, run_analysis, select
    from ..analysis.zoo import SMOKE_FAMILIES

    tiers = spec.get('tiers')
    rules = select(tiers=list(tiers) if tiers else None)
    zoo_families = None
    if spec.get('zoo') == 'smoke':
        rules = rules + [get('zoo-abstract-trace')]
        zoo_families = SMOKE_FAMILIES
    report = run_analysis(AnalysisContext(zoo_families=zoo_families), rules)
    return {'status': 'ok' if report.exit_code == 0 else 'failed',
            'exit_code': report.exit_code,
            'violations': len(report.violations),
            'waived': len(report.waived),
            'errors': report.errors,
            'rules': {n: r['status'] for n, r in report.rules.items()}}


def _run_autotune(spec: Dict, live: bool) -> Dict:
    """Verify the autotuner's predicted top-K against real step timings.

    Ranks the space analytically (the same zero-lowering tier the elastic
    re-solve uses), times the top-K distinct (fsdp, tp, batch) configs' real
    jitted steps via `_build_tiny_step` (measured global-step time =
    micro-step time x accum), and fits the predicted->measured correction
    factor as the geomean of the K ratios. Live runs hand the fitted factor
    back for persistence into BENCH_SELF.json ('_autotune_doc'); dry runs
    exercise the full path but never persist — a CPU-fitted factor must not
    leak into real solver runs."""
    import math
    import time as _time

    import jax

    from ..autotune import autotune

    model_kwargs = dict(spec.get('model_kwargs', {}))
    top_k = int(spec.get('top_k', 3))
    result = autotune(
        spec['model'], dict(model_kwargs, img_size=spec['img_size']),
        global_batch=int(spec['global_batch']),
        probe_anchor=False, correction=1.0,
        allow_remat=False, include_block_scan=False)

    # dedupe scan/remat variants: the timed step is always scanned, no remat
    chosen, seen = [], set()
    for rp in result.ranked:
        key = (rp.point.config.fsdp, rp.point.config.tp,
               rp.point.config.batch_size)
        if key not in seen:
            seen.add(key)
            chosen.append(rp)
        if len(chosen) >= top_k:
            break

    measured = []
    for rp in chosen:
        cfg = rp.point.config
        run_one_step, _n, _meta = _build_tiny_step(dict(
            spec, batch=cfg.batch_size, fsdp=cfg.fsdp if cfg.fsdp > 1 else 0,
            tp=cfg.tp if cfg.tp > 1 else 0))
        jax.block_until_ready(run_one_step())   # compile + warm
        t0 = _time.perf_counter()
        for _ in range(int(spec.get('steps', 3))):
            loss = run_one_step()
        jax.block_until_ready(loss)
        micro_ms = (_time.perf_counter() - t0) * 1e3 / int(spec.get('steps', 3))
        measured.append({'config': cfg.label(),
                         'predicted_ms': round(rp.cost.step_ms, 4),
                         'measured_ms': round(micro_ms * cfg.grad_accum, 4)})

    ratios = [m['measured_ms'] / m['predicted_ms'] for m in measured
              if m['predicted_ms'] > 0 and m['measured_ms'] > 0]
    correction = math.exp(sum(math.log(r) for r in ratios) / len(ratios)) \
        if ratios else 1.0
    by_measured = sorted(range(len(measured)),
                         key=lambda i: measured[i]['measured_ms'])
    out: Dict = {
        'tier': result.tier,
        'candidates': len(result.ranked),
        'top_k': [m['config'] for m in measured],
        'measured': measured,
        'winner_confirmed': bool(by_measured and by_measured[0] == 0),
        'correction': round(correction, 4),
    }
    if live:
        out['_autotune_doc'] = {'correction': out['correction'],
                                'fitted_at': _now(),
                                'model': spec['model'],
                                'global_batch': int(spec['global_batch']),
                                'measured': measured}
    return out


def _run_multihost(spec: Dict) -> Dict:
    """Run the host-loss kill drill (timm_tpu.resilience.multihost) as a bench
    step: real 2-process cluster bring-up, SIGKILL mid-epoch, survivor KV
    consensus, crash-safe manifest commit. A failed check fails the step."""
    import shutil
    import tempfile

    from ..resilience.multihost import run_kill_drill

    workdir = spec.get('workdir') or tempfile.mkdtemp(prefix='bench_multihost_')
    result = run_kill_drill(
        workdir,
        processes=int(spec.get('processes', 2)),
        kill_update=int(spec.get('kill_update', 4)),
        compare=bool(spec.get('compare', False)),
        resume=bool(spec.get('resume', False)),
        timeout=float(spec.get('timeout', 420)))
    if not result['ok']:
        failed = sorted(k for k, v in result['checks'].items() if not v)
        raise RuntimeError(
            f'kill drill failed checks {failed} (logs kept in {workdir})')
    if not spec.get('workdir'):
        shutil.rmtree(workdir, ignore_errors=True)
    return {'checks': result['checks'], 'details': result['details']}


def _run_family_sweep(spec: Dict) -> Dict:
    """Re-derive the family coverage matrix and diff it against the checked-in
    fixture (analysis/coverage.py). Any family whose measured capabilities
    drifted from tests/fixtures/coverage_matrix.json — a capability lost OR a
    new one left unpinned — fails the step, so a bench round never reports
    numbers for machinery the matrix says no longer works."""
    from ..analysis.coverage import (
        SMOKE_COVERAGE_FAMILIES, diff_matrix, family_coverage, load_matrix,
    )

    families = None
    if spec.get('families') == 'smoke':
        families = list(SMOKE_COVERAGE_FAMILIES)
    rows = family_coverage(families=families)
    problems = diff_matrix(load_matrix()['families'], rows)
    if problems:
        raise RuntimeError('coverage matrix drift:\n' + '\n'.join(problems))
    deep = [m for m, r in rows.items() if r['deep']]
    return {'families': len(rows), 'deep': len(deep),
            'green': sum(1 for m in deep
                         if rows[m]['sharded_donated_step'] and rows[m]['serve_aot']),
            'scan_capable': sum(1 for r in rows.values()
                                if r['stage_or_block_scan'])}


def _run_step(step: Dict, dry_run: bool, trace_dir: Optional[str]) -> Dict:
    spec = step['dry'] if dry_run else step['live']
    if step['kind'] == 'analysis':
        return _run_analysis(spec)
    if step['kind'] == 'family_sweep':
        return _run_family_sweep(spec)
    if step['kind'] == 'train':
        return _run_train(spec)
    if step['kind'] == 'flash':
        return _run_flash(spec)
    if step['kind'] == 'profile':
        return _run_profile(spec, trace_dir)
    if step['kind'] == 'serve':
        return _run_serve(spec)
    if step['kind'] == 'quant_serve':
        return _run_quant_serve(spec)
    if step['kind'] == 'naflex':
        return _run_naflex(spec)
    if step['kind'] == 'kernels':
        return _run_kernels(spec, live=not dry_run)
    if step['kind'] == 'autotune':
        return _run_autotune(spec, live=not dry_run)
    if step['kind'] == 'multihost':
        return _run_multihost(spec)
    raise ValueError(f"unknown replay step kind {step['kind']!r}")


def run_replay(dry_run: bool = True, self_path: Optional[str] = None,
               names: Optional[Sequence[str]] = None,
               trace_dir: Optional[str] = None, log=None) -> Tuple[Dict, int]:
    """Execute the checklist (all steps, or the `names` subset) and persist
    the run into BENCH_SELF.json after EVERY step. Returns (replay_doc,
    exit_code); exit_code is 0 iff no step failed."""
    from ..parallel import mesh as mesh_mod

    steps = list(REPLAY_STEPS)
    if names is not None:
        wanted = set(names)
        unknown = wanted - {s['id'] for s in steps}
        if unknown:
            raise ValueError(f'unknown replay step(s): {sorted(unknown)}')
        steps = [s for s in steps if s['id'] in wanted]

    replay_doc: Dict = {'dry_run': bool(dry_run), 'started_at': _now(),
                        'steps': [], 'total': len(steps),
                        'completed': 0, 'failed': 0, 'skipped': 0}
    autotune_doc: Dict = {}

    def persist():
        if self_path:
            doc = load_self_doc(self_path)
            doc['replay'] = replay_doc
            if autotune_doc:
                # the live autotune step's fitted correction factor —
                # autotune.load_correction reads it on every later solve
                doc['autotune'] = autotune_doc
            save_self_doc(self_path, doc)

    persist()
    saved_mesh = mesh_mod.peek_global_mesh()
    try:
        for step in steps:
            t0 = time.perf_counter()
            rec: Dict = {'id': step['id'], 'item': step['item'], 'title': step['title']}
            try:
                result = _run_step(step, dry_run, trace_dir)
                autotune_doc.update(result.pop('_autotune_doc', {}))
                rec['status'] = result.pop('status', 'ok')
                key = 'reason' if rec['status'] == 'skipped' else 'result'
                rec[key] = result.get('reason') if rec['status'] == 'skipped' else result
            except Exception as e:
                rec['status'] = 'failed'
                rec['error'] = f'{type(e).__name__}: {e}'
            rec['wall_s'] = round(time.perf_counter() - t0, 2)
            replay_doc['steps'].append(rec)
            replay_doc['completed' if rec['status'] == 'ok' else
                       ('skipped' if rec['status'] == 'skipped' else 'failed')] += 1
            persist()
            if log is not None:
                log(f"replay {step['id']} [{rec['status']}] {rec['wall_s']}s")
    finally:
        mesh_mod._GLOBAL_MESH = saved_mesh
    replay_doc['finished_at'] = _now()
    persist()
    return replay_doc, (0 if replay_doc['failed'] == 0 else 2)
