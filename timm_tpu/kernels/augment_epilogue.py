"""Fused device-augment epilogue (Pallas).

The PR-9 `DeviceAugment` program (data/device_augment.py
`augment_image_batch`) is pure streaming: uint8 -> [0,1] float -> erase ->
mixup -> normalize -> cast. XLA executes it as several HBM passes over the
(B, H, W, C) canvas — the float upcast, each erase `where`, the lam blend +
cutmix paste (which also re-reads the flipped batch), and the normalize each
stream the full image. This kernel runs the whole epilogue per image in one
grid step: block b DMAs its own uint8 row AND the batch-flipped row (the
mixup partner, via a reversed index map — the flipped row is erased with
*its* boxes, exactly like the reference where `x_flip = erased[::-1]`),
applies erase/mix/normalize in VMEM, and writes the normalized out_dtype
image once.

Layout: (B, H, W, C) is viewed as (B, H, W*C) so the minor axis is dense;
a lane's pixel-x coordinate is `lane // C`, and the per-channel mean/std/
erase-fill vectors are baked in as W-tiled compile-time rows. Identity is
encoded in values (lam=1, zero boxes) per the device_augment convention, so
one compiled program serves mixup/cutmix/erase/no-op batches alike.

Scope (the declared regime, see the registry entry): 'const' erase mode
only. 'pixel' mode needs a full random canvas (not one-pass by nature) and
'rand' carries per-box fills; both fall back to the XLA program in
`augment_image_batch_fused`, as does any future mask form the kernel does
not mirror. The numpy oracle `augment_image_batch_np` remains the source of
truth; the XLA program is the A/B reference arm.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .registry import KernelCase, KernelSpec, register

__all__ = ['augment_epilogue', 'augment_image_batch_fused',
           'augment_epilogue_supported']


def _interpret() -> bool:
    return jax.default_backend() != 'tpu'


def augment_epilogue_supported(batch, re_mode: str = 'const') -> bool:
    """The fused kernel mirrors the 'const'-erase epilogue only; 'pixel'
    noise canvases and 'rand' per-box fills stay on the XLA program."""
    return re_mode == 'const' and 'erase_fill' not in batch


def _epilogue_kernel(lam_ref, cut_ref, bbox_ref, eb_ref, ebf_ref,
                     mean_ref, std_ref, fill_ref,
                     img_ref, flip_ref, o_ref, *,
                     channels: int, erase_k: int):
    # blocks: img/flip/o (1, H, W*C); scalars per image in SMEM; mean/std/
    # fill are W-tiled (1, W*C) rows shared by every grid step.
    h, wc = o_ref.shape[1], o_ref.shape[2]
    x = img_ref[0].astype(jnp.float32) / 255.0
    xf = flip_ref[0].astype(jnp.float32) / 255.0
    row = jax.lax.broadcasted_iota(jnp.int32, (h, wc), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (h, wc), 1) // channels
    if erase_k:
        fill = fill_ref[...]
        for k in range(erase_k):
            top, left, eh, ew = (eb_ref[0, k, j] for j in range(4))
            ins = (row >= top) & (row < top + eh) & (col >= left) & (col < left + ew)
            x = jnp.where(ins, fill, x)
            # the mixup partner is the ERASED flipped row -> its own boxes
            top, left, eh, ew = (ebf_ref[0, k, j] for j in range(4))
            ins = (row >= top) & (row < top + eh) & (col >= left) & (col < left + ew)
            xf = jnp.where(ins, fill, xf)
    lam = lam_ref[0, 0]
    mixed = x * lam + xf * (1.0 - lam)
    yl, yh, xl, xh = (bbox_ref[0, j] for j in range(4))
    ins = (row >= yl) & (row < yh) & (col >= xl) & (col < xh)
    cut = jnp.where(ins, xf, x)
    x = jnp.where(cut_ref[0, 0] != 0, cut, mixed)
    x = (x - mean_ref[...]) / std_ref[...]
    o_ref[0] = x.astype(o_ref.dtype)


def augment_epilogue(image, lam, use_cutmix, bbox, erase_box, *,
                     mean, std, re_mean, out_dtype=jnp.float32):
    """One-pass epilogue over (B, H, W, C) uint8 `image`. Per-image params:
    `lam` (B,) f32, `use_cutmix` (B,) bool/int, `bbox` (B, 4) and
    `erase_box` (B, K, 4) int (zero boxes are no-ops)."""
    b, h, w, c = image.shape
    k = int(erase_box.shape[1]) if erase_box.size else 0
    img2 = image.reshape(b, h, w * c)
    lam2 = jnp.asarray(lam, jnp.float32).reshape(b, 1)
    cut2 = jnp.asarray(use_cutmix, jnp.int32).reshape(b, 1)
    bbox2 = jnp.asarray(bbox, jnp.int32).reshape(b, 4)
    if k:
        eb2 = jnp.asarray(erase_box, jnp.int32).reshape(b, k, 4)
    else:
        eb2 = jnp.zeros((b, 1, 4), jnp.int32)

    mean_row = jnp.asarray(np.tile(np.asarray(mean, np.float32), w))[None]
    std_row = jnp.asarray(np.tile(np.asarray(std, np.float32), w))[None]
    fill_row = jnp.asarray(np.tile(np.asarray(re_mean, np.float32), w))[None]

    smem = functools.partial(pl.BlockSpec, memory_space=pltpu.SMEM)
    row_spec = pl.BlockSpec((1, w * c), lambda i: (0, 0))
    kern = functools.partial(_epilogue_kernel, channels=c, erase_k=k)
    out = pl.pallas_call(
        kern,
        grid=(b,),
        in_specs=[
            smem((1, 1), lambda i: (i, 0)),                       # lam
            smem((1, 1), lambda i: (i, 0)),                       # use_cutmix
            smem((1, 4), lambda i: (i, 0)),                       # cutmix bbox
            smem((1, max(k, 1), 4), lambda i: (i, 0, 0)),         # erase boxes
            smem((1, max(k, 1), 4), lambda i: (b - 1 - i, 0, 0)),  # flipped row's
            row_spec,                                             # mean (W-tiled)
            row_spec,                                             # std
            row_spec,                                             # erase fill
            pl.BlockSpec((1, h, w * c), lambda i: (i, 0, 0)),     # image row
            pl.BlockSpec((1, h, w * c), lambda i: (b - 1 - i, 0, 0)),  # mix partner
        ],
        out_specs=pl.BlockSpec((1, h, w * c), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, w * c), out_dtype),
        interpret=_interpret(),
    )(lam2, cut2, bbox2, eb2, eb2, mean_row, std_row, fill_row, img2, img2)
    return out.reshape(b, h, w, c)


def augment_image_batch_fused(batch, *, mean, std, re_mode='const',
                              re_mean=(0.0, 0.0, 0.0), re_std=(1.0, 1.0, 1.0),
                              noise_seed=42, num_classes=0, smoothing=0.0,
                              out_dtype=jnp.float32):
    """Drop-in twin of `augment_image_batch` that routes the image epilogue
    through the fused kernel when the batch is in regime; target math (tiny)
    and out-of-regime erase modes stay on the XLA program."""
    from ..data.device_augment import augment_image_batch, mixup_targets

    if not augment_epilogue_supported(batch, re_mode):
        return augment_image_batch(
            batch, mean=mean, std=std, re_mode=re_mode, re_mean=re_mean,
            re_std=re_std, noise_seed=noise_seed, num_classes=num_classes,
            smoothing=smoothing, out_dtype=out_dtype)
    img = batch['image']
    b = img.shape[0]
    has_mix = 'lam' in batch
    x = augment_epilogue(
        img,
        batch.get('lam', jnp.ones((b,), jnp.float32)),
        batch.get('use_cutmix', jnp.zeros((b,), jnp.int32)),
        batch.get('bbox', jnp.zeros((b, 4), jnp.int32)),
        batch.get('erase_box', jnp.zeros((b, 0, 4), jnp.int32)),
        mean=mean, std=std, re_mean=re_mean, out_dtype=out_dtype)
    if has_mix:
        y = mixup_targets(batch['target'], batch['lam'], num_classes, smoothing)
    else:
        y = batch['target']
    return x, y


# ---------------------------------------------------------------------------
# registry entry


def _make_inputs(seed: int = 0, batch: int = 8, size: int = 32,
                 erase_k: int = 1, with_mix: bool = True,
                 with_erase: bool = True, num_classes: int = 10):
    rng = np.random.default_rng(seed)
    b, h = batch, size
    out = {
        'image': jnp.asarray(rng.integers(0, 256, (b, h, h, 3)), jnp.uint8),
        'target': jnp.asarray(rng.integers(0, num_classes, (b,)), jnp.int32),
    }
    if with_erase:
        boxes = np.zeros((b, erase_k, 4), np.int32)
        for i in range(b):
            for kk in range(erase_k):
                eh, ew = rng.integers(4, h // 2, 2)
                boxes[i, kk] = (rng.integers(0, h - eh), rng.integers(0, h - ew),
                                eh, ew)
        out['erase_box'] = jnp.asarray(boxes)
    if with_mix:
        yl = rng.integers(0, h // 2, (b,))
        xl = rng.integers(0, h // 2, (b,))
        out['lam'] = jnp.asarray(rng.uniform(0.2, 1.0, (b,)), jnp.float32)
        out['use_cutmix'] = jnp.asarray(rng.integers(0, 2, (b,)), bool)
        out['bbox'] = jnp.asarray(
            np.stack([yl, yl + h // 4, xl, xl + h // 4], 1), jnp.int32)
    return {'batch': out}


_STATICS = dict(mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225),
                re_mean=(0.485, 0.456, 0.406), num_classes=10, smoothing=0.1)


def _reference(batch, **statics):
    from ..data.device_augment import augment_image_batch
    return augment_image_batch(batch, **statics)


register(KernelSpec(
    name='augment_epilogue',
    module=__name__,
    regime="DeviceAugment 'const'-erase epilogue at loader batch shapes "
           '(e.g. 128x224x224x3 uint8): pure streaming that XLA runs as '
           'several full-canvas HBM passes, fused here to one read of the '
           'image + its mixup partner and one normalized write',
    gate='win wall-clock vs the jitted XLA augment program at the live '
         'loader shape on TPU — or delete (the XLA program stays for '
         "'pixel'/'rand' modes either way)",
    parity_tol=1e-6,
    kernel_fn=augment_image_batch_fused,
    reference_fn=_reference,
    make_inputs=_make_inputs,
    cases=(
        KernelCase(
            name='mix_erase',
            dry=dict(batch=8, size=32, erase_k=1),
            live=dict(batch=128, size=224, erase_k=1),
            statics=dict(_STATICS),
            desc='mixup/cutmix + const erase + normalize, the full epilogue',
        ),
        KernelCase(
            name='no_mix',
            dry=dict(batch=8, size=32, with_mix=False),
            live=dict(batch=128, size=224, with_mix=False),
            statics=dict(_STATICS),
            desc='identity-mix regime (eval-style erase+normalize only)',
        ),
    ),
    backends=('tpu',),
))
