# no-kernel-registry: infrastructure module — consumes the registry, not a kernel
"""Win-or-delete harness over the kernel registry.

Three consumers, one spec table (registry.py):

1. **Parity** — `parity_check` runs kernel vs reference at a case's `dry`
   shapes with BOTH arms jitted (on non-TPU backends the kernel arm lowers
   via ``pallas_call(interpret=True)``). Jitting both arms matters: XLA
   normalizes bf16 arithmetic to f32 compute, so an eager reference would
   round intermediates the compiled train step never rounds.
   tests/test_kernels.py parametrizes over `parity_cases()` — that's the
   auto-generated per-kernel parity test.

2. **Budgets** — `lower_case` lowers both arms and reports jaxpr eqn counts
   plus the bytes story: analytic one-pass `io_bytes` for the kernel arm
   (registry.default_io_bytes — interpret-mode cost_analysis numbers are
   emulation artifacts, so we budget the HBM contract instead) vs the
   compiled reference's ``cost_analysis()['bytes accessed']``. The
   perfbudget `kernels` probe pins these per kernel.

3. **Verdicts** — `ab_verdict` produces the keep/delete/pending line for
   `bench.py --kernels` and the replay `kernels` step: parity failure is an
   immediate `delete` (a wrong kernel loses regardless of speed); on a
   backend outside the spec's declared `backends` the verdict is `pending`
   (the first healthy relay window on real hardware settles it); otherwise
   the kernel must win wall-clock at EVERY declared regime case or it is
   `delete`.
"""
from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional, Tuple

from . import registry
from .registry import KernelCase, KernelSpec, default_io_bytes

__all__ = ['parity_cases', 'parity_check', 'lower_case', 'kernel_metrics',
           'ab_case', 'ab_verdict', 'run_kernel_ab', 'format_verdict_line']


def _jit_arm(fn, statics: Dict):
    """Jit an arm over the inputs pytree; `statics` are partial-bound python
    values (dtypes, masks, coefficients), never traced."""
    import jax
    bound = functools.partial(fn, **statics)
    return jax.jit(lambda kw: bound(**kw))


def parity_cases() -> List[Tuple[KernelSpec, KernelCase]]:
    """Every (spec, case) pair in the registry — the parametrization grid
    for the auto-generated parity tests."""
    return [(spec, case) for spec in registry.all_specs() for case in spec.cases]


def parity_check(spec: KernelSpec, case: KernelCase, seed: int = 0) -> Dict:
    """Max abs error between jitted kernel and jitted reference at the
    case's dry shapes, leaf-for-leaf over the output pytree."""
    import jax
    import jax.numpy as jnp

    inputs = spec.make_inputs(seed=seed, **case.dry)
    out_k = _jit_arm(spec.kernel_fn, case.statics)(inputs)
    out_r = _jit_arm(spec.reference_fn, case.statics)(inputs)
    leaves_k, leaves_r = jax.tree.leaves(out_k), jax.tree.leaves(out_r)
    assert len(leaves_k) == len(leaves_r), (
        f'{spec.name}/{case.name}: kernel and reference output pytrees '
        f'disagree ({len(leaves_k)} vs {len(leaves_r)} leaves)')
    err = 0.0
    for a, b in zip(leaves_k, leaves_r):
        d = jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))
        err = max(err, float(d))
    return {'kernel': spec.name, 'case': case.name, 'max_abs_err': err,
            'tol': spec.parity_tol, 'ok': err <= spec.parity_tol}


def lower_case(spec: KernelSpec, case: KernelCase, seed: int = 0) -> Dict:
    """Lower both arms at the case's dry shapes; return the budgetable
    numbers (all deterministic on a fixed jax/XLA version)."""
    import jax

    from ..utils.compile_cache import count_jaxpr_eqns

    inputs = spec.make_inputs(seed=seed, **case.dry)
    fk = _jit_arm(spec.kernel_fn, case.statics)
    fr = _jit_arm(spec.reference_fn, case.statics)
    eqns_k = count_jaxpr_eqns(jax.make_jaxpr(fk)(inputs).jaxpr)
    eqns_r = count_jaxpr_eqns(jax.make_jaxpr(fr)(inputs).jaxpr)
    cost = fr.lower(inputs).compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    ref_bytes = int(cost.get('bytes accessed', 0))
    io = default_io_bytes(spec, case, inputs=inputs)
    return {
        'kernel': spec.name,
        'case': case.name,
        'kernel_eqns': int(eqns_k),
        'ref_eqns': int(eqns_r),
        'io_bytes': int(io),
        'ref_bytes_accessed': ref_bytes,
        'wins_bytes': bool(io < ref_bytes),
    }


def kernel_metrics(seed: int = 0) -> Dict[str, object]:
    """Flat metrics dict for the perfbudget `kernels` probe: per kernel the
    first declared case is the budget anchor."""
    metrics: Dict[str, object] = {'kernels_registered': len(registry.all_specs())}
    for spec in registry.all_specs():
        m = lower_case(spec, spec.cases[0], seed=seed)
        metrics[f'{spec.name}_eqns'] = m['kernel_eqns']
        metrics[f'{spec.name}_ref_eqns'] = m['ref_eqns']
        metrics[f'{spec.name}_io_bytes'] = m['io_bytes']
        metrics[f'{spec.name}_ref_bytes_accessed'] = m['ref_bytes_accessed']
        metrics[f'{spec.name}_wins_bytes'] = m['wins_bytes']
    return metrics


def _best_ms(fn, inputs, steps: int) -> float:
    import jax
    jax.block_until_ready(fn(inputs))  # warmup / compile
    best = float('inf')
    for _ in range(max(1, steps)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(inputs))
        best = min(best, (time.perf_counter() - t0) * 1e3)
    return best


def ab_case(spec: KernelSpec, case: KernelCase, *, live: bool = False,
            steps: int = 5, seed: int = 0) -> Dict:
    """Time kernel vs reference at one regime case (dry or live arm)."""
    inputs = spec.make_inputs(seed=seed, **(case.live if live else case.dry))
    fk = _jit_arm(spec.kernel_fn, case.statics)
    fr = _jit_arm(spec.reference_fn, case.statics)
    tk = _best_ms(fk, inputs, steps)
    tr = _best_ms(fr, inputs, steps)
    return {'case': case.name, 'arm': 'live' if live else 'dry',
            'kernel_ms': round(tk, 4), 'ref_ms': round(tr, 4),
            'win': bool(tk < tr)}


def ab_verdict(spec: KernelSpec, *, live: bool = False, steps: int = 5,
               seed: int = 0) -> Dict:
    """The keep/delete/pending record for one kernel."""
    import jax

    backend = jax.default_backend()
    rec: Dict = {
        'kernel': spec.name,
        'regime': spec.regime,
        'gate': spec.gate,
        'backend': backend,
        'backends_claimed': list(spec.backends),
    }
    parity = [parity_check(spec, case, seed=seed) for case in spec.cases]
    rec['parity_max_err'] = max(p['max_abs_err'] for p in parity)
    rec['parity_tol'] = spec.parity_tol
    rec['parity_ok'] = all(p['ok'] for p in parity)
    if not rec['parity_ok']:
        rec['verdict'] = 'delete'
        rec['reason'] = (f'parity failure: max err {rec["parity_max_err"]:.3g} '
                         f'> tol {spec.parity_tol:.3g} — wrong beats slow')
        return rec
    if backend not in spec.backends:
        rec['verdict'] = 'pending'
        rec['reason'] = (f'regime claims {"/".join(spec.backends)}; this run is '
                         f'on {backend} (parity only) — first healthy relay '
                         f'window on claimed hardware settles the gate')
        return rec
    rec['cases'] = [ab_case(spec, case, live=live, steps=steps, seed=seed)
                    for case in spec.cases]
    wins = all(c['win'] for c in rec['cases'])
    rec['verdict'] = 'keep' if wins else 'delete'
    lost = [c['case'] for c in rec['cases'] if not c['win']]
    rec['reason'] = ('wins wall-clock at every declared regime case' if wins
                     else f'loses to the XLA reference at: {", ".join(lost)}')
    return rec


def run_kernel_ab(*, live: bool = False, steps: int = 5,
                  seed: int = 0) -> List[Dict]:
    """One verdict record per registered kernel (sorted by name)."""
    return [ab_verdict(spec, live=live, steps=steps, seed=seed)
            for spec in registry.all_specs()]


def format_verdict_line(rec: Dict) -> str:
    return (f"kernel {rec['kernel']}: {rec['verdict'].upper()} "
            f"[parity {rec['parity_max_err']:.2e} <= {rec['parity_tol']:.0e}: "
            f"{'ok' if rec['parity_ok'] else 'FAIL'}] — {rec['reason']}")
