# no-kernel-registry: package init — re-exports, no kernel defined here
"""TPU Pallas kernels for hot ops, behind a win-or-delete registry.

Every kernel module here registers a `KernelSpec` (registry.py): a declared
regime (the shapes/dtypes/mask pattern where it claims to beat XLA), a
reference XLA implementation, and a parity tolerance. harness.py turns those
specs into the auto-generated CPU-interpreter parity tests, the perfbudget
`kernels` probe, and the `bench.py --kernels` keep/delete verdicts; an
unregistered kernel module fails the lint in tests/test_kernels.py.

Portfolio:
- `flash_attention` — fused attention behind `use_fused_attn()` dispatch
  (layers/attention.py); gate: win at masked N>=576 or delete.
- `fused_adamw` — one-HBM-pass AdamW+EMA update, the opt-in
  `TrainingTask(fused_update=True)` path; optax stays default + oracle.
- `augment_epilogue` — one-pass uint8->erase->mix->normalize epilogue for
  the PR-9 `DeviceAugment` program ('const' erase regime).
"""
from .flash_attention import flash_attention, flash_attention_supported
from .fused_adamw import fused_adamw_apply, fused_adamw_step
from .augment_epilogue import augment_epilogue_supported, augment_image_batch_fused
from .registry import KernelCase, KernelSpec, all_specs, ensure_registered

__all__ = [
    'flash_attention', 'flash_attention_supported',
    'fused_adamw_apply', 'fused_adamw_step',
    'augment_epilogue_supported', 'augment_image_batch_fused',
    'KernelCase', 'KernelSpec', 'all_specs', 'ensure_registered',
]
