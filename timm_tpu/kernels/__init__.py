"""TPU Pallas kernels for hot ops.

`flash_attention` is the Pallas fused-attention kernel used behind the
`use_fused_attn()` config switch (see timm_tpu/layers/attention.py).
"""
from .flash_attention import flash_attention, flash_attention_supported
