# no-kernel-registry: infrastructure module — the registry itself, not a kernel
"""Kernel registry: every Pallas kernel declares its win regime as DATA.

SNIPPETS.md [3]'s pjit premise is that the compiler owns layout, so a
hand-written kernel is guilty until proven innocent: it must carry (a) a
**reference XLA implementation** (the parity oracle AND the A/B baseline it
has to beat), (b) a **declared regime** — the concrete shapes/dtypes/mask
pattern where it claims to win, split into a `dry` arm (tiny, CPU-interpret,
tier-1-smoked) and a `live` arm (the claimed shapes, decided on hardware) —
and (c) a **parity tolerance**. harness.py consumes these specs to
auto-generate the per-kernel parity test, the perfbudget `kernels` probe
metrics, and the `bench.py --kernels` keep/delete verdict lines; an
unregistered kernel module cannot land (tests/test_kernels.py lint).

Kernel modules register themselves at import time; `ensure_registered()`
imports the portfolio so registry consumers never observe a half-populated
table.
"""
from __future__ import annotations

import dataclasses
import importlib
from typing import Callable, Dict, Optional, Sequence, Tuple

__all__ = ['KernelCase', 'KernelSpec', 'register', 'unregister', 'get',
           'all_specs', 'kernel_names', 'ensure_registered', 'default_io_bytes']

# modules whose import populates the registry (the portfolio)
_PORTFOLIO = ('flash_attention', 'fused_adamw', 'augment_epilogue')


@dataclasses.dataclass(frozen=True)
class KernelCase:
    """One point of a kernel's declared regime. `dry` / `live` are kwargs for
    the spec's `make_inputs` — same runner, different scale (the replay dry/
    live pattern): dry is tiny and CPU-provable, live is the claimed shape
    the hardware A/B decides on. `statics` are forwarded to BOTH the kernel
    and the reference (compile-time config: dtypes, masks, coefficients)."""
    name: str
    dry: Dict = dataclasses.field(default_factory=dict)
    live: Dict = dataclasses.field(default_factory=dict)
    statics: Dict = dataclasses.field(default_factory=dict)
    desc: str = ''


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """A registered kernel: implementation + oracle + executable win claim.

    `kernel_fn` / `reference_fn` share one signature: ``fn(**inputs,
    **case.statics)`` where `inputs = make_inputs(seed=..., **case.dry)`
    (or `.live`). Outputs may be a single array or a pytree; parity compares
    them leaf-for-leaf. `backends` scopes where the win claim is decidable —
    off those backends the harness emits a `pending` verdict (parity still
    measured, via `pallas_call(interpret=True)`)."""
    name: str
    module: str                      # python module the lint checks off
    regime: str                      # prose: where the kernel claims to win
    gate: str                        # the win-or-delete sentence
    parity_tol: float
    kernel_fn: Callable
    reference_fn: Callable
    make_inputs: Callable            # (seed=0, **case_kwargs) -> {name: array}
    cases: Tuple[KernelCase, ...]
    backends: Tuple[str, ...] = ('tpu',)

    def __post_init__(self):
        if not self.cases:
            raise ValueError(f'kernel {self.name!r}: declared regime is empty '
                             '(at least one KernelCase required)')
        if not (self.parity_tol > 0):
            raise ValueError(f'kernel {self.name!r}: parity_tol must be > 0')


_REGISTRY: Dict[str, KernelSpec] = {}


def register(spec: KernelSpec) -> KernelSpec:
    if spec.name in _REGISTRY:
        raise ValueError(f'kernel {spec.name!r} already registered')
    _REGISTRY[spec.name] = spec
    return spec


def unregister(name: str) -> None:
    _REGISTRY.pop(name, None)


def ensure_registered() -> None:
    """Import the portfolio modules (idempotent) so every kernel's
    import-time registration has run before the registry is consumed."""
    for mod in _PORTFOLIO:
        importlib.import_module(f'{__package__}.{mod}')


def get(name: str) -> KernelSpec:
    ensure_registered()
    if name not in _REGISTRY:
        raise KeyError(f'kernel {name!r} not registered '
                       f'(have: {sorted(_REGISTRY)})')
    return _REGISTRY[name]


def all_specs() -> Tuple[KernelSpec, ...]:
    ensure_registered()
    return tuple(_REGISTRY[name] for name in sorted(_REGISTRY))


def kernel_names() -> Tuple[str, ...]:
    ensure_registered()
    return tuple(sorted(_REGISTRY))


def default_io_bytes(spec: KernelSpec, case: KernelCase,
                     inputs: Optional[Dict] = None, seed: int = 0) -> int:
    """Analytic one-pass HBM bytes of a kernel invocation: every input
    operand read once + every output written once. For a Pallas kernel this
    IS the HBM traffic contract (each grid block is DMA'd HBM->VMEM exactly
    once; intermediates live in VMEM) — the number the XLA arm's pre-fusion
    ``cost_analysis()['bytes accessed']`` is compared against in the
    perfbudget `kernels` probe."""
    import jax

    if inputs is None:
        inputs = spec.make_inputs(seed=seed, **case.dry)
    total = sum(int(leaf.nbytes) for leaf in jax.tree.leaves(inputs))
    out = jax.eval_shape(lambda kw: spec.reference_fn(**kw, **case.statics), inputs)
    total += sum(int(leaf.size) * leaf.dtype.itemsize for leaf in jax.tree.leaves(out))
    return total
