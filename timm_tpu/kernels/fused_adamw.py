"""One-HBM-pass fused AdamW + EMA update (Pallas).

PERF.md §2 item 3: the unfused optax step streams the fp32 optimizer state
through HBM several times per update — scale_by_adam reads (g, m, v) and
writes (m, v, u), add_decayed_weights re-reads p, apply_updates reads p and
writes p, and the EMA pass re-reads p and rewrites ema. This kernel does the
whole thing in ONE pass over (p, g, m, v, ema) tiles: each 8 KiB-lane block
is DMA'd HBM->VMEM once, the full AdamW + weight-decay + EMA arithmetic runs
in VMEM, and (p', m', v', ema') stream back out through the same buffers
(``input_output_aliases`` — the donation story of the surrounding jitted
train step is unchanged).

Parity contract: the math below mirrors optax 0.2.3's
``adamw = scale_by_adam -> add_decayed_weights(mask) -> scale_by_lr`` chain
*operation for operation*, including the weak-type promotion that makes
``b1 * mu`` a bfloat16 multiply when ``mu_dtype=bfloat16`` and the
f32-before-cast bias-corrected numerator. tests/test_kernels.py holds a
5-step end-to-end drift of ≤1e-6 against the default optax TrainingTask
path; the optax path stays the default and the parity oracle.

Two entry points:
- ``fused_adamw_apply`` — raw-tree functional core (what the registry A/Bs),
- ``fused_adamw_step`` — opt_state-aware wrapper used by TrainingTask's
  opt-in ``fused_update=True`` path: finds the single ScaleByAdamState
  inside the inject_hyperparams chain and replaces it functionally, so the
  opt_state pytree structure (and therefore PR-5 sharding specs and
  donation) is untouched.
"""
from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .registry import KernelCase, KernelSpec, register

__all__ = ['fused_adamw_apply', 'fused_adamw_step', 'unfused_adamw_reference']

_LANES = 128      # TPU lane width
_SUBLANE = 16     # bf16-safe second-minor multiple
_BLOCK_ROWS = 512  # 512x128 fp32 = 256 KiB per operand; 6 operands < 2 MiB VMEM


def _interpret() -> bool:
    return jax.default_backend() != 'tpu'


def _kernel(scal_ref, p_ref, g_ref, m_ref, v_ref, e_ref,
            op_ref, om_ref, ov_ref, oe_ref, *,
            b1: float, b2: float, eps: float, wd: float, has_ema: bool):
    # scal = [lr, 1-b1**t, 1-b2**t, ema_decay] in SMEM (fp32)
    lr = scal_ref[0, 0]
    bc1 = scal_ref[0, 1]
    bc2 = scal_ref[0, 2]
    g = g_ref[...]
    p = p_ref[...]
    # scale_by_adam: update_moment / update_moment_per_elem_norm. The stored
    # mu may be bfloat16; writing optax's expression verbatim reproduces its
    # weak-type promotion (b1 * mu stays in mu's dtype, the add promotes).
    m_new = (1 - b1) * g + b1 * m_ref[...]
    v_new = (1 - b2) * (g * g) + b2 * v_ref[...]
    # bias_correction divides the *pre-cast* (promoted fp32) moments
    m_hat = m_new / bc1
    v_hat = v_new / bc2
    u = m_hat / (jnp.sqrt(v_hat) + eps)
    if wd:  # add_decayed_weights (wd = 0.0 on masked-off leaves)
        u = u + wd * p
    # scale_by_learning_rate(lr) then apply_updates: p + (-lr) * u
    p_new = p + (-lr) * u
    op_ref[...] = p_new
    om_ref[...] = m_new.astype(om_ref.dtype)
    ov_ref[...] = v_new
    if has_ema:
        d = scal_ref[0, 3]
        e32 = e_ref[...].astype(jnp.float32)
        oe_ref[...] = (e32 * d + p_new.astype(jnp.float32) * (1 - d)).astype(oe_ref.dtype)
    else:
        oe_ref[...] = e_ref[...]


def _pad_rows(n: int) -> int:
    rows = -(-n // _LANES)
    rows = -(-rows // _SUBLANE) * _SUBLANE
    if rows > _BLOCK_ROWS:
        rows = -(-rows // _BLOCK_ROWS) * _BLOCK_ROWS
    return rows


def _tile(x: jax.Array, rows: int) -> jax.Array:
    flat = x.reshape(-1)
    pad = rows * _LANES - flat.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(rows, _LANES)


def _untile(t: jax.Array, shape, dtype) -> jax.Array:
    n = int(np.prod(shape)) if shape else 1
    return t.reshape(-1)[:n].reshape(shape).astype(dtype)


def _leaf_update(p, g, m, v, e, scal, *, b1, b2, eps, wd, has_ema):
    """Run the fused kernel over one (padded, row-tiled) parameter leaf.
    Padded tail elements are inert: g=m=v=0 there gives u = 0/(sqrt(0)+eps)
    = 0, so the pad never contaminates real lanes."""
    rows = _pad_rows(max(1, p.size))
    block = min(rows, _BLOCK_ROWS)
    grid = (rows // block,)
    tiles = [_tile(a, rows) for a in (p, g, m, v)]
    tiles.append(_tile(e, rows) if e is not None else jnp.zeros_like(tiles[0]))
    bspec = pl.BlockSpec((block, _LANES), lambda i: (i, 0))
    kern = functools.partial(_kernel, b1=b1, b2=b2, eps=eps, wd=wd,
                             has_ema=has_ema and e is not None)
    out = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + [bspec] * 5,
        out_specs=[bspec] * 4,
        out_shape=[
            jax.ShapeDtypeStruct((rows, _LANES), p.dtype),
            jax.ShapeDtypeStruct((rows, _LANES), m.dtype),
            jax.ShapeDtypeStruct((rows, _LANES), v.dtype),
            jax.ShapeDtypeStruct((rows, _LANES), (e.dtype if e is not None else p.dtype)),
        ],
        # one pass, in place: p/m/v/ema stream back through their own buffers
        input_output_aliases={1: 0, 3: 1, 4: 2, 5: 3},
        interpret=_interpret(),
    )(scal, *tiles)
    p_new = _untile(out[0], p.shape, p.dtype)
    m_new = _untile(out[1], m.shape, m.dtype)
    v_new = _untile(out[2], v.shape, v.dtype)
    e_new = _untile(out[3], e.shape, e.dtype) if e is not None else None
    return p_new, m_new, v_new, e_new


def fused_adamw_apply(params, grads, mu, nu, ema, count, lr, ema_decay, *,
                      b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                      weight_decay: float = 0.0, mu_dtype=None, wd_mask=None):
    """Raw-tree fused update. Returns (new_params, new_mu, new_nu, new_ema);
    `count` is the PRE-increment step counter (optax convention). `ema` may
    be None. `wd_mask` is a boolean pytree matching `params` (the
    param_groups_weight_decay mask); masked-off leaves skip weight decay."""
    del mu_dtype  # stored mu dtype already encodes it; kernel honors ref dtypes
    count_inc = optax.safe_int32_increment(count)
    # bias corrections written exactly as optax.bias_correction computes them
    # (python-float decay ** int32 count, weak-typed f32 result)
    scal = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(1 - b1 ** count_inc, jnp.float32),
        jnp.asarray(1 - b2 ** count_inc, jnp.float32),
        jnp.asarray(ema_decay if ema_decay is not None else 0.0, jnp.float32),
    ]).reshape(1, 4)

    p_leaves, treedef = jax.tree.flatten(params)
    g_leaves = treedef.flatten_up_to(grads)
    m_leaves = treedef.flatten_up_to(mu)
    v_leaves = treedef.flatten_up_to(nu)
    e_leaves = treedef.flatten_up_to(ema) if ema is not None else [None] * len(p_leaves)
    if wd_mask is not None:
        mask_leaves = treedef.flatten_up_to(wd_mask)
    else:
        mask_leaves = [True] * len(p_leaves)

    outs = [
        _leaf_update(p, g, m, v, e, scal,
                     b1=b1, b2=b2, eps=eps,
                     wd=(weight_decay if mk else 0.0),
                     has_ema=ema is not None)
        for p, g, m, v, e, mk in zip(p_leaves, g_leaves, m_leaves,
                                     v_leaves, e_leaves, mask_leaves)
    ]
    new_params = jax.tree.unflatten(treedef, [o[0] for o in outs])
    new_mu = jax.tree.unflatten(treedef, [o[1] for o in outs])
    new_nu = jax.tree.unflatten(treedef, [o[2] for o in outs])
    new_ema = (jax.tree.unflatten(treedef, [o[3] for o in outs])
               if ema is not None else None)
    return new_params, new_mu, new_nu, new_ema


def unfused_adamw_reference(params, grads, mu, nu, ema, count, lr, ema_decay, *,
                            b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                            weight_decay: float = 0.0, mu_dtype=None, wd_mask=None):
    """The XLA baseline the kernel must beat: literally the unfused optax
    chain (scale_by_adam -> masked add_decayed_weights -> scale_by_lr ->
    apply_updates) plus the separate EMA pass. Also the parity oracle."""
    adam = optax.scale_by_adam(b1=b1, b2=b2, eps=eps, mu_dtype=mu_dtype)
    updates, new_state = adam.update(
        grads, optax.ScaleByAdamState(count=count, mu=mu, nu=nu))
    if weight_decay:
        wd_tx = optax.add_decayed_weights(weight_decay)
        if wd_mask is not None:
            wd_tx = optax.masked(wd_tx, wd_mask)
        updates, _ = wd_tx.update(updates, wd_tx.init(params), params)
    updates = jax.tree.map(lambda u: (-lr) * u, updates)
    new_params = optax.apply_updates(params, updates)
    if ema is not None:
        from ..utils.model_ema import ema_update
        new_ema = ema_update(ema, new_params, ema_decay)
    else:
        new_ema = None
    return new_params, new_state.mu, new_state.nu, new_ema


# ---------------------------------------------------------------------------
# opt_state surgery for TrainingTask


def _is_adam_state(s) -> bool:
    return hasattr(s, 'mu') and hasattr(s, 'nu') and hasattr(s, 'count')


def _find_adam_states(state) -> list:
    found = []
    if _is_adam_state(state):
        return [state]
    if hasattr(state, '_fields'):
        for f in state._fields:
            found.extend(_find_adam_states(getattr(state, f)))
    elif isinstance(state, (tuple, list)):
        for s in state:
            found.extend(_find_adam_states(s))
    elif isinstance(state, dict):
        for s in state.values():
            found.extend(_find_adam_states(s))
    return found


def validate_fused_opt_state(opt_state) -> None:
    """Raise unless `opt_state` contains exactly one ScaleByAdamState — the
    shape produced by the plain adamw chain fused_adamw mirrors."""
    n = len(_find_adam_states(opt_state))
    if n != 1:
        raise ValueError(
            f'fused_update=True requires a plain adamw optimizer chain with '
            f'exactly one ScaleByAdamState in its opt_state (found {n}); '
            f'lookahead/caution/layer-decay wrappers change the update math '
            f'and are not mirrored by the fused kernel')


def _rebuild_state(state, new_adam, lr):
    """Functionally rebuild opt_state with the adam state replaced, the
    inject_hyperparams counter advanced, and learning_rate refreshed —
    structure-preserving, so shardings and donation aliases are untouched."""
    if _is_adam_state(state):
        return new_adam
    if hasattr(state, '_fields'):
        vals = {f: _rebuild_state(getattr(state, f), new_adam, lr)
                for f in state._fields}
        if 'hyperparams' in vals and isinstance(vals['hyperparams'], dict):
            if 'count' in vals:
                vals['count'] = optax.safe_int32_increment(getattr(state, 'count'))
            hp = dict(vals['hyperparams'])
            if 'learning_rate' in hp and lr is not None:
                hp['learning_rate'] = jnp.asarray(lr, hp['learning_rate'].dtype)
            vals['hyperparams'] = hp
        return type(state)(**vals)
    if isinstance(state, tuple):
        return tuple(_rebuild_state(s, new_adam, lr) for s in state)
    if isinstance(state, list):
        return [_rebuild_state(s, new_adam, lr) for s in state]
    if isinstance(state, dict):
        return {k: _rebuild_state(v, new_adam, lr) for k, v in state.items()}
    return state


def fused_adamw_step(params, grads, opt_state, ema_params, *, lr, ema_decay,
                     b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                     weight_decay: float = 0.0, mu_dtype=None, wd_mask=None):
    """Drop-in replacement for `optimizer.update + optax.apply_updates
    (+ ema_update)` inside the donated train step. Returns
    (new_params, new_opt_state, new_ema) with new_ema None when
    ema_params is None."""
    adam = _find_adam_states(opt_state)
    if len(adam) != 1:
        raise ValueError('fused_adamw_step: expected exactly one '
                         f'ScaleByAdamState in opt_state, found {len(adam)}')
    adam = adam[0]
    new_params, new_mu, new_nu, new_ema = fused_adamw_apply(
        params, grads, adam.mu, adam.nu, ema_params, adam.count, lr, ema_decay,
        b1=b1, b2=b2, eps=eps, weight_decay=weight_decay, mu_dtype=mu_dtype,
        wd_mask=wd_mask)
    new_adam = optax.ScaleByAdamState(
        count=optax.safe_int32_increment(adam.count), mu=new_mu, nu=new_nu)
    new_opt_state = _rebuild_state(opt_state, new_adam, lr)
    return new_params, new_opt_state, new_ema


# ---------------------------------------------------------------------------
# registry entry


def _make_inputs(seed: int = 0, sizes=((64, 256), (256,), (8, 8, 32)),
                 step: int = 3, mu_dtype=None, with_ema: bool = True):
    rng = np.random.default_rng(seed)

    def tree(scale, dtype=np.float32):
        return {f'leaf{i}': jnp.asarray(rng.standard_normal(s) * scale, dtype)
                for i, s in enumerate(sizes)}

    mu = tree(0.01)
    if mu_dtype is not None:
        mu = jax.tree.map(lambda x: x.astype(mu_dtype), mu)
    nu = jax.tree.map(lambda x: jnp.abs(x) * 1e-3, tree(0.1))
    return dict(
        params=tree(1.0),
        grads=tree(0.1),
        mu=mu,
        nu=nu,
        ema=tree(1.0) if with_ema else None,
        count=jnp.asarray(step, jnp.int32),
        lr=jnp.asarray(0.02, jnp.float32),
        ema_decay=jnp.asarray(0.999, jnp.float32),
    )


register(KernelSpec(
    name='fused_adamw',
    module=__name__,
    regime='fp32 AdamW(+EMA) state at ViT scale: the update is pure HBM '
           'streaming (PERF.md §2 item 3, ~2.08 GB/step at ViT-S/16), so one '
           'fused pass over (p, g, m, v, ema) vs the ~4-pass unfused chain',
    gate='win wall-clock on the live ViT-scale leaf set on TPU, with the '
         'one-pass io-bytes reduction pinned as a perfbudget band — or delete',
    parity_tol=1e-6,
    kernel_fn=fused_adamw_apply,
    reference_fn=unfused_adamw_reference,
    make_inputs=_make_inputs,
    cases=(
        KernelCase(
            name='fp32',
            dry=dict(sizes=((64, 256), (256,), (8, 8, 32))),
            live=dict(sizes=((1024, 4096), (4096, 1024), (1024, 1024),
                             (1024,), (197, 1024))),
            statics=dict(weight_decay=0.05),
            desc='fp32 moments, decayed + undecayed leaf mix',
        ),
        KernelCase(
            name='mu_bf16',
            dry=dict(sizes=((64, 256), (256,)), mu_dtype='bfloat16'),
            live=dict(sizes=((1024, 4096), (4096, 1024), (1024, 1024)),
                      mu_dtype='bfloat16'),
            statics=dict(weight_decay=0.05, mu_dtype=jnp.bfloat16),
            desc='TIMM_TPU_MU_DTYPE=bfloat16 first-moment storage',
        ),
    ),
    backends=('tpu',),
))
