"""Pallas TPU flash attention (placeholder dispatch until kernel lands).

The real kernel is task #10; this module keeps the dispatch contract stable:
`flash_attention_supported(q, k, v, mask)` gates the call site.
"""
from __future__ import annotations

import jax


def flash_attention_supported(q, k, v, mask=None) -> bool:
    return False


def flash_attention(q, k, v, mask=None, scale=None):
    raise NotImplementedError('Pallas flash attention kernel not yet available')
