"""Pallas TPU flash attention.

Dispatch status (PERF.md "Kernel portfolio & win-or-delete harness",
originally VERDICT r3 weak #4): the kernel is **opt-in only** —
`TIMM_TPU_PALLAS_ATTN=1` — because the plain einsum+softmax graph that XLA
fuses beat it at every unmasked image-model shape measured on v5e (ViT-B/16
train: 867 einsum vs 786 XLA-fused vs 573 Pallas img/s/chip). The deletion
gate — **win at masked N≥576** (NaFlex key-padding shapes, where the XLA
path must materialize a masked N² fp32 tensor this kernel never builds)
**or be deleted** — is no longer prose: it is the registry entry at the
bottom of this file, whose masked 576/784/1024 regime cases `bench.py
--kernels` times against the `_sdpa` reference to emit the keep/delete
verdict. The tile-aligned token-padding path (vision_transformer.py
`pad_tokens_to`) threads exactly that key-padding mask here, which is the
prerequisite for running the gate experiment on live hardware.

Forward: blocked online-softmax kernel — Q blocks on the grid, KV chunks in a
fori_loop, running (max, denom, acc) carried functionally. Supports an
optional *key-padding* bool mask (the NaFlex case, reference
naflexvit.py:972-1040): (B, N) or (B, 1, 1, N), True = valid key. Any other
mask form (additive float masks, per-query 2D attention masks) raises — the
kernel would silently ignore the non-key-padding structure otherwise; those
forms stay on the XLA path in timm_tpu/layers/attention.py.

Backward: custom_vjp recomputes attention with plain XLA ops — exact same
math, N x N materialized only in the bwd pass (fine at image-model sequence
lengths); the fwd pass never materializes the score matrix.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _supported_backend() -> bool:
    try:
        return jax.default_backend() == 'tpu'
    except Exception:
        return False


def flash_attention_supported(q, k, v, mask=None) -> bool:
    """Gate for the dispatch in layers/attention.py.

    Benchmarked on v5e: plain einsum+softmax (which XLA fuses) is the default
    for N<=1024 and jax.nn.dot_product_attention above that — both beat this
    kernel at every unmasked image-model shape tested (ViT-B/16 train: 867
    einsum vs 786 XLA-fused vs 573 Pallas img/s/chip). Recorded decision
    (PERF.md): the kernel stays explicit opt-in (TIMM_TPU_PALLAS_ATTN=1);
    the keep-or-delete experiment is masked N≥576 (NaFlex / token-padding
    key-padding masks) on live hardware — if it does not win there, it is
    deleted.
    """
    import os
    if os.environ.get('TIMM_TPU_PALLAS_ATTN', '0') != '1':
        return False
    if not _supported_backend():
        return False
    if q.ndim != 4:
        return False
    B, H, N, D = q.shape
    if D > 256 or k.shape != q.shape or v.shape != q.shape:
        return False  # MHA only (no MQA/GQA yet), head dim within one lane tile
    if N < 128:
        return False  # too small to beat the fused XLA path
    if mask is not None:
        if mask.dtype != jnp.bool_:
            return False
        # key-padding masks only: (B, N), (B, 1, 1, N)
        if mask.shape not in ((B, N), (B, 1, 1, N)):
            return False
    return True


def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, scale: float, block_k: int, kv_len: int):
    # refs: q (BQ, D), k (N, D), v (N, D), mask (1, N) bool, o (BQ, D)
    # matmul inputs stay in the source dtype (bf16 on the fast path) with fp32
    # accumulation — halves MXU input bandwidth vs upcasting.
    q = q_ref[0, 0] * jnp.asarray(scale, q_ref.dtype)
    bq = q.shape[0]
    d = q.shape[1]
    num_k_blocks = kv_len // block_k

    def body(i, carry):
        acc, m_i, l_i = carry
        k_chunk = k_ref[0, 0, pl.ds(i * block_k, block_k), :]
        v_chunk = v_ref[0, 0, pl.ds(i * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_chunk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)  # (BQ, BK)
        kmask = mask_ref[0, 0, pl.ds(i * block_k, block_k)]
        s = jnp.where(kmask[None, :], s, -1e30)
        m_new = jnp.maximum(m_i, s.max(axis=-1))
        alpha = jnp.exp(m_i - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_i * alpha + p.sum(axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_chunk.dtype), v_chunk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), -1e30, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m_i, l_i = jax.lax.fori_loop(0, num_k_blocks, body, (acc0, m0, l0))
    o_ref[0, 0] = (acc / jnp.maximum(l_i, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_fwd_impl(q, k, v, key_mask, scale: float, block_q: int = 256, block_k: int = 512):
    B, H, N, D = q.shape
    Nk = k.shape[2]
    block_q = min(block_q, max(128, 1 << (N - 1).bit_length()))
    block_q = min(block_q, N) if N % 128 == 0 else min(block_q, 256)
    block_k = min(block_k, max(128, 1 << (Nk - 1).bit_length()))

    # pad sequence dims to block multiples; padded keys masked out
    pad_q = (-N) % block_q
    pad_k = (-Nk) % block_k
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0))) if pad_q else q
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0))) if pad_k else k
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0))) if pad_k else v
    if key_mask is None:
        key_mask = jnp.ones((B, Nk), jnp.bool_)
    km = jnp.pad(key_mask, ((0, 0), (0, pad_k)), constant_values=False) if pad_k else key_mask
    km = km[:, None, :]  # (B, 1, Nkp) so the block's trailing dims satisfy tiling

    Np, Nkp = N + pad_q, Nk + pad_k
    grid = (B, H, Np // block_q)
    kernel = functools.partial(_fwd_kernel, scale=scale, block_k=block_k, kv_len=Nkp)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, Nkp, D), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, Nkp, D), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, Nkp), lambda b, h, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Np, D), q.dtype),
        interpret=jax.default_backend() != 'tpu',  # CPU tests run the kernel interpreted
    )(qp, kp, vp, km)
    if pad_q:
        out = out[:, :, :N]
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flash(q, k, v, key_mask, scale):
    return _flash_fwd_impl(q, k, v, key_mask, scale)


def _flash_fwd_rule(q, k, v, key_mask, scale):
    out = _flash_fwd_impl(q, k, v, key_mask, scale)
    return out, (q, k, v, key_mask)


def _flash_bwd_rule(scale, residuals, g):
    q, k, v, key_mask = residuals
    # exact recompute in fp32 via XLA (N x N lives only here)
    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    s = jnp.einsum('bhqd,bhkd->bhqk', qf, kf)
    if key_mask is not None:
        s = jnp.where(key_mask[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    gf = g.astype(jnp.float32)
    dv = jnp.einsum('bhqk,bhqd->bhkd', p, gf)
    dp = jnp.einsum('bhqd,bhkd->bhqk', gf, vf)
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = jnp.einsum('bhqk,bhkd->bhqd', ds, kf) * scale
    dk = jnp.einsum('bhqk,bhqd->bhkd', ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), None


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, mask=None, scale: Optional[float] = None):
    """(B, H, N, D) fused attention with optional key-padding mask.

    `mask` must be a bool key-padding mask, (B, N) or (B, 1, 1, N) with
    True = valid key. Anything else raises: this kernel only applies
    key-padding structure, and silently flattening a full additive /
    per-query mask into it would produce wrong output.
    """
    scale = float(scale) if scale is not None else q.shape[-1] ** -0.5
    key_mask = None
    if mask is not None:
        B, _, N, _ = q.shape
        Nk = k.shape[2]
        if mask.dtype != jnp.bool_:
            raise ValueError(
                f'flash_attention only supports bool key-padding masks; got dtype {mask.dtype}. '
                'Additive float masks must use the XLA attention path '
                '(timm_tpu.layers.scaled_dot_product_attention with fused=False).')
        if mask.shape not in ((B, Nk), (B, 1, 1, Nk)):
            raise ValueError(
                f'flash_attention only supports key-padding masks of shape {(B, Nk)} or '
                f'{(B, 1, 1, Nk)}; got {mask.shape}. Per-query attention masks would be '
                'silently collapsed to their first query row — use the XLA path instead.')
        key_mask = mask[:, 0, 0, :] if mask.ndim == 4 else mask
    return _flash(q, k, v, key_mask, scale)


# ---------------------------------------------------------------------------
# registry entry: the masked-N>=576-or-delete gate as executable data


def _registry_reference(q, k, v, mask):
    from ..layers.attention import _sdpa
    return _sdpa(q, k, v, attn_mask=mask)


def _registry_kernel(q, k, v, mask):
    return flash_attention(q, k, v, mask=mask)


def _registry_inputs(seed: int = 0, batch: int = 2, heads: int = 2,
                     seq: int = 576, head_dim: int = 64,
                     valid_frac: float = 0.8, dtype: str = 'float32'):
    import numpy as np
    rng = np.random.default_rng(seed)
    shape = (batch, heads, seq, head_dim)
    q, k, v = (jnp.asarray(rng.standard_normal(shape) * 0.5, dtype)
               for _ in range(3))
    # NaFlex-style key padding: a varying valid prefix per batch row
    mask = np.zeros((batch, 1, 1, seq), bool)
    for i in range(batch):
        mask[i, ..., :max(1, int(seq * valid_frac) - 8 * i)] = True
    return dict(q=q, k=k, v=v, mask=jnp.asarray(mask))


def _register():
    from .registry import KernelCase, KernelSpec, register
    register(KernelSpec(
        name='flash_attention',
        module=__name__,
        regime='key-padding-masked attention at NaFlex packed lengths '
               '(N in {576, 784, 1024}, D<=256): the XLA path materializes '
               'a masked N^2 fp32 score tensor this kernel never builds',
        gate='win at masked N>=576 on TPU or be deleted (v5e already showed '
             'XLA winning every unmasked image-model shape)',
        parity_tol=2e-2,
        kernel_fn=_registry_kernel,
        reference_fn=_registry_reference,
        make_inputs=_registry_inputs,
        cases=(
            KernelCase(
                name='masked_n576',
                dry=dict(batch=2, heads=2, seq=576, head_dim=64),
                live=dict(batch=16, heads=12, seq=576, head_dim=64,
                          dtype='bfloat16'),
                desc='NaFlex 384px/16 packed bucket',
            ),
            KernelCase(
                name='masked_n784',
                dry=dict(batch=1, heads=2, seq=784, head_dim=64),
                live=dict(batch=16, heads=12, seq=784, head_dim=64,
                          dtype='bfloat16'),
                desc='NaFlex 448px/16 packed bucket',
            ),
            KernelCase(
                name='masked_n1024',
                dry=dict(batch=1, heads=1, seq=1024, head_dim=64),
                live=dict(batch=16, heads=12, seq=1024, head_dim=64,
                          dtype='bfloat16'),
                desc='NaFlex max packed bucket',
            ),
        ),
        backends=('tpu',),
    ))


_register()
