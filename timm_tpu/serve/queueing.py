"""Continuous-batching admission queue.

Requests arrive one at a time (open-loop traffic); the device steps over
bucket-shaped batches. This queue decouples the two: arrivals append to a
per-model FIFO, and the engine's scheduler asks for the next ADMISSION — a
(model, requests) run that is ready to step. A model's pending run is ready
when any of:

  * it can fill the LARGEST declared bucket (throughput-optimal), or
  * its oldest request's deadline (submit time + max_wait) has expired —
    the run is admitted PARTIAL into the smallest bucket that fits, padded
    with masked slots, so no request ever starves waiting for a full batch, or
  * the queue is draining (shutdown flushes everything immediately).

Among ready models the one whose oldest request has waited longest goes
first (global FIFO fairness across models).
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ['ServeFuture', 'ServeRequest', 'RequestQueue']


class ServeFuture:
    """Completion handle for one submitted request (threading, not asyncio:
    the engine's scheduler is a thread and callers may be WSGI workers)."""

    def __init__(self):
        self._done = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None
        self.done_at: Optional[float] = None

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError('serve request not completed within timeout')
        if self._exc is not None:
            raise self._exc
        return self._result

    def _set_result(self, value):
        self._result = value
        self.done_at = time.perf_counter()
        self._done.set()

    def _set_exception(self, exc: BaseException):
        self._exc = exc
        self.done_at = time.perf_counter()
        self._done.set()


class ServeRequest:
    __slots__ = ('id', 'model', 'image', 'submit_t', 'deadline', 'future')

    def __init__(self, rid: int, model: str, image, submit_t: float, deadline: float):
        self.id = rid
        self.model = model
        self.image = image
        self.submit_t = submit_t
        self.deadline = deadline
        self.future = ServeFuture()


class RequestQueue:
    """Thread-safe admission queue. ``submit`` is called from request
    threads; ``wait_admission`` blocks the scheduler until a run is ready
    (or the timeout/next-deadline passes)."""

    def __init__(self, max_bucket: int, max_wait_s: float = 0.010,
                 max_pending: int = 10_000):
        self.max_bucket = int(max_bucket)
        self.max_wait_s = float(max_wait_s)
        self.max_pending = int(max_pending)
        self._cond = threading.Condition()
        self._pending: 'OrderedDict[str, deque[ServeRequest]]' = OrderedDict()
        self._n_pending = 0
        self._ids = itertools.count()
        self._closed = False
        self._draining = False

    # -- producer side --------------------------------------------------------

    def submit(self, model: str, image, now: Optional[float] = None) -> ServeFuture:
        now = time.perf_counter() if now is None else now
        with self._cond:
            if self._closed:
                raise RuntimeError('serve queue is shut down; no new requests accepted')
            if self._n_pending >= self.max_pending:
                raise RuntimeError(
                    f'serve queue over capacity ({self._n_pending} pending >= '
                    f'max_pending={self.max_pending}); shed load upstream')
            req = ServeRequest(next(self._ids), model, image, now, now + self.max_wait_s)
            self._pending.setdefault(model, deque()).append(req)
            self._n_pending += 1
            self._cond.notify_all()
            return req.future

    # -- scheduler side -------------------------------------------------------

    def __len__(self) -> int:
        with self._cond:
            return self._n_pending

    def pending(self, model: str) -> int:
        with self._cond:
            return len(self._pending.get(model, ()))

    def finished(self) -> bool:
        """True once the queue is closed and fully drained (scheduler exit)."""
        with self._cond:
            return self._closed and self._n_pending == 0

    def _ready_model(self, now: float) -> Optional[str]:
        """Oldest-first among models whose run is ready (locked)."""
        best, best_t = None, None
        for model, q in self._pending.items():
            if not q:
                continue
            head = q[0]
            if self._draining or len(q) >= self.max_bucket or head.deadline <= now:
                if best_t is None or head.submit_t < best_t:
                    best, best_t = model, head.submit_t
        return best

    def _next_deadline(self) -> Optional[float]:
        heads = [q[0].deadline for q in self._pending.values() if q]
        return min(heads) if heads else None

    def wait_admission(self, timeout: Optional[float] = None
                       ) -> Optional[Tuple[str, List[ServeRequest]]]:
        """Block until a run is ready and pop it: up to ``max_bucket``
        requests of one model, oldest model first. Returns None when the
        timeout expires with nothing ready (the engine uses those gaps to
        retire in-flight device steps)."""
        end = None if timeout is None else time.perf_counter() + timeout
        with self._cond:
            while True:
                now = time.perf_counter()
                model = self._ready_model(now)
                if model is not None:
                    q = self._pending[model]
                    take = min(len(q), self.max_bucket)
                    reqs = [q.popleft() for _ in range(take)]
                    self._n_pending -= take
                    return model, reqs
                if self._closed and self._n_pending == 0:
                    return None
                # sleep until a new arrival, the nearest deadline, or timeout
                waits = []
                if end is not None:
                    waits.append(end - now)
                nd = self._next_deadline()
                if nd is not None:
                    waits.append(nd - now)
                if end is not None and now >= end:
                    return None
                self._cond.wait(timeout=min(waits) if waits else None)
                if end is not None and time.perf_counter() >= end and \
                        self._ready_model(time.perf_counter()) is None:
                    return None

    # -- shutdown -------------------------------------------------------------

    def drain(self):
        """Flush: every pending run becomes immediately ready (partial
        buckets allowed) regardless of deadline."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    def close(self, drain: bool = True):
        with self._cond:
            self._closed = True
            self._draining = self._draining or drain
            if not drain:
                failed = [r for q in self._pending.values() for r in q]
                self._pending.clear()
                self._n_pending = 0
            else:
                failed = []
            self._cond.notify_all()
        for r in failed:
            r.future._set_exception(RuntimeError('serve queue shut down without drain'))
