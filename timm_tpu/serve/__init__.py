"""Serving subsystem: continuous batching over AOT-warmed bucketed shapes.

See ``engine.InferenceEngine`` for the engine, ``drill`` for the CPU-runnable
load drill (``bench.py --serve``), and README "Serving" for usage.
"""
from .bucketing import (
    DEFAULT_BUCKETS, batch_bucket, pad_rows, select_bucket, strip_rows,
    validate_buckets,
)
from .drill import canonical_drill, quant_residency_drill, run_load_drill, summary_line
from .engine import InferenceEngine, collect_cache_events
from .queueing import RequestQueue, ServeFuture, ServeRequest
from .residency import ModelPool, ResidentModel

__all__ = [
    'DEFAULT_BUCKETS', 'batch_bucket', 'pad_rows', 'select_bucket',
    'strip_rows', 'validate_buckets',
    'canonical_drill', 'quant_residency_drill', 'run_load_drill', 'summary_line',
    'InferenceEngine', 'collect_cache_events',
    'RequestQueue', 'ServeFuture', 'ServeRequest',
    'ModelPool', 'ResidentModel',
]
