"""Bucketed batch shapes for serving.

XLA compiles one executable per input shape. A service that hands every
arriving batch size to ``jax.jit`` compiles an unbounded family of programs —
the first request of each novel size pays seconds of compile latency, and the
compile cache fills with single-use entries. The serving engine instead
declares a SMALL fixed set of batch buckets up front (e.g. 1/4/16/64/256),
AOT-compiles exactly those shapes at startup, and pads every admitted run up
to the smallest fitting bucket with masked slots whose outputs are stripped
on the host. No shape outside the declared set ever reaches the compiler.

The same helpers fix the last-batch recompile in ``inference.py`` /
``validate.py``: a 10,000-image folder evaluated at batch 256 ends with a
novel 16-row batch that used to trigger a fresh XLA compile for one step —
padding it back up to the 256 bucket reuses the executable every other batch
used.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

__all__ = [
    'DEFAULT_BUCKETS', 'validate_buckets', 'select_bucket', 'batch_bucket',
    'pad_rows', 'strip_rows',
]

# powers-of-4 ladder: at most ~4x padded waste per admitted run, 5 programs
# to AOT-compile per model at startup
DEFAULT_BUCKETS = (1, 4, 16, 64, 256)


def validate_buckets(buckets: Sequence[int], divisor: int = 1) -> Tuple[int, ...]:
    """Normalize a declared bucket set: unique positive ints, ascending.

    ``divisor`` is the mesh batch-shard count — every bucket must divide over
    it or the padded batch could never be sharded (shard_batch would raise at
    serve time; failing at engine construction names the problem instead).
    """
    if not buckets:
        raise ValueError('declared bucket set is empty; serving needs at least one batch bucket')
    out = sorted({int(b) for b in buckets})
    if out[0] <= 0:
        raise ValueError(f'batch buckets must be positive, got {tuple(buckets)}')
    if divisor > 1:
        bad = [b for b in out if b % divisor != 0]
        if bad:
            raise ValueError(
                f'bucket(s) {bad} are not divisible by the mesh batch-shard count '
                f'{divisor}: every bucket shape is sharded over the product of ALL '
                f'mesh axes. Declare buckets that are multiples of {divisor} '
                f'(e.g. {[max(b // divisor, 1) * divisor for b in bad]}).')
    return tuple(out)


def select_bucket(n: int, buckets: Sequence[int]) -> int:
    """The smallest declared bucket that fits ``n`` requests.

    The queue never admits more than the largest bucket in one run, so an
    oversized ``n`` here is a scheduling bug — refused loudly rather than
    silently handed to the compiler as a novel shape.
    """
    if n <= 0:
        raise ValueError(f'cannot bucket a batch of {n} requests')
    for b in buckets:
        if n <= b:
            return int(b)
    raise ValueError(
        f'{n} requests exceed the largest declared bucket {max(buckets)}; '
        f'the admission queue must split runs to at most the largest bucket')


def batch_bucket(batch_size: int, divisor: int = 1) -> int:
    """The single padded batch shape for a fixed-batch-size eval loop:
    ``batch_size`` rounded up to the mesh batch-shard count, so every batch —
    including the final partial one — runs through ONE compiled executable."""
    divisor = max(1, int(divisor))
    return -(-int(batch_size) // divisor) * divisor


def pad_rows(x: np.ndarray, bucket: int, *more) -> Tuple:
    """Pad arrays up to ``bucket`` rows with masked slots.

    Slots are filled by repeating row 0 (finite, in-distribution values — a
    zero image would be the only all-black sample the model ever sees, and
    NaN-poisoned padding would trip the non-finite sentinel in shared code
    paths). Returns ``(x_padded, *more_padded, valid)`` where ``valid`` is a
    bool mask marking real rows; consumers drop padded-slot outputs with
    ``strip_rows`` (or fold ``valid`` into their reduction like validate.py).
    """
    arrays = (x,) + more
    n = int(arrays[0].shape[0])
    if n > bucket:
        raise ValueError(f'batch of {n} rows does not fit bucket {bucket}')
    for a in arrays[1:]:
        if int(a.shape[0]) != n:
            raise ValueError(f'row-count mismatch: {n} vs {a.shape[0]}')
    valid = np.zeros(bucket, bool)
    valid[:n] = True
    if n == bucket:
        return arrays + (valid,)
    out = []
    for a in arrays:
        a = np.asarray(a)
        out.append(np.concatenate([a, np.repeat(a[:1], bucket - n, axis=0)]))
    return tuple(out) + (valid,)


def strip_rows(out, n: int):
    """Drop padded-slot rows from a step output (or pytree of outputs)."""
    import jax
    return jax.tree.map(lambda a: a[:n], out)
