"""Continuous-batching inference engine with AOT-warmed bucketed shapes.

The engine decouples request arrival from device stepping:

  * requests land in a :class:`~timm_tpu.serve.queueing.RequestQueue`; the
    scheduler thread admits runs of up to the largest declared bucket —
    full buckets immediately, partial buckets when the oldest request's
    deadline expires (no request starves waiting for batch-mates);
  * every (model, bucket) program is **AOT-compiled at startup** via
    ``jax.jit(...).lower().compile()``. With the persistent compile cache
    (PR 4) warm, a restart re-loads executables from disk instead of
    recompiling — restart-to-ready is disk-bound, not compile-bound. The
    per-model prewarm records JAX's cache hit/miss events so a deployment
    can assert "zero fresh compiles" after the first boot;
  * dispatch is **double-buffered**: ``jax.device_put`` uploads batch N+1
    (asynchronously, into a donated input buffer) while the device still
    runs batch N; the scheduler only blocks on a result once
    ``transfer_depth`` steps are in flight — the DevicePrefetcher pattern
    from PR 4 applied to the request path;
  * **no shape outside the declared bucket set ever reaches the compiler**:
    runs are padded to the smallest fitting bucket and executed through the
    precompiled AOT executables, which reject any other shape; the engine
    additionally asserts the bucket is declared before every dispatch;
  * multiple models stay resident through an HBM-budgeted LRU
    :class:`~timm_tpu.serve.residency.ModelPool`; ``block_scan`` defaults ON
    (for serving, the O(1)-in-depth startup-latency win dominates and the
    re-stack HBM cost doesn't — PERF.md).

CPU-runnable end to end: the load drill (serve/drill.py, ``bench.py
--serve``) exercises all of the above as a tier-1 smoke.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import Counter, deque
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..utils.compile_cache import cache_event_total as _event_total
from ..utils.compile_cache import collect_cache_events
from .bucketing import DEFAULT_BUCKETS, pad_rows, select_bucket, strip_rows, validate_buckets
from .queueing import RequestQueue, ServeFuture
from .residency import ModelPool, ResidentModel

_logger = logging.getLogger(__name__)

__all__ = ['InferenceEngine', 'collect_cache_events']


class _Inflight:
    __slots__ = ('out', 'requests', 'bucket', 'dispatched_at')

    def __init__(self, out, requests, bucket, dispatched_at):
        self.out = out
        self.requests = requests
        self.bucket = bucket
        self.dispatched_at = dispatched_at


class InferenceEngine:
    """See module docstring. Typical use::

        engine = InferenceEngine(buckets=(1, 4, 16, 64), max_wait_ms=5.0)
        engine.add_model('vit_base_patch16_224', checkpoint='best.npz')
        engine.start()
        future = engine.submit(image)           # (H, W, C) float32, normalized
        logits = future.result(timeout=1.0)     # (num_classes,) float32
        engine.shutdown(drain=True)

    The engine serves ONE mesh (default: a single device — one serving
    replica per process). Pass an explicit ``('data','fsdp'[, 'model'])``
    mesh to shard weights/batches over multiple chips; every bucket must
    then be divisible by ``mesh.size`` (validated at construction).
    """

    def __init__(
            self,
            buckets: Sequence[int] = DEFAULT_BUCKETS,
            max_wait_ms: float = 10.0,
            mesh=None,
            transfer_depth: int = 2,
            hbm_budget_bytes: Optional[int] = None,
            block_scan: bool = True,
            input_dtype=None,
            max_pending: int = 10_000,
            configure_cache: bool = True,
            persist_all_programs: bool = False,
    ):
        import jax
        import jax.numpy as jnp

        from ..parallel import create_mesh, data_sharding
        from ..utils import configure_compile_cache

        if configure_cache:
            # serving startup wants every bucket program on disk: restart-to-
            # ready must be disk-bound. persist_all_programs drops the
            # min-compile-time threshold so even sub-second bucket programs
            # (small models / small buckets) persist.
            configure_compile_cache(
                min_compile_time_secs=0.0 if persist_all_programs else None)
        self.mesh = mesh if mesh is not None else create_mesh(devices=jax.devices()[:1])
        self._n_batch_shards = int(self.mesh.size)
        self.buckets = validate_buckets(buckets, divisor=self._n_batch_shards)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.transfer_depth = max(1, int(transfer_depth))
        self.block_scan = block_scan
        self.input_dtype = input_dtype or jnp.float32
        self._data_sharding = data_sharding(self.mesh, ndim=4)
        self._queue = RequestQueue(max_bucket=self.buckets[-1],
                                   max_wait_s=self.max_wait_s,
                                   max_pending=max_pending)
        self.pool = ModelPool(self.mesh, budget_bytes=hbm_budget_bytes,
                              prewarm_fn=self._prewarm)
        # executables survive weight eviction: an AOT program holds code, not
        # parameters, so re-admitting an evicted model costs a factory build +
        # device_put, never a recompile. Bounded by models x buckets.
        self._exec_cache: Dict[Tuple[str, int], object] = {}
        self._inflight: 'deque[_Inflight]' = deque()
        self._thread: Optional[threading.Thread] = None
        self._started = False
        self.stats: Dict = {
            'submitted': 0, 'completed': 0, 'failed': 0, 'steps': 0,
            'padded_slots': 0, 'steps_by_bucket': Counter(),
            'request_sizes': Counter(),   # dispatched-batch size histogram
            'prewarm': {}, 'max_inflight': 0,
        }

    # -- model registration / prewarm -----------------------------------------

    def add_model(self, name: str, factory=None, checkpoint: Optional[str] = None,
                  input_size: Optional[Tuple[int, int, int]] = None,
                  prewarm: bool = True, quantize: Optional[str] = None,
                  quantized_checkpoint: Optional[str] = None,
                  **model_kwargs) -> None:
        """Register ``name`` with the residency pool. ``factory`` overrides
        the default ``timm_tpu.create_model(name, **model_kwargs)`` (+
        optional verified checkpoint load). ``prewarm=True`` loads and
        AOT-compiles every bucket now; otherwise the first request pays it.
        ``quantize='int8'`` serves post-training weight-only int8: the LRU
        budget is charged the ~0.27x footprint and every bucket program
        compiles against the int8 pytree with dequant fused at use
        (``quantized_checkpoint`` loads saved qvalues/scales instead of
        re-quantizing the factory's weights)."""
        if factory is None:
            def factory():
                import timm_tpu
                model = timm_tpu.create_model(name, **model_kwargs)
                if checkpoint:
                    from ..models import load_checkpoint
                    load_checkpoint(model, checkpoint)
                return model
        if input_size is None and 'img_size' in model_kwargs:
            s = int(model_kwargs['img_size'])
            input_size = (s, s, 3)

        base_factory = factory

        def serving_factory():
            model = base_factory()
            if self.block_scan and hasattr(model, 'set_block_scan'):
                # startup latency dominates serving; scan keeps the per-bucket
                # trace/compile O(1) in depth (heterogeneous stacks fall back
                # to the loop inside the model, bit-identically)
                model.set_block_scan(True)
            model.eval()
            return model

        self.pool.register(name, serving_factory, input_size=input_size,
                           quantize=quantize,
                           quantized_checkpoint=quantized_checkpoint)
        if prewarm:
            self.pool.acquire(name)

    def _prewarm(self, res: ResidentModel) -> None:
        """AOT-compile every declared bucket for a freshly-loaded model,
        recording wall time and compile-cache hit/miss events."""
        t0 = time.perf_counter()
        exec_hits = 0
        with collect_cache_events() as events:
            for bucket in self.buckets:
                key = (res.name, bucket)
                exe = self._exec_cache.get(key)
                if exe is not None:
                    exec_hits += 1
                else:
                    exe = self._compile_bucket(res, bucket)
                    self._exec_cache[key] = exe
                res.compiled[bucket] = exe
        ms = (time.perf_counter() - t0) * 1e3
        stats = {
            'programs': len(self.buckets),
            'ms': round(ms, 1),
            'exec_cache_hits': exec_hits,
            'cache_hits': _event_total(events, 'cache_hits'),
            'fresh_compiles': _event_total(events, 'cache_misses'),
        }
        res.prewarm_stats.update(stats)
        self.stats['prewarm'][res.name] = stats
        _logger.info(
            f'serve prewarm {res.name}: {stats["programs"]} bucket programs in '
            f'{ms:.0f}ms ({stats["cache_hits"]} disk-cache hits, '
            f'{stats["fresh_compiles"]} fresh compiles)')

    def _bucket_jit(self, res: ResidentModel):
        """The ONE construction of a bucket program's jit: donation of the
        input batch buffer is declared here and only here, so both the prewarm
        compile path and `donation_report` observe the same program — a
        dropped `donate_argnums` is visible to the lint, not just to grep."""
        import jax
        import jax.numpy as jnp
        from flax import nnx

        graphdef = res.graphdef

        if res.quantize:
            from ..quantize import dequantize_tree

            def infer(state, x):
                # dequant INSIDE the program: the int8 qvalues/scales are the
                # program inputs (what HBM holds between steps); the dense
                # weights are fused transients of the matmul epilogue
                return nnx.merge(graphdef, dequantize_tree(state))(x).astype(jnp.float32)
        else:
            def infer(state, x):
                return nnx.merge(graphdef, state)(x).astype(jnp.float32)

        # donate the input buffer: each step uploads a fresh batch, XLA may
        # reuse it as scratch instead of holding both copies in HBM. When the
        # backend can't alias it (CPU, logits smaller than the image batch)
        # jax warns per-shape; that's the expected no-op case, not a bug.
        return jax.jit(infer, donate_argnums=(1,))

    def _bucket_in_spec(self, res: ResidentModel, bucket: int):
        import jax
        h, w, c = res.input_size
        return jax.ShapeDtypeStruct((bucket, h, w, c), self.input_dtype,
                                    sharding=self._data_sharding)

    def _compile_bucket(self, res: ResidentModel, bucket: int):
        import warnings
        x_spec = self._bucket_in_spec(res, bucket)
        with warnings.catch_warnings():
            warnings.filterwarnings('ignore', message='Some donated buffers were not usable')
            return self._bucket_jit(res).lower(res.state, x_spec).compile()

    def aot_executables(self, model: str) -> Dict[int, object]:
        """bucket -> compiled AOT executable for `model` (prewarmed or first-
        request-compiled so far). The perfbudget probe and the serve donation
        lint introspect these directly (`cost_analysis()`, HLO text)."""
        return {b: exe for (name, b), exe in self._exec_cache.items() if name == model}

    def donation_report(self, model: str) -> Dict[int, Dict]:
        """Per-bucket evidence that the input-batch donation actually reaches
        the compiled program, asserted via the lowering/executable rather than
        `donate_argnums` presence in source.

        Two observable outcomes, either of which proves the donor was
        declared and threaded through:
          * the compiled HLO header carries an ``input_output_alias`` entry
            (backend aliased the donated buffer — the TPU/live case);
          * lowering emitted jax's "Some donated buffers were not usable"
            warning (backend could not alias — the CPU/logits-smaller case;
            the warning is emitted ONLY for declared donors, so its presence
            is positive evidence the donation survived to lowering).
        If `donate_argnums` is removed from `_bucket_jit`, both signals
        disappear and `declared` goes False for every bucket."""
        import warnings
        res = self.pool.acquire(model)
        out: Dict[int, Dict] = {}
        for bucket in self.buckets:
            jitted = self._bucket_jit(res)
            with warnings.catch_warnings(record=True) as rec:
                warnings.simplefilter('always')
                lowered = jitted.lower(res.state, self._bucket_in_spec(res, bucket))
            unusable = any('donated buffers were not usable' in str(w.message) for w in rec)
            exe = self._exec_cache.get((model, bucket))
            if exe is None:
                exe = lowered.compile()
            header = exe.as_text().splitlines()[0] if hasattr(exe, 'as_text') else ''
            aliases = (header.count('may-alias') + header.count('must-alias')
                       if 'input_output_alias' in header else 0)
            out[bucket] = {
                'declared': bool(aliases or unusable),
                'aliases': int(aliases),
                'unusable_on_backend': bool(unusable),
            }
        return out

    # -- request path ---------------------------------------------------------

    def submit(self, image, model: Optional[str] = None) -> ServeFuture:
        """Enqueue one image; returns a future resolving to its logits row."""
        if not self._started:
            raise RuntimeError('InferenceEngine.submit before start(); call start() first')
        if model is None:
            registered = self.pool.registered
            if len(registered) != 1:
                raise ValueError(
                    f'model= is required when {len(registered)} models are registered '
                    f'({list(registered)})')
            model = registered[0]
        future = self._queue.submit(model, image)
        self.stats['submitted'] += 1
        return future

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> 'InferenceEngine':
        if self._started:
            return self
        self._started = True
        self._thread = threading.Thread(target=self._loop, name='serve-scheduler',
                                        daemon=True)
        self._thread.start()
        return self

    def shutdown(self, drain: bool = True, timeout: float = 120.0) -> None:
        """Stop the engine. ``drain=True`` (the default) completes every
        pending and in-flight request first; ``drain=False`` fails pending
        requests and completes only the in-flight device steps."""
        if not self._started:
            return
        self._queue.close(drain=drain)
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise RuntimeError('serve scheduler failed to drain within '
                                   f'{timeout}s at shutdown')
            self._thread = None
        self._started = False
        advisory = self.bucket_advisory()
        if advisory:
            _logger.info(
                f'serve: bucket ladder {advisory["current"]} wasted '
                f'{advisory["current_waste"]:.1%} of computed rows over '
                f'{advisory["requests"]} dispatches; '
                f'autotune.propose_buckets suggests {advisory["proposed"]} '
                f'({advisory["proposed_waste"]:.1%} waste). Advisory only — '
                f'restart with buckets={tuple(advisory["proposed"])} to apply.')

    def __enter__(self) -> 'InferenceEngine':
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=not any(exc))

    # -- scheduler ------------------------------------------------------------

    def _loop(self) -> None:
        try:
            while True:
                # with steps in flight, poll briefly so result retirement
                # interleaves with admission; otherwise block until work,
                # a deadline, or shutdown
                timeout = 0.0005 if self._inflight else None
                admission = self._queue.wait_admission(timeout=timeout)
                if admission is None:
                    if self._inflight:
                        self._retire(self._inflight.popleft())
                        continue
                    if self._queue.finished():
                        break
                    continue
                self._dispatch(*admission)
                while len(self._inflight) >= self.transfer_depth:
                    self._retire(self._inflight.popleft())
        finally:
            while self._inflight:
                self._retire(self._inflight.popleft())

    def _dispatch(self, model_name: str, requests) -> None:
        import jax
        import jax.numpy as jnp

        try:
            res = self.pool.acquire(model_name)
            bucket = select_bucket(len(requests), self.buckets)
            x = np.stack([np.asarray(r.image) for r in requests])
            x, _valid = pad_rows(x, bucket)
            # hard guarantee: nothing outside the declared set reaches the
            # compiler — the AOT executables reject novel shapes, and this
            # assert catches a scheduling bug before the device does
            assert x.shape[0] in self.buckets, \
                f'batch shape {x.shape[0]} outside declared buckets {self.buckets}'
            # async upload (double-buffer): overlaps the running device step
            x_dev = jax.device_put(jnp.asarray(x, self.input_dtype), self._data_sharding)
            out = res.compiled[bucket](res.state, x_dev)
            self._inflight.append(_Inflight(out, requests, bucket, time.perf_counter()))
            self.stats['steps'] += 1
            self.stats['steps_by_bucket'][bucket] += 1
            self.stats['request_sizes'][len(requests)] += 1
            self.stats['padded_slots'] += bucket - len(requests)
            self.stats['max_inflight'] = max(self.stats['max_inflight'], len(self._inflight))
        except Exception as e:
            _logger.exception(f'serve dispatch failed for {model_name} '
                              f'x{len(requests)}: {e}')
            for r in requests:
                r.future._set_exception(e)
            self.stats['failed'] += len(requests)

    def _retire(self, item: _Inflight) -> None:
        try:
            logits = np.asarray(item.out)  # blocks until the device step lands
            logits = strip_rows(logits, len(item.requests))
            for i, r in enumerate(item.requests):
                r.future._set_result(logits[i])
            self.stats['completed'] += len(item.requests)
        except Exception as e:
            _logger.exception(f'serve step failed at retirement: {e}')
            for r in item.requests:
                r.future._set_exception(e)
            self.stats['failed'] += len(item.requests)

    # -- introspection --------------------------------------------------------

    def pending(self) -> int:
        return len(self._queue)

    def snapshot_stats(self) -> Dict:
        """Point-in-time copy of engine + pool counters (drill reporting)."""
        out = dict(self.stats)
        out['steps_by_bucket'] = dict(self.stats['steps_by_bucket'])
        out['request_sizes'] = dict(self.stats['request_sizes'])
        out['pool'] = dict(self.pool.stats)
        out['resident'] = list(self.pool.resident_names)
        return out

    def bucket_advisory(self, max_buckets: int = 5) -> Optional[Dict]:
        """Compare the declared bucket ladder against the optimal ladder for
        the dispatched-batch size histogram (`autotune.propose_buckets`).
        Returns None until traffic exists or when the declared ladder is
        already optimal; advisory only — ladders are compile-time surface."""
        hist = {s: c for s, c in self.stats['request_sizes'].items() if c > 0}
        if not hist:
            return None
        from ..autotune import ladder_waste, propose_buckets
        proposed = propose_buckets(hist, max_buckets=max(len(self.buckets),
                                                         max_buckets))
        current_waste = ladder_waste(self.buckets, hist)
        proposed_waste = ladder_waste(proposed, hist)
        if tuple(proposed) == tuple(sorted(self.buckets)) \
                or proposed_waste >= current_waste:
            return None
        return {'current': tuple(sorted(self.buckets)),
                'proposed': tuple(proposed),
                'current_waste': round(current_waste, 4),
                'proposed_waste': round(proposed_waste, 4),
                'requests': int(sum(hist.values()))}
