"""Multi-model residency: HBM-budgeted LRU pool over the registry.

The registry exposes ~900 entrypoints; a serving process can keep only a few
resident in HBM at once. The pool loads models lazily from registered
factories (``timm_tpu.create_model`` + optional checkpoint), places their
state on the mesh under the FSDP/TP partition rules, hands each new resident
to the engine's prewarm hook (per-model AOT compile of every declared
bucket, warmed from the persistent compile cache), and evicts the
least-recently-used resident when the per-device budget is exceeded.

Eviction drops the pool's references; JAX frees the device buffers once the
engine's in-flight steps release theirs, so an evicted model's outstanding
batches still complete. A single model larger than the whole budget is kept
(serving it is the job) with a loud warning rather than an eviction livelock.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional

import numpy as np

_logger = logging.getLogger(__name__)

__all__ = ['ResidentModel', 'ModelPool']


class ResidentModel:
    """One loaded model: split graphdef/state on the mesh + the per-bucket
    compiled executables the engine attaches at prewarm."""

    def __init__(self, name: str, graphdef, state, param_bytes: int, input_size,
                 quantize: Optional[str] = None):
        self.name = name
        self.graphdef = graphdef
        self.state = state
        self.param_bytes = int(param_bytes)
        self.input_size = input_size  # (H, W, C) the compiled programs expect
        self.quantize = quantize  # None (dense) or 'int8' ({'qvalues','scales'} state)
        self.compiled: Dict[int, object] = {}  # bucket -> AOT executable
        self.prewarm_stats: Dict[str, float] = {}
        self.last_used = time.perf_counter()

    def touch(self):
        self.last_used = time.perf_counter()


def _state_bytes_per_device(state, mesh) -> int:
    """Per-device HBM the state occupies under the partition rules (the
    budget is per chip — replicated totals would overcount sharded models)."""
    from ..parallel import param_bytes_per_device
    try:
        _, sharded = param_bytes_per_device(state, mesh)
        return int(sharded)
    except Exception:
        import jax

        from ..parallel.sharding import leaf_itemsize
        return int(sum(
            int(np.prod(getattr(l, 'shape', ()) or (1,))) * leaf_itemsize(l.dtype)
            for l in jax.tree.leaves(state)))


class ModelPool:
    """LRU residency over lazily-built models.

    ``register(name, factory)`` declares how to build a model (it is NOT
    loaded yet); ``acquire(name)`` returns the resident entry, loading —
    and evicting — as needed. ``prewarm_fn`` (set by the engine) runs once
    per load, before the model serves its first request.
    """

    def __init__(self, mesh, budget_bytes: Optional[int] = None,
                 prewarm_fn: Optional[Callable[[ResidentModel], None]] = None):
        self.mesh = mesh
        self.budget_bytes = budget_bytes
        self.prewarm_fn = prewarm_fn
        self._factories: Dict[str, Callable[[], object]] = {}
        self._resident: 'OrderedDict[str, ResidentModel]' = OrderedDict()
        self._lock = threading.RLock()
        self.stats = {'loads': 0, 'evictions': 0, 'hits': 0}

    # -- registration ---------------------------------------------------------

    def register(self, name: str, factory: Callable[[], object],
                 input_size=None, quantize: Optional[str] = None,
                 quantized_checkpoint: Optional[str] = None):
        """``input_size`` — (H, W, C) the compiled programs will expect;
        resolved from the model's default_cfg when omitted. ``quantize='int8'``
        applies post-training weight-only quantization at load: the resident
        state becomes the ``{'qvalues','scales'}`` pytree, the LRU budget is
        charged the real int8 footprint, and the engine's bucket programs
        compile against the int8 tree (dequant-at-use). A
        ``quantized_checkpoint`` (from ``quantize.save_quantized``) replaces
        the on-the-fly transform with saved qvalues/scales."""
        if quantize not in (None, 'int8'):
            raise ValueError(f'unsupported quantize mode {quantize!r} (only int8)')
        if quantized_checkpoint and not quantize:
            quantize = 'int8'
        with self._lock:
            self._factories[name] = (factory, input_size, quantize, quantized_checkpoint)

    @property
    def registered(self):
        return tuple(self._factories)

    @property
    def resident_names(self):
        with self._lock:
            return tuple(self._resident)

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(r.param_bytes for r in self._resident.values())

    # -- residency ------------------------------------------------------------

    def acquire(self, name: str) -> ResidentModel:
        with self._lock:
            res = self._resident.get(name)
            if res is not None:
                self._resident.move_to_end(name)
                res.touch()
                self.stats['hits'] += 1
                return res
            if name not in self._factories:
                raise KeyError(f'model {name!r} not registered with the serve pool '
                               f'(registered: {list(self._factories)})')
            return self._load(name)

    def _load(self, name: str) -> ResidentModel:
        import jax
        from flax import nnx

        from ..parallel import build_param_shardings

        t0 = time.perf_counter()
        factory, input_size, quantize, quantized_checkpoint = self._factories[name]
        model = factory()
        model.eval()
        if input_size is None:
            cfg = getattr(model, 'default_cfg', None) or {}
            chw = cfg.get('input_size') or (3, 224, 224)
            input_size = (int(chw[1]), int(chw[2]), int(chw[0]))  # CHW cfg → HWC input
        h, w, c = (int(s) for s in input_size)
        graphdef, state = nnx.split(model)
        dense_bytes = None
        if quantize:
            from ..quantize import load_quantized, quantize_tree
            dense_bytes = _state_bytes_per_device(state, self.mesh)
            if quantized_checkpoint:
                state = load_quantized(quantized_checkpoint, state)
            else:
                state = quantize_tree(state)
        # the budget sees the ACTUAL loaded pytree's dtypes: an int8 model is
        # charged int8 bytes, not the factory default dtype's
        nbytes = _state_bytes_per_device(state, self.mesh)
        self._evict_to_fit(nbytes, loading=name, dense_bytes=dense_bytes)
        if 'fsdp' in self.mesh.axis_names or 'model' in self.mesh.axis_names:
            if quantize:
                from ..parallel import build_quant_shardings
                state = jax.device_put(state, build_quant_shardings(state, self.mesh))
            else:
                state = jax.device_put(state, build_param_shardings(state, self.mesh))
        res = ResidentModel(name, graphdef, state, nbytes, (h, w, c), quantize=quantize)
        res.prewarm_stats['load_ms'] = (time.perf_counter() - t0) * 1e3
        if self.prewarm_fn is not None:
            self.prewarm_fn(res)
        self._resident[name] = res
        self.stats['loads'] += 1
        _logger.info(
            f'serve pool: loaded {name}{" [int8]" if quantize else ""} '
            f'({nbytes / 1e6:.1f} MB/device, '
            f'{len(self._resident)} resident, '
            f'{self.resident_bytes() / 1e6:.1f} MB of '
            f'{"unbounded" if self.budget_bytes is None else f"{self.budget_bytes / 1e6:.1f} MB"} budget)')
        return res

    def _evict_to_fit(self, incoming_bytes: int, loading: str,
                      dense_bytes: Optional[int] = None):
        if self.budget_bytes is None:
            return
        if incoming_bytes > self.budget_bytes:
            quant_note = ('' if dense_bytes is None else
                          f', already int8-quantized from {dense_bytes / 1e6:.1f} MB dense')
            _logger.warning(
                f'serve pool: model {loading!r} alone ({incoming_bytes / 1e6:.1f} MB/device'
                f'{quant_note}) '
                f'exceeds the HBM budget ({self.budget_bytes / 1e6:.1f} MB); '
                f'keeping it resident anyway — raise the budget or serve a smaller model')
        while self._resident and \
                self.resident_bytes() + incoming_bytes > self.budget_bytes:
            victim, res = self._resident.popitem(last=False)  # LRU order
            self.stats['evictions'] += 1
            _logger.info(
                f'serve pool: evicted {victim} ({res.param_bytes / 1e6:.1f} MB/device) '
                f'to fit {loading} within the {self.budget_bytes / 1e6:.1f} MB budget')

    def evict(self, name: str) -> bool:
        with self._lock:
            res = self._resident.pop(name, None)
            if res is not None:
                self.stats['evictions'] += 1
            return res is not None

    def clear(self):
        with self._lock:
            self._resident.clear()
