"""Open-loop load drill for the serving engine.

Synthetic Poisson traffic (open-loop: arrival times are drawn up front and do
NOT wait for completions, so queueing delay is measured honestly — a
closed-loop generator would throttle itself and hide it) is replayed against
an :class:`~timm_tpu.serve.engine.InferenceEngine`, reporting p50/p99 request
latency and sustained img/s against the offered load.

``canonical_drill`` is the tier-1 A/B smoke (``bench.py --serve --dry-run``):
the SAME arrival schedule replayed twice —

  * **continuous batching**: declared buckets, deadline-bounded admission,
    double-buffered dispatch, two models sharing an HBM budget sized to hold
    only one (forcing exactly the LRU eviction path);
  * **per-request baseline**: bucket set ``(1,)`` with zero wait — every
    request is its own device step, the service the engine replaces.

It asserts continuous batching sustains strictly higher img/s at equal
offered load, that every dispatched shape was a declared bucket, and that
the eviction path fired. CPU-runnable end to end.
"""
from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

import numpy as np

from .engine import InferenceEngine

__all__ = ['run_load_drill', 'canonical_drill', 'quant_residency_drill', 'summary_line']


def _poisson_arrivals(num: int, rate_per_s: float, seed: int) -> np.ndarray:
    rng = np.random.RandomState(seed)
    gaps = rng.exponential(1.0 / rate_per_s, size=num)
    gaps[0] = 0.0  # first request arrives at t=0
    return np.cumsum(gaps)


def run_load_drill(
        model_names: Sequence[str] = ('test_vit',),
        buckets: Sequence[int] = (4, 16),
        num_requests: int = 96,
        rate_per_s: float = 2000.0,
        img_size: int = 32,
        max_wait_ms: float = 15.0,
        hbm_budget_bytes: Optional[int] = None,
        per_request: bool = False,
        seed: int = 0,
        mesh=None,
        persist_all_programs: bool = False,
        result_timeout: float = 300.0,
        quantize: Optional[str] = None,
) -> Dict:
    """Replay one Poisson schedule against one engine configuration.

    ``per_request=True`` turns the engine into the baseline it replaces:
    bucket set ``(1,)``, zero admission wait, no transfer overlap.
    ``quantize='int8'`` loads every model weight-only-quantized (the A arm of
    the quant residency drill).
    """
    if per_request:
        buckets, max_wait_ms, transfer_depth = (1,), 0.0, 1
    else:
        transfer_depth = 2
    engine = InferenceEngine(
        buckets=buckets, max_wait_ms=max_wait_ms, mesh=mesh,
        transfer_depth=transfer_depth, hbm_budget_bytes=hbm_budget_bytes,
        persist_all_programs=persist_all_programs)

    t_warm0 = time.perf_counter()
    for name in model_names:
        engine.add_model(name, img_size=img_size, quantize=quantize)
    startup_ms = (time.perf_counter() - t_warm0) * 1e3

    arrivals = _poisson_arrivals(num_requests, rate_per_s, seed)
    # a small pool of distinct in-distribution images, reused round-robin
    rng = np.random.RandomState(seed + 1)
    images = rng.standard_normal((8, img_size, img_size, 3)).astype(np.float32)
    # phase split across models: all model-A traffic, then all model-B — the
    # access pattern that exercises LRU residency (B's load evicts cold A
    # under a one-model budget) without thrashing on every step
    n_models = len(model_names)
    model_of = [model_names[min(i * n_models // num_requests, n_models - 1)]
                for i in range(num_requests)]

    engine.start()
    futures, submit_ts = [], []
    t0 = time.perf_counter()
    try:
        for i in range(num_requests):
            lag = (t0 + arrivals[i]) - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
            futures.append(engine.submit(images[i % len(images)], model=model_of[i]))
            submit_ts.append(time.perf_counter())
        results = [f.result(timeout=result_timeout) for f in futures]
    finally:
        engine.shutdown(drain=True)

    stats = engine.snapshot_stats()
    # acceptance guard: nothing outside the declared bucket set ever reached
    # the compiler (the engine's AOT executables enforce this per step; the
    # drill re-checks the ledger end-to-end)
    dispatched = set(stats['steps_by_bucket'])
    assert dispatched <= set(engine.buckets), \
        f'off-bucket shapes dispatched: {sorted(dispatched - set(engine.buckets))}'
    assert stats['failed'] == 0 and stats['completed'] == num_requests, \
        f'drill lost requests: {stats["completed"]}/{num_requests} ok, {stats["failed"]} failed'
    for r in results:
        assert np.all(np.isfinite(r)), 'non-finite logits in drill output'

    lat_ms = np.array([(f.done_at - t) * 1e3 for f, t in zip(futures, submit_ts)])
    t_end = max(f.done_at for f in futures)
    p50, p99 = np.percentile(lat_ms, [50, 99])
    return {
        'mode': 'per_request' if per_request else 'continuous',
        'models': list(model_names),
        'buckets': list(engine.buckets),
        'num_requests': num_requests,
        'offered_rps': round(num_requests / max(arrivals[-1], 1e-9), 1),
        'img_per_s': round(num_requests / max(t_end - t0, 1e-9), 1),
        'p50_ms': round(float(p50), 2),
        'p99_ms': round(float(p99), 2),
        'steps': stats['steps'],
        'steps_by_bucket': stats['steps_by_bucket'],
        'padded_slots': stats['padded_slots'],
        'evictions': stats['pool']['evictions'],
        'resident': stats['resident'],
        'quantize': quantize,
        'startup_ms': round(startup_ms, 1),
        'prewarm': stats['prewarm'],
    }


def _param_bytes(name: str, img_size: int) -> int:
    """Host-side parameter byte count for sizing the drill's HBM budget
    (models here are tiny; building one on CPU to measure is cheap)."""
    import jax
    import timm_tpu
    from flax import nnx

    _, state = nnx.split(timm_tpu.create_model(name, img_size=img_size))
    return int(sum(
        int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        for leaf in jax.tree.leaves(state) if hasattr(leaf, 'shape')))


def canonical_drill(
        model_names: Sequence[str] = ('test_vit', 'test_vit2'),
        buckets: Sequence[int] = (4, 16),
        num_requests: int = 256,
        rate_per_s: float = 2000.0,
        img_size: int = 32,
        seed: int = 0,
        persist_all_programs: bool = False,
) -> Dict:
    """The tier-1 A/B drill: two models, two buckets, budget forces one
    eviction; continuous batching must beat the per-request baseline."""
    # budget holds the larger model alone but never both → loading the second
    # model exercises the LRU eviction path exactly once per phase change
    budget = int(1.25 * max(_param_bytes(n, img_size) for n in model_names))
    common = dict(model_names=model_names, num_requests=num_requests,
                  rate_per_s=rate_per_s, img_size=img_size, seed=seed,
                  hbm_budget_bytes=budget,
                  persist_all_programs=persist_all_programs)
    continuous = run_load_drill(buckets=buckets, **common)
    baseline = run_load_drill(per_request=True, **common)

    assert continuous['evictions'] >= 1, \
        f'HBM budget {budget} failed to trigger LRU eviction: {continuous}'
    assert continuous['img_per_s'] > baseline['img_per_s'], (
        f'continuous batching ({continuous["img_per_s"]} img/s) did not beat the '
        f'per-request baseline ({baseline["img_per_s"]} img/s) at equal offered load')
    return {
        'continuous': continuous,
        'per_request': baseline,
        'speedup': round(continuous['img_per_s'] / max(baseline['img_per_s'], 1e-9), 2),
        'hbm_budget_bytes': budget,
    }


def quant_residency_drill(
        model_names: Sequence[str] = ('test_vit', 'test_vit2'),
        buckets: Sequence[int] = (4, 16),
        num_requests: int = 256,
        rate_per_s: float = 2000.0,
        img_size: int = 32,
        seed: int = 0,
        persist_all_programs: bool = False,
) -> Dict:
    """The int8 A/B residency drill: the SAME Poisson schedule and the SAME
    one-model HBM budget replayed twice, fp32 vs weight-only int8.

    Under a budget sized for 1.25x the larger fp32 model, the fp32 arm
    thrashes — prewarm of model B evicts A, then each traffic phase change
    reloads/evicts again (3 LRU evictions for the two-model phase-split
    schedule) — while the int8 arm (~0.27x bytes per model) fits BOTH models
    resident simultaneously with zero evictions. Same budget, 2x the models.
    """
    budget = int(1.25 * max(_param_bytes(n, img_size) for n in model_names))
    common = dict(model_names=model_names, buckets=buckets,
                  num_requests=num_requests, rate_per_s=rate_per_s,
                  img_size=img_size, seed=seed, hbm_budget_bytes=budget,
                  persist_all_programs=persist_all_programs)
    fp32 = run_load_drill(**common)
    int8 = run_load_drill(quantize='int8', **common)

    assert fp32['evictions'] >= 1, \
        f'HBM budget {budget} failed to force fp32 LRU evictions: {fp32}'
    assert int8['evictions'] == 0, \
        f'int8 arm evicted under the one-fp32-model budget {budget}: {int8}'
    assert sorted(int8['resident']) == sorted(model_names), (
        f'int8 arm should hold all {len(model_names)} models resident under '
        f'the one-fp32-model budget; resident={int8["resident"]}')
    return {
        'fp32': fp32,
        'int8': int8,
        'hbm_budget_bytes': budget,
        'fp32_evictions': fp32['evictions'],
        'int8_resident': len(int8['resident']),
    }


def summary_line(ab: Dict) -> str:
    c, b = ab['continuous'], ab['per_request']
    return (
        f'serve-drill: continuous {c["img_per_s"]} img/s '
        f'(p50 {c["p50_ms"]}ms / p99 {c["p99_ms"]}ms, buckets {tuple(c["buckets"])}, '
        f'{c["evictions"]} eviction(s)) vs per-request {b["img_per_s"]} img/s '
        f'(p50 {b["p50_ms"]}ms / p99 {b["p99_ms"]}ms) -> {ab["speedup"]}x '
        f'at {c["offered_rps"]} req/s offered')
