"""Runtime compatibility shims for the flax version in the environment.

The codebase targets flax>=0.12 (`nnx.List` module containers, `nnx.data`
attribute marking). Older flax (0.10.x) lacks both names but treats plain
Python lists assigned to module attributes as graph containers and plain
attribute assignment as data, so the shims below are behaviour-preserving:

* ``nnx.List`` → ``list``. flax 0.10 registers list elements in the module
  graph directly; `nnx.split`/`nnx.state` traverse them identically.
* ``nnx.data``  → identity. The 0.12 helper only *marks* a value as pytree
  data; 0.10 needs no marking.
* ``nnx.Rngs.fork`` → draw one key per stream into a fresh ``Rngs``. Same
  observable behaviour: the parent stream counts advance, the child is
  independent and storable on a module.
* ``nnx.Variable.__setitem__`` → functional ``.at[idx].set`` on the wrapped
  array. 0.10 forwards item assignment to the (immutable) jax array and
  crashes; 0.12 supports it natively.
* ``nnx.to_flat_state`` → ``State.flat_state()`` items, the 0.10 spelling of
  the same flattening.
* ``nnx.to_pure_dict`` → ``State.to_pure_dict()``, ditto.

Imported for its side effects at the very top of ``timm_tpu/__init__``,
before any model module can touch the missing attributes. No-op on flax
versions that already provide the real APIs.
"""
from __future__ import annotations

from flax import nnx

if not hasattr(nnx, 'List'):
    nnx.List = list

if not hasattr(nnx, 'data'):
    def _data_identity(value):
        return value

    nnx.data = _data_identity

if not hasattr(nnx.Rngs, 'fork'):
    def _rngs_fork(self, **kwargs):
        return nnx.Rngs(**{name: stream() for name, stream in self.items()})

    nnx.Rngs.fork = _rngs_fork


if not hasattr(nnx, 'to_flat_state'):
    def _to_flat_state(state):
        flat = state.flat_state()
        return list(flat.items()) if hasattr(flat, 'items') else list(flat)

    nnx.to_flat_state = _to_flat_state

if not hasattr(nnx, 'to_pure_dict'):
    def _to_pure_dict(state):
        return state.to_pure_dict()

    nnx.to_pure_dict = _to_pure_dict

    # flax 0.11+ merged VariableState into Variable, so flat-state leaves
    # support item access; give the 0.10 VariableState the same surface
    # (callers do `leaf[...]` / `leaf[...] = v` then nnx.update(model, state))
    from flax.nnx import variablelib as _variablelib

    if not hasattr(_variablelib.VariableState, '__getitem__'):
        def _vs_getitem(self, idx):
            return self.value if idx is Ellipsis else self.value[idx]

        def _vs_setitem(self, idx, value):
            if idx is Ellipsis:
                self.value = value
            else:
                self.value = self.value.at[idx].set(value)

        _variablelib.VariableState.__getitem__ = _vs_getitem
        _variablelib.VariableState.__setitem__ = _vs_setitem


def _variable_setitem_broken() -> bool:
    import jax.numpy as jnp
    p = nnx.Param(jnp.zeros((2,)))
    try:
        p[...] = jnp.ones((2,))
        return False
    except TypeError:
        return True


if _variable_setitem_broken():
    def _variable_setitem(self, idx, value):
        self.value = self.value.at[idx].set(value)

    nnx.Variable.__setitem__ = _variable_setitem
