"""Weight-only int8 PTQ: per-output-channel symmetric scales, dequant-at-use.

The quantized representation is a plain dict pytree

    {'qvalues': <nnx.State with eligible kernels replaced by int8 arrays>,
     'scales':  {param_path: per-output-channel scale, original dtype}}

chosen so that (a) the flattened leaf paths still end in ``.kernel`` /
``.bias`` / … exactly like the dense state — every existing regex partition
rule and the per-device byte accounting keep working unmodified — and (b)
the whole thing passes through ``jax.jit`` as one argument (string-keyed
dicts are static structure; only the arrays are traced).

Quantization math (per eligible kernel ``w`` of shape ``(..., out)``):

    scale = max(|w|, axis=all-but-last) / 127        # one scale per output channel
    q     = clip(round(w / scale), -127, 127).int8   # symmetric, zero-point-free
    w'    = q.astype(scale.dtype) * scale            # dequant-at-use, inside jit

which bounds the elementwise error by ``scale / 2`` (the absmax itself maps
to exactly +/-127, so clipping never bites). The scale keeps the original
param dtype so dequantization restores it without auxiliary metadata.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

QUANT_QVALUES = 'qvalues'
QUANT_SCALES = 'scales'

# Kernels below this element count stay dense: the scale + int8 overhead and
# the extra dequant op outweigh the bytes saved (mirrors MIN_SHARD_SIZE).
MIN_QUANT_SIZE = 1024


def default_quant_predicate(path: str, leaf) -> bool:
    """Eligible = a floating matmul kernel of useful size. Biases, norm
    params, class/pos embeddings and tiny kernels keep their dtype."""
    shape = getattr(leaf, 'shape', ())
    dtype = getattr(leaf, 'dtype', None)
    return (
        path.endswith('.kernel')
        and len(shape) >= 2
        and dtype is not None and np.issubdtype(np.dtype(dtype), np.floating)
        and int(np.prod(shape)) >= MIN_QUANT_SIZE
    )


def is_quantized(tree) -> bool:
    return (isinstance(tree, dict)
            and QUANT_QVALUES in tree and QUANT_SCALES in tree)


def _channel_scale(w):
    import jax.numpy as jnp
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)),
                   axis=tuple(range(w.ndim - 1)))
    # a dead (all-zero) output channel gets scale 1 so dequant is exact zero
    scale = jnp.where(amax > 0, amax, 127.0) / 127.0
    return scale.astype(w.dtype)


def quantize_tree(state, *, predicate: Optional[Callable] = None) -> dict:
    """Pure pytree -> pytree: dense ``nnx.State`` (or any param tree) to the
    quantized ``{'qvalues', 'scales'}`` representation. Structure of
    ``qvalues`` is identical to ``state`` — only eligible leaves change dtype."""
    import jax
    import jax.numpy as jnp

    from ..parallel.sharding import _kp_str

    predicate = predicate or default_quant_predicate
    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    scales: Dict[str, object] = {}
    qleaves = []
    for kp, leaf in flat:
        path = _kp_str(kp)
        if predicate(path, leaf):
            scale = _channel_scale(leaf)
            q = jnp.clip(jnp.round(leaf.astype(jnp.float32)
                                   / scale.astype(jnp.float32)),
                         -127, 127).astype(jnp.int8)
            scales[path] = scale
            qleaves.append(q)
        else:
            qleaves.append(leaf)
    return {QUANT_QVALUES: jax.tree_util.tree_unflatten(treedef, qleaves),
            QUANT_SCALES: scales}


def dequantize_tree(qstate):
    """Jit-traceable inverse: int8 leaves become ``q * scale`` in the scale's
    dtype. Called *inside* the serve/eval program so the dense weights are
    XLA transients and the int8 tensors are what lives in HBM."""
    import jax

    from ..parallel.sharding import _kp_str

    qvalues, scales = qstate[QUANT_QVALUES], qstate[QUANT_SCALES]
    flat, treedef = jax.tree_util.tree_flatten_with_path(qvalues)
    out = []
    for kp, leaf in flat:
        scale = scales.get(_kp_str(kp))
        out.append(leaf if scale is None else leaf.astype(scale.dtype) * scale)
    return jax.tree_util.tree_unflatten(treedef, out)


def quantized_paths(qstate) -> tuple:
    return tuple(sorted(qstate[QUANT_SCALES]))


def tree_bytes(tree) -> int:
    """Host-side byte count of any pytree from shapes/dtypes (works on
    abstract leaves too — no device transfer)."""
    import jax
    return int(sum(
        int(np.prod(getattr(l, 'shape', ()) or (1,))) * np.dtype(l.dtype).itemsize
        for l in jax.tree.leaves(tree)))


def quantization_stats(state, qstate) -> dict:
    dense, quant = tree_bytes(state), tree_bytes(qstate)
    return {
        'num_quantized': len(qstate[QUANT_SCALES]),
        'dense_bytes': dense,
        'quantized_bytes': quant,
        'bytes_ratio': quant / max(dense, 1),
    }


# -- quantized checkpoints ----------------------------------------------------
#
# Flat npz with prefixed keys; mesh-shape-agnostic like the dense checkpoints
# (arrays are gathered to host on save, re-placed by the loader's caller).

_Q_PREFIX = 'int8.q::'
_S_PREFIX = 'int8.scale::'
_D_PREFIX = 'dense::'


def save_quantized(qstate, path: str) -> None:
    import jax

    from ..parallel.sharding import _kp_str

    flat, _ = jax.tree_util.tree_flatten_with_path(qstate[QUANT_QVALUES])
    scales = qstate[QUANT_SCALES]
    arrays = {}
    for kp, leaf in flat:
        p = _kp_str(kp)
        prefix = _Q_PREFIX if p in scales else _D_PREFIX
        arrays[prefix + p] = np.asarray(leaf)
    for p, s in scales.items():
        arrays[_S_PREFIX + p] = np.asarray(s)
    np.savez(path, **arrays)


def load_quantized(path: str, template_state) -> dict:
    """Rebuild a quantized pytree from ``save_quantized`` output using a
    freshly-built model's dense state as the structure template."""
    import jax

    from ..parallel.sharding import _kp_str

    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files}
    scales = {k[len(_S_PREFIX):]: arrays[k]
              for k in arrays if k.startswith(_S_PREFIX)}
    flat, treedef = jax.tree_util.tree_flatten_with_path(template_state)
    leaves = []
    for kp, leaf in flat:
        p = _kp_str(kp)
        key = (_Q_PREFIX + p) if p in scales else (_D_PREFIX + p)
        if key not in arrays:
            raise KeyError(f'quantized checkpoint {path!r} is missing {key!r} '
                           f'(model/checkpoint mismatch)')
        a = arrays[key]
        if tuple(a.shape) != tuple(getattr(leaf, 'shape', ())):
            raise ValueError(
                f'quantized checkpoint {path!r}: shape mismatch at {p!r} '
                f'({a.shape} vs model {getattr(leaf, "shape", ())})')
        leaves.append(a)
    return {QUANT_QVALUES: jax.tree_util.tree_unflatten(treedef, leaves),
            QUANT_SCALES: scales}
