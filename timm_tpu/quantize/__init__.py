"""Post-training weight-only int8 quantization for the serve path.

``quantize_tree`` is a pure pytree -> pytree transform over any model's
``nnx.State``: every eligible kernel is replaced by an int8 tensor plus a
per-output-channel symmetric scale, everything else (biases, norms, small
embeddings) stays in its original dtype. ``dequantize_tree`` is the
jit-traceable inverse used *inside* the serve/eval program, so XLA keeps the
int8 weights in HBM (they are program inputs) and the fp32/bf16 copies are
fused transients of the matmul epilogue — the HBM residency and bandwidth of
weights halve while activations stay full precision.

Scales ride the existing GSPMD partition rules: see
``parallel.sharding.build_quant_shardings`` (each scale inherits the model
axis of its kernel's last dim, so fsdp/tp placement is unchanged application
code and dequant stays collective-free).
"""
from .int8 import (
    QUANT_QVALUES, QUANT_SCALES, default_quant_predicate, dequantize_tree,
    is_quantized, load_quantized, quantization_stats, quantize_tree,
    quantized_paths, save_quantized, tree_bytes,
)

__all__ = [
    'QUANT_QVALUES', 'QUANT_SCALES', 'default_quant_predicate',
    'dequantize_tree', 'is_quantized', 'load_quantized', 'quantization_stats',
    'quantize_tree', 'quantized_paths', 'save_quantized', 'tree_bytes',
]
