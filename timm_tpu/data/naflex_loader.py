"""NaFlex data pipeline: variable-resolution images → padded token batches
(reference: timm/data/naflex_dataset.py:31-565, naflex_loader.py:27-458,
naflex_transforms.py:496-849).

TPU-first: a fixed set of seq-len buckets, each with an adaptive batch size
from a token budget — batch shapes are static per bucket, so the train step
compiles once per bucket (no recompile storms from variable resolution).

Batches are dicts: {patches (B, L, P*P*C), patch_coord (B, L, 2),
patch_valid (B, L), seq_len, target (B,)}.
"""
from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
from PIL import Image

from .constants import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD
from .transforms import RandomHorizontalFlip, str_to_pil_interp

__all__ = ['NaFlexCollator', 'NaFlexLoader', 'calculate_naflex_batch_size',
           'create_naflex_loader', 'patchify_np', 'resize_to_seq_len']


def calculate_naflex_batch_size(
        tokens_per_batch: int,
        seq_len: int,
        max_size: Optional[int] = None,
        divisor: int = 1,
        rounding: str = 'floor',
) -> int:
    """Token budget → batch size (reference naflex_dataset.py:31)."""
    batch_size = tokens_per_batch / seq_len
    if rounding == 'floor':
        batch_size = int(math.floor(batch_size / divisor) * divisor)
    elif rounding == 'ceil':
        batch_size = int(math.ceil(batch_size / divisor) * divisor)
    else:
        batch_size = int(round(batch_size / divisor) * divisor)
    batch_size = max(divisor, batch_size)
    if max_size is not None:
        batch_size = min(batch_size, max_size)
    return batch_size


def resize_to_seq_len(img: Image.Image, seq_len: int, patch_size: int, interpolation='bicubic'):
    """Resize preserving aspect so grid_h*grid_w <= seq_len
    (reference naflex_transforms.py:496 RandomResizedCropToSequence eval path)."""
    w, h = img.size
    p = patch_size
    aspect = w / h
    # largest (gh, gw) with gh*gw <= seq_len and gw/gh ~= aspect
    gh = max(1, int(math.floor(math.sqrt(seq_len / aspect))))
    gw = max(1, int(math.floor(gh * aspect)))
    while gh * gw > seq_len:
        if gw >= gh:
            gw -= 1
        else:
            gh -= 1
    while (gh + 1) * gw <= seq_len and (gh + 1) * p <= h * 4:
        gh += 1
    while gh * (gw + 1) <= seq_len and (gw + 1) * p <= w * 4:
        gw += 1
    interp = str_to_pil_interp(interpolation) if isinstance(interpolation, str) else interpolation
    return img.resize((gw * p, gh * p), interp)


def patchify_np(arr: np.ndarray, patch_size: int):
    """HWC float array → (N, P*P*C) patches + (N, 2) coords."""
    H, W, C = arr.shape
    P = patch_size
    gh, gw = H // P, W // P
    arr = arr[:gh * P, :gw * P]
    patches = arr.reshape(gh, P, gw, P, C).transpose(0, 2, 1, 3, 4).reshape(gh * gw, P * P * C)
    yy, xx = np.meshgrid(np.arange(gh), np.arange(gw), indexing='ij')
    coord = np.stack([yy, xx], axis=-1).reshape(gh * gw, 2)
    return patches, coord


class NaFlexRandomErasing:
    """Token-space random erasing (reference naflex_random_erasing.py:1):
    erase a random rectangle of PATCHES using grid coords — applied after
    patchify, so it composes with any patch size / sequence length."""

    def __init__(self, probability: float = 0.5, min_area: float = 0.02, max_area: float = 1 / 3,
                 mode: str = 'pixel', rng: Optional[random.Random] = None):
        self.probability = probability
        self.min_area = min_area
        self.max_area = max_area
        assert mode in ('pixel', 'const')
        self.mode = mode
        self.rng = rng or random.Random()

    def sample_mask(self, coord: np.ndarray) -> Optional[np.ndarray]:
        """Device-augment split: draw the erase rectangle only, returning the
        (N,) token mask (None when the probability gate fails). Fills happen
        on device: 'pixel' noise from a threaded jax.random key, 'const'
        zeros (see data/device_augment.py augment_naflex_batch)."""
        if self.rng.random() > self.probability:
            return None
        gh = int(coord[:, 0].max()) + 1
        gw = int(coord[:, 1].max()) + 1
        area = gh * gw
        target_area = self.rng.uniform(self.min_area, self.max_area) * area
        eh = max(1, min(gh, int(round(math.sqrt(target_area)))))
        ew = max(1, min(gw, int(round(target_area / eh))))
        top = self.rng.randint(0, gh - eh)
        left = self.rng.randint(0, gw - ew)
        return ((coord[:, 0] >= top) & (coord[:, 0] < top + eh) &
                (coord[:, 1] >= left) & (coord[:, 1] < left + ew))

    def __call__(self, patches: np.ndarray, coord: np.ndarray):
        mask = self.sample_mask(coord)
        if mask is None:
            return patches
        patches = patches.copy()
        if self.mode == 'pixel':
            # noise drawn from a generator seeded off self.rng → reproducible
            nrng = np.random.RandomState(self.rng.randrange(2 ** 31))
            patches[mask] = nrng.randn(int(mask.sum()), patches.shape[1]).astype(patches.dtype)
        else:
            patches[mask] = 0.0
        return patches


class NaFlexCollator:
    """Pad a list of (patches, coord, target[, target_b, lam]) to seq_len
    (reference naflex_dataset.py:74-153). When mixup metadata is present the
    batch carries `target_b` (partner labels) and per-sample `lam` weights."""

    def __init__(self, patch_size: int = 16, in_chans: int = 3):
        self.patch_size = patch_size
        self.in_chans = in_chans
        self.patch_dim = patch_size * patch_size * in_chans

    def __call__(self, samples: List[Tuple], seq_len: int, patch_size: Optional[int] = None,
                 erase_masks: Optional[List[Optional[np.ndarray]]] = None) -> Dict:
        B = len(samples)
        p_size = patch_size or self.patch_size
        patch_dim = p_size * p_size * self.in_chans
        patches = np.zeros((B, seq_len, patch_dim), np.float32)
        coord = np.zeros((B, seq_len, 2), np.int32)
        valid = np.zeros((B, seq_len), bool)
        targets = np.zeros((B,), np.int64)
        targets_b = np.zeros((B,), np.int64)
        lam = np.ones((B,), np.float32)
        has_mix = False
        for i, s in enumerate(samples):
            p, c, t = s[0], s[1], s[2]
            n = min(len(p), seq_len)
            patches[i, :n] = p[:n]
            coord[i, :n] = c[:n]
            valid[i, :n] = True
            targets[i] = t
            if len(s) > 3:
                targets_b[i] = s[3]
                lam[i] = s[4]
                has_mix = True
            else:
                targets_b[i] = t
        out = {
            'patches': patches,
            'patch_coord': coord,
            'patch_valid': valid,
            'seq_len': seq_len,
            'target': targets,
        }
        if patch_size is not None:
            out['patch_size'] = p_size
        if has_mix:
            out['target_b'] = targets_b
            out['lam'] = lam
        if erase_masks is not None:
            # device-augment split: the fill happens on device, the host only
            # ships the sampled token masks (padding rows stay False)
            em = np.zeros((B, seq_len), bool)
            for i, m in enumerate(erase_masks):
                if m is not None:
                    n = min(len(m), seq_len)
                    em[i, :n] = m[:n]
            out['erase_mask'] = em
        return out


class NaFlexLoader:
    """Iterable over token-budget batches with per-epoch (seq_len, batch_size)
    schedules (reference NaFlexMapDatasetWrapper, naflex_dataset.py:200)."""

    def __init__(
            self,
            dataset,
            tokens_per_batch: int = 576 * 64,
            seq_lens: Sequence[int] = (128, 256, 576, 784, 1024),
            patch_size: int = 16,
            patch_size_choices: Optional[Sequence[int]] = None,
            patch_size_choice_probs: Optional[Sequence[float]] = None,
            is_training: bool = False,
            mean=IMAGENET_DEFAULT_MEAN,
            std=IMAGENET_DEFAULT_STD,
            interpolation: str = 'bicubic',
            hflip: float = 0.5,
            mixup_alpha: float = 0.0,
            cutmix_alpha: float = 0.0,
            mixup_prob: float = 1.0,
            mixup_switch_prob: float = 0.5,
            re_prob: float = 0.0,
            re_mode: str = 'pixel',
            seed: int = 42,
            process_index: int = 0,
            process_count: int = 1,
            batch_divisor: int = 1,
            device_augment: bool = False,
            bucket_mode: str = 'budget',
    ):
        if bucket_mode not in ('budget', 'native'):
            raise ValueError(f"bucket_mode must be 'budget' or 'native', got {bucket_mode!r}")
        if bucket_mode == 'native':
            if process_count > 1:
                raise ValueError(
                    'bucket_mode="native" assigns batches from per-image sizes, which is '
                    'data-dependent and cannot keep multi-host SPMD programs in lockstep; '
                    'use bucket_mode="budget" for multi-process training')
            if patch_size_choices:
                raise ValueError(
                    'bucket_mode="native" uses a fixed patch_size (bucket assignment '
                    'depends on it); patch_size_choices is only supported in budget mode')
        self.dataset = dataset
        self.tokens_per_batch = tokens_per_batch
        self.seq_lens = tuple(sorted(seq_lens))
        self.patch_size = patch_size
        self.patch_size_choices = tuple(patch_size_choices) if patch_size_choices else None
        if self.patch_size_choices and patch_size_choice_probs:
            assert len(patch_size_choice_probs) == len(self.patch_size_choices)
            self.patch_size_choice_probs = tuple(patch_size_choice_probs)
        elif self.patch_size_choices:
            self.patch_size_choice_probs = (1.0 / len(self.patch_size_choices),) * len(self.patch_size_choices)
        else:
            self.patch_size_choice_probs = None
        self.is_training = is_training
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.interpolation = interpolation
        self.hflip = RandomHorizontalFlip(hflip) if is_training and hflip > 0 else None
        self.mixup_alpha = mixup_alpha if is_training else 0.0
        self.cutmix_alpha = cutmix_alpha if is_training else 0.0
        self.mixup_prob = mixup_prob
        self.mixup_switch_prob = mixup_switch_prob
        self.random_erasing = NaFlexRandomErasing(
            re_prob, mode=re_mode, rng=random.Random(seed * 7919 + 13)) \
            if re_prob > 0 and is_training else None
        self.seed = seed
        self.epoch = 0
        self.process_index = process_index
        self.process_count = process_count
        self.batch_divisor = max(1, batch_divisor)
        self.device_augment = device_augment
        self.bucket_mode = bucket_mode
        self._native_len = None  # exact batch count, known after one native epoch
        self.collator = NaFlexCollator(patch_size)
        # dataset must yield PIL images: disable any tensor transform
        if getattr(dataset, 'transform', None) is not None:
            import logging
            logging.getLogger(__name__).warning(
                'NaFlexLoader clearing existing dataset.transform — the NaFlex '
                'pipeline does its own resize/patchify; do not share this '
                'dataset instance with a tensor loader')
            dataset.transform = None

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def _schedule(self) -> List[Tuple[int, int, List[int]]]:
        """Assign samples to (seq_len, batch) groups for this epoch.

        Multi-host safe: the schedule is computed over the GLOBAL index list
        with per-batch sizes divisible by process_count, and every process
        takes its slice of every batch — all hosts see the same batch count
        and shapes, so SPMD collectives stay in sync.
        """
        rng = random.Random(self.seed + self.epoch)
        n = len(self.dataset)
        indices = list(range(n))
        if self.is_training:
            rng.shuffle(indices)
        batches = []
        pos = 0
        divisor = self.process_count * self.batch_divisor
        while pos < len(indices):
            seq_len = rng.choice(self.seq_lens) if self.is_training else self.seq_lens[-1]
            if self.is_training and self.patch_size_choices:
                patch_size = rng.choices(self.patch_size_choices, self.patch_size_choice_probs)[0]
            else:
                patch_size = self.patch_size
            bs = calculate_naflex_batch_size(
                self.tokens_per_batch, seq_len, divisor=divisor)
            group = indices[pos:pos + bs]
            pos += bs
            if len(group) < bs:
                if self.is_training:
                    break  # drop ragged trailing batch in training (all hosts agree)
                # eval: pad by wrapping so the batch shape stays full
                group = group + indices[:bs - len(group)]
            # this host's slice of the global batch
            local = group[self.process_index::self.process_count]
            batches.append((seq_len, patch_size, bs // self.process_count, local))
        return batches

    def __len__(self):
        if self.bucket_mode == 'native':
            if self._native_len is not None:
                return self._native_len
            # estimate before the first epoch (bucket assignment is
            # data-dependent); exact after one full pass
            divisor = self.process_count * self.batch_divisor
            bs = calculate_naflex_batch_size(
                self.tokens_per_batch, self.seq_lens[-1], divisor=divisor)
            return max(1, len(self.dataset) // bs)
        return len(self._schedule())

    def _load_array(self, img) -> np.ndarray:
        arr = np.asarray(img, np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if not self.device_augment:
            # device-augment keeps [0,1] floats; the jitted device program
            # normalizes (mixup commutes with the affine normalize, and erase
            # runs post-normalize on device just like the host path)
            arr = (arr - self.mean) / self.std
        return arr

    def _make_samples(self, arrays, targets, patch_size, mix_rng):
        """Mixup + patchify + erase for one batch group. Returns (samples,
        erase_masks) where erase_masks is None unless the device-augment
        split is active (then it parallels `samples`, entries None when the
        per-sample probability gate failed)."""
        do_mix = ((self.mixup_alpha > 0 or self.cutmix_alpha > 0) and len(arrays) > 1
                  and mix_rng.random() < self.mixup_prob)
        if do_mix:
            from .naflex_mixup import mix_batch_variable_size
            arrays, lams, pair_to = mix_batch_variable_size(
                arrays, mixup_alpha=self.mixup_alpha, cutmix_alpha=self.cutmix_alpha,
                switch_prob=self.mixup_switch_prob, rng=mix_rng)
        sample_masks = self.device_augment and self.random_erasing is not None
        erase_masks = [] if sample_masks else None
        samples = []
        for i, arr in enumerate(arrays):
            p, c = patchify_np(arr, patch_size)
            if sample_masks:
                erase_masks.append(self.random_erasing.sample_mask(c))
            elif self.random_erasing is not None:
                p = self.random_erasing(p, c)
            if do_mix:
                t_b = targets[pair_to[i]] if i in pair_to else targets[i]
                samples.append((p, c, targets[i], t_b, lams[i]))
            else:
                samples.append((p, c, targets[i]))
        return samples, erase_masks

    def _iter_budget(self):
        mix_rng = random.Random(self.seed * 31 + self.epoch)
        for seq_len, patch_size, bs, group in self._schedule():
            arrays, targets = [], []
            for idx in group:
                img, target = self.dataset[idx]
                if self.hflip is not None:
                    img = self.hflip(img)
                img = resize_to_seq_len(img, seq_len, patch_size, self.interpolation)
                arrays.append(self._load_array(img))
                targets.append(target)
            samples, erase_masks = self._make_samples(arrays, targets, patch_size, mix_rng)
            yield self.collator(
                samples, seq_len,
                patch_size=patch_size if self.patch_size_choices else None,
                erase_masks=erase_masks)

    def _iter_native(self):
        """Smallest-fit bucketing (reuses serve/bucketing.py semantics): each
        image goes to the smallest ladder bucket holding its NATIVE grid's
        token count, instead of a randomly scheduled seq_len. Batches are
        emitted whenever a bucket's buffer fills; training drops ragged
        leftovers, eval wrap-pads them so shapes stay static."""
        from ..serve.bucketing import select_bucket
        mix_rng = random.Random(self.seed * 31 + self.epoch)
        rng = random.Random(self.seed + self.epoch)
        indices = list(range(len(self.dataset)))
        if self.is_training:
            rng.shuffle(indices)
        p = self.patch_size
        divisor = self.process_count * self.batch_divisor
        bucket_bs = {s: calculate_naflex_batch_size(self.tokens_per_batch, s, divisor=divisor)
                     for s in self.seq_lens}
        buffers = {s: [] for s in self.seq_lens}
        max_bucket = self.seq_lens[-1]
        count = 0

        def emit(seq_len, buf):
            arrays = [a for a, _ in buf]
            targets = [t for _, t in buf]
            samples, erase_masks = self._make_samples(arrays, targets, p, mix_rng)
            return self.collator(samples, seq_len, erase_masks=erase_masks)

        for idx in indices:
            img, target = self.dataset[idx]
            if self.hflip is not None:
                img = self.hflip(img)
            w, h = img.size
            tokens = max(1, round(h / p)) * max(1, round(w / p))
            bucket = select_bucket(min(tokens, max_bucket), self.seq_lens)
            img = resize_to_seq_len(img, bucket, p, self.interpolation)
            buffers[bucket].append((self._load_array(img), target))
            if len(buffers[bucket]) == bucket_bs[bucket]:
                yield emit(bucket, buffers[bucket])
                buffers[bucket] = []
                count += 1
        if not self.is_training:
            for s in self.seq_lens:
                buf = buffers[s]
                if buf:
                    reps = -(-bucket_bs[s] // len(buf))
                    yield emit(s, (buf * reps)[:bucket_bs[s]])
                    count += 1
        self._native_len = count

    def __iter__(self):
        if self.bucket_mode == 'native':
            return self._iter_native()
        return self._iter_budget()


def create_naflex_loader(
        dataset,
        patch_size: int = 16,
        patch_size_choices: Optional[Sequence[int]] = None,
        patch_size_choice_probs: Optional[Sequence[float]] = None,
        train_seq_lens: Sequence[int] = (128, 256, 576, 784, 1024),
        max_seq_len: int = 576,
        batch_size: int = 32,  # batch size at max_seq_len → token budget
        is_training: bool = False,
        mean=IMAGENET_DEFAULT_MEAN,
        std=IMAGENET_DEFAULT_STD,
        interpolation: str = 'bicubic',
        hflip: float = 0.5,
        mixup_alpha: float = 0.0,
        cutmix_alpha: float = 0.0,
        mixup_prob: float = 1.0,
        mixup_switch_prob: float = 0.5,
        re_prob: float = 0.0,
        re_mode: str = 'pixel',
        seed: int = 42,
        grad_accum_steps: int = 1,
        device_augment: bool = False,
        bucket_mode: str = 'budget',
        device_prefetch: int = 0,
        **kwargs,
):
    """(reference naflex_loader.py:225).

    With grad accumulation the token budget scales by the accum steps so the
    jitted step's microbatches are each `batch_size` — the effective update
    batch matches the tuple pipeline's global batch (batch_size * accum).

    device_augment=True moves normalize + random-erase fill into a donated
    jitted on-device program (one per bucket shape); the host ships [0,1]
    patches plus sampled erase-token masks. device_prefetch>0 additionally
    wraps the loader in a DevicePrefetcher so transfers overlap the step."""
    import jax
    tokens_per_batch = batch_size * max(1, grad_accum_steps) * max_seq_len
    seq_lens = train_seq_lens if is_training else (max_seq_len,)
    loader = NaFlexLoader(
        dataset,
        tokens_per_batch=tokens_per_batch,
        seq_lens=seq_lens,
        patch_size=patch_size,
        patch_size_choices=patch_size_choices,
        patch_size_choice_probs=patch_size_choice_probs,
        is_training=is_training,
        mean=mean,
        std=std,
        interpolation=interpolation,
        hflip=hflip,
        mixup_alpha=mixup_alpha,
        cutmix_alpha=cutmix_alpha,
        mixup_prob=mixup_prob,
        mixup_switch_prob=mixup_switch_prob,
        re_prob=re_prob,
        re_mode=re_mode,
        seed=seed,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        batch_divisor=max(1, grad_accum_steps),
        device_augment=device_augment,
        bucket_mode=bucket_mode,
    )
    if device_prefetch:
        from .loader import DevicePrefetcher
        loader = DevicePrefetcher(loader, size=device_prefetch)
    if device_augment:
        from .device_augment import NaFlexDeviceAugment
        loader = NaFlexDeviceAugment(
            loader, mean=mean, std=std, re_mode=re_mode, noise_seed=seed)
    return loader
