"""Dataset factory (reference: timm/data/dataset_factory.py:63-230).

Name-scheme dispatch: '' / 'folder' → ImageFolder; 'hfds/name' → HuggingFace
map-style datasets (when the library is present). TFDS/WDS schemes raise with
guidance until those readers land.
"""
from __future__ import annotations

import os
from typing import Optional

from .dataset import ImageDataset

__all__ = ['create_dataset']


def _search_split(root: str, split: str) -> str:
    split_name = split.split('[')[0]
    try_root = os.path.join(root, split_name)
    if os.path.exists(try_root):
        return try_root
    def _try(syn):
        p = os.path.join(root, syn)
        return p if os.path.exists(p) else None
    if split_name in ('validation', 'val'):
        for syn in ('val', 'validation', 'eval', 'test'):
            p = _try(syn)
            if p:
                return p
    if split_name == 'train':
        p = _try('training')
        if p:
            return p
    return root


class HfdsWrapper:
    """Map-style HF datasets → (PIL, label) samples."""

    def __init__(self, name, root, split, input_key='image', target_key='label'):
        import datasets as hfds
        split = {'validation': 'validation', 'val': 'validation', 'train': 'train'}.get(split, split)
        self.ds = hfds.load_dataset(name, cache_dir=root or None, split=split)
        self.input_key = input_key
        self.target_key = target_key
        self.transform = None
        self.target_transform = None

    def __len__(self):
        return len(self.ds)

    def __getitem__(self, index):
        item = self.ds[int(index)]
        img = item[self.input_key]
        if img.mode != 'RGB':
            img = img.convert('RGB')
        if self.transform is not None:
            img = self.transform(img)
        target = item.get(self.target_key, -1)
        if self.target_transform is not None:
            target = self.target_transform(target)
        return img, target


def create_dataset(
        name: str = '',
        root: Optional[str] = None,
        split: str = 'validation',
        search_split: bool = True,
        class_map=None,
        is_training: bool = False,
        num_classes: Optional[int] = None,
        input_img_mode: str = 'RGB',
        **kwargs,
):
    """(reference dataset_factory.py:63)."""
    kwargs = {k: v for k, v in kwargs.items() if v is not None}
    name = name or ''
    if name.startswith('hfds/'):
        return HfdsWrapper(name[5:], root, split, **{k: kwargs[k] for k in ('input_key', 'target_key') if k in kwargs})
    if name.startswith('wds/'):
        import jax
        from .dataset import IterableImageDataset
        from .readers_streaming import ReaderWds
        reader = ReaderWds(
            root=name[4:] if name[4:] else root,
            split=split,
            is_training=is_training,
            seed=kwargs.get('seed', 42),
            input_img_mode=input_img_mode,
            input_key=kwargs.get('input_key'),
            target_key=kwargs.get('target_key'),
            dist_rank=jax.process_index(),
            dist_num_replicas=jax.process_count(),
        )
        return IterableImageDataset(root, reader=reader)
    if name.startswith('tfds/'):
        import jax
        from .dataset import IterableImageDataset
        from .readers_streaming import ReaderTfds
        reader = ReaderTfds(
            root=root, name=name[5:], split=split, is_training=is_training,
            seed=kwargs.get('seed', 42), input_img_mode=input_img_mode,
            dist_rank=jax.process_index(), dist_num_replicas=jax.process_count(),
        )
        return IterableImageDataset(root, reader=reader)
    if name.startswith('hfids/'):
        import jax

        from .dataset import IterableImageDataset
        from .readers_streaming import ReaderHfids
        reader = ReaderHfids(
            name=name[6:], root=root, split=split, is_training=is_training,
            seed=kwargs.get('seed', 42), input_img_mode=input_img_mode,
            input_key=kwargs.get('input_key', 'image'),
            target_key=kwargs.get('target_key', 'label'),
            dist_rank=jax.process_index(), dist_num_replicas=jax.process_count(),
        )
        return IterableImageDataset(root, reader=reader)
    if name.startswith('torch/'):
        # torchvision dataset schemes (reference dataset_factory.py:63-230);
        # torchvision is an optional dependency here
        try:
            from torchvision import datasets as tv_datasets
        except ImportError as e:
            raise ImportError(
                'torch/ dataset schemes require torchvision, which is not installed') from e
        name = name[6:].lower()
        tv_split = 'train' if is_training or split in ('train', 'training') else 'val'
        _simple = dict(
            cifar10=tv_datasets.CIFAR10, cifar100=tv_datasets.CIFAR100,
            mnist=tv_datasets.MNIST, kmnist=tv_datasets.KMNIST,
            fashion_mnist=tv_datasets.FashionMNIST, qmnist=tv_datasets.QMNIST,
        )
        if name in _simple:
            return _simple[name](root=root, train=tv_split == 'train', download=kwargs.get('download', False))
        if name == 'image_folder' or name == 'folder':
            if search_split and root and os.path.isdir(root):
                root = _search_split(root, split)
            return tv_datasets.ImageFolder(root)
        if name == 'places365':
            return tv_datasets.Places365(
                root=root, split='train-standard' if tv_split == 'train' else 'val',
                download=kwargs.get('download', False))
        if name == 'imagenet':
            return tv_datasets.ImageNet(root=root, split=tv_split)
        raise ValueError(f'Unknown torchvision dataset {name}')
    # tar file(s): map-style reader over image members
    if root and (str(root).endswith('.tar') or name == 'tar'):
        from .readers_streaming import ReaderImageInTar
        reader = ReaderImageInTar(root, class_map=class_map or '', input_img_mode=input_img_mode)
        return ImageDataset(root, reader=reader, split=split, input_img_mode=input_img_mode)
    # folder default
    if search_split and root and os.path.isdir(root):
        root = _search_split(root, split)
    return ImageDataset(
        root, split=split, class_map=class_map or '', input_img_mode=input_img_mode, **kwargs)
