"""Data config resolution (reference: timm/data/config.py:8-129)."""
from __future__ import annotations

import logging

from .constants import (
    DEFAULT_CROP_MODE, DEFAULT_CROP_PCT, IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD,
)

_logger = logging.getLogger(__name__)

__all__ = ['resolve_data_config', 'resolve_model_data_config']


def resolve_data_config(
        args=None,
        pretrained_cfg=None,
        model=None,
        use_test_size: bool = False,
        verbose: bool = False,
):
    """Merge CLI args > model pretrained_cfg > defaults (reference config.py:8)."""
    args = args or {}
    pretrained_cfg = pretrained_cfg or {}
    if not pretrained_cfg and model is not None and hasattr(model, 'pretrained_cfg'):
        pc = model.pretrained_cfg
        pretrained_cfg = pc.to_dict() if hasattr(pc, 'to_dict') else dict(pc)

    data_config = {}

    # input size
    in_chans = 3
    if args.get('in_chans') is not None:
        in_chans = args['in_chans']
    elif args.get('chans') is not None:
        in_chans = args['chans']
    input_size = (in_chans, 224, 224)
    if args.get('input_size') is not None:
        assert len(args['input_size']) == 3
        input_size = tuple(args['input_size'])
        in_chans = input_size[0]
    elif args.get('img_size') is not None:
        assert isinstance(args['img_size'], int)
        input_size = (in_chans, args['img_size'], args['img_size'])
    else:
        if use_test_size and pretrained_cfg.get('test_input_size'):
            input_size = pretrained_cfg['test_input_size']
        elif pretrained_cfg.get('input_size'):
            input_size = pretrained_cfg['input_size']
    data_config['input_size'] = tuple(input_size)

    # interpolation / mean / std
    data_config['interpolation'] = args.get('interpolation') or pretrained_cfg.get('interpolation', 'bicubic')
    data_config['mean'] = tuple(args.get('mean') or pretrained_cfg.get('mean', IMAGENET_DEFAULT_MEAN))
    data_config['std'] = tuple(args.get('std') or pretrained_cfg.get('std', IMAGENET_DEFAULT_STD))
    if args.get('mean') is not None:
        mean = tuple(args['mean'])
        if len(mean) == 1:
            mean = mean * in_chans
        data_config['mean'] = mean
    if args.get('std') is not None:
        std = tuple(args['std'])
        if len(std) == 1:
            std = std * in_chans
        data_config['std'] = std

    # crop
    crop_pct = DEFAULT_CROP_PCT
    if args.get('crop_pct'):
        crop_pct = args['crop_pct']
    else:
        if use_test_size and pretrained_cfg.get('test_crop_pct'):
            crop_pct = pretrained_cfg['test_crop_pct']
        elif pretrained_cfg.get('crop_pct'):
            crop_pct = pretrained_cfg['crop_pct']
    data_config['crop_pct'] = crop_pct
    data_config['crop_mode'] = args.get('crop_mode') or pretrained_cfg.get('crop_mode', DEFAULT_CROP_MODE)

    if verbose:
        _logger.info('Data processing configuration for current model + dataset:')
        for n, v in data_config.items():
            _logger.info(f'\t{n}: {str(v)}')
    return data_config


def resolve_model_data_config(model, args=None, pretrained_cfg=None, use_test_size=False, verbose=False):
    return resolve_data_config(
        args=args, pretrained_cfg=pretrained_cfg, model=model,
        use_test_size=use_test_size, verbose=verbose)
