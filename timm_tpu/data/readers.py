"""Dataset readers (reference: timm/data/readers/ — ReaderImageFolder at
reader_image_folder.py:59, class-map handling, factory)."""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

__all__ = ['ReaderImageFolder', 'create_reader', 'load_class_map']

IMG_EXTENSIONS = ('.png', '.jpg', '.jpeg', '.gif', '.bmp', '.webp', '.ppm', '.tif', '.tiff')


def natural_key(string_: str):
    import re
    return [int(s) if s.isdigit() else s for s in re.split(r'(\d+)', string_.lower())]


def load_class_map(map_or_filename, root: str = ''):
    if isinstance(map_or_filename, dict):
        return map_or_filename
    class_map_path = map_or_filename
    if not os.path.exists(class_map_path):
        class_map_path = os.path.join(root, class_map_path)
        assert os.path.exists(class_map_path), f'Cannot locate specified class map file ({map_or_filename})'
    class_map_ext = os.path.splitext(map_or_filename)[-1].lower()
    if class_map_ext == '.txt':
        with open(class_map_path) as f:
            class_to_idx = {v.strip(): k for k, v in enumerate(f)}
    elif class_map_ext == '.json':
        import json
        with open(class_map_path) as f:
            class_to_idx = json.load(f)
    else:
        raise AssertionError(f'Unsupported class map file extension ({class_map_ext})')
    return class_to_idx


def find_images_and_targets(
        folder: str,
        types=IMG_EXTENSIONS,
        class_to_idx: Optional[Dict] = None,
        sort: bool = True,
):
    labels = []
    filenames = []
    for root, _, files in os.walk(folder, topdown=False, followlinks=True):
        rel_path = os.path.relpath(root, folder) if root != folder else ''
        label = rel_path.replace(os.path.sep, '_')
        for f in files:
            _, ext = os.path.splitext(f)
            if ext.lower() in types:
                filenames.append(os.path.join(root, f))
                labels.append(label)
    if class_to_idx is None:
        unique_labels = set(labels)
        sorted_labels = sorted(unique_labels, key=natural_key)
        class_to_idx = {c: idx for idx, c in enumerate(sorted_labels)}
    images_and_targets = [
        (f, class_to_idx[l]) for f, l in zip(filenames, labels) if l in class_to_idx]
    if sort:
        images_and_targets = sorted(images_and_targets, key=lambda k: natural_key(k[0]))
    return images_and_targets, class_to_idx


class ReaderImageFolder:
    """folder-of-class-folders reader (reference reader_image_folder.py:59)."""

    def __init__(self, root: str, class_map='', input_key=None, target_key=None):
        self.root = root
        class_to_idx = None
        if class_map:
            class_to_idx = load_class_map(class_map, root)
        self.samples, self.class_to_idx = find_images_and_targets(root, class_to_idx=class_to_idx)
        if len(self.samples) == 0:
            raise RuntimeError(
                f'Found 0 images in subfolders of {root}. Supported extensions: {", ".join(IMG_EXTENSIONS)}')

    def __getitem__(self, index: int):
        path, target = self.samples[index]
        return open(path, 'rb'), target

    def __len__(self):
        return len(self.samples)

    def _filename(self, index, basename=False, absolute=False):
        filename = self.samples[index][0]
        if basename:
            filename = os.path.basename(filename)
        elif not absolute:
            filename = os.path.relpath(filename, self.root)
        return filename

    def filename(self, index, basename=False, absolute=False):
        return self._filename(index, basename=basename, absolute=absolute)

    def filenames(self, basename=False, absolute=False):
        return [self._filename(i, basename=basename, absolute=absolute) for i in range(len(self))]


def create_reader(name: str, root: str, split: str = 'train', **kwargs):
    """Reader factory (reference reader_factory.py). Expects `root` to be the
    final split directory — split resolution happens once, in
    dataset_factory._search_split. Folder reader is the built-in; tfds/wds/hf
    schemes layer on later."""
    name = (name or '').lower()
    prefix = ''
    if ':' in name:
        prefix, _, name = name.partition(':')
    if prefix in ('', 'folder'):
        return ReaderImageFolder(root, **kwargs)
    raise ValueError(f'Unsupported reader scheme: {prefix}')
