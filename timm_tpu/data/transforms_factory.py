"""Transform pipeline factory (reference: timm/data/transforms_factory.py:20-520)."""
from __future__ import annotations

from typing import Optional, Tuple, Union

from .auto_augment import augment_and_mix_transform, auto_augment_transform, rand_augment_transform
from .constants import DEFAULT_CROP_PCT, IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD
from .transforms import (
    CenterCrop, CenterCropOrPad, ColorJitter, Compose, RandomApply,
    RandomGaussianBlur, RandomGrayscale, RandomHorizontalFlip,
    RandomResizedCropAndInterpolation, RandomVerticalFlip, Resize, ResizeKeepRatio,
    ToNumpy, TrimBorder, str_to_pil_interp,
)

__all__ = ['create_transform', 'transforms_imagenet_train', 'transforms_imagenet_eval', 'transforms_noaug_train']


def transforms_noaug_train(
        img_size=224,
        interpolation='bilinear',
        output_dtype=None,
        **kwargs,
):
    if interpolation == 'random':
        interpolation = 'bilinear'
    return Compose([
        Resize(img_size if isinstance(img_size, int) else max(img_size), interpolation=interpolation),
        CenterCrop(img_size),
        ToNumpy(output_dtype) if output_dtype is not None else ToNumpy(),
    ])


def transforms_imagenet_train(
        img_size=224,
        scale=None,
        ratio=None,
        train_crop_mode=None,
        hflip: float = 0.5,
        vflip: float = 0.0,
        color_jitter: Union[float, Tuple] = 0.4,
        color_jitter_prob: Optional[float] = None,
        grayscale_prob: float = 0.0,
        gaussian_blur_prob: float = 0.0,
        auto_augment: Optional[str] = None,
        interpolation: str = 'random',
        mean=IMAGENET_DEFAULT_MEAN,
        re_prob: float = 0.0,
        re_mode: str = 'const',
        re_count: int = 1,
        re_num_splits: int = 0,
        separate: bool = False,
        output_dtype=None,
        **kwargs,
):
    """Train pipeline (reference transforms_factory.py:65). `output_dtype`
    overrides the ToNumpy dtype — np.uint8 keeps raw bytes for the
    device-augment path."""
    scale = tuple(scale or (0.08, 1.0))
    ratio = tuple(ratio or (3. / 4., 4. / 3.))
    primary_tfl = [RandomResizedCropAndInterpolation(img_size, scale=scale, ratio=ratio, interpolation=interpolation)]
    if hflip > 0.0:
        primary_tfl.append(RandomHorizontalFlip(p=hflip))
    if vflip > 0.0:
        primary_tfl.append(RandomVerticalFlip(p=vflip))

    secondary_tfl = []
    if auto_augment:
        assert isinstance(auto_augment, str)
        img_size_min = img_size if isinstance(img_size, int) else min(img_size)
        aa_params = dict(
            translate_const=int(img_size_min * 0.45),
            img_mean=tuple(int(round(255 * x)) for x in mean),
        )
        if interpolation and interpolation != 'random':
            aa_params['interpolation'] = str_to_pil_interp(interpolation)
        if auto_augment.startswith('rand'):
            secondary_tfl.append(rand_augment_transform(auto_augment, aa_params))
        elif auto_augment.startswith('augmix'):
            secondary_tfl.append(augment_and_mix_transform(auto_augment, aa_params))
        else:
            secondary_tfl.append(auto_augment_transform(auto_augment, aa_params))
    elif color_jitter is not None and color_jitter != 0:
        if isinstance(color_jitter, (list, tuple)):
            assert len(color_jitter) in (3, 4)
        else:
            color_jitter = (float(color_jitter),) * 3
        jitter = ColorJitter(*color_jitter)
        secondary_tfl.append(
            RandomApply(jitter, p=color_jitter_prob) if color_jitter_prob is not None else jitter)
    if grayscale_prob:
        secondary_tfl.append(RandomGrayscale(p=grayscale_prob))
    if gaussian_blur_prob:
        secondary_tfl.append(RandomGaussianBlur(p=gaussian_blur_prob))

    final_tfl = [ToNumpy(output_dtype) if output_dtype is not None else ToNumpy()]
    # NOTE: RandomErasing runs post-collate on the batch (see loader.py) to
    # mirror the reference's device-side erasing placement.
    if separate:
        return (Compose(primary_tfl), Compose(secondary_tfl), Compose(final_tfl))
    return Compose(primary_tfl + secondary_tfl + final_tfl)


def transforms_imagenet_eval(
        img_size=224,
        crop_pct: Optional[float] = None,
        crop_mode: Optional[str] = None,
        crop_border_pixels: Optional[int] = None,
        interpolation: str = 'bilinear',
        output_dtype=None,
        **kwargs,
):
    """Eval pipeline w/ crop modes (reference transforms_factory.py:273)."""
    crop_pct = crop_pct or DEFAULT_CROP_PCT
    if isinstance(img_size, (tuple, list)):
        assert len(img_size) == 2
        scale_size = tuple(int(x / crop_pct) for x in img_size)
    else:
        scale_size = int(img_size / crop_pct)
    if interpolation == 'random':
        interpolation = 'bilinear'

    crop_mode = crop_mode or 'center'
    tfl = []
    if crop_border_pixels:
        tfl.append(TrimBorder(crop_border_pixels))
    if crop_mode == 'squash':
        size = (img_size, img_size) if isinstance(img_size, int) else img_size
        ss = (scale_size, scale_size) if isinstance(scale_size, int) else scale_size
        tfl += [Resize(ss, interpolation=interpolation), CenterCrop(img_size)]
    elif crop_mode == 'border':
        tfl += [ResizeKeepRatio(img_size, longest=1.0, interpolation=interpolation), CenterCropOrPad(img_size)]
    else:  # center
        tfl += [Resize(scale_size, interpolation=interpolation), CenterCrop(img_size)]
    tfl.append(ToNumpy(output_dtype) if output_dtype is not None else ToNumpy())
    return Compose(tfl)


def create_transform(
        input_size=224,
        is_training: bool = False,
        no_aug: bool = False,
        train_crop_mode=None,
        scale=None,
        ratio=None,
        hflip: float = 0.5,
        vflip: float = 0.0,
        color_jitter=0.4,
        color_jitter_prob=None,
        grayscale_prob=0.0,
        gaussian_blur_prob=0.0,
        auto_augment=None,
        interpolation: str = 'bilinear',
        mean=IMAGENET_DEFAULT_MEAN,
        std=IMAGENET_DEFAULT_STD,
        re_prob: float = 0.0,
        re_mode: str = 'const',
        re_count: int = 1,
        re_num_splits: int = 0,
        crop_pct=None,
        crop_mode=None,
        crop_border_pixels=None,
        separate: bool = False,
        output_dtype=None,
        **kwargs,
):
    """(reference transforms_factory.py:379)."""
    if isinstance(input_size, (tuple, list)):
        img_size = input_size[-2:]
        if img_size[0] == img_size[1]:
            img_size = img_size[0]
    else:
        img_size = input_size

    if is_training and no_aug:
        return transforms_noaug_train(img_size, interpolation=interpolation,
                                      output_dtype=output_dtype)
    if is_training:
        return transforms_imagenet_train(
            img_size,
            scale=scale,
            ratio=ratio,
            train_crop_mode=train_crop_mode,
            hflip=hflip,
            vflip=vflip,
            color_jitter=color_jitter,
            color_jitter_prob=color_jitter_prob,
            grayscale_prob=grayscale_prob,
            gaussian_blur_prob=gaussian_blur_prob,
            auto_augment=auto_augment,
            interpolation=interpolation,
            mean=mean,
            re_prob=re_prob,
            re_mode=re_mode,
            re_count=re_count,
            re_num_splits=re_num_splits,
            separate=separate,
            output_dtype=output_dtype,
        )
    return transforms_imagenet_eval(
        img_size,
        crop_pct=crop_pct,
        crop_mode=crop_mode,
        crop_border_pixels=crop_border_pixels,
        interpolation=interpolation,
        output_dtype=output_dtype,
    )
