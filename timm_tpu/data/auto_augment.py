"""AutoAugment / RandAugment / AugMix (reference: timm/data/auto_augment.py:1-1000).

PIL-op implementations with the same magnitude conventions and config-string
grammar as the reference ('rand-m9-mstd0.5-inc1', 'original', 'v0',
'augmix-m5-w4-d2'), so recipes transfer unchanged.
"""
from __future__ import annotations

import math
import random
import re
from typing import Dict, List, Optional, Tuple, Union

import numpy as np
from PIL import Image, ImageEnhance, ImageOps

__all__ = [
    'auto_augment_transform', 'rand_augment_transform', 'augment_and_mix_transform',
    'AutoAugment', 'RandAugment', 'AugMixAugment',
]

_LEVEL_DENOM = 10.0
_FILL = (128, 128, 128)


# ---- PIL ops ---------------------------------------------------------------

def _interpolation(kwargs):
    interp = kwargs.pop('resample', Image.BILINEAR)
    if isinstance(interp, (list, tuple)):
        return random.choice(interp)
    return interp


def shear_x(img, factor, **kwargs):
    return img.transform(img.size, Image.AFFINE, (1, factor, 0, 0, 1, 0),
                         resample=_interpolation(kwargs), fillcolor=kwargs.get('fillcolor', _FILL))


def shear_y(img, factor, **kwargs):
    return img.transform(img.size, Image.AFFINE, (1, 0, 0, factor, 1, 0),
                         resample=_interpolation(kwargs), fillcolor=kwargs.get('fillcolor', _FILL))


def translate_x_rel(img, pct, **kwargs):
    pixels = pct * img.size[0]
    return img.transform(img.size, Image.AFFINE, (1, 0, pixels, 0, 1, 0),
                         resample=_interpolation(kwargs), fillcolor=kwargs.get('fillcolor', _FILL))


def translate_y_rel(img, pct, **kwargs):
    pixels = pct * img.size[1]
    return img.transform(img.size, Image.AFFINE, (1, 0, 0, 0, 1, pixels),
                         resample=_interpolation(kwargs), fillcolor=kwargs.get('fillcolor', _FILL))


def translate_x_abs(img, pixels, **kwargs):
    return img.transform(img.size, Image.AFFINE, (1, 0, pixels, 0, 1, 0),
                         resample=_interpolation(kwargs), fillcolor=kwargs.get('fillcolor', _FILL))


def translate_y_abs(img, pixels, **kwargs):
    return img.transform(img.size, Image.AFFINE, (1, 0, 0, 0, 1, pixels),
                         resample=_interpolation(kwargs), fillcolor=kwargs.get('fillcolor', _FILL))


def rotate(img, degrees, **kwargs):
    return img.rotate(degrees, resample=_interpolation(kwargs), fillcolor=kwargs.get('fillcolor', _FILL))


def auto_contrast(img, **kwargs):
    return ImageOps.autocontrast(img)


def invert(img, **kwargs):
    return ImageOps.invert(img)


def equalize(img, **kwargs):
    return ImageOps.equalize(img)


def solarize(img, thresh, **kwargs):
    return ImageOps.solarize(img, thresh)


def solarize_add(img, add, thresh=128, **kwargs):
    lut = [min(255, i + add) if i < thresh else i for i in range(256)]
    if img.mode in ('L', 'RGB'):
        if img.mode == 'RGB':
            lut = lut + lut + lut
        return img.point(lut)
    return img


def posterize(img, bits, **kwargs):
    if bits >= 8:
        return img
    return ImageOps.posterize(img, bits)


def contrast(img, factor, **kwargs):
    return ImageEnhance.Contrast(img).enhance(factor)


def color(img, factor, **kwargs):
    return ImageEnhance.Color(img).enhance(factor)


def brightness(img, factor, **kwargs):
    return ImageEnhance.Brightness(img).enhance(factor)


def sharpness(img, factor, **kwargs):
    return ImageEnhance.Sharpness(img).enhance(factor)


def gaussian_blur(img, factor, **kwargs):
    from PIL import ImageFilter
    return img.filter(ImageFilter.GaussianBlur(radius=factor))


def desaturate(img, factor, **kwargs):
    return ImageEnhance.Color(img).enhance(min(1.0, factor))


# ---- magnitude → op-arg conversion -----------------------------------------

def _randomly_negate(v):
    return -v if random.random() > 0.5 else v


def _rotate_level(level, _hparams):
    return (_randomly_negate((level / _LEVEL_DENOM) * 30.0),)


def _enhance_level(level, _hparams):
    return ((level / _LEVEL_DENOM) * 1.8 + 0.1,)


def _enhance_increasing_level(level, _hparams):
    return (max(0.1, 1.0 + _randomly_negate((level / _LEVEL_DENOM) * 0.9)),)


def _shear_level(level, _hparams):
    return (_randomly_negate((level / _LEVEL_DENOM) * 0.3),)


def _translate_abs_level(level, hparams):
    translate_const = hparams.get('translate_const', 250)
    return (_randomly_negate((level / _LEVEL_DENOM) * translate_const),)


def _translate_rel_level(level, hparams):
    translate_pct = hparams.get('translate_pct', 0.45)
    return (_randomly_negate((level / _LEVEL_DENOM) * translate_pct),)


def _posterize_level(level, _hparams):
    return (int((level / _LEVEL_DENOM) * 4),)


def _posterize_increasing_level(level, _hparams):
    return (4 - int((level / _LEVEL_DENOM) * 4),)


def _posterize_original_level(level, _hparams):
    return (int((level / _LEVEL_DENOM) * 4) + 4,)


def _solarize_level(level, _hparams):
    return (min(256, int((level / _LEVEL_DENOM) * 256)),)


def _solarize_increasing_level(level, _hparams):
    return (256 - _solarize_level(level, _hparams)[0],)


def _solarize_add_level(level, _hparams):
    return (min(128, int((level / _LEVEL_DENOM) * 110)),)


def _gaussian_blur_level(level, _hparams):
    return (0.1 + (level / _LEVEL_DENOM) * 1.9,)


def _desaturate_level(level, _hparams):
    return (min(1.0, 0.1 + (level / _LEVEL_DENOM) * 0.9),)


def _none_level(level, _hparams):
    return ()


LEVEL_TO_ARG = {
    'AutoContrast': _none_level,
    'Equalize': _none_level,
    'Invert': _none_level,
    'Rotate': _rotate_level,
    'Posterize': _posterize_level,
    'PosterizeIncreasing': _posterize_increasing_level,
    'PosterizeOriginal': _posterize_original_level,
    'Solarize': _solarize_level,
    'SolarizeIncreasing': _solarize_increasing_level,
    'SolarizeAdd': _solarize_add_level,
    'Color': _enhance_level,
    'ColorIncreasing': _enhance_increasing_level,
    'Contrast': _enhance_level,
    'ContrastIncreasing': _enhance_increasing_level,
    'Brightness': _enhance_level,
    'BrightnessIncreasing': _enhance_increasing_level,
    'Sharpness': _enhance_level,
    'SharpnessIncreasing': _enhance_increasing_level,
    'ShearX': _shear_level,
    'ShearY': _shear_level,
    'TranslateX': _translate_abs_level,
    'TranslateY': _translate_abs_level,
    'TranslateXRel': _translate_rel_level,
    'TranslateYRel': _translate_rel_level,
    'GaussianBlur': _gaussian_blur_level,
    'Desaturate': _desaturate_level,
}

NAME_TO_OP = {
    'AutoContrast': auto_contrast,
    'Equalize': equalize,
    'Invert': invert,
    'Rotate': rotate,
    'Posterize': posterize,
    'PosterizeIncreasing': posterize,
    'PosterizeOriginal': posterize,
    'Solarize': solarize,
    'SolarizeIncreasing': solarize,
    'SolarizeAdd': solarize_add,
    'Color': color,
    'ColorIncreasing': color,
    'Contrast': contrast,
    'ContrastIncreasing': contrast,
    'Brightness': brightness,
    'BrightnessIncreasing': brightness,
    'Sharpness': sharpness,
    'SharpnessIncreasing': sharpness,
    'ShearX': shear_x,
    'ShearY': shear_y,
    'TranslateX': translate_x_abs,
    'TranslateY': translate_y_abs,
    'TranslateXRel': translate_x_rel,
    'TranslateYRel': translate_y_rel,
    'GaussianBlur': gaussian_blur,
    'Desaturate': desaturate,
}


class AugmentOp:
    def __init__(self, name: str, prob: float = 0.5, magnitude: float = 10, hparams: Optional[Dict] = None):
        hparams = hparams or {}
        self.name = name
        self.aug_fn = NAME_TO_OP[name]
        self.level_fn = LEVEL_TO_ARG[name]
        self.prob = prob
        self.magnitude = magnitude
        self.hparams = hparams.copy()
        self.kwargs = dict(
            fillcolor=hparams.get('img_mean', _FILL),
            resample=hparams.get('interpolation', (Image.BILINEAR, Image.BICUBIC)),
        )
        # magnitude noise: gaussian std / uniform range around magnitude
        self.magnitude_std = self.hparams.get('magnitude_std', 0)
        self.magnitude_max = self.hparams.get('magnitude_max', None)

    def __call__(self, img):
        if self.prob < 1.0 and random.random() > self.prob:
            return img
        magnitude = self.magnitude
        if self.magnitude_std > 0:
            if self.magnitude_std == float('inf'):
                magnitude = random.uniform(0, magnitude)
            else:
                magnitude = random.gauss(magnitude, self.magnitude_std)
        upper = self.magnitude_max or _LEVEL_DENOM
        magnitude = max(0.0, min(magnitude, upper))
        level_args = self.level_fn(magnitude, self.hparams)
        return self.aug_fn(img, *level_args, **self.kwargs)

    def __repr__(self):
        return f'{self.__class__.__name__}(name={self.name}, p={self.prob}, m={self.magnitude})'


# ---- AutoAugment policies ---------------------------------------------------

def _policy_v0(hparams):
    policy = [
        [('Equalize', 0.8, 1), ('ShearY', 0.8, 4)],
        [('Color', 0.4, 9), ('Equalize', 0.6, 3)],
        [('Color', 0.4, 1), ('Rotate', 0.6, 8)],
        [('Solarize', 0.8, 3), ('Equalize', 0.4, 7)],
        [('Solarize', 0.4, 2), ('Solarize', 0.6, 2)],
        [('Color', 0.2, 0), ('Equalize', 0.8, 8)],
        [('Equalize', 0.4, 8), ('SolarizeAdd', 0.8, 3)],
        [('ShearX', 0.2, 9), ('Rotate', 0.6, 8)],
        [('Color', 0.6, 1), ('Equalize', 1.0, 2)],
        [('Invert', 0.4, 9), ('Rotate', 0.6, 0)],
        [('Equalize', 1.0, 9), ('ShearY', 0.6, 3)],
        [('Color', 0.4, 7), ('Equalize', 0.6, 0)],
        [('Posterize', 0.4, 6), ('AutoContrast', 0.4, 7)],
        [('Solarize', 0.6, 8), ('Color', 0.6, 9)],
        [('Solarize', 0.2, 4), ('Rotate', 0.8, 9)],
        [('Rotate', 1.0, 7), ('TranslateYRel', 0.8, 9)],
        [('ShearX', 0.0, 0), ('Solarize', 0.8, 4)],
        [('ShearY', 0.8, 0), ('Color', 0.6, 4)],
        [('Color', 1.0, 0), ('Rotate', 0.6, 2)],
        [('Equalize', 0.8, 4), ('Equalize', 0.0, 8)],
        [('Equalize', 1.0, 4), ('AutoContrast', 0.6, 2)],
        [('ShearY', 0.4, 7), ('SolarizeAdd', 0.6, 7)],
        [('Posterize', 0.8, 2), ('Solarize', 0.6, 10)],
        [('Solarize', 0.6, 8), ('Equalize', 0.6, 1)],
        [('Color', 0.8, 6), ('Rotate', 0.4, 5)],
    ]
    return [[AugmentOp(*a, hparams=hparams) for a in sp] for sp in policy]


def _policy_original(hparams):
    policy = [
        [('PosterizeOriginal', 0.4, 8), ('Rotate', 0.6, 9)],
        [('Solarize', 0.6, 5), ('AutoContrast', 0.6, 5)],
        [('Equalize', 0.8, 8), ('Equalize', 0.6, 3)],
        [('PosterizeOriginal', 0.6, 7), ('PosterizeOriginal', 0.6, 6)],
        [('Equalize', 0.4, 7), ('Solarize', 0.2, 4)],
        [('Equalize', 0.4, 4), ('Rotate', 0.8, 8)],
        [('Solarize', 0.6, 3), ('Equalize', 0.6, 7)],
        [('PosterizeOriginal', 0.8, 5), ('Equalize', 1.0, 2)],
        [('Rotate', 0.2, 3), ('Solarize', 0.6, 8)],
        [('Equalize', 0.6, 8), ('PosterizeOriginal', 0.4, 6)],
        [('Rotate', 0.8, 8), ('Color', 0.4, 0)],
        [('Rotate', 0.4, 9), ('Equalize', 0.6, 2)],
        [('Equalize', 0.0, 7), ('Equalize', 0.8, 8)],
        [('Invert', 0.6, 4), ('Equalize', 1.0, 8)],
        [('Color', 0.6, 4), ('Contrast', 1.0, 8)],
        [('Rotate', 0.8, 8), ('Color', 1.0, 2)],
        [('Color', 0.8, 8), ('Solarize', 0.8, 7)],
        [('Sharpness', 0.4, 7), ('Invert', 0.6, 8)],
        [('ShearX', 0.6, 5), ('Equalize', 1.0, 9)],
        [('Color', 0.4, 0), ('Equalize', 0.6, 3)],
        [('Equalize', 0.4, 7), ('Solarize', 0.2, 4)],
        [('Solarize', 0.6, 5), ('AutoContrast', 0.6, 5)],
        [('Invert', 0.6, 4), ('Equalize', 1.0, 8)],
        [('Color', 0.6, 4), ('Contrast', 1.0, 8)],
        [('Equalize', 0.8, 8), ('Equalize', 0.6, 3)],
    ]
    return [[AugmentOp(*a, hparams=hparams) for a in sp] for sp in policy]


def _policy_3a(hparams):
    policy = [
        [('Solarize', 1.0, 5)],
        [('Desaturate', 1.0, 10)],
        [('GaussianBlur', 1.0, 10)],
    ]
    return [[AugmentOp(*a, hparams=hparams) for a in sp] for sp in policy]


class AutoAugment:
    def __init__(self, policy):
        self.policy = policy

    def __call__(self, img):
        sub_policy = random.choice(self.policy)
        for op in sub_policy:
            img = op(img)
        return img


def auto_augment_policy(name: str = 'v0', hparams: Optional[Dict] = None):
    hparams = hparams or {}
    if name == 'original':
        return _policy_original(hparams)
    if name in ('v0', 'v0r'):
        return _policy_v0(hparams)
    if name == '3a':
        return _policy_3a(hparams)
    raise ValueError(f'Unknown AA policy {name}')


def auto_augment_transform(config_str: str, hparams: Optional[Dict] = None):
    """'original-mstd0.5' → AutoAugment (reference auto_augment.py:565)."""
    config = config_str.split('-')
    policy_name = config[0]
    hparams = dict(hparams or {})
    for c in config[1:]:
        cs = re.split(r'(\d.*)', c)
        if len(cs) < 2:
            continue
        key, val = cs[:2]
        if key == 'mstd':
            hparams['magnitude_std'] = float(val)
    return AutoAugment(auto_augment_policy(policy_name, hparams))


# ---- RandAugment ------------------------------------------------------------

_RAND_TRANSFORMS = [
    'AutoContrast', 'Equalize', 'Invert', 'Rotate', 'Posterize', 'Solarize',
    'SolarizeAdd', 'Color', 'Contrast', 'Brightness', 'Sharpness',
    'ShearX', 'ShearY', 'TranslateXRel', 'TranslateYRel',
]

_RAND_INCREASING_TRANSFORMS = [
    'AutoContrast', 'Equalize', 'Invert', 'Rotate', 'PosterizeIncreasing',
    'SolarizeIncreasing', 'SolarizeAdd', 'ColorIncreasing', 'ContrastIncreasing',
    'BrightnessIncreasing', 'SharpnessIncreasing', 'ShearX', 'ShearY',
    'TranslateXRel', 'TranslateYRel',
]


class RandAugment:
    def __init__(self, ops, num_layers: int = 2, choice_weights=None):
        self.ops = ops
        self.num_layers = num_layers
        self.choice_weights = choice_weights

    def __call__(self, img):
        ops = np.random.choice(
            self.ops, self.num_layers,
            replace=self.choice_weights is None, p=self.choice_weights)
        for op in ops:
            img = op(img)
        return img


def rand_augment_transform(config_str: str, hparams: Optional[Dict] = None, transforms=None):
    """Parse 'rand-m9-mstd0.5-inc1' etc. (reference auto_augment.py:762)."""
    magnitude = _LEVEL_DENOM
    num_layers = 2
    hparams = dict(hparams or {})
    transforms = transforms or _RAND_TRANSFORMS
    config = config_str.split('-')
    assert config[0] == 'rand'
    for c in config[1:]:
        if c.startswith('t_'):
            continue
        cs = re.split(r'(\d.*)', c)
        if len(cs) < 2:
            continue
        key, val = cs[:2]
        if key == 'mstd':
            mstd = float(val)
            if mstd > 100:
                mstd = float('inf')
            hparams['magnitude_std'] = mstd
        elif key == 'mmax':
            hparams['magnitude_max'] = int(val)
        elif key == 'inc':
            if bool(int(val)):
                transforms = _RAND_INCREASING_TRANSFORMS
        elif key == 'm':
            magnitude = int(val)
        elif key == 'n':
            num_layers = int(val)
        elif key == 'p':
            hparams['prob'] = float(val)
    prob = hparams.pop('prob', 0.5)
    ra_ops = [AugmentOp(name, prob=prob, magnitude=magnitude, hparams=hparams) for name in transforms]
    return RandAugment(ra_ops, num_layers)


# ---- AugMix -----------------------------------------------------------------

_AUGMIX_TRANSFORMS = [
    'AutoContrast', 'ColorIncreasing', 'ContrastIncreasing', 'BrightnessIncreasing',
    'SharpnessIncreasing', 'Equalize', 'Rotate', 'PosterizeIncreasing',
    'SolarizeIncreasing', 'ShearX', 'ShearY', 'TranslateXRel', 'TranslateYRel',
]


class AugMixAugment:
    """(reference auto_augment.py:878)."""

    def __init__(self, ops, alpha: float = 1.0, width: int = 3, depth: int = -1, blended: bool = False):
        self.ops = ops
        self.alpha = alpha
        self.width = width
        self.depth = depth

    def __call__(self, img):
        mixing_weights = np.float32(np.random.dirichlet([self.alpha] * self.width))
        m = np.float32(np.random.beta(self.alpha, self.alpha))
        mixed = np.zeros(np.asarray(img).shape, dtype=np.float32)
        for mw in mixing_weights:
            depth = self.depth if self.depth > 0 else np.random.randint(1, 4)
            ops = np.random.choice(self.ops, depth, replace=True)
            img_aug = img
            for op in ops:
                img_aug = op(img_aug)
            mixed += mw * np.asarray(img_aug, dtype=np.float32)
        mixed = (1.0 - m) * np.asarray(img, dtype=np.float32) + m * mixed
        return Image.fromarray(np.clip(mixed, 0, 255).astype(np.uint8))


def augment_and_mix_transform(config_str: str, hparams: Optional[Dict] = None):
    """Parse 'augmix-m5-w4-d2' (reference auto_augment.py:~960)."""
    magnitude = 3
    width = 3
    depth = -1
    alpha = 1.0
    hparams = dict(hparams or {})
    config = config_str.split('-')
    assert config[0] == 'augmix'
    for c in config[1:]:
        cs = re.split(r'(\d.*)', c)
        if len(cs) < 2:
            continue
        key, val = cs[:2]
        if key == 'mstd':
            hparams['magnitude_std'] = float(val)
        elif key == 'm':
            magnitude = int(val)
        elif key == 'w':
            width = int(val)
        elif key == 'd':
            depth = int(val)
        elif key == 'a':
            alpha = float(val)
    hparams.setdefault('magnitude_std', float('inf'))
    ops = [AugmentOp(name, prob=1.0, magnitude=magnitude, hparams=hparams) for name in _AUGMIX_TRANSFORMS]
    return AugMixAugment(ops, alpha=alpha, width=width, depth=depth)
