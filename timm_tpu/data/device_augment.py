"""On-device batch augmentation (ROADMAP item 3).

Mixup/CutMix blending + soft-target construction, RandomErasing region fill,
and normalize/dtype-cast re-expressed as pure jittable functions that run on
the accelerator *after* transfer, so the host stages only decode, resize and
collate uint8. Each transform is split in two:

  * host-side **parameter sampling** — ``Mixup.sample_params`` /
    ``RandomErasing.sample_params`` draw lam, cutmix bboxes and erase
    rectangles as tiny arrays that ride the batch;
  * device-side **application** — the functions below consume those params
    with pure jnp math (broadcast coordinate masks, never dynamic slicing),
    so the jitted program is shape-stable: one compile per batch shape, zero
    recompiles after warmup.

Identity is always encoded in *values* (lam=1, zero boxes), never in pytree
structure, so every batch of a given shape hits the same compiled program.
'pixel'-mode erase noise is the one draw that happens on device, from a
``jax.random`` key threaded as (seed, epoch, step) — deterministic and
resumable without shipping a (B, H, W, C) noise canvas over PCIe.

Numpy twins of every applier live here too; they are the parity oracle for
tests and the documentation of exactly what the device program computes.
"""
from __future__ import annotations

import functools
import warnings

import numpy as np

import jax
import jax.numpy as jnp

from timm_tpu.parallel.mesh import shard_batch

__all__ = [
    'mixup_images', 'mixup_targets', 'erase_images', 'augment_image_batch',
    'augment_naflex_batch', 'mixup_images_np', 'mixup_targets_np',
    'erase_images_np', 'augment_image_batch_np', 'pixel_noise',
    'DeviceAugment', 'DeviceAugmentStage', 'NaFlexDeviceAugment',
    'batch_donate_argnums',
]

# donating the uint8 image buffer frees it as soon as the program runs, but it
# can never alias the float32 output; silence the (per-compile) jax warning
warnings.filterwarnings('ignore', message='Some donated buffers were not usable')


def batch_donate_argnums():
    """Donation spec for the augment programs: `(0,)` (donate the batch dict)
    on accelerator backends, `()` on CPU.

    XLA:CPU mis-executes these programs after a persistent-compile-cache
    round-trip when their inputs are donated: the freshly compiled executable
    is correct (and gets persisted), but the DESERIALIZED executable returns
    corrupted buffers — garbage/NaN patches — on every later warm process.
    The donated train step round-trips fine, so the defect is specific to
    this program shape (identity pass-through outputs aliasing donated
    inputs). Donation only pays for itself in accelerator HBM anyway, so it
    is gated on the backend rather than dropped outright."""
    return () if jax.default_backend() == 'cpu' else (0,)


def _noise_key(noise_seed, epoch, step):
    key = jax.random.fold_in(jax.random.PRNGKey(noise_seed), epoch)
    return jax.random.fold_in(key, step)


def pixel_noise(shape, noise_seed, epoch, step, mean=None, std=None):
    """The 'pixel'-mode erase fill canvas: mean + std * N(0, 1), generated
    from a (seed, epoch, step)-threaded key. Runs under jit on device; the
    numpy parity oracle calls it eagerly and converts — jax.random is
    deterministic across both."""
    noise = jax.random.normal(_noise_key(noise_seed, epoch, step), shape, jnp.float32)
    if mean is not None:
        noise = jnp.asarray(mean, jnp.float32) + jnp.asarray(std, jnp.float32) * noise
    return noise


# -- device appliers ----------------------------------------------------------

def mixup_images(x, lam, use_cutmix, bbox):
    """Blend (B, H, W, C) float x with its batch flip. Per-row params unify
    the host batch/elem/pair modes: row i mixes with original row B-1-i using
    lam[i]; cutmix rows paste the bbox[i]=(yl, yh, xl, xh) region instead."""
    x_flip = x[::-1]
    lam_b = lam[:, None, None, None]
    mixed = x * lam_b + x_flip * (1.0 - lam_b)
    yy = jnp.arange(x.shape[1])[None, :, None]
    xx = jnp.arange(x.shape[2])[None, None, :]
    yl, yh, xl, xh = (bbox[:, i][:, None, None] for i in range(4))
    inside = (yy >= yl) & (yy < yh) & (xx >= xl) & (xx < xh)
    cut = jnp.where(inside[..., None], x_flip, x)
    return jnp.where(use_cutmix[:, None, None, None], cut, mixed)


def mixup_targets(target, lam, num_classes, smoothing=0.0):
    """Per-row soft targets: smoothed one-hot of target blended with the
    batch-flipped labels (mixup.mixup_target generalized to vector lam)."""
    off = smoothing / num_classes
    on = 1.0 - smoothing + off
    y1 = jax.nn.one_hot(target, num_classes, dtype=jnp.float32) * (on - off) + off
    y2 = jax.nn.one_hot(target[::-1], num_classes, dtype=jnp.float32) * (on - off) + off
    return y1 * lam[:, None] + y2 * (1.0 - lam[:, None])


def erase_images(x, erase_box, fill=None, *, mode='const', mean=(0.0, 0.0, 0.0),
                 noise=None):
    """Fill K rectangles per row. erase_box is (B, K, 4) = (top, left, eh, ew);
    zero boxes are no-ops. Fill source by (static) mode: 'const' uses the
    channel color `mean`, 'rand' indexes `fill` (B, K, C), 'pixel' reads the
    `noise` canvas (B, H, W, C). Boxes apply in slot order (last write wins,
    like the host's sequential in-place stores)."""
    yy = jnp.arange(x.shape[1])[None, :, None]
    xx = jnp.arange(x.shape[2])[None, None, :]
    mean_c = jnp.asarray(mean, x.dtype)
    for k in range(erase_box.shape[1]):
        top, left, eh, ew = (erase_box[:, k, j][:, None, None] for j in range(4))
        inside = (yy >= top) & (yy < top + eh) & (xx >= left) & (xx < left + ew)
        if mode == 'pixel':
            fill_k = noise
        elif mode == 'rand':
            fill_k = fill[:, k][:, None, None, :]
        else:
            fill_k = mean_c
        x = jnp.where(inside[..., None], fill_k, x)
    return x


def augment_image_batch(batch, *, mean, std, re_mode='const',
                        re_mean=(0.0, 0.0, 0.0), re_std=(1.0, 1.0, 1.0),
                        noise_seed=42, num_classes=0, smoothing=0.0,
                        out_dtype=jnp.float32):
    """The fused device program: uint8 -> [0,1] float -> erase -> mixup ->
    normalize -> cast, mirroring the host pipeline order (loader collate
    erase, train-loop mixup, task normalize). `batch` carries the image, the
    int target, and the sampled params; returns (input, target) where target
    is the soft matrix when mixup params ride the batch."""
    x = batch['image'].astype(jnp.float32) / 255.0
    if 'erase_box' in batch:
        noise = None
        if re_mode == 'pixel':
            noise = pixel_noise(x.shape, noise_seed, batch['noise_epoch'],
                                batch['noise_step'], re_mean, re_std)
        x = erase_images(x, batch['erase_box'], batch.get('erase_fill'),
                         mode=re_mode, mean=re_mean, noise=noise)
    if 'lam' in batch:
        x = mixup_images(x, batch['lam'], batch['use_cutmix'], batch['bbox'])
        y = mixup_targets(batch['target'], batch['lam'], num_classes, smoothing)
    else:
        y = batch['target']
    x = (x - jnp.asarray(mean, jnp.float32)) / jnp.asarray(std, jnp.float32)
    return x.astype(out_dtype), y


def augment_naflex_batch(batch, *, mean, std, re_mode='const', noise_seed=42):
    """NaFlex packed variant: normalize (B, L, D) patches with per-channel
    mean/std tiled to the (P*P*C,) patch dim (channel-fastest flatten order),
    then fill erased token slots — in normalized space, matching the host
    NaFlexRandomErasing ('pixel' draws device noise from the threaded key,
    'const' fills 0). Param keys are consumed; everything else (coords, valid
    mask, targets) passes through for the train step."""
    p = batch['patches'].astype(jnp.float32)
    reps = p.shape[-1] // len(mean)
    p = (p - jnp.tile(jnp.asarray(mean, jnp.float32), reps)) / \
        jnp.tile(jnp.asarray(std, jnp.float32), reps)
    if 'erase_mask' in batch:
        if re_mode == 'pixel':
            fill = pixel_noise(p.shape, noise_seed, batch['noise_epoch'],
                               batch['noise_step'])
        else:
            fill = jnp.zeros((), jnp.float32)
        p = jnp.where(batch['erase_mask'][..., None], fill, p)
    out = {k: v for k, v in batch.items()
           if k not in ('erase_mask', 'noise_epoch', 'noise_step')}
    out['patches'] = p
    return out


# -- numpy parity oracles -----------------------------------------------------

def mixup_images_np(x, lam, use_cutmix, bbox):
    x = np.asarray(x, np.float32)
    x_flip = x[::-1]
    lam_b = np.asarray(lam, np.float32)[:, None, None, None]
    mixed = x * lam_b + x_flip * (1.0 - lam_b)
    yy = np.arange(x.shape[1])[None, :, None]
    xx = np.arange(x.shape[2])[None, None, :]
    yl, yh, xl, xh = (bbox[:, i][:, None, None] for i in range(4))
    inside = (yy >= yl) & (yy < yh) & (xx >= xl) & (xx < xh)
    cut = np.where(inside[..., None], x_flip, x)
    return np.where(np.asarray(use_cutmix)[:, None, None, None], cut, mixed)


def mixup_targets_np(target, lam, num_classes, smoothing=0.0):
    from timm_tpu.data.mixup import one_hot
    off = smoothing / num_classes
    on = 1.0 - smoothing + off
    y1 = one_hot(np.asarray(target), num_classes, on, off)
    y2 = one_hot(np.asarray(target)[::-1], num_classes, on, off)
    lam = np.asarray(lam, np.float32)[:, None]
    return y1 * lam + y2 * (1.0 - lam)


def erase_images_np(x, erase_box, fill=None, *, mode='const',
                    mean=(0.0, 0.0, 0.0), noise=None):
    x = np.array(x, np.float32)
    for i in range(x.shape[0]):
        for k in range(erase_box.shape[1]):
            top, left, eh, ew = (int(v) for v in erase_box[i, k])
            if eh == 0 or ew == 0:
                continue
            if mode == 'pixel':
                x[i, top:top + eh, left:left + ew] = noise[i, top:top + eh, left:left + ew]
            elif mode == 'rand':
                x[i, top:top + eh, left:left + ew] = fill[i, k]
            else:
                x[i, top:top + eh, left:left + ew] = np.asarray(mean, np.float32)
    return x


def augment_image_batch_np(batch, *, mean, std, re_mode='const',
                           re_mean=(0.0, 0.0, 0.0), re_std=(1.0, 1.0, 1.0),
                           noise_seed=42, num_classes=0, smoothing=0.0,
                           out_dtype=np.float32):
    x = np.asarray(batch['image']).astype(np.float32) / 255.0
    if 'erase_box' in batch:
        noise = None
        if re_mode == 'pixel':
            noise = np.asarray(pixel_noise(
                x.shape, noise_seed, int(batch['noise_epoch']),
                int(batch['noise_step']), re_mean, re_std))
        x = erase_images_np(x, batch['erase_box'], batch.get('erase_fill'),
                            mode=re_mode, mean=re_mean, noise=noise)
    if 'lam' in batch:
        x = mixup_images_np(x, batch['lam'], batch['use_cutmix'], batch['bbox'])
        y = mixup_targets_np(batch['target'], batch['lam'], num_classes, smoothing)
    else:
        y = np.asarray(batch['target'])
    x = (x - np.asarray(mean, np.float32)) / np.asarray(std, np.float32)
    return x.astype(out_dtype), y


# -- pipeline stages ----------------------------------------------------------

class DeviceAugment:
    """One jitted augment program; jit re-specializes per batch shape
    (bucketed loaders hit a small fixed program set, zero recompiles after
    warmup). On accelerators the batch is donated, freeing the staged
    uint8/param buffers as soon as the program runs (see
    batch_donate_argnums for why CPU is excluded).

    `fused_epilogue` (default: TIMM_TPU_PALLAS_AUGMENT=1) routes the image
    epilogue through the one-pass Pallas kernel
    (kernels/augment_epilogue.py, registered win-or-delete); it only covers
    'const' erase mode, and the kernel wrapper itself falls back to this XLA
    program for out-of-regime batches, so the switch is always safe."""

    def __init__(self, mean, std, re_mode='const', re_mean=None, re_std=None,
                 num_classes=0, smoothing=0.0, noise_seed=42,
                 out_dtype=jnp.float32, fused_epilogue=None):
        if fused_epilogue is None:
            import os
            fused_epilogue = os.environ.get('TIMM_TPU_PALLAS_AUGMENT', '0') == '1'
        if fused_epilogue:
            from timm_tpu.kernels.augment_epilogue import augment_image_batch_fused
            augment_fn = augment_image_batch_fused
        else:
            augment_fn = augment_image_batch
        self.fused_epilogue = bool(fused_epilogue)
        self.fn = jax.jit(functools.partial(
            augment_fn,
            mean=tuple(mean), std=tuple(std), re_mode=re_mode,
            re_mean=tuple(re_mean if re_mean is not None else (0.0,) * len(mean)),
            re_std=tuple(re_std if re_std is not None else (1.0,) * len(std)),
            noise_seed=noise_seed, num_classes=num_classes, smoothing=smoothing,
            out_dtype=out_dtype), donate_argnums=batch_donate_argnums())

    def __call__(self, batch):
        return self.fn(batch)


class DeviceAugmentStage:
    """Iterable stage: consumes uint8 (image, target) batches from a loader
    (or a DevicePrefetcher wrapping one), samples augmentation params on the
    host, and yields (input, target) device arrays produced by the donated
    jitted augment program — soft targets when a Mixup sampler is attached."""

    def __init__(self, loader, mean, std, mixup=None, random_erasing=None,
                 re_mode='const', noise_seed=42, out_dtype=jnp.float32,
                 mesh=None):
        self.loader = loader
        self.mixup = mixup
        self.random_erasing = random_erasing
        self.re_mode = re_mode
        self._mesh = mesh
        self._epoch = 0
        self._augment = DeviceAugment(
            mean, std, re_mode=re_mode,
            re_mean=getattr(random_erasing, 'mean', None),
            re_std=getattr(random_erasing, 'std', None),
            num_classes=getattr(mixup, 'num_classes', 0),
            smoothing=getattr(mixup, 'label_smoothing', 0.0),
            noise_seed=noise_seed, out_dtype=out_dtype)

    def set_epoch(self, epoch: int):
        self._epoch = int(epoch)
        if hasattr(self.loader, 'set_epoch'):
            self.loader.set_epoch(epoch)
        if self.mixup is not None:
            self.mixup.set_epoch(epoch)
        if self.random_erasing is not None:
            self.random_erasing.set_epoch(epoch)

    def __len__(self):
        return len(self.loader)

    def __getattr__(self, name):
        return getattr(self.loader, name)

    def __iter__(self):
        for step, (x, t) in enumerate(self.loader):
            batch = {'image': x, 'target': t}
            if self.random_erasing is not None:
                batch.update(self.random_erasing.sample_params(x.shape))
                if self.re_mode == 'pixel':
                    batch['noise_epoch'] = np.uint32(self._epoch)
                    batch['noise_step'] = np.uint32(step)
            if self.mixup is not None:
                batch.update(self.mixup.sample_params(x.shape))
            yield self._augment(shard_batch(batch, self._mesh))


class NaFlexDeviceAugment:
    """Iterable stage for packed NaFlex dict batches: normalize + token erase
    run on device under one donated program per bucket shape; host metadata
    ('seq_len', 'patch_size') and param keys are kept out of / stripped from
    the device dict, so the yielded batch feeds the train step directly."""

    _HOST_KEYS = ('seq_len', 'patch_size')

    def __init__(self, loader, mean, std, re_mode='const', noise_seed=42,
                 mesh=None):
        self.loader = loader
        self.re_mode = re_mode
        self._mesh = mesh
        self._epoch = 0
        self.fn = jax.jit(functools.partial(
            augment_naflex_batch, mean=tuple(mean), std=tuple(std),
            re_mode=re_mode, noise_seed=noise_seed),
            donate_argnums=batch_donate_argnums())

    def set_epoch(self, epoch: int):
        self._epoch = int(epoch)
        if hasattr(self.loader, 'set_epoch'):
            self.loader.set_epoch(epoch)

    def __len__(self):
        return len(self.loader)

    def __getattr__(self, name):
        return getattr(self.loader, name)

    def __iter__(self):
        for step, batch in enumerate(self.loader):
            host_meta = {k: batch[k] for k in self._HOST_KEYS if k in batch}
            dev = {k: v for k, v in batch.items() if k not in host_meta}
            if self.re_mode == 'pixel' and 'erase_mask' in dev:
                dev['noise_epoch'] = np.uint32(self._epoch)
                dev['noise_step'] = np.uint32(step)
            out = self.fn(shard_batch(dev, self._mesh))
            out.update(host_meta)
            yield out
