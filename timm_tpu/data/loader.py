"""Batch loader with threaded decode + prefetch
(reference: timm/data/loader.py:30-504).

TPU-native redesign of the reference's DataLoader+PrefetchLoader pair:
  * worker threads decode/augment (PIL releases the GIL in libjpeg), a
    bounded queue gives pipelined prefetch — replaces torch worker procs
  * per-host sharding for multi-process (pod) runs replaces the distributed
    sampler: each host reads its `jax.process_index()` slice
  * normalization happens on device inside the consuming step (mean/std are
    published as loader attributes), mirroring the reference's on-GPU
    normalize (loader.py:124-159)
  * RandomErasing applies post-collate on the host batch
"""
from __future__ import annotations

import queue
import random
import threading
from typing import Callable, Optional, Tuple

import numpy as np

from ..resilience import SkipBudget, TooManyBadSamples, get_fault_injector, retry_io
from .constants import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD
from .random_erasing import RandomErasing
from .transforms_factory import create_transform

__all__ = ['create_loader', 'DevicePrefetcher', 'StreamingLoader', 'ThreadedLoader']

# marker a worker emits for a sample dropped against the poison budget, so the
# collator keeps its consumed-count bookkeeping without padding the batch
_SKIPPED = object()


class StreamingLoader:
    """Batch loader over an ITERABLE dataset (wds/tfds streaming readers).

    The reader owns shard assignment (process x worker). During training with
    `num_workers > 1` and a worker-aware reader (set_worker_info), N producer
    threads each stream a worker-strided copy of the reader and decode/augment
    in parallel; otherwise a single producer thread prefetches ahead of the
    consumer. Either way a bounded queue overlaps input work with the device
    step. RandomErasing applies post-collate like ThreadedLoader. For
    multi-host runs with a known sample count, batch counts are EQUALIZED:
    every host emits exactly `len(self)` batches per epoch, cycling its
    stream if its shard slice runs short (the streaming analogue of the
    padded distributed sampler). Single-host streams naturally (short final
    batch on eval).
    """

    def __init__(
            self,
            dataset,
            batch_size: int,
            is_training: bool = False,
            drop_last: Optional[bool] = None,
            num_workers: int = 1,
            prefetch: int = 4,
            re_prob: float = 0.0,
            re_mode: str = 'const',
            re_count: int = 1,
            re_num_splits: int = 0,
            mean=IMAGENET_DEFAULT_MEAN,
            std=IMAGENET_DEFAULT_STD,
            process_index: int = 0,
            process_count: int = 1,
            seed: int = 42,
            **kwargs,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.is_training = is_training
        self.drop_last = is_training if drop_last is None else drop_last
        self.num_workers = max(1, num_workers)
        self.prefetch = prefetch
        self.epoch = 0
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.random_erasing = RandomErasing(
            probability=re_prob, mode=re_mode, min_count=re_count,
            num_splits=re_num_splits, mean=self.mean, std=self.std,
            seed=seed) if re_prob > 0 and is_training else None
        self.process_index = process_index
        self.process_count = process_count

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        if self.random_erasing is not None:
            self.random_erasing.set_epoch(epoch)  # resume-reproducible stream
        if hasattr(self.dataset, 'set_epoch'):
            self.dataset.set_epoch(epoch)

    def _num_batches(self) -> Optional[int]:
        try:
            n = len(self.dataset)
        except TypeError:
            return None
        per_host = n // self.process_count if self.process_count > 1 else n
        if self.drop_last:
            return max(per_host // self.batch_size, 1)
        return max(-(-per_host // self.batch_size), 1)

    def __len__(self):
        n = self._num_batches()
        if n is None:
            raise TypeError(
                'streaming dataset length unknown (no sample count); '
                'pass --epoch-size or provide an _info.json sidecar')
        return n

    def __iter__(self):
        if hasattr(self.dataset, 'set_epoch'):
            self.dataset.set_epoch(self.epoch)
        # single host: no lockstep requirement — stream naturally (short final
        # batch on eval, like ThreadedLoader). Multi-host: equalize counts.
        target_batches = self._num_batches() if self.process_count > 1 or self.drop_last else None

        stop = threading.Event()
        sample_q: 'queue.Queue' = queue.Queue(maxsize=self.prefetch * self.batch_size)

        def _worker_streams():
            """Worker-strided reader copies (or None when unsupported)."""
            reader = getattr(self.dataset, 'reader', None)
            if not (self.is_training and self.num_workers > 1 and reader is not None
                    and hasattr(reader, 'set_worker_info')):
                return None
            import copy
            transform = getattr(self.dataset, 'transform', None)
            target_transform = getattr(self.dataset, 'target_transform', None)

            def stream(worker_reader):
                for img, target in worker_reader:
                    if transform is not None:
                        img = transform(img)
                    if target_transform is not None:
                        target = target_transform(target)
                    yield img, target

            out = []
            for w in range(self.num_workers):
                r = copy.copy(reader)
                r.set_worker_info(w, self.num_workers)
                out.append(stream(r))
            return out

        needed = None if target_batches is None else target_batches * self.batch_size
        emitted_lock = threading.Lock()
        state = {'emitted': 0}

        def producer(stream):
            try:
                for sample in stream:
                    if stop.is_set():
                        return
                    with emitted_lock:
                        if needed is not None and state['emitted'] >= needed:
                            return
                        state['emitted'] += 1
                    sample_q.put(sample)
            except Exception as e:
                sample_q.put(e)

        def run_producers():
            # outer loop restarts the full stream set when the shard slice
            # ran short of the equalized count (multi-host lockstep)
            while True:
                streams = _worker_streams() or [iter(self.dataset)]
                threads = []
                for s in streams:
                    t = threading.Thread(target=producer, args=(s,), daemon=True)
                    t.start()
                    threads.append(t)
                for t in threads:
                    t.join()
                with emitted_lock:
                    done = (needed is None or state['emitted'] == 0
                            or state['emitted'] >= needed)
                if done or stop.is_set():
                    break
                if hasattr(self.dataset, 'set_epoch'):
                    self.dataset.set_epoch(self.epoch + 1000 + state['emitted'])
            sample_q.put(None)

        threading.Thread(target=run_producers, daemon=True).start()

        batch_imgs, batch_targets = [], []
        try:
            while True:
                item = sample_q.get()
                if item is None:
                    break
                if isinstance(item, Exception):
                    raise item
                img, target = item
                batch_imgs.append(img)
                batch_targets.append(target)
                if len(batch_imgs) == self.batch_size:
                    yield self._collate(batch_imgs, batch_targets)
                    batch_imgs, batch_targets = [], []
            if batch_imgs and not self.drop_last:
                yield self._collate(batch_imgs, batch_targets)
        finally:
            stop.set()
            try:
                while True:
                    sample_q.get_nowait()
            except queue.Empty:
                pass

    def _collate(self, imgs, targets):
        x, t = _collate_arrays(imgs, targets)
        if self.random_erasing is not None:
            x = self.random_erasing(x)
        return x, t



class DevicePrefetcher:
    """Double-buffer device-prefetch stage over any host-batch iterable.

    The host loaders above stop at numpy: the consuming step then pays a
    synchronous host→device transfer per batch (an input stall the device
    sits idle through). This wrapper keeps up to ``size`` upcoming batches in
    flight on device — ``jax.device_put`` dispatches the transfer
    asynchronously, so batch k+1 streams to HBM while the step runs on batch
    k. Batches are sharded over the global mesh batch axis via
    ``parallel.shard_batch`` (single-device meshes degrade to a plain
    device_put); re-sharding the yielded arrays downstream is a no-op.

    Drain/stop semantics (PR-3 preemption contract): early termination of the
    consumer (preemption checkpoint, exception, ``break``) closes the inner
    iterator through the generator's ``finally`` — worker threads observe
    their stop event and exit, and prefetched-but-unyielded device batches
    are simply dropped. The recovery checkpoint records the index of the last
    *yielded* batch, so ``--resume auto`` skip-counting is unaffected by the
    prefetch depth.

    Attribute access (``len()``, ``sampler``, ``mean``/``std``,
    ``set_epoch``…) delegates to the wrapped loader.
    """

    def __init__(self, loader, size: int = 2):
        self.loader = loader
        self.size = max(1, int(size))

    def __getattr__(self, name):
        return getattr(self.loader, name)

    def __len__(self):
        return len(self.loader)

    def __iter__(self):
        import collections

        from ..parallel import shard_batch

        buf = collections.deque()
        it = iter(self.loader)
        try:
            while len(buf) < self.size:
                try:
                    buf.append(shard_batch(next(it)))
                except StopIteration:
                    break
            while buf:
                out = buf.popleft()
                try:
                    buf.append(shard_batch(next(it)))
                except StopIteration:
                    pass
                yield out
        finally:
            buf.clear()
            close = getattr(it, 'close', None)
            if close is not None:
                close()


def _collate_arrays(imgs, targets):
    """Stack a list of samples; AugMix tuple samples (clean, aug1..augN) are
    concatenated split-major along batch with targets repeated per split
    (reference loader.py fast_collate tuple path)."""
    if isinstance(imgs[0], (tuple, list)):
        n_splits = len(imgs[0])
        x = np.concatenate([np.stack([im[j] for im in imgs]) for j in range(n_splits)])
        t = np.tile(np.asarray(targets), n_splits)
        return x, t
    return np.stack(imgs), np.asarray(targets)


class ThreadedLoader:
    def __init__(
            self,
            dataset,
            batch_size: int,
            is_training: bool = False,
            num_workers: int = 4,
            drop_last: Optional[bool] = None,
            shuffle: Optional[bool] = None,
            seed: int = 42,
            num_aug_repeats: int = 0,
            prefetch: int = 4,
            re_prob: float = 0.0,
            re_mode: str = 'const',
            re_count: int = 1,
            re_num_splits: int = 0,
            mean=IMAGENET_DEFAULT_MEAN,
            std=IMAGENET_DEFAULT_STD,
            process_index: int = 0,
            process_count: int = 1,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.is_training = is_training
        self.num_workers = max(1, num_workers)
        self.drop_last = is_training if drop_last is None else drop_last
        self.shuffle = is_training if shuffle is None else shuffle
        self.seed = seed
        self.epoch = 0
        self.prefetch = prefetch
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.random_erasing = RandomErasing(
            probability=re_prob, mode=re_mode, min_count=re_count,
            num_splits=re_num_splits, mean=self.mean, std=self.std,
            seed=seed) if re_prob > 0 and is_training else None
        self.process_index = process_index
        self.process_count = process_count
        self.num_aug_repeats = num_aug_repeats if is_training else 0

        self._local_indices = self._shard_indices(shuffled=False)

    def _repeat_aug_indices(self, rng) -> np.ndarray:
        """Repeated-augmentation sampling (reference distributed_sampler.py:54
        RepeatAugSampler): each sample appears `num_repeats` times adjacent in
        the shuffled order, replicas take interleaved slices (so each replica
        sees a DIFFERENT augmentation of the same image), and each replica
        truncates to ~len(dataset)/replicas samples per epoch."""
        import math
        n = len(self.dataset)
        reps = self.num_aug_repeats
        world = max(1, self.process_count)
        indices = np.arange(n)
        if self.shuffle:
            rng.shuffle(indices)
        indices = np.repeat(indices, reps)
        num_samples = int(math.ceil(n * reps / world))
        total = num_samples * world
        indices = np.concatenate([indices, indices[:total - len(indices)]])
        local = indices[self.process_index::world]
        # selected_round=256, selected_ratio=world (reference defaults)
        num_selected = int(math.floor(n // 256 * 256 / world)) if n >= 256 \
            else int(math.ceil(n / world))
        return local[:num_selected]

    def _shard_indices(self, shuffled: bool):
        rng = np.random.RandomState(self.seed + self.epoch)
        if self.num_aug_repeats:
            return self._repeat_aug_indices(rng)
        n = len(self.dataset)
        indices = np.arange(n)
        if shuffled:
            rng.shuffle(indices)
        if self.process_count > 1:
            # pad to equal per-host length (reference OrderedDistributedSampler)
            per_host = -(-n // self.process_count)
            padded = np.concatenate([indices, indices[:per_host * self.process_count - n]])
            indices = padded[self.process_index::self.process_count]
        return indices

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        if self.random_erasing is not None:
            self.random_erasing.set_epoch(epoch)  # resume-reproducible stream

    def __len__(self):
        n = len(self._local_indices)
        if self.drop_last:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def __iter__(self):
        indices = self._shard_indices(shuffled=self.shuffle)
        num_batches = len(indices) // self.batch_size if self.drop_last \
            else -(-len(indices) // self.batch_size)

        sample_q: 'queue.Queue' = queue.Queue(maxsize=self.prefetch * self.batch_size)
        batch_q: 'queue.Queue' = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def _put(q, item) -> bool:
            # put that stays responsive to shutdown (early-terminated iteration)
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        skip_budget = SkipBudget()

        def _read(idx):
            injector = get_fault_injector()
            if injector is not None and injector.io_error_tick():
                raise IOError(f'[fault-inject] sample read {idx}')
            return self.dataset[int(idx)]

        def worker(worker_indices):
            for idx in worker_indices:
                if stop.is_set():
                    return
                try:
                    # transient I/O faults (OSError) ride through jittered
                    # exponential backoff; anything still failing is poison
                    sample = retry_io(lambda: _read(idx), retries=3, base_delay=0.05,
                                      desc=f'sample {int(idx)}')
                except Exception as e:
                    try:
                        skip_budget.record(e, f'sample index {int(idx)}')
                        sample = _SKIPPED
                    except TooManyBadSamples as fatal:
                        sample = fatal  # budget exhausted: fail the epoch loudly
                if not _put(sample_q, (int(idx), sample)):
                    return

        used = indices[:num_batches * self.batch_size] if self.drop_last else indices
        workers = []
        for w in range(self.num_workers):
            t = threading.Thread(target=worker, args=(used[w::self.num_workers],), daemon=True)
            t.start()
            workers.append(t)

        # training batches collate in arrival order (indices are already a
        # fresh shuffle, and this keeps sample_q backpressure intact); eval
        # restores deterministic index order so results are reproducible.
        # repeat-aug emits DUPLICATE indices, which the ordered path's
        # pending-by-index bookkeeping cannot represent — always unordered.
        ordered = not self.shuffle and not self.num_aug_repeats

        def collator():
            pending = {}
            order = list(used)
            pos = 0
            consumed = 0
            batch_imgs, batch_targets = [], []

            def emit(force_last: bool):
                nonlocal batch_imgs, batch_targets
                if len(batch_imgs) == self.batch_size or (force_last and batch_imgs and not self.drop_last):
                    x, t = _collate_arrays(batch_imgs, batch_targets)
                    if self.random_erasing is not None:
                        x = self.random_erasing(x)
                    ok = _put(batch_q, (x, t))
                    batch_imgs, batch_targets = [], []
                    return ok
                return True

            try:
                while consumed < len(order) and not stop.is_set():
                    try:
                        idx, sample = sample_q.get(timeout=0.1)
                    except queue.Empty:
                        continue
                    consumed += 1
                    if isinstance(sample, Exception):
                        raise sample
                    if ordered:
                        pending[idx] = sample
                        while pos < len(order) and int(order[pos]) in pending:
                            s = pending.pop(int(order[pos]))
                            pos += 1
                            if s is not _SKIPPED:
                                img, target = s
                                batch_imgs.append(img)
                                batch_targets.append(target)
                            if not emit(force_last=pos == len(order)):
                                return
                    else:
                        if sample is not _SKIPPED:
                            img, target = sample
                            batch_imgs.append(img)
                            batch_targets.append(target)
                        if not emit(force_last=consumed == len(order)):
                            return
            except Exception as e:
                _put(batch_q, e)
            finally:
                _put(batch_q, None)

        ct = threading.Thread(target=collator, daemon=True)
        ct.start()

        try:
            while True:
                item = batch_q.get()
                if item is None:
                    break
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            stop.set()
            # drain so blocked threads can observe stop and exit
            try:
                while True:
                    batch_q.get_nowait()
            except queue.Empty:
                pass

    @property
    def sampler(self):
        return self  # set_epoch lives here; parity shim


def create_loader(
        dataset,
        input_size,
        batch_size: int,
        is_training: bool = False,
        no_aug: bool = False,
        re_prob: float = 0.0,
        re_mode: str = 'const',
        re_count: int = 1,
        re_split: bool = False,
        train_crop_mode=None,
        scale=None,
        ratio=None,
        hflip: float = 0.5,
        vflip: float = 0.0,
        color_jitter: float = 0.4,
        color_jitter_prob=None,
        grayscale_prob: float = 0.0,
        gaussian_blur_prob: float = 0.0,
        auto_augment=None,
        num_aug_repeats: int = 0,
        num_aug_splits: int = 0,
        interpolation: str = 'bilinear',
        mean=IMAGENET_DEFAULT_MEAN,
        std=IMAGENET_DEFAULT_STD,
        num_workers: int = 4,
        distributed: bool = False,
        crop_pct: Optional[float] = None,
        crop_mode: Optional[str] = None,
        crop_border_pixels: Optional[int] = None,
        collate_fn=None,
        fp16: bool = False,
        drop_last: Optional[bool] = None,
        seed: int = 42,
        persistent_workers: bool = True,
        worker_seeding: str = 'all',
        device_prefetch: int = 0,
        device_augment: bool = False,
        mixup=None,
        **kwargs,
):
    """(reference loader.py:205). Returns a ThreadedLoader yielding
    (images NHWC float32 [0,1], targets int) numpy batches.

    ``device_prefetch=N`` (default 0 = off) appends a DevicePrefetcher stage
    that keeps up to N batches in flight on device (sharded over the global
    mesh), overlapping host→device transfer with the running step. Leave off
    when the consumer still mutates batches on host (mixup, grad-accum
    concatenation).

    ``device_augment=True`` moves RandomErasing, Mixup/CutMix (pass the Mixup
    sampler via ``mixup=``) and normalize off the host: batches collate as
    raw uint8, the host samples only the augmentation *parameters*, and one
    donated jitted program per batch shape does the float math on device
    (data/device_augment.py). The loader then yields (input, target) device
    arrays — soft targets when mixup is active."""
    import jax

    if num_aug_repeats and not hasattr(dataset, '__getitem__'):
        raise ValueError('--aug-repeats requires a map-style (indexable) dataset')
    if device_augment:
        from .mixup import FastCollateMixup
        if isinstance(collate_fn, FastCollateMixup) or isinstance(mixup, FastCollateMixup):
            raise ValueError(
                'device_augment=True already applies mixup on device; a host-side '
                'FastCollateMixup collate would double-apply it. Pass a plain '
                'Mixup instance via mixup= (parameter sampling only) instead.')
        if not is_training:
            raise ValueError('device_augment=True is a train-path stage '
                             '(eval batches are not augmented)')
    if collate_fn is not None:
        raise NotImplementedError('custom collate_fn is not supported by ThreadedLoader')

    re_num_splits = 0
    if re_split:
        re_num_splits = num_aug_splits or 2

    # create_loader owns the dataset transform (reference loader.py:205 does
    # the same — the pipeline is derived from loader args)
    dataset.transform = create_transform(
        input_size,
        is_training=is_training,
        no_aug=no_aug,
        train_crop_mode=train_crop_mode,
        scale=scale,
        ratio=ratio,
        hflip=hflip,
        vflip=vflip,
        color_jitter=color_jitter,
        color_jitter_prob=color_jitter_prob,
        grayscale_prob=grayscale_prob,
        gaussian_blur_prob=gaussian_blur_prob,
        auto_augment=auto_augment,
        interpolation=interpolation,
        mean=mean,
        std=std,
        crop_pct=crop_pct,
        crop_mode=crop_mode,
        crop_border_pixels=crop_border_pixels,
        re_prob=0.0,  # RE applied post-collate by the loader
        separate=num_aug_splits > 0,
        output_dtype=np.uint8 if device_augment else None,
    )

    loader_kwargs = dict(
        batch_size=batch_size,
        is_training=is_training,
        drop_last=drop_last,
        # device_augment: host collates raw uint8 and samples erase params
        # only — the DeviceAugmentStage below owns erase application
        re_prob=0.0 if device_augment else re_prob,
        re_mode=re_mode,
        re_count=re_count,
        re_num_splits=re_num_splits,
        mean=mean,
        std=std,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        seed=seed,
    )
    if not hasattr(dataset, '__getitem__'):
        # iterable (streaming) dataset: the reader owns shard assignment
        loader = StreamingLoader(dataset, num_workers=num_workers, **loader_kwargs)
    else:
        loader = ThreadedLoader(
            dataset,
            num_workers=num_workers,
            num_aug_repeats=num_aug_repeats,
            **loader_kwargs,
        )
    if device_prefetch:
        loader = DevicePrefetcher(loader, size=device_prefetch)
    if device_augment:
        from .device_augment import DeviceAugmentStage
        import jax.numpy as jnp
        re_sampler = RandomErasing(
            probability=re_prob, mode=re_mode, min_count=re_count,
            num_splits=re_num_splits, mean=np.asarray(mean, np.float32),
            std=np.asarray(std, np.float32), seed=seed) if re_prob > 0 else None
        loader = DeviceAugmentStage(
            loader, mean=mean, std=std, mixup=mixup, random_erasing=re_sampler,
            re_mode=re_mode, noise_seed=seed,
            out_dtype=jnp.float16 if fp16 else jnp.float32)
    return loader
