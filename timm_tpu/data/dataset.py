"""Datasets (reference: timm/data/dataset.py:21-207)."""
from __future__ import annotations

import io
import logging
from typing import Callable, Optional

import numpy as np
from PIL import Image

from .readers import create_reader

_logger = logging.getLogger(__name__)

__all__ = ['ImageDataset', 'AugMixDataset']


class ImageDataset:
    def __init__(
            self,
            root: str,
            reader=None,
            split: str = 'train',
            class_map='',
            input_img_mode: str = 'RGB',
            transform: Optional[Callable] = None,
            target_transform: Optional[Callable] = None,
            **kwargs,
    ):
        if reader is None or isinstance(reader, str):
            reader = create_reader(reader or '', root=root, split=split, class_map=class_map)
        self.reader = reader
        self.input_img_mode = input_img_mode
        self.transform = transform
        self.target_transform = target_transform
        self._consecutive_errors = 0

    def __getitem__(self, index: int):
        img, target = self.reader[index]
        try:
            img = Image.open(img)
            img.load()
            self._consecutive_errors = 0
        except Exception as e:
            _logger.warning(f'Skipped sample (index {index}, file {self.reader.filename(index)}). {str(e)}')
            self._consecutive_errors += 1
            if self._consecutive_errors < 50:
                return self[(index + 1) % len(self.reader)]
            raise e
        if self.input_img_mode and img.mode != self.input_img_mode:
            img = img.convert(self.input_img_mode)
        if self.transform is not None:
            img = self.transform(img)
        if target is None:
            target = -1
        elif self.target_transform is not None:
            target = self.target_transform(target)
        return img, target

    def __len__(self):
        return len(self.reader)

    def filename(self, index, basename=False, absolute=False):
        return self.reader.filename(index, basename, absolute)

    def filenames(self, basename=False, absolute=False):
        return self.reader.filenames(basename, absolute)


class IterableImageDataset:
    """Wraps an iterable (streaming) reader with transforms
    (reference dataset.py IterableImageDataset)."""

    def __init__(
            self,
            root: str,
            reader=None,
            transform: Optional[Callable] = None,
            target_transform: Optional[Callable] = None,
            **kwargs,
    ):
        assert reader is not None, 'IterableImageDataset requires a constructed streaming reader'
        self.reader = reader
        self.transform = transform
        self.target_transform = target_transform

    def __iter__(self):
        for img, target in self.reader:
            if self.transform is not None:
                img = self.transform(img)
            if self.target_transform is not None:
                target = self.target_transform(target)
            yield img, target

    def __len__(self):
        return len(self.reader)

    def set_epoch(self, epoch: int):
        if hasattr(self.reader, 'set_epoch'):
            self.reader.set_epoch(epoch)

    def set_worker_info(self, worker_id: int, num_workers: int):
        if hasattr(self.reader, 'set_worker_info'):
            self.reader.set_worker_info(worker_id, num_workers)


class AugMixDataset:
    """Returns (clean, aug1..augN) tuples for JSD training
    (reference dataset.py:170)."""

    def __init__(self, dataset: ImageDataset, num_splits: int = 2):
        self.dataset = dataset
        self.num_splits = num_splits
        self.augmentation = None
        self.normalize = None

    def _set_transforms(self, x):
        assert isinstance(x, (list, tuple)) and len(x) == 3
        self.dataset.transform = x[0]
        self.augmentation = x[1]
        self.normalize = x[2]

    @property
    def transform(self):
        return self.dataset.transform

    @transform.setter
    def transform(self, x):
        self._set_transforms(x)

    def _normalize(self, x):
        return x if self.normalize is None else self.normalize(x)

    def __getitem__(self, i):
        x, y = self.dataset[i]  # all splits share the same initial transform
        x_list = [self._normalize(x)]
        for _ in range(self.num_splits - 1):
            x_list.append(self._normalize(self.augmentation(x)))
        return tuple(x_list), y

    def __len__(self):
        return len(self.dataset)
