"""Dataset class-label metadata (reference: timm/data/dataset_info.py +
imagenet_info.py + _info/ data files).

The bundled `_info/*.json` files are DATASET METADATA (WordNet synset ids and
lemmas for the ImageNet label spaces — published facts of the datasets, not
reference code), re-serialized compactly from the public label lists.
"""
from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Union

__all__ = ['DatasetInfo', 'ImageNetInfo', 'CustomDatasetInfo', 'infer_imagenet_subset']

_INFO_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), '_info')

_SUBSETS = {
    'imagenet': 'imagenet1k.json',
    'imagenet1k': 'imagenet1k.json',
    'imagenet12k': 'imagenet12k.json',
}

# num_classes → subset name (reference imagenet_info.py infer_imagenet_subset)
_NUM_CLASSES_TO_SUBSET = {
    1000: 'imagenet-1k',
    11821: 'imagenet-12k',
}


def infer_imagenet_subset(model_or_cfg) -> Optional[str]:
    """Guess the ImageNet label space from a model / pretrained cfg
    (reference imagenet_info.py:22-42)."""
    if hasattr(model_or_cfg, 'pretrained_cfg'):
        num_classes = getattr(model_or_cfg.pretrained_cfg, 'num_classes', None) \
            or getattr(model_or_cfg, 'num_classes', None)
    elif isinstance(model_or_cfg, dict):
        num_classes = model_or_cfg.get('num_classes')
    else:
        num_classes = getattr(model_or_cfg, 'num_classes', None)
    return _NUM_CLASSES_TO_SUBSET.get(num_classes)


class DatasetInfo:
    def num_classes(self) -> int:
        raise NotImplementedError

    def label_names(self) -> List[str]:
        raise NotImplementedError

    def index_to_label_name(self, index: int) -> str:
        raise NotImplementedError

    def index_to_description(self, index: int, detailed: bool = False) -> str:
        raise NotImplementedError

    def label_name_to_description(self, label: str, detailed: bool = False) -> str:
        raise NotImplementedError


class ImageNetInfo(DatasetInfo):
    """ImageNet label metadata (reference imagenet_info.py:48-95)."""

    def __init__(self, subset: str = 'imagenet-1k'):
        key = re.sub(r'[-_\s]', '', subset.lower())
        assert key in _SUBSETS, f'Unknown imagenet subset {subset}'
        with open(os.path.join(_INFO_DIR, _SUBSETS[key])) as f:
            data = json.load(f)
        self._synsets: List[str] = data['synsets']
        self._lemmas: Dict[str, str] = data.get('lemmas', {})
        self._definitions: Dict[str, str] = data.get('definitions', {})

    def num_classes(self) -> int:
        return len(self._synsets)

    def label_names(self) -> List[str]:
        return self._synsets

    def index_to_label_name(self, index: int) -> str:
        assert 0 <= index < len(self._synsets)
        return self._synsets[index]

    def label_name_to_description(self, label: str, detailed: bool = False) -> str:
        lemma = self._lemmas.get(label, label)
        if detailed and label in self._definitions:
            return f'{lemma}: {self._definitions[label]}'
        return lemma

    def index_to_description(self, index: int, detailed: bool = False) -> str:
        return self.label_name_to_description(self.index_to_label_name(index), detailed=detailed)


class CustomDatasetInfo(DatasetInfo):
    """Label metadata from an explicit mapping (reference dataset_info.py)."""

    def __init__(self, label_names: Union[List[str], Dict[int, str]],
                 label_descriptions: Optional[Dict[str, str]] = None):
        if isinstance(label_names, dict):
            label_names = [label_names[i] for i in sorted(label_names)]
        self._label_names = list(label_names)
        self._label_descriptions = label_descriptions or {}

    def num_classes(self) -> int:
        return len(self._label_names)

    def label_names(self) -> List[str]:
        return self._label_names

    def index_to_label_name(self, index: int) -> str:
        return self._label_names[index]

    def label_name_to_description(self, label: str, detailed: bool = False) -> str:
        return self._label_descriptions.get(label, label)

    def index_to_description(self, index: int, detailed: bool = False) -> str:
        return self.label_name_to_description(self.index_to_label_name(index), detailed=detailed)
