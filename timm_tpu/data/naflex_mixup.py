"""Mixup/CutMix for variable-size (NaFlex) batches
(reference: timm/data/naflex_mixup.py:23-180).

Operates on the list of post-resize HWC numpy arrays BEFORE patchification:
samples are sorted by aspect ratio and paired with their nearest neighbor,
then only the mutual central overlap region of each pair is mixed (Mixup) or
cut-pasted (CutMix). Per-sample effective lambdas account for the overlap
fraction, so the target mixing matches exactly what happened to the pixels.
"""
from __future__ import annotations

import random
from typing import Dict, List, Tuple

import numpy as np

__all__ = ['mix_batch_variable_size']


def mix_batch_variable_size(
        imgs: List[np.ndarray],
        mixup_alpha: float = 0.8,
        cutmix_alpha: float = 1.0,
        switch_prob: float = 0.5,
        local_shuffle: int = 4,
        rng: random.Random = None,
) -> Tuple[List[np.ndarray], List[float], Dict[int, int]]:
    """Mix a batch of HWC float arrays pairwise.

    Returns (mixed_imgs, lam_list, pair_to); lam_list[i] is the weight of
    sample i's OWN content in its mixed image, pair_to[i] the partner index
    (absent for an odd unpaired sample).
    """
    if len(imgs) < 2:
        return imgs, [1.0] * len(imgs), {}
    rng = rng or random
    if mixup_alpha > 0.0 and cutmix_alpha > 0.0:
        use_cutmix = rng.random() < switch_prob
        alpha = cutmix_alpha if use_cutmix else mixup_alpha
    elif mixup_alpha > 0.0:
        use_cutmix, alpha = False, mixup_alpha
    elif cutmix_alpha > 0.0:
        use_cutmix, alpha = True, cutmix_alpha
    else:
        raise ValueError('both mixup_alpha and cutmix_alpha are zero')
    # drawn from the caller's seeded rng so epochs replay deterministically
    lam_raw = float(min(max(rng.betavariate(alpha, alpha), 0.0), 1.0))

    order = sorted(range(len(imgs)), key=lambda i: imgs[i].shape[1] / imgs[i].shape[0])
    if local_shuffle > 1:
        for start in range(0, len(order), local_shuffle):
            sub = order[start:start + local_shuffle]
            rng.shuffle(sub)
            order[start:start + local_shuffle] = sub

    pair_to: Dict[int, int] = {}
    for a, b in zip(order[::2], order[1::2]):
        pair_to[a] = b
        pair_to[b] = a
    odd_one = order[-1] if len(imgs) % 2 else None

    mixed: List[np.ndarray] = [None] * len(imgs)
    lam_list: List[float] = [1.0] * len(imgs)

    # cutmix rectangle chosen once in the overlap frame, shared by both pair
    # members (reference draws per pair; mirrored here via the pair loop)
    done = set()
    for i in range(len(imgs)):
        if i == odd_one or i in done:
            if i == odd_one:
                mixed[i] = imgs[i]
            continue
        j = pair_to[i]
        xi, xj = imgs[i], imgs[j]
        hi, wi = xi.shape[:2]
        hj, wj = xj.shape[:2]
        oh, ow = min(hi, hj), min(wi, wj)
        ti, li = (hi - oh) // 2, (wi - ow) // 2
        tj, lj = (hj - oh) // 2, (wj - ow) // 2

        if use_cutmix:
            cut_ratio = np.sqrt(1.0 - lam_raw)
            ch, cw = int(oh * cut_ratio), int(ow * cut_ratio)
            if ch and cw:
                cy = rng.randint(0, oh - ch)
                cx = rng.randint(0, ow - cw)
            else:
                cy = cx = 0
            for a, xa, xb, (ta, la), (tb, lb), ha, wa in (
                    (i, xi, xj, (ti, li), (tj, lj), hi, wi),
                    (j, xj, xi, (tj, lj), (ti, li), hj, wj)):
                out = xa.copy()
                if ch and cw:
                    out[ta + cy:ta + cy + ch, la + cx:la + cx + cw] = \
                        xb[tb + cy:tb + cy + ch, lb + cx:lb + cx + cw]
                mixed[a] = out
                lam_list[a] = 1.0 - (ch * cw) / float(ha * wa)
        else:
            for a, xa, xb, (ta, la), (tb, lb), ha, wa in (
                    (i, xi, xj, (ti, li), (tj, lj), hi, wi),
                    (j, xj, xi, (tj, lj), (ti, li), hj, wj)):
                out = xa.copy()
                patch_a = xa[ta:ta + oh, la:la + ow]
                patch_b = xb[tb:tb + oh, lb:lb + ow]
                out[ta:ta + oh, la:la + ow] = lam_raw * patch_a + (1.0 - lam_raw) * patch_b
                mixed[a] = out
                # effective own-content weight: mixed overlap + untouched border
                overlap_frac = (oh * ow) / float(ha * wa)
                lam_list[a] = 1.0 - overlap_frac * (1.0 - lam_raw)
        done.add(i)
        done.add(j)
    return mixed, lam_list, pair_to
