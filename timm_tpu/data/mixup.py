"""Mixup / CutMix on host batches (reference: timm/data/mixup.py:90-349).

Operates on numpy (B, H, W, C) batches + int targets, emitting mixed images
and soft-target matrices. Host-side keeps the jitted step free of RNG state.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = ['Mixup', 'FastCollateMixup', 'mixup_target', 'rand_bbox']


def _randint(low, high, size=None, rng=None):
    """Half-open [low, high) integer draw from `rng` (np.random.Generator) or
    the legacy global np.random state when rng is None."""
    if rng is None:
        return np.random.randint(low, high, size=size)
    return rng.integers(low, high, size=size)


def one_hot(x, num_classes, on_value=1.0, off_value=0.0):
    out = np.full((x.shape[0], num_classes), off_value, dtype=np.float32)
    out[np.arange(x.shape[0]), x] = on_value
    return out


def mixup_target(target, num_classes, lam=1.0, smoothing=0.0):
    off_value = smoothing / num_classes
    on_value = 1.0 - smoothing + off_value
    y1 = one_hot(target, num_classes, on_value, off_value)
    y2 = one_hot(target[::-1], num_classes, on_value, off_value)
    return y1 * lam + y2 * (1.0 - lam)


def rand_bbox(img_shape, lam, margin=0.0, count=None, rng=None):
    """(reference mixup.py:40). `rng` is an optional np.random.Generator; when
    None the legacy global np.random stream is used (not resume-safe)."""
    ratio = np.sqrt(1 - lam)
    img_h, img_w = img_shape[-3:-1]
    cut_h, cut_w = int(img_h * ratio), int(img_w * ratio)
    margin_y, margin_x = int(margin * cut_h), int(margin * cut_w)
    cy = _randint(0 + margin_y, img_h - margin_y, size=count, rng=rng)
    cx = _randint(0 + margin_x, img_w - margin_x, size=count, rng=rng)
    yl = np.clip(cy - cut_h // 2, 0, img_h)
    yh = np.clip(cy + cut_h // 2, 0, img_h)
    xl = np.clip(cx - cut_w // 2, 0, img_w)
    xh = np.clip(cx + cut_w // 2, 0, img_w)
    return yl, yh, xl, xh


def rand_bbox_minmax(img_shape, minmax, count=None, rng=None):
    assert len(minmax) == 2
    img_h, img_w = img_shape[-3:-1]
    cut_h = _randint(int(img_h * minmax[0]), int(img_h * minmax[1]), size=count, rng=rng)
    cut_w = _randint(int(img_w * minmax[0]), int(img_w * minmax[1]), size=count, rng=rng)
    yl = _randint(0, img_h - cut_h, size=count, rng=rng)
    xl = _randint(0, img_w - cut_w, size=count, rng=rng)
    return yl, yl + cut_h, xl, xl + cut_w


def cutmix_bbox_and_lam(img_shape, lam, ratio_minmax=None, correct_lam=True, count=None,
                        rng=None):
    if ratio_minmax is not None:
        yl, yu, xl, xu = rand_bbox_minmax(img_shape, ratio_minmax, count=count, rng=rng)
    else:
        yl, yu, xl, xu = rand_bbox(img_shape, lam, count=count, rng=rng)
    if correct_lam or ratio_minmax is not None:
        bbox_area = (yu - yl) * (xu - xl)
        lam = 1.0 - bbox_area / float(img_shape[-3] * img_shape[-2])
    return (yl, yu, xl, xu), lam


class Mixup:
    """(reference mixup.py:90) — batch/pair/elem modes."""

    def __init__(
            self,
            mixup_alpha: float = 1.0,
            cutmix_alpha: float = 0.0,
            cutmix_minmax=None,
            prob: float = 1.0,
            switch_prob: float = 0.5,
            mode: str = 'batch',
            correct_lam: bool = True,
            label_smoothing: float = 0.1,
            num_classes: int = 1000,
            seed: Optional[int] = None,
    ):
        self.mixup_alpha = mixup_alpha
        self.cutmix_alpha = cutmix_alpha
        self.cutmix_minmax = cutmix_minmax
        if self.cutmix_minmax is not None:
            assert len(self.cutmix_minmax) == 2
            self.cutmix_alpha = 1.0
        self.mix_prob = prob
        self.switch_prob = switch_prob
        self.label_smoothing = label_smoothing
        self.num_classes = num_classes
        self.mode = mode
        self.correct_lam = correct_lam
        self.mixup_enabled = True
        # seed=None keeps the legacy global np.random stream (not resume-safe);
        # with a seed, set_epoch(e) re-derives the stream so `--resume auto`
        # replays the exact mixup boxes of the original run
        self.seed = seed
        self._rng = np.random.default_rng(seed) if seed is not None else None

    def set_epoch(self, epoch: int):
        if self.seed is not None:
            self._rng = np.random.default_rng((self.seed, epoch))

    def _rand(self):
        return self._rng.random() if self._rng is not None else np.random.rand()

    def _beta(self, alpha):
        return (self._rng.beta(alpha, alpha) if self._rng is not None
                else np.random.beta(alpha, alpha))

    def _params_per_batch(self):
        lam = 1.0
        use_cutmix = False
        if self.mixup_enabled and self._rand() < self.mix_prob:
            if self.mixup_alpha > 0.0 and self.cutmix_alpha > 0.0:
                use_cutmix = self._rand() < self.switch_prob
                lam_mix = self._beta(self.cutmix_alpha) if use_cutmix else \
                    self._beta(self.mixup_alpha)
            elif self.mixup_alpha > 0.0:
                lam_mix = self._beta(self.mixup_alpha)
            elif self.cutmix_alpha > 0.0:
                use_cutmix = True
                lam_mix = self._beta(self.cutmix_alpha)
            else:
                raise ValueError('One of mixup_alpha > 0., cutmix_alpha > 0. required')
            lam = float(lam_mix)
        return lam, use_cutmix

    def _mix_batch(self, x):
        lam, use_cutmix = self._params_per_batch()
        if lam == 1.0:
            return x, 1.0
        x_flipped = x[::-1]
        if use_cutmix:
            (yl, yh, xl, xh), lam = cutmix_bbox_and_lam(
                x.shape, lam, ratio_minmax=self.cutmix_minmax, correct_lam=self.correct_lam,
                rng=self._rng)
            x = x.copy()
            x[:, yl:yh, xl:xh] = x_flipped[:, yl:yh, xl:xh]
        else:
            x = x * lam + x_flipped * (1.0 - lam)
        return x, lam

    def _mix_elem_or_pair(self, x, pair: bool):
        batch_size = x.shape[0]
        num_elem = batch_size // 2 if pair else batch_size
        lam_out = np.ones(batch_size, dtype=np.float32)
        x_orig = x  # read-only source; single copy below is mutated
        x = x.copy()
        for i in range(num_elem):
            j = batch_size - i - 1
            lam, use_cutmix = self._params_per_batch()
            if lam == 1.0:
                continue
            if use_cutmix:
                (yl, yh, xl, xh), lam = cutmix_bbox_and_lam(
                    x[i].shape, lam, ratio_minmax=self.cutmix_minmax, correct_lam=self.correct_lam,
                    rng=self._rng)
                x[i][yl:yh, xl:xh] = x_orig[j][yl:yh, xl:xh]
                if pair:
                    x[j][yl:yh, xl:xh] = x_orig[i][yl:yh, xl:xh]
            else:
                x[i] = x[i] * lam + x_orig[j] * (1 - lam)
                if pair:
                    x[j] = x[j] * lam + x_orig[i] * (1 - lam)
            lam_out[i] = lam
            if pair:
                lam_out[j] = lam
        return x, lam_out

    def sample_params(self, batch_shape):
        """Device-augment split: draw the *parameters* of a mix (per-row lam,
        cutmix flag, bbox) without touching pixels, consuming the RNG stream in
        the same order as __call__ so a seeded run is bit-identical either way.

        Returns {'lam': (B,) f32, 'use_cutmix': (B,) bool, 'bbox': (B, 4) i32
        as (yl, yh, xl, xh)}. Untouched rows encode identity in *values*
        (lam=1, zero bbox) so the pytree structure riding the batch is always
        the same and the jitted applier stays one program per shape."""
        batch_size = int(batch_shape[0])
        lam_out = np.ones(batch_size, dtype=np.float32)
        use_cut = np.zeros(batch_size, dtype=bool)
        bbox = np.zeros((batch_size, 4), dtype=np.int32)
        if self.mode == 'batch':
            lam, use_cutmix = self._params_per_batch()
            if lam != 1.0:
                if use_cutmix:
                    (yl, yh, xl, xh), lam = cutmix_bbox_and_lam(
                        tuple(batch_shape), lam, ratio_minmax=self.cutmix_minmax,
                        correct_lam=self.correct_lam, rng=self._rng)
                    bbox[:] = (yl, yh, xl, xh)
                    use_cut[:] = True
                lam_out[:] = lam
        else:
            pair = self.mode == 'pair'
            if pair:
                assert batch_size % 2 == 0, 'Batch size should be even for pair mixup'
            num_elem = batch_size // 2 if pair else batch_size
            for i in range(num_elem):
                j = batch_size - i - 1
                lam, use_cutmix = self._params_per_batch()
                if lam == 1.0:
                    continue
                if use_cutmix:
                    (yl, yh, xl, xh), lam = cutmix_bbox_and_lam(
                        tuple(batch_shape[1:]), lam, ratio_minmax=self.cutmix_minmax,
                        correct_lam=self.correct_lam, rng=self._rng)
                    bbox[i] = (yl, yh, xl, xh)
                    use_cut[i] = True
                    if pair:
                        bbox[j] = bbox[i]
                        use_cut[j] = True
                lam_out[i] = lam
                if pair:
                    lam_out[j] = lam
        return {'lam': lam_out, 'use_cutmix': use_cut, 'bbox': bbox}

    def __call__(self, x, target):
        if self.mode == 'batch':
            x, lam = self._mix_batch(x)
            target = mixup_target(target, self.num_classes, lam, self.label_smoothing)
        else:
            pair = self.mode == 'pair'
            if pair:
                assert x.shape[0] % 2 == 0, 'Batch size should be even for pair mixup'
            x, lam = self._mix_elem_or_pair(x, pair)
            off = self.label_smoothing / self.num_classes
            on = 1.0 - self.label_smoothing + off
            y1 = one_hot(target, self.num_classes, on, off)
            y2 = one_hot(target[::-1], self.num_classes, on, off)
            target = y1 * lam[:, None] + y2 * (1.0 - lam[:, None])
        return x, target


class FastCollateMixup(Mixup):
    """Collate-time variant — identical math on this host pipeline; kept for
    API parity with reference mixup.py:221."""

    def __call__(self, batch, _=None):
        xs = np.stack([b[0] for b in batch])
        ts = np.asarray([b[1] for b in batch])
        return super().__call__(xs, ts)
