from .auto_augment import (
    AugMixAugment, AutoAugment, RandAugment, augment_and_mix_transform,
    auto_augment_transform, rand_augment_transform,
)
from .config import resolve_data_config, resolve_model_data_config
from .constants import (
    DEFAULT_CROP_MODE, DEFAULT_CROP_PCT, IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD,
    IMAGENET_INCEPTION_MEAN, IMAGENET_INCEPTION_STD, OPENAI_CLIP_MEAN, OPENAI_CLIP_STD,
)
from .dataset import AugMixDataset, ImageDataset
from .dataset_factory import create_dataset
from .device_augment import (
    DeviceAugment, DeviceAugmentStage, NaFlexDeviceAugment,
    augment_image_batch, augment_image_batch_np, augment_naflex_batch,
)
from .loader import StreamingLoader, ThreadedLoader, create_loader
from .readers_streaming import ReaderImageInTar, ReaderTfds, ReaderWds, assign_shards
from .mixup import FastCollateMixup, Mixup
from .naflex_loader import NaFlexCollator, NaFlexLoader, calculate_naflex_batch_size, create_naflex_loader
from .random_erasing import RandomErasing
from .readers import ReaderImageFolder, create_reader
from .real_labels import RealLabelsImagenet
from .transforms import (
    CenterCrop, CenterCropOrPad, Compose, RandomResizedCropAndInterpolation,
    Resize, ResizeKeepRatio, ToNumpy,
)
from .transforms_factory import create_transform
