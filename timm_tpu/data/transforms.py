"""Host-side image transforms on PIL images / numpy arrays
(reference: timm/data/transforms.py:1-583).

Transforms compose PIL→PIL; the terminal ToNumpy yields float32 HWC in [0,1].
Normalization happens on device (fused into the jitted step input path), so
the host pipeline stays uint8/float32-cheap.
"""
from __future__ import annotations

import math
import random
import warnings
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np
from PIL import Image

__all__ = [
    'Compose', 'ToNumpy', 'RandomResizedCropAndInterpolation', 'CenterCropOrPad',
    'ResizeKeepRatio', 'RandomHorizontalFlip', 'RandomVerticalFlip', 'ColorJitter',
    'Resize', 'CenterCrop', 'str_to_pil_interp', 'interp_mode_to_str', 'RandomChoice',
]

_PIL_INTERP = {
    'nearest': Image.NEAREST,
    'bilinear': Image.BILINEAR,
    'bicubic': Image.BICUBIC,
    'lanczos': Image.LANCZOS,
    'hamming': Image.HAMMING,
    'box': Image.BOX,
}
_RANDOM_INTERPOLATION = (Image.BILINEAR, Image.BICUBIC)


def str_to_pil_interp(mode_str: str):
    return _PIL_INTERP.get(mode_str, Image.BICUBIC)


def interp_mode_to_str(mode) -> str:
    for k, v in _PIL_INTERP.items():
        if v == mode:
            return k
    return 'bicubic'


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img

    def __repr__(self):
        return 'Compose(' + ', '.join(repr(t) for t in self.transforms) + ')'


class ToNumpy:
    """PIL → float32 HWC ndarray in [0,1] (normalization is on-device).

    With dtype=np.uint8 the raw bytes pass through untouched — the
    device-augment path transfers uint8 and does the /255 + float math in the
    jitted on-device program (see data/device_augment.py)."""

    def __init__(self, dtype=np.float32):
        self.dtype = dtype

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.dtype == np.uint8:
            return arr.astype(np.uint8)
        if arr.dtype == np.uint8:
            arr = arr.astype(self.dtype) / 255.0
        return arr.astype(self.dtype)


class RandomHorizontalFlip:
    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, img):
        if random.random() < self.p:
            return img.transpose(Image.FLIP_LEFT_RIGHT)
        return img


class RandomVerticalFlip:
    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, img):
        if random.random() < self.p:
            return img.transpose(Image.FLIP_TOP_BOTTOM)
        return img


class Resize:
    def __init__(self, size, interpolation='bilinear'):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        interp = str_to_pil_interp(self.interpolation) if isinstance(self.interpolation, str) else self.interpolation
        if isinstance(self.size, int):
            w, h = img.size
            short, long = (w, h) if w <= h else (h, w)
            if short == self.size:
                return img
            new_short = self.size
            new_long = int(self.size * long / short)
            new_w, new_h = (new_short, new_long) if w <= h else (new_long, new_short)
            return img.resize((new_w, new_h), interp)
        return img.resize(self.size[::-1], interp)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        w, h = img.size
        th, tw = self.size
        left = int(round((w - tw) / 2.0))
        top = int(round((h - th) / 2.0))
        return img.crop((left, top, left + tw, top + th))


class CenterCropOrPad:
    """Center crop w/ padding when image is smaller (reference transforms.py:314)."""

    def __init__(self, size, fill=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.fill = fill

    def __call__(self, img):
        w, h = img.size
        th, tw = self.size
        if w < tw or h < th:
            new = Image.new(img.mode, (max(w, tw), max(h, th)),
                            tuple([self.fill] * len(img.getbands())) if img.getbands() else self.fill)
            new.paste(img, ((max(w, tw) - w) // 2, (max(h, th) - h) // 2))
            img = new
            w, h = img.size
        left = int(round((w - tw) / 2.0))
        top = int(round((h - th) / 2.0))
        return img.crop((left, top, left + tw, top + th))


class ResizeKeepRatio:
    """Resize keeping aspect ratio, longest or shortest criteria
    (reference transforms.py:~430)."""

    def __init__(self, size, longest: float = 0.0, interpolation='bilinear', fill=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.longest = longest
        self.interpolation = interpolation

    def __call__(self, img):
        w, h = img.size
        target_h, target_w = self.size
        ratio_h, ratio_w = h / target_h, w / target_w
        ratio = max(ratio_h, ratio_w) * self.longest + min(ratio_h, ratio_w) * (1.0 - self.longest)
        new_w, new_h = int(round(w / ratio)), int(round(h / ratio))
        interp = str_to_pil_interp(self.interpolation) if isinstance(self.interpolation, str) else self.interpolation
        return img.resize((new_w, new_h), interp)


class RandomChoice:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        return random.choice(self.transforms)(img)


class RandomApply:
    def __init__(self, transform, p: float = 0.5):
        self.transform = transform
        self.p = p

    def __call__(self, img):
        if random.random() < self.p:
            return self.transform(img)
        return img


class RandomGrayscale:
    def __init__(self, p: float = 0.1):
        self.p = p

    def __call__(self, img):
        if random.random() < self.p:
            return img.convert('L').convert(img.mode)
        return img


class RandomGaussianBlur:
    def __init__(self, p: float = 0.1, radius_range=(0.1, 2.0)):
        self.p = p
        self.radius_range = radius_range

    def __call__(self, img):
        if random.random() < self.p:
            from PIL import ImageFilter
            return img.filter(ImageFilter.GaussianBlur(radius=random.uniform(*self.radius_range)))
        return img


class TrimBorder:
    """Crop `border_size` pixels from every edge (reference transforms.py TrimBorder)."""

    def __init__(self, border_size: int):
        self.border_size = border_size

    def __call__(self, img):
        w, h = img.size
        b = self.border_size
        if b <= 0 or w <= 2 * b or h <= 2 * b:
            return img
        return img.crop((b, b, w - b, h - b))


class RandomResizedCropAndInterpolation:
    """RRC w/ random interpolation choice (reference transforms.py:166)."""

    def __init__(
            self,
            size,
            scale: Tuple[float, float] = (0.08, 1.0),
            ratio: Tuple[float, float] = (3. / 4., 4. / 3.),
            interpolation: Union[str, Sequence] = 'bilinear',
    ):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        if scale[0] > scale[1] or ratio[0] > ratio[1]:
            warnings.warn('range should be of kind (min, max)')
        self.scale = scale
        self.ratio = ratio
        if interpolation == 'random':
            self.interpolation = _RANDOM_INTERPOLATION
        else:
            self.interpolation = str_to_pil_interp(interpolation) if isinstance(interpolation, str) else interpolation

    @staticmethod
    def get_params(img, scale, ratio):
        w, h = img.size
        area = w * h
        for _ in range(10):
            target_area = random.uniform(*scale) * area
            log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
            aspect_ratio = math.exp(random.uniform(*log_ratio))
            tw = int(round(math.sqrt(target_area * aspect_ratio)))
            th = int(round(math.sqrt(target_area / aspect_ratio)))
            if tw <= w and th <= h:
                left = random.randint(0, w - tw)
                top = random.randint(0, h - th)
                return top, left, th, tw
        # fallback: center crop to in-range aspect
        in_ratio = w / h
        if in_ratio < min(ratio):
            tw = w
            th = int(round(tw / min(ratio)))
        elif in_ratio > max(ratio):
            th = h
            tw = int(round(th * max(ratio)))
        else:
            tw, th = w, h
        left = (w - tw) // 2
        top = (h - th) // 2
        return top, left, th, tw

    def __call__(self, img):
        top, left, th, tw = self.get_params(img, self.scale, self.ratio)
        if isinstance(self.interpolation, (tuple, list)):
            interp = random.choice(self.interpolation)
        else:
            interp = self.interpolation
        img = img.crop((left, top, left + tw, top + th))
        return img.resize(self.size[::-1], interp)


class ColorJitter:
    """Brightness/contrast/saturation(/hue) jitter on PIL images."""

    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0, hue=0.0):
        self.brightness = self._range(brightness)
        self.contrast = self._range(contrast)
        self.saturation = self._range(saturation)
        self.hue = self._range(hue, center=0.0, bound=0.5, clip_first=False)

    @staticmethod
    def _range(value, center=1.0, bound=float('inf'), clip_first=True):
        if isinstance(value, (tuple, list)):
            return tuple(value) if value[0] != value[1] or value[0] != center else None
        if value == 0:
            return None
        lo = center - value
        if clip_first:
            lo = max(lo, 0.0)
        return (max(lo, -bound), min(center + value, bound))

    def __call__(self, img):
        from PIL import ImageEnhance
        ops = []
        if self.brightness:
            ops.append(lambda im: ImageEnhance.Brightness(im).enhance(random.uniform(*self.brightness)))
        if self.contrast:
            ops.append(lambda im: ImageEnhance.Contrast(im).enhance(random.uniform(*self.contrast)))
        if self.saturation:
            ops.append(lambda im: ImageEnhance.Color(im).enhance(random.uniform(*self.saturation)))
        if self.hue:
            def hue_op(im):
                f = random.uniform(*self.hue)
                hsv = im.convert('HSV')
                arr = np.array(hsv)
                arr[..., 0] = (arr[..., 0].astype(np.int16) + int(f * 255)) % 256
                return Image.fromarray(arr, 'HSV').convert(im.mode)
            ops.append(hue_op)
        random.shuffle(ops)
        for op in ops:
            img = op(img)
        return img
