"""Random Erasing on numpy batches (reference: timm/data/random_erasing.py).

The reference erases on-device inside its CUDA prefetcher; here erasing is a
cheap numpy op applied post-collate on the host batch (HWC float images),
keeping the device step purely functional.
"""
from __future__ import annotations

import math
import random
from typing import Optional

import numpy as np

__all__ = ['RandomErasing']


class RandomErasing:
    def __init__(
            self,
            probability: float = 0.5,
            min_area: float = 0.02,
            max_area: float = 1 / 3,
            min_aspect: float = 0.3,
            max_aspect=None,
            mode: str = 'const',
            min_count: int = 1,
            max_count=None,
            num_splits: int = 0,
            mean=None,
            std=None,
            seed: Optional[int] = None,
    ):
        self.probability = probability
        self.min_area = min_area
        self.max_area = max_area
        max_aspect = max_aspect or 1 / min_aspect
        self.log_aspect_ratio = (math.log(min_aspect), math.log(max_aspect))
        self.min_count = min_count
        self.max_count = max_count or min_count
        self.num_splits = num_splits
        self.mode = mode.lower()
        assert self.mode in ('const', 'rand', 'pixel')
        # fills are expressed in *normalized* space (the reference erases after
        # on-device normalization); since this runs on [0,1] images before the
        # device normalize, map them back: x01 = mean + std * normalized
        self.mean = np.asarray(mean if mean is not None else (0.0, 0.0, 0.0), np.float32)
        self.std = np.asarray(std if std is not None else (1.0, 1.0, 1.0), np.float32)
        # seed=None keeps the legacy global random/np.random streams (not
        # resume-safe); with a seed, set_epoch(e) re-derives the stream so a
        # resumed run replays identical erase rectangles
        self.seed = seed
        self._rng = np.random.default_rng(seed) if seed is not None else None

    def set_epoch(self, epoch: int):
        if self.seed is not None:
            self._rng = np.random.default_rng((self.seed, epoch))

    def _random(self):
        return self._rng.random() if self._rng is not None else random.random()

    def _uniform(self, a, b):
        return self._rng.uniform(a, b) if self._rng is not None else random.uniform(a, b)

    def _randint(self, a, b):
        """Inclusive [a, b] like random.randint."""
        return int(self._rng.integers(a, b, endpoint=True)) if self._rng is not None \
            else random.randint(a, b)

    def _randn(self, *shape):
        return (self._rng.standard_normal(shape).astype(np.float32) if self._rng is not None
                else np.random.randn(*shape).astype(np.float32))

    def _erase_one(self, img):
        h, w, c = img.shape
        area = h * w
        count = self.min_count if self.min_count == self.max_count else \
            self._randint(self.min_count, self.max_count)
        for _ in range(count):
            for _ in range(10):
                target_area = self._uniform(self.min_area, self.max_area) * area / count
                aspect_ratio = math.exp(self._uniform(*self.log_aspect_ratio))
                eh = int(round(math.sqrt(target_area * aspect_ratio)))
                ew = int(round(math.sqrt(target_area / aspect_ratio)))
                if ew < w and eh < h:
                    top = self._randint(0, h - eh)
                    left = self._randint(0, w - ew)
                    if self.mode == 'pixel':
                        noise = self._randn(eh, ew, c)
                        img[top:top + eh, left:left + ew] = (self.mean + self.std * noise).astype(img.dtype)
                    elif self.mode == 'rand':
                        noise = self._randn(1, 1, c)
                        img[top:top + eh, left:left + ew] = (self.mean + self.std * noise).astype(img.dtype)
                    else:
                        img[top:top + eh, left:left + ew] = self.mean.astype(img.dtype)
                    break
        return img

    def __call__(self, batch):
        """batch: (B, H, W, C) float ndarray, modified in place."""
        batch_start = batch.shape[0] // self.num_splits if self.num_splits > 1 else 0
        for i in range(batch_start, batch.shape[0]):
            if self._random() <= self.probability:
                self._erase_one(batch[i])
        return batch

    def sample_params(self, batch_shape):
        """Device-augment split: draw erase rectangles (and 'rand'-mode fill
        colors) without touching pixels, consuming the RNG stream in the same
        order as __call__ so a seeded run is bit-identical either way — except
        'pixel' mode, whose per-pixel noise is generated on device from a
        threaded jax.random key instead of host randn.

        Returns {'erase_box': (B, K, 4) i32 as (top, left, eh, ew)} plus, for
        mode='rand', {'erase_fill': (B, K, C) f32} ([0,1]-space fill colors).
        K = max_count; unused slots are all-zero boxes (eh=ew=0 → no-op), so
        the pytree riding the batch is shape-stable."""
        b, h, w, c = (int(d) for d in batch_shape)
        k = self.max_count
        boxes = np.zeros((b, k, 4), dtype=np.int32)
        fill = np.zeros((b, k, c), dtype=np.float32) if self.mode == 'rand' else None
        area = h * w
        batch_start = b // self.num_splits if self.num_splits > 1 else 0
        for i in range(batch_start, b):
            if self._random() > self.probability:
                continue
            count = self.min_count if self.min_count == self.max_count else \
                self._randint(self.min_count, self.max_count)
            slot = 0
            for _ in range(count):
                for _ in range(10):
                    target_area = self._uniform(self.min_area, self.max_area) * area / count
                    aspect_ratio = math.exp(self._uniform(*self.log_aspect_ratio))
                    eh = int(round(math.sqrt(target_area * aspect_ratio)))
                    ew = int(round(math.sqrt(target_area / aspect_ratio)))
                    if ew < w and eh < h:
                        top = self._randint(0, h - eh)
                        left = self._randint(0, w - ew)
                        boxes[i, slot] = (top, left, eh, ew)
                        if self.mode == 'rand':
                            fill[i, slot] = self.mean + self.std * self._randn(c)
                        slot += 1
                        break
        out = {'erase_box': boxes}
        if fill is not None:
            out['erase_fill'] = fill
        return out
