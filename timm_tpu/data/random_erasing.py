"""Random Erasing on numpy batches (reference: timm/data/random_erasing.py).

The reference erases on-device inside its CUDA prefetcher; here erasing is a
cheap numpy op applied post-collate on the host batch (HWC float images),
keeping the device step purely functional.
"""
from __future__ import annotations

import math
import random

import numpy as np

__all__ = ['RandomErasing']


class RandomErasing:
    def __init__(
            self,
            probability: float = 0.5,
            min_area: float = 0.02,
            max_area: float = 1 / 3,
            min_aspect: float = 0.3,
            max_aspect=None,
            mode: str = 'const',
            min_count: int = 1,
            max_count=None,
            num_splits: int = 0,
            mean=None,
            std=None,
    ):
        self.probability = probability
        self.min_area = min_area
        self.max_area = max_area
        max_aspect = max_aspect or 1 / min_aspect
        self.log_aspect_ratio = (math.log(min_aspect), math.log(max_aspect))
        self.min_count = min_count
        self.max_count = max_count or min_count
        self.num_splits = num_splits
        self.mode = mode.lower()
        assert self.mode in ('const', 'rand', 'pixel')
        # fills are expressed in *normalized* space (the reference erases after
        # on-device normalization); since this runs on [0,1] images before the
        # device normalize, map them back: x01 = mean + std * normalized
        self.mean = np.asarray(mean if mean is not None else (0.0, 0.0, 0.0), np.float32)
        self.std = np.asarray(std if std is not None else (1.0, 1.0, 1.0), np.float32)

    def _erase_one(self, img):
        h, w, c = img.shape
        area = h * w
        count = self.min_count if self.min_count == self.max_count else \
            random.randint(self.min_count, self.max_count)
        for _ in range(count):
            for _ in range(10):
                target_area = random.uniform(self.min_area, self.max_area) * area / count
                aspect_ratio = math.exp(random.uniform(*self.log_aspect_ratio))
                eh = int(round(math.sqrt(target_area * aspect_ratio)))
                ew = int(round(math.sqrt(target_area / aspect_ratio)))
                if ew < w and eh < h:
                    top = random.randint(0, h - eh)
                    left = random.randint(0, w - ew)
                    if self.mode == 'pixel':
                        noise = np.random.randn(eh, ew, c).astype(np.float32)
                        img[top:top + eh, left:left + ew] = (self.mean + self.std * noise).astype(img.dtype)
                    elif self.mode == 'rand':
                        noise = np.random.randn(1, 1, c).astype(np.float32)
                        img[top:top + eh, left:left + ew] = (self.mean + self.std * noise).astype(img.dtype)
                    else:
                        img[top:top + eh, left:left + ew] = self.mean.astype(img.dtype)
                    break
        return img

    def __call__(self, batch):
        """batch: (B, H, W, C) float ndarray, modified in place."""
        batch_start = batch.shape[0] // self.num_splits if self.num_splits > 1 else 0
        for i in range(batch_start, batch.shape[0]):
            if random.random() <= self.probability:
                self._erase_one(batch[i])
        return batch
