"""Streaming / sharded dataset readers
(reference: timm/data/readers/reader_wds.py, reader_tfds.py,
reader_image_in_tar.py).

Three readers for ImageNet-scale multi-host input:

  * ReaderImageInTar — map-style index over image members of tar file(s);
    labels from the member's parent directory name.
  * ReaderWds — iterable webdataset-style shard reader implemented directly
    on `tarfile` (no webdataset dependency): samples are members grouped by
    basename key, image from .jpg/.jpeg/.png/.webp, target from .cls/.json.
  * ReaderTfds — tensorflow_datasets wrapper (gated on the library being
    installed; this image ships without it, so construction raises with
    guidance — the sharding logic is exercised via ReaderWds which shares it).

Shard assignment follows the reference's InputContext scheme
(reader_tfds.py:207-249): the shard list is dealt round-robin over
`global_worker_id = dist_rank * num_workers + worker_id`. When there are
fewer shards than global workers, workers instead interleave SAMPLES within
their round-robin shard subset (even-split fallback).
"""
from __future__ import annotations

import glob
import io
import json
import logging
import os
import random
import tarfile
from typing import Callable, List, Optional, Tuple

from ..resilience import SkipBudget, get_fault_injector, retry_io

_logger = logging.getLogger(__name__)

__all__ = ['ReaderImageInTar', 'ReaderWds', 'ReaderTfds', 'assign_shards', 'expand_shard_pattern']

IMG_EXTENSIONS = ('.jpg', '.jpeg', '.png', '.webp', '.bmp')


def assign_shards(shards: List, global_worker_id: int, global_num_workers: int) -> List:
    """Round-robin shard assignment (reference InputContext semantics).
    Returns the subset of `shards` owned by this worker. When there are fewer
    shards than workers, multiple workers share a shard (caller interleaves
    samples via `sample_stride`)."""
    if global_num_workers <= 1:
        return list(shards)
    if len(shards) >= global_num_workers:
        return list(shards[global_worker_id::global_num_workers])
    # fewer shards than workers: worker w reads shard w % num_shards and
    # interleaves samples with the other workers mapped to the same shard
    return [shards[global_worker_id % len(shards)]]


def expand_shard_pattern(pattern: str) -> List[str]:
    """Expand `{000..012}` brace ranges and glob wildcards into a shard list."""
    import re
    m = re.search(r'\{(\d+)\.\.(\d+)\}', pattern)
    if m:
        lo, hi = m.group(1), m.group(2)
        width = len(lo)
        out = []
        for i in range(int(lo), int(hi) + 1):
            out.extend(expand_shard_pattern(pattern[:m.start()] + str(i).zfill(width) + pattern[m.end():]))
        return out
    if any(c in pattern for c in '*?['):
        return sorted(glob.glob(pattern))
    if os.path.isdir(pattern):
        return sorted(
            os.path.join(pattern, f) for f in os.listdir(pattern) if f.endswith('.tar'))
    return [pattern]


def _decode_image(data: bytes, input_img_mode: str = 'RGB'):
    from PIL import Image
    img = Image.open(io.BytesIO(data))
    img.load()
    if input_img_mode and img.mode != input_img_mode:
        img = img.convert(input_img_mode)
    return img


class ReaderImageInTar:
    """Map-style reader over images inside tar file(s)
    (reference reader_image_in_tar.py:191). Class labels come from each
    member's first path component (`<class>/<name>.jpg`)."""

    def __init__(self, root: str, class_map='', input_img_mode: str = 'RGB'):
        self.input_img_mode = input_img_mode
        tars = expand_shard_pattern(root)
        assert tars, f'no tar files found at {root}'
        self.samples: List[Tuple[str, str, str]] = []  # (tar_path, member_name, class_name)
        class_names = set()
        for tp in tars:
            with tarfile.open(tp) as tf:
                for m in tf.getmembers():
                    if not m.isfile():
                        continue
                    ext = os.path.splitext(m.name)[1].lower()
                    if ext not in IMG_EXTENSIONS:
                        continue
                    cls = m.name.split('/')[0] if '/' in m.name else ''
                    class_names.add(cls)
                    self.samples.append((tp, m.name, cls))
        self.samples.sort(key=lambda s: (s[0], s[1]))
        if class_map:
            from .readers import load_class_map
            self.class_to_idx = load_class_map(class_map)
        else:
            self.class_to_idx = {c: i for i, c in enumerate(sorted(class_names))}
        # tarfile seeks a shared file object; keep one handle PER THREAD so
        # ThreadedLoader workers don't interleave reads
        import threading
        self._tls = threading.local()

    def _tar(self, path):
        cache = getattr(self._tls, 'tars', None)
        if cache is None:
            cache = self._tls.tars = {}
        tf = cache.get(path)
        if tf is None:
            tf = cache[path] = tarfile.open(path)
        return tf

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, index: int):
        # returns (file-like, target) matching the ImageDataset reader contract
        tp, name, cls = self.samples[index]
        data = self._tar(tp).extractfile(name).read()
        return io.BytesIO(data), self.class_to_idx.get(cls, -1)

    def filename(self, index, basename=False, absolute=False):
        name = self.samples[index][1]
        return os.path.basename(name) if basename else name

    def filenames(self, basename=False, absolute=False):
        return [self.filename(i, basename) for i in range(len(self.samples))]


class ReaderWds:
    """Iterable webdataset-shard reader (reference reader_wds.py:262),
    implemented directly on `tarfile`.

    Each epoch: shards are (optionally) shuffled with a common seed, dealt to
    `dist_rank * num_workers + worker_id` round-robin, then streamed with a
    sample shuffle buffer. With fewer shards than workers, co-assigned
    workers interleave samples by stride.
    """

    def __init__(
            self,
            root: str,
            split: str = 'train',
            is_training: bool = False,
            batch_size: Optional[int] = None,
            seed: int = 42,
            shuffle_size: int = 2048,
            input_img_mode: str = 'RGB',
            input_key: Optional[str] = None,
            target_key: Optional[str] = None,
            dist_rank: int = 0,
            dist_num_replicas: int = 1,
    ):
        self.shards = expand_shard_pattern(root)
        assert self.shards, f'no shards found at {root}'
        self.is_training = is_training
        self.seed = seed
        self.shuffle_size = shuffle_size if is_training else 0
        self.input_img_mode = input_img_mode
        self.input_key = input_key
        self.target_key = target_key
        self.dist_rank = dist_rank
        self.dist_num_replicas = dist_num_replicas
        self.num_workers = 1
        self.worker_id = 0
        self.epoch = -1
        # sample count estimate: read a sidecar _info.json if present
        info_path = os.path.join(os.path.dirname(self.shards[0]), '_info.json')
        self.num_samples = None
        if os.path.exists(info_path):
            try:
                with open(info_path) as f:
                    self.num_samples = int(json.load(f).get('num_samples'))
            except (OSError, ValueError, TypeError) as e:
                _logger.warning(
                    f'Ignoring unreadable shard sidecar {info_path} ({e!r}); '
                    f'the loader length will be unknown — pass --epoch-size')

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def set_worker_info(self, worker_id: int, num_workers: int):
        self.worker_id = worker_id
        self.num_workers = max(1, num_workers)

    def __len__(self):
        if self.num_samples is None:
            raise TypeError('ReaderWds length unknown (no _info.json); use an explicit step count')
        return self.num_samples

    def _iter_shard(self, path):
        """Yield (key, {ext: bytes}) groups from one shard, in tar order.
        Shard open rides the transient-I/O retry policy (network filesystems
        drop tar opens far more often than member reads)."""
        cur_key, cur = None, {}
        with retry_io(lambda: tarfile.open(path), retries=3, base_delay=0.1,
                      retry_on=(OSError, tarfile.ReadError), desc=f'open shard {path}') as tf:
            for m in tf:
                if not m.isfile():
                    continue
                base, ext = os.path.splitext(m.name)
                ext = ext.lower().lstrip('.')
                if cur_key is not None and base != cur_key:
                    yield cur_key, cur
                    cur = {}
                cur_key = base
                cur[ext] = tf.extractfile(m).read()
            if cur_key is not None and cur:
                yield cur_key, cur

    def _decode(self, sample):
        img_data = None
        if self.input_key and self.input_key in sample:
            img_data = sample[self.input_key]
        else:
            for ext in ('jpg', 'jpeg', 'png', 'webp'):
                if ext in sample:
                    img_data = sample[ext]
                    break
        if img_data is None:
            return None
        img = _decode_image(img_data, self.input_img_mode)
        target = -1
        if self.target_key and self.target_key in sample:
            target = int(sample[self.target_key])
        elif 'cls' in sample:
            target = int(sample['cls'].decode())
        elif 'json' in sample:
            meta = json.loads(sample['json'])
            target = int(meta.get('label', meta.get('cls', -1)))
        return img, target

    def __iter__(self):
        global_num_workers = self.dist_num_replicas * self.num_workers
        global_worker_id = self.dist_rank * self.num_workers + self.worker_id
        shards = list(self.shards)
        rng = random.Random(self.seed + max(self.epoch, 0))
        if self.is_training:
            rng.shuffle(shards)  # common seed: all workers agree on the deal
        my_shards = assign_shards(shards, global_worker_id, global_num_workers)
        subshard = len(shards) < global_num_workers and global_num_workers > 1
        if subshard:
            # workers co-assigned to my shard are {w : w % S == gwid % S};
            # stride by that group's size so each sample lands on exactly one
            # worker even when S does not divide the worker count
            S = len(shards)
            group = global_worker_id % S
            stride = len(range(group, global_num_workers, S))
            offset = global_worker_id // S
        else:
            stride, offset = 1, 0

        buf = []
        i = -1
        skip_budget = SkipBudget()
        injector = get_fault_injector()
        for shard in my_shards:
            for key, sample in self._iter_shard(shard):
                i += 1
                if subshard and i % stride != offset:
                    continue
                if injector is not None and injector.io_error_tick():
                    # injected read fault counts against the poison budget so
                    # the skip accounting itself is exercised by drills
                    skip_budget.record(IOError('[fault-inject] sample read'), f'{shard}:{key}')
                    continue
                try:
                    decoded = self._decode(sample)
                except Exception as e:
                    # undecodable member = poison, not transient: skip within
                    # budget instead of killing the epoch (or hiding it)
                    skip_budget.record(e, f'{shard}:{key}')
                    continue
                if decoded is None:
                    continue
                if self.shuffle_size:
                    buf.append(decoded)
                    if len(buf) >= self.shuffle_size:
                        j = rng.randrange(len(buf))
                        yield buf.pop(j)
                else:
                    yield decoded
        while buf:
            j = rng.randrange(len(buf))
            yield buf.pop(j)


class ReaderTfds:
    """tensorflow_datasets wrapper (reference reader_tfds.py:70-340).

    Requires `tensorflow_datasets` (not shipped in this image). Shard
    distribution uses the same `assign_shards` round-robin over
    global workers; fine-grained even splits fall back to sample striding.
    """

    def __init__(self, root, name, split='train', is_training=False, batch_size=None,
                 seed=42, input_img_mode='RGB', dist_rank=0, dist_num_replicas=1, **kwargs):
        try:
            import tensorflow_datasets as tfds  # noqa: F401
        except ImportError as e:
            raise ImportError(
                'ReaderTfds requires tensorflow_datasets, which is not installed in this '
                'environment. Use a wds/ shard set or folder dataset instead.') from e
        import tensorflow_datasets as tfds
        self.builder = tfds.builder(name, data_dir=root or None)
        self.split = split
        self.is_training = is_training
        self.seed = seed
        self.input_img_mode = input_img_mode
        self.dist_rank = dist_rank
        self.dist_num_replicas = dist_num_replicas
        self.num_workers = 1
        self.worker_id = 0
        self.epoch = -1
        self.split_info = self.builder.info.splits[split.split('[')[0]]
        try:
            # sliced splits ('train[:10%]') report their sliced count
            self.num_samples = self.builder.info.splits[split].num_examples
        except (KeyError, ValueError) as e:
            _logger.debug(f'No sliced count for tfds split {split!r} ({e!r}); '
                          f'using the full-split count')
            self.num_samples = self.split_info.num_examples

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def set_worker_info(self, worker_id: int, num_workers: int):
        self.worker_id = worker_id
        self.num_workers = max(1, num_workers)

    def __len__(self):
        return self.num_samples

    def __iter__(self):
        import tensorflow_datasets as tfds
        from PIL import Image
        global_num_workers = self.dist_num_replicas * self.num_workers
        global_worker_id = self.dist_rank * self.num_workers + self.worker_id
        subsplit = None
        input_context = None
        if global_num_workers > 1:
            if self.split_info.num_shards < global_num_workers or not self.is_training:
                subsplit = tfds.even_splits(self.split, global_num_workers)[global_worker_id]
            else:
                import tensorflow as tf
                input_context = tf.distribute.InputContext(
                    num_input_pipelines=global_num_workers,
                    input_pipeline_id=global_worker_id,
                    num_replicas_in_sync=self.dist_num_replicas)
        read_config = tfds.ReadConfig(
            shuffle_seed=self.seed + max(self.epoch, 0),
            shuffle_reshuffle_each_iteration=True,
            input_context=input_context)
        ds = self.builder.as_dataset(
            split=subsplit or self.split,
            shuffle_files=self.is_training,
            read_config=read_config)
        for ex in ds.as_numpy_iterator():
            img = Image.fromarray(ex['image'])
            if self.input_img_mode and img.mode != self.input_img_mode:
                img = img.convert(self.input_img_mode)
            yield img, int(ex.get('label', -1))


class ReaderHfids:
    """Hugging Face streaming (IterableDataset) reader
    (reference readers/reader_hfids.py:29). `name` is a hub dataset or a local
    builder such as 'imagefolder' (with `root` as its data_dir), loaded with
    streaming=True; shards are distributed with .shard() and training epochs
    use the builtin buffered shuffle keyed on (seed, epoch)."""

    def __init__(
            self,
            name: str,
            root: Optional[str] = None,
            split: str = 'train',
            is_training: bool = False,
            seed: int = 42,
            shuffle_size: int = 2048,
            input_key: str = 'image',
            input_img_mode: str = 'RGB',
            target_key: str = 'label',
            dist_rank: int = 0,
            dist_num_replicas: int = 1,
    ):
        import datasets as hfds
        split = {'val': 'validation'}.get(split, split)
        load_kwargs = {}
        if name in ('imagefolder',):
            load_kwargs['data_dir'] = root
        else:
            load_kwargs['cache_dir'] = root or None
        self.ds = hfds.load_dataset(name, split=split, streaming=True, **load_kwargs)
        self.is_training = is_training
        self.seed = seed
        self.shuffle_size = shuffle_size if is_training else 0
        self.input_key = input_key
        self.input_img_mode = input_img_mode
        self.target_key = target_key
        self.dist_rank = dist_rank
        self.dist_num_replicas = dist_num_replicas
        self.num_workers = 1
        self.worker_id = 0
        self.epoch = -1
        self.num_samples = getattr(self.ds.info.splits.get(split), 'num_examples', None) \
            if getattr(self.ds, 'info', None) and self.ds.info.splits else None

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def set_worker_info(self, worker_id: int, num_workers: int):
        self.worker_id = worker_id
        self.num_workers = num_workers

    def __len__(self):
        if self.num_samples is None:
            raise TypeError('streaming hfids dataset length unknown')
        return self.num_samples

    def __iter__(self):
        ds = self.ds
        # shuffle FIRST so the stride-split fallback below still sees a
        # shuffled stream (a raw generator can't be shuffled)
        if self.is_training and self.shuffle_size:
            ds = ds.shuffle(seed=self.seed + max(self.epoch, 0), buffer_size=self.shuffle_size)
        total_shards = self.dist_num_replicas * self.num_workers
        index = self.dist_rank * self.num_workers + self.worker_id
        if total_shards > 1:
            try:
                ds = ds.shard(num_shards=total_shards, index=index)
            except Exception as e:
                # unshardable stream: fall back to stride-based sample split
                _logger.warning(f'hfids stream is not shardable ({e!r}); falling back '
                                f'to stride-{total_shards} sample interleave')
                ds = (s for i, s in enumerate(ds) if i % total_shards == index)
        for item in ds:
            img = item[self.input_key]
            if hasattr(img, 'convert') and self.input_img_mode and img.mode != self.input_img_mode:
                img = img.convert(self.input_img_mode)
            yield img, item[self.target_key]
