"""FSDP-style parameter/optimizer sharding (ZeRO over the mesh 'fsdp' axis).

The reference framework replicates every parameter and optimizer slot on
every chip (DDP); model size is then capped by one chip's HBM and AdamW pays
full replicated m/v traffic (PERF.md §2 item 3). Here the 1-axis data mesh
grows an optional second axis, ``('data', 'fsdp')``:

  * the BATCH is sharded over the product of both axes (every device computes
    different samples — plain data parallelism from the loss's view);
  * large matmul WEIGHTS are sharded over 'fsdp' along one dimension, small
    params (biases, norm scales, cls/pos embeddings) stay replicated;
  * OPTIMIZER state inherits each param's spec leaf-for-leaf (ZeRO-1/2:
    m/v shards live only on the devices that own the param shard).

Everything is expressed as `NamedSharding` annotations consumed by GSPMD
(Xu et al.): XLA inserts the all-gathers before use and reduce-scatters after
the backward pass; no hand-written collectives. The partition decision is a
small ordered list of REGEX RULES over the '.'-joined param path — the t5x /
big_vision logical-axis-rules idiom — so models can override placement
without touching module code.

Specs are shape-validated: a rule only shards a dimension when the dim is
divisible by the fsdp axis size; otherwise the param is replicated (logged
once per path). This keeps every model loadable on any mesh shape.
"""
from __future__ import annotations

import logging
import re
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_logger = logging.getLogger(__name__)

__all__ = [
    'PartitionRule', 'default_partition_rules', 'match_rule',
    'spec_for_param', 'build_param_shardings', 'path_specs',
    'inherit_param_specs', 'build_opt_shardings',
    'shard_pytree', 'abstract_init_sharded', 'create_sharded_model',
    'replicated_like', 'fsdp_size', 'param_bytes_per_device',
]

# Sharding a tiny tensor buys no memory and costs collective latency; params
# below this element count are replicated even when a shard rule matches.
MIN_SHARD_SIZE = 1024


@dataclass(frozen=True)
class PartitionRule:
    """One ordered partition rule: `pattern` is re.search'ed against the
    '.'-joined param path; first match wins.

    `action` is either 'fsdp_largest' (shard the largest dimension divisible
    by the fsdp axis size), 'replicate', or an explicit PartitionSpec-like
    tuple (validated against the leaf's rank/divisibility at apply time).
    """
    pattern: str
    action: Any = 'fsdp_largest'
    name: str = ''

    def matches(self, path: str) -> bool:
        return re.search(self.pattern, path) is not None


def default_partition_rules() -> Tuple[PartitionRule, ...]:
    """FSDP rules for the timm_tpu model families. Ordered, first-match-wins,
    mutually exclusive on every ViT param path (tests assert exactly one rule
    matches each param):

      1. 2D+ matmul / conv kernels        -> shard largest divisible dim
      2. biases                           -> replicate
      3. norm scales / LayerScale gammas  -> replicate
      4. tokens & position embeddings     -> replicate
      5. everything else                  -> replicate (catch-all)
    """
    return (
        PartitionRule(r'\.kernel$', 'fsdp_largest', name='kernel'),
        PartitionRule(r'\.bias$', 'replicate', name='bias'),
        PartitionRule(r'(^|\.)(scale|weight|gamma|gamma_1|gamma_2|lambda_q1|lambda_q2|lambda_k1|lambda_k2)$',
                      'replicate', name='norm-scale'),
        PartitionRule(r'(^|\.)(cls_token|reg_token|dist_token|pos_embed|pos_embed_win|relative_position_bias_table|'
                      r'embedding|latent|probe|mask_token)($|\.)', 'replicate', name='token-embed'),
        PartitionRule(r'.*', 'replicate', name='catch-all'),
    )


def fsdp_size(mesh: Mesh) -> int:
    """Size of the 'fsdp' axis, or 1 when the mesh has none."""
    return int(mesh.shape['fsdp']) if 'fsdp' in mesh.axis_names else 1


def match_rule(path: str, rules: Optional[Sequence[PartitionRule]] = None) -> Tuple[int, PartitionRule]:
    """First-match-wins rule lookup; returns (index, rule). The default rule
    set ends with a catch-all so this always resolves."""
    rules = rules if rules is not None else default_partition_rules()
    for i, rule in enumerate(rules):
        if rule.matches(path):
            return i, rule
    raise ValueError(f'No partition rule matched param path {path!r} '
                     f'(rule sets should end with a catch-all)')


def spec_for_param(
        path: str,
        shape: Sequence[int],
        mesh: Mesh,
        rules: Optional[Sequence[PartitionRule]] = None,
        min_shard_size: int = MIN_SHARD_SIZE,
) -> P:
    """Resolve one param's PartitionSpec from the rule table + its shape.

    Shape validation is part of the contract: when the matched rule wants to
    shard but no dimension is divisible by the fsdp axis size (or the param is
    tiny), the param falls back to replicated so any checkpoint loads on any
    mesh shape.
    """
    n_shard = fsdp_size(mesh)
    if n_shard <= 1:
        return P()
    _, rule = match_rule(path, rules)
    action = rule.action
    if action == 'replicate':
        return P()
    size = int(np.prod(shape)) if len(shape) else 1
    if action == 'fsdp_largest':
        if len(shape) < 2 or size < min_shard_size:
            return P()
        # largest divisible dim → most even memory split; ties break to the
        # RIGHTMOST such dim (output features; matches megatron convention)
        best = None
        for i, d in enumerate(shape):
            if d % n_shard == 0 and (best is None or d >= shape[best]):
                best = i
        if best is None:
            _logger.debug(f'fsdp: no dim of {path} {tuple(shape)} divisible by {n_shard}; replicating')
            return P()
        spec = [None] * len(shape)
        spec[best] = 'fsdp'
        return P(*spec)
    # explicit spec tuple: validate rank + divisibility, else replicate loudly
    spec = tuple(action)
    if len(spec) != len(shape):
        _logger.warning(f'fsdp rule {rule.name or rule.pattern!r} spec {spec} does not match '
                        f'rank of {path} {tuple(shape)}; replicating')
        return P()
    for axis_name, d in zip(spec, shape):
        if axis_name is not None and d % int(mesh.shape[axis_name]) != 0:
            _logger.warning(f'fsdp rule {rule.name or rule.pattern!r}: dim {d} of {path} not '
                            f'divisible by mesh axis {axis_name!r}; replicating')
            return P()
    return P(*spec)


def _kp_str(kp) -> str:
    parts = []
    for p in kp:
        for attr in ('key', 'idx', 'name'):
            if hasattr(p, attr):
                v = str(getattr(p, attr))
                if v != 'value':  # drop the nnx Variable '.value' hop
                    parts.append(v)
                break
        else:
            parts.append(str(p))
    return '.'.join(parts)


def path_specs(
        tree,
        mesh: Mesh,
        rules: Optional[Sequence[PartitionRule]] = None,
        min_shard_size: int = MIN_SHARD_SIZE,
) -> Dict[str, P]:
    """{'.'-joined path: PartitionSpec} for every array leaf of `tree`
    (arrays or ShapeDtypeStructs both work)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {
        _kp_str(kp): spec_for_param(_kp_str(kp), getattr(leaf, 'shape', ()), mesh, rules, min_shard_size)
        for kp, leaf in flat
    }


def build_param_shardings(
        tree,
        mesh: Mesh,
        rules: Optional[Sequence[PartitionRule]] = None,
        min_shard_size: int = MIN_SHARD_SIZE,
):
    """Tree of NamedShardings with `tree`'s structure (model param pytree →
    its placement). With no 'fsdp' axis every leaf is replicated, so the
    single-axis data mesh behaves exactly as before."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    shardings = [
        NamedSharding(mesh, spec_for_param(_kp_str(kp), getattr(leaf, 'shape', ()), mesh, rules, min_shard_size))
        for kp, leaf in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, shardings)


def replicated_like(tree, mesh: Mesh):
    """Tree of fully-replicated NamedShardings with `tree`'s structure."""
    rep = NamedSharding(mesh, P())
    return jax.tree.map(lambda _: rep, tree)


def inherit_param_specs(
        state_tree,
        param_path_specs: Dict[str, P],
        mesh: Mesh,
):
    """Optimizer-state shardings: each leaf whose path ENDS WITH a param path
    (optax nests the param pytree under mu/nu/trace/... so the param path is
    a suffix, e.g. `0.mu.blocks.0.attn.qkv.kernel`) inherits that param's
    spec when the shapes agree; every other leaf (step counts, injected
    hyperparams, factored-statistics vectors) is replicated.

    This is what makes buffer DONATION legal: XLA aliases a donated input to
    an output only when their shardings match, so m/v must live exactly where
    their param lives.
    """
    # longest param path first so `fc.kernel` can't shadow `blocks.0.fc.kernel`
    by_len = sorted(param_path_specs.items(), key=lambda kv: -len(kv[0]))
    flat, treedef = jax.tree_util.tree_flatten_with_path(state_tree)
    out = []
    for kp, leaf in flat:
        path = _kp_str(kp)
        spec = P()
        for ppath, pspec in by_len:
            if path == ppath or path.endswith('.' + ppath):
                spec = pspec
                break
        # shape guard: bf16-reduced m keeps the param's shape, but factored
        # or scalar slots (adafactor row/col stats, counts) must not inherit
        # a spec of the wrong rank
        shape = getattr(leaf, 'shape', ())
        if len(spec) > len(shape) or any(
                ax is not None and shape[i] % int(mesh.shape[ax]) != 0
                for i, ax in enumerate(spec) if i < len(shape)):
            spec = P()
        out.append(NamedSharding(mesh, spec))
    return jax.tree_util.tree_unflatten(treedef, out)


def build_opt_shardings(optimizer, params, mesh: Mesh,
                        rules: Optional[Sequence[PartitionRule]] = None):
    """Shardings for `optimizer.init(params)`'s state without materializing
    it: `jax.eval_shape` gives the abstract state tree, then every m/v leaf
    inherits its param's spec."""
    abstract = jax.eval_shape(optimizer.init, params)
    return inherit_param_specs(abstract, path_specs(params, mesh, rules), mesh), abstract


def shard_pytree(tree, shardings):
    """device_put a pytree according to a matching tree of NamedShardings."""
    return jax.device_put(tree, shardings)


def abstract_init_sharded(init_fn: Callable, shardings_fn: Callable, *args):
    """Create state directly on-mesh without a replicated host copy:
    `jax.eval_shape(init_fn, *args)` determines the output structure,
    `shardings_fn(abstract_out)` assigns a NamedSharding per leaf, and the
    jitted init materializes each shard on its owning devices only.

    This is the PERF.md §2 item 3 memory story for optimizer state: AdamW m/v
    for ViT-L is ~2.4 GB fp32 replicated; created through here on an fsdp=4
    axis each device ever holds ~0.6 GB.
    """
    abstract = jax.eval_shape(init_fn, *args)
    shardings = shardings_fn(abstract)
    try:
        return jax.jit(init_fn, out_shardings=shardings)(*args), shardings
    except Exception as e:  # pragma: no cover - exotic non-traceable init
        _logger.warning(f'abstract sharded init failed ({e!r}); falling back to '
                        'eager init + device_put (a transient replicated copy exists)')
        return jax.device_put(init_fn(*args), shardings), shardings


def create_sharded_model(
        factory: Callable[[], Any],
        mesh: Mesh,
        rules: Optional[Sequence[PartitionRule]] = None,
        min_shard_size: int = MIN_SHARD_SIZE,
):
    """Build an nnx model with its params created DIRECTLY on-mesh.

    `nnx.eval_shape(factory)` runs the constructor abstractly (no arrays are
    materialized), the partition rules are resolved against the abstract
    param shapes, and a jitted `factory()` with `out_shardings` initializes
    each param shard on its owning devices — a replicated host copy of the
    full model never exists. Falls back to eager construction + device_put
    for factories that do not trace (e.g. pretrained-weight loading inside
    the constructor), which preserves behaviour at a transient memory cost.
    """
    from flax import nnx

    try:
        abs_model = nnx.eval_shape(factory)
        graphdef, abs_state = nnx.split(abs_model)
        flat, treedef = jax.tree_util.tree_flatten_with_path(abs_state)
        shardings = jax.tree_util.tree_unflatten(treedef, [
            NamedSharding(mesh, spec_for_param(_kp_str(kp), getattr(leaf, 'shape', ()), mesh, rules, min_shard_size))
            for kp, leaf in flat
        ])

        def init_state():
            return nnx.state(factory())

        state = jax.jit(init_state, out_shardings=shardings)()
        return nnx.merge(graphdef, state)
    except Exception as e:
        _logger.warning(f'create_sharded_model: abstract init failed ({e!r}); '
                        'building eagerly and resharding')
        model = factory()
        graphdef, state = nnx.split(model)
        flat, treedef = jax.tree_util.tree_flatten_with_path(state)
        shardings = jax.tree_util.tree_unflatten(treedef, [
            NamedSharding(mesh, spec_for_param(_kp_str(kp), getattr(leaf, 'shape', ()), mesh, rules, min_shard_size))
            for kp, leaf in flat
        ])
        nnx.update(model, jax.device_put(state, shardings))
        return model


def param_bytes_per_device(tree, mesh: Mesh,
                           rules: Optional[Sequence[PartitionRule]] = None) -> Tuple[int, int]:
    """(replicated_bytes, fsdp_sharded_bytes) a single device would hold for
    `tree` under the rule set — the PERF.md 'Sharding & memory' numbers."""
    n = fsdp_size(mesh)
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    rep = shard = 0
    for kp, leaf in flat:
        nbytes = int(np.prod(getattr(leaf, 'shape', ()) or (1,))) * np.dtype(leaf.dtype).itemsize
        rep += nbytes
        spec = spec_for_param(_kp_str(kp), getattr(leaf, 'shape', ()), mesh, rules)
        shard += nbytes // n if any(ax is not None for ax in spec) else nbytes
    return rep, shard
