"""FSDP-style parameter/optimizer sharding (ZeRO over the mesh 'fsdp' axis).

The reference framework replicates every parameter and optimizer slot on
every chip (DDP); model size is then capped by one chip's HBM and AdamW pays
full replicated m/v traffic (PERF.md §2 item 3). Here the 1-axis data mesh
grows an optional second axis, ``('data', 'fsdp')``:

  * the BATCH is sharded over the product of both axes (every device computes
    different samples — plain data parallelism from the loss's view);
  * large matmul WEIGHTS are sharded over 'fsdp' along one dimension, small
    params (biases, norm scales, cls/pos embeddings) stay replicated;
  * OPTIMIZER state inherits each param's spec leaf-for-leaf (ZeRO-1/2:
    m/v shards live only on the devices that own the param shard).

Everything is expressed as `NamedSharding` annotations consumed by GSPMD
(Xu et al.): XLA inserts the all-gathers before use and reduce-scatters after
the backward pass; no hand-written collectives. The partition decision is a
small ordered list of REGEX RULES over the '.'-joined param path — the t5x /
big_vision logical-axis-rules idiom — so models can override placement
without touching module code.

Specs are shape-validated: a rule only shards a dimension when the dim is
divisible by the fsdp axis size; otherwise the param is replicated (logged
once per path). This keeps every model loadable on any mesh shape.
"""
from __future__ import annotations

import logging
import re
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_logger = logging.getLogger(__name__)

__all__ = [
    'PartitionRule', 'default_partition_rules', 'match_rule',
    'spec_for_param', 'build_param_shardings', 'path_specs',
    'inherit_param_specs', 'build_opt_shardings',
    'quant_scale_spec', 'quant_path_specs', 'build_quant_shardings',
    'shard_pytree', 'abstract_init_sharded', 'create_sharded_model',
    'replicated_like', 'fsdp_size', 'tp_size', 'param_bytes_per_device',
    'activation_bytes_per_device',
]

# Sharding a tiny tensor buys no memory and costs collective latency; params
# below this element count are replicated even when a shard rule matches.
MIN_SHARD_SIZE = 1024


@dataclass(frozen=True)
class PartitionRule:
    """One ordered partition rule: `pattern` is re.search'ed against the
    '.'-joined param path; first match wins.

    `action` is one of 'fsdp_largest' (shard the largest dimension divisible
    by the fsdp axis size), 'megatron_col' / 'megatron_row' (tensor
    parallelism: shard the output / input feature dim over 'model', stacking
    'fsdp' on another dim when both axes exist; with no 'model' axis these
    delegate to 'fsdp_largest' so tp=1 placement is bit-identical to the
    2-axis mesh), 'replicate', or an explicit PartitionSpec-like tuple
    (validated against the leaf's rank/divisibility at apply time).
    """
    pattern: str
    action: Any = 'fsdp_largest'
    name: str = ''

    def matches(self, path: str) -> bool:
        return re.search(self.pattern, path) is not None


# Tensor-parallel kernel paths (Megatron split): column-parallel layers write
# the dimension that gets CONSUMED shard-local downstream (attention heads for
# qkv/q/k/v, MLP hidden for fc1*), row-parallel layers read it back and XLA
# emits one reduce per pair (attn.proj, mlp.fc2). The generic kernel rule
# excludes all four via lookahead so the rule table stays DISJOINT — the
# exactly-one-rule test is what keeps placement auditable.
#
# Hierarchical families route through the same four rules: metaformer wraps
# attention as `token_mixer`, pvt_v2 splits q from kv, and a 1x1 projection
# conv (NHWC Linear) matches the same suffixes — its kernel is rank 2, so the
# megatron specs apply unchanged. Convnext's NHWC MLP fc1/fc2 Linears already
# match the mlp rules.
_TP_ATTN_QKV = r'\.(?:attn|token_mixer)\.(?:qkv|q_proj|k_proj|v_proj|q|kv)\.kernel$'
_TP_ATTN_OUT = r'\.(?:attn|token_mixer)\.proj\.kernel$'
_TP_MLP_IN = r'\.mlp\.(?:fc1|fc1_g|fc1_x)\.kernel$'
_TP_MLP_OUT = r'\.mlp\.fc2\.kernel$'
_TP_KERNEL_PATTERNS = (_TP_ATTN_QKV, _TP_ATTN_OUT, _TP_MLP_IN, _TP_MLP_OUT)
_GENERIC_KERNEL = r'^(?!.*(?:' + '|'.join(_TP_KERNEL_PATTERNS) + r')).*\.kernel$'


def default_partition_rules() -> Tuple[PartitionRule, ...]:
    """FSDP + tensor-parallel rules for the timm_tpu model families. Ordered,
    first-match-wins, mutually exclusive on every ViT param path (tests assert
    exactly one rule matches each param):

      1. attention qkv / q,k,v kernels    -> heads over 'model' (column)
      2. attention output proj kernels    -> input dim over 'model' (row)
      3. MLP fc1 (incl. glu gates)        -> hidden over 'model' (column)
      4. MLP fc2                          -> hidden over 'model' (row)
      5. other 2D+ matmul / conv kernels  -> shard largest divisible dim
      6. biases                           -> replicate
      7. norm scales / LayerScale gammas  -> replicate
      8. tokens & position embeddings     -> replicate
      9. everything else                  -> replicate (catch-all)

    Rules 1-4 fall back to 'fsdp_largest' placement when the mesh has no
    'model' axis, so tp=1 reproduces the 2-axis table exactly.
    """
    return (
        PartitionRule(_TP_ATTN_QKV, 'megatron_col', name='attn-qkv'),
        PartitionRule(_TP_ATTN_OUT, 'megatron_row', name='attn-out'),
        PartitionRule(_TP_MLP_IN, 'megatron_col', name='mlp-fc1'),
        PartitionRule(_TP_MLP_OUT, 'megatron_row', name='mlp-fc2'),
        PartitionRule(_GENERIC_KERNEL, 'fsdp_largest', name='kernel'),
        # `_bias(es)` covers the decomposed-qkv q/v biases (beit/eva/swinv2)
        # and the levit/efficientformer/tinyvit attention-bias tables
        PartitionRule(r'(\.|_)bias(es)?$', 'replicate', name='bias'),
        PartitionRule(r'(^|\.)(scale|weight|gamma|gamma_1|gamma_2|gamma1|gamma2|gamma3|gamma_xca|'
                      r'lambda_q1|lambda_q2|lambda_k1|lambda_k2|logit_scale|temperature|gain)$',
                      'replicate', name='norm-scale'),
        # the leading lookahead keeps this DISJOINT from the kernel/bias
        # rules when a module is itself named pos_embed/... (xcit's conv
        # positional encoding nests real kernels under `pos_embed.`)
        PartitionRule(r'^(?!.*\.(?:kernel|bias)$)(?:.*\.)?'
                      r'(?:cls_token|reg_token|dist_token|pos_embed|pos_embed_win|pos_embed_x|pos_embed_y|'
                      r'relative_position_bias_table|rel_pos_w|rel_pos_h|embedding|latent|probe|mask_token)($|\.)',
                      'replicate', name='token-embed'),
        PartitionRule(r'.*', 'replicate', name='catch-all'),
    )


def fsdp_size(mesh: Mesh) -> int:
    """Size of the 'fsdp' axis, or 1 when the mesh has none."""
    return int(mesh.shape['fsdp']) if 'fsdp' in mesh.axis_names else 1


def tp_size(mesh: Mesh) -> int:
    """Size of the 'model' (tensor-parallel) axis, or 1 when the mesh has none."""
    return int(mesh.shape['model']) if 'model' in mesh.axis_names else 1


def match_rule(path: str, rules: Optional[Sequence[PartitionRule]] = None) -> Tuple[int, PartitionRule]:
    """First-match-wins rule lookup; returns (index, rule). The default rule
    set ends with a catch-all so this always resolves."""
    rules = rules if rules is not None else default_partition_rules()
    for i, rule in enumerate(rules):
        if rule.matches(path):
            return i, rule
    raise ValueError(f'No partition rule matched param path {path!r} '
                     f'(rule sets should end with a catch-all)')


_WARNED_PATHS = set()


def _warn_once(path: str, msg: str):
    """Log a WARNING the first time a given param path degrades — loud enough
    to audit (tests assert on it), quiet enough not to spam every step."""
    if path not in _WARNED_PATHS:
        _WARNED_PATHS.add(path)
        _logger.warning(msg)


def _fsdp_largest_spec(path: str, shape: Sequence[int], mesh: Mesh,
                       min_shard_size: int) -> P:
    """'fsdp_largest' action: shard the largest fsdp-divisible dim.

    Conv kernels (rank >= 3, nnx layout ``(*window, in // groups, out)``)
    always shard the OUTPUT-CHANNEL dim instead of the largest one: the
    spatial window dims are tiny and never divisible, and sharding the input
    dim would force an all-gather of the kernel before the contraction while
    the out dim reduce-scatters for free with the NHWC activation layout.
    Depthwise kernels (in // groups == 1) replicate — their whole weight is
    smaller than one dense row and GSPMD handles grouped convs poorly when
    the group dim is split.
    """
    n_shard = fsdp_size(mesh)
    size = int(np.prod(shape)) if len(shape) else 1
    if n_shard <= 1 or len(shape) < 2 or size < min_shard_size:
        return P()
    if len(shape) >= 3:
        if shape[-2] == 1 or shape[-1] % n_shard != 0:
            _logger.debug(f'fsdp: conv kernel {path} {tuple(shape)} depthwise or out dim '
                          f'not divisible by {n_shard}; replicating')
            return P()
        spec = [None] * len(shape)
        spec[-1] = 'fsdp'
        return P(*spec)
    # largest divisible dim → most even memory split; ties break to the
    # RIGHTMOST such dim (output features; matches megatron convention)
    best = None
    for i, d in enumerate(shape):
        if d % n_shard == 0 and (best is None or d >= shape[best]):
            best = i
    if best is None:
        _logger.debug(f'fsdp: no dim of {path} {tuple(shape)} divisible by {n_shard}; replicating')
        return P()
    spec = [None] * len(shape)
    spec[best] = 'fsdp'
    return P(*spec)


def _megatron_spec(path: str, shape: Sequence[int], mesh: Mesh, rule_name: str,
                   col: bool, min_shard_size: int) -> P:
    """'megatron_col'/'megatron_row' actions: tensor-parallel kernel split.

    Column-parallel shards the LAST dim (output features — stacked heads for
    qkv, MLP hidden for fc1) over 'model'; row-parallel shards the FIRST dim
    (input features). When the mesh also has an fsdp axis the largest
    remaining divisible dim picks up 'fsdp' too (2-D sharded weights,
    MaxText-style), which is what the optimizer m/v inherit so donation
    aliasing stays legal. Without a 'model' axis this IS 'fsdp_largest' —
    tp=1 placement is bit-identical to the 2-axis mesh. A head/hidden dim
    not divisible by the tp size replicates with a logged warning (never
    silently): the checkpoint still loads, placement is just degraded.

    Conv kernels (rank >= 3): column stays the last dim (out channels), row
    becomes dim -2 — the input-channel dim of the nnx ``(*window, in, out)``
    layout — so a 1x1 projection conv gets exactly the Linear placement.
    """
    n_tp = tp_size(mesh)
    if n_tp <= 1:
        return _fsdp_largest_spec(path, shape, mesh, min_shard_size)
    size = int(np.prod(shape)) if len(shape) else 1
    if len(shape) < 2 or size < min_shard_size:
        return P()
    if col:
        model_dim = len(shape) - 1
    else:
        model_dim = len(shape) - 2 if len(shape) >= 3 else 0
    if shape[model_dim] % n_tp != 0:
        _warn_once(path, (
            f"tp rule {rule_name!r}: {'output' if col else 'input'} dim "
            f'{shape[model_dim]} of {path} {tuple(shape)} is not divisible by '
            f"the 'model' axis size {n_tp}; replicating this param"))
        return P()
    spec = [None] * len(shape)
    spec[model_dim] = 'model'
    n_fsdp = fsdp_size(mesh)
    if n_fsdp > 1:
        best = None
        for i, d in enumerate(shape):
            if i != model_dim and d % n_fsdp == 0 and (best is None or d >= shape[best]):
                best = i
        if best is not None:
            spec[best] = 'fsdp'
    return P(*spec)


def spec_for_param(
        path: str,
        shape: Sequence[int],
        mesh: Mesh,
        rules: Optional[Sequence[PartitionRule]] = None,
        min_shard_size: int = MIN_SHARD_SIZE,
) -> P:
    """Resolve one param's PartitionSpec from the rule table + its shape.

    Shape validation is part of the contract: when the matched rule wants to
    shard but no dimension is divisible by the owning axis size (or the param
    is tiny), the param falls back to replicated so any checkpoint loads on
    any mesh shape.
    """
    if fsdp_size(mesh) <= 1 and tp_size(mesh) <= 1:
        return P()
    _, rule = match_rule(path, rules)
    action = rule.action
    if action == 'replicate':
        return P()
    if action == 'fsdp_largest':
        return _fsdp_largest_spec(path, shape, mesh, min_shard_size)
    if action in ('megatron_col', 'megatron_row'):
        return _megatron_spec(path, shape, mesh, rule.name or rule.pattern,
                              action == 'megatron_col', min_shard_size)
    # explicit spec tuple: validate rank + divisibility, else replicate loudly
    spec = tuple(action)
    if len(spec) != len(shape):
        _logger.warning(f'fsdp rule {rule.name or rule.pattern!r} spec {spec} does not match '
                        f'rank of {path} {tuple(shape)}; replicating')
        return P()
    for axis_name, d in zip(spec, shape):
        if axis_name is not None and d % int(mesh.shape[axis_name]) != 0:
            _logger.warning(f'fsdp rule {rule.name or rule.pattern!r}: dim {d} of {path} not '
                            f'divisible by mesh axis {axis_name!r}; replicating')
            return P()
    return P(*spec)


def _kp_str(kp) -> str:
    parts = []
    for p in kp:
        for attr in ('key', 'idx', 'name'):
            if hasattr(p, attr):
                v = str(getattr(p, attr))
                if v != 'value':  # drop the nnx Variable '.value' hop
                    parts.append(v)
                break
        else:
            parts.append(str(p))
    return '.'.join(parts)


def path_specs(
        tree,
        mesh: Mesh,
        rules: Optional[Sequence[PartitionRule]] = None,
        min_shard_size: int = MIN_SHARD_SIZE,
) -> Dict[str, P]:
    """{'.'-joined path: PartitionSpec} for every array leaf of `tree`
    (arrays or ShapeDtypeStructs both work)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {
        _kp_str(kp): spec_for_param(_kp_str(kp), getattr(leaf, 'shape', ()), mesh, rules, min_shard_size)
        for kp, leaf in flat
    }


def build_param_shardings(
        tree,
        mesh: Mesh,
        rules: Optional[Sequence[PartitionRule]] = None,
        min_shard_size: int = MIN_SHARD_SIZE,
):
    """Tree of NamedShardings with `tree`'s structure (model param pytree →
    its placement). With no 'fsdp' axis every leaf is replicated, so the
    single-axis data mesh behaves exactly as before."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    shardings = [
        NamedSharding(mesh, spec_for_param(_kp_str(kp), getattr(leaf, 'shape', ()), mesh, rules, min_shard_size))
        for kp, leaf in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, shardings)


def replicated_like(tree, mesh: Mesh):
    """Tree of fully-replicated NamedShardings with `tree`'s structure."""
    rep = NamedSharding(mesh, P())
    return jax.tree.map(lambda _: rep, tree)


def inherit_param_specs(
        state_tree,
        param_path_specs: Dict[str, P],
        mesh: Mesh,
):
    """Optimizer-state shardings: each leaf whose path ENDS WITH a param path
    (optax nests the param pytree under mu/nu/trace/... so the param path is
    a suffix, e.g. `0.mu.blocks.0.attn.qkv.kernel`) inherits that param's
    spec when the shapes agree; every other leaf (step counts, injected
    hyperparams, factored-statistics vectors) is replicated.

    This is what makes buffer DONATION legal: XLA aliases a donated input to
    an output only when their shardings match, so m/v must live exactly where
    their param lives.
    """
    # longest param path first so `fc.kernel` can't shadow `blocks.0.fc.kernel`
    by_len = sorted(param_path_specs.items(), key=lambda kv: -len(kv[0]))
    flat, treedef = jax.tree_util.tree_flatten_with_path(state_tree)
    out = []
    for kp, leaf in flat:
        path = _kp_str(kp)
        spec = P()
        for ppath, pspec in by_len:
            if path == ppath or path.endswith('.' + ppath):
                spec = pspec
                break
        # shape guard: bf16-reduced m keeps the param's shape, but factored
        # or scalar slots (adafactor row/col stats, counts) must not inherit
        # a spec of the wrong rank
        shape = getattr(leaf, 'shape', ())
        if len(spec) > len(shape) or any(
                ax is not None and shape[i] % int(mesh.shape[ax]) != 0
                for i, ax in enumerate(spec) if i < len(shape)):
            spec = P()
        out.append(NamedSharding(mesh, spec))
    return jax.tree_util.tree_unflatten(treedef, out)


def quant_scale_spec(kernel_spec: P, scale_shape: Sequence[int], mesh: Mesh) -> P:
    """Spec for a per-output-channel scale vector: it shards with the LAST
    axis of its kernel's spec (the output-channel dim it indexes), so a
    tensor-parallel column kernel keeps its dequant ``q * scale`` entirely
    shard-local — no collectives enter the serve program. Any mismatch
    (kernel replicated, scale not divisible) falls back to replicated, which
    is always legal for a vector this small."""
    if not kernel_spec or len(kernel_spec) == 0:
        return P()
    last = kernel_spec[-1]
    if last is None or not scale_shape:
        return P()
    axes = last if isinstance(last, tuple) else (last,)
    size = 1
    for ax in axes:
        size *= int(mesh.shape[ax])
    if int(scale_shape[0]) % size != 0:
        return P()
    return P(last)


def quant_path_specs(
        qstate,
        mesh: Mesh,
        rules: Optional[Sequence[PartitionRule]] = None,
        min_shard_size: int = MIN_SHARD_SIZE,
) -> Dict[str, P]:
    """{path: spec} for a quantized ``{'qvalues', 'scales'}`` pytree.

    The int8 qvalue leaves resolve through the SAME rule table as their
    dense originals (their stripped paths are identical, and the rules are
    shape-based, not dtype-based), so fsdp/tp placement is unchanged by
    quantization. Scales inherit by path exactly like m/v/EMA inherit from
    params — see ``quant_scale_spec``.
    """
    from ..quantize.int8 import QUANT_QVALUES, QUANT_SCALES
    qvalues, scales = qstate[QUANT_QVALUES], qstate[QUANT_SCALES]
    flat, _ = jax.tree_util.tree_flatten_with_path(qvalues)
    specs: Dict[str, P] = {}
    kernel_specs: Dict[str, P] = {}
    for kp, leaf in flat:
        path = _kp_str(kp)
        spec = spec_for_param(path, getattr(leaf, 'shape', ()), mesh, rules, min_shard_size)
        specs[f'{QUANT_QVALUES}.{path}'] = spec
        kernel_specs[path] = spec
    for path, scale in scales.items():
        specs[f'{QUANT_SCALES}.{path}'] = quant_scale_spec(
            kernel_specs.get(path, P()), getattr(scale, 'shape', ()), mesh)
    return specs


def build_quant_shardings(
        qstate,
        mesh: Mesh,
        rules: Optional[Sequence[PartitionRule]] = None,
        min_shard_size: int = MIN_SHARD_SIZE,
):
    """NamedSharding tree with the quantized pytree's structure (the quant
    analogue of ``build_param_shardings``)."""
    specs = quant_path_specs(qstate, mesh, rules, min_shard_size)
    flat, treedef = jax.tree_util.tree_flatten_with_path(qstate)
    shardings = [NamedSharding(mesh, specs[_kp_str(kp)]) for kp, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, shardings)


def build_opt_shardings(optimizer, params, mesh: Mesh,
                        rules: Optional[Sequence[PartitionRule]] = None):
    """Shardings for `optimizer.init(params)`'s state without materializing
    it: `jax.eval_shape` gives the abstract state tree, then every m/v leaf
    inherits its param's spec."""
    abstract = jax.eval_shape(optimizer.init, params)
    return inherit_param_specs(abstract, path_specs(params, mesh, rules), mesh), abstract


def shard_pytree(tree, shardings):
    """device_put a pytree according to a matching tree of NamedShardings."""
    return jax.device_put(tree, shardings)


def abstract_init_sharded(init_fn: Callable, shardings_fn: Callable, *args):
    """Create state directly on-mesh without a replicated host copy:
    `jax.eval_shape(init_fn, *args)` determines the output structure,
    `shardings_fn(abstract_out)` assigns a NamedSharding per leaf, and the
    jitted init materializes each shard on its owning devices only.

    This is the PERF.md §2 item 3 memory story for optimizer state: AdamW m/v
    for ViT-L is ~2.4 GB fp32 replicated; created through here on an fsdp=4
    axis each device ever holds ~0.6 GB.
    """
    abstract = jax.eval_shape(init_fn, *args)
    shardings = shardings_fn(abstract)
    try:
        return jax.jit(init_fn, out_shardings=shardings)(*args), shardings
    except Exception as e:  # pragma: no cover - exotic non-traceable init
        _logger.warning(f'abstract sharded init failed ({e!r}); falling back to '
                        'eager init + device_put (a transient replicated copy exists)')
        return jax.device_put(init_fn(*args), shardings), shardings


def create_sharded_model(
        factory: Callable[[], Any],
        mesh: Mesh,
        rules: Optional[Sequence[PartitionRule]] = None,
        min_shard_size: int = MIN_SHARD_SIZE,
):
    """Build an nnx model with its params created DIRECTLY on-mesh.

    `nnx.eval_shape(factory)` runs the constructor abstractly (no arrays are
    materialized), the partition rules are resolved against the abstract
    param shapes, and a jitted `factory()` with `out_shardings` initializes
    each param shard on its owning devices — a replicated host copy of the
    full model never exists. Falls back to eager construction + device_put
    for factories that do not trace (e.g. pretrained-weight loading inside
    the constructor), which preserves behaviour at a transient memory cost.
    """
    from flax import nnx

    try:
        abs_model = nnx.eval_shape(factory)
        graphdef, abs_state = nnx.split(abs_model)
        flat, treedef = jax.tree_util.tree_flatten_with_path(abs_state)
        shardings = jax.tree_util.tree_unflatten(treedef, [
            NamedSharding(mesh, spec_for_param(_kp_str(kp), getattr(leaf, 'shape', ()), mesh, rules, min_shard_size))
            for kp, leaf in flat
        ])

        def init_state():
            return nnx.state(factory())

        state = jax.jit(init_state, out_shardings=shardings)()
        return nnx.merge(graphdef, state)
    except Exception as e:
        _logger.warning(f'create_sharded_model: abstract init failed ({e!r}); '
                        'building eagerly and resharding')
        model = factory()
        graphdef, state = nnx.split(model)
        flat, treedef = jax.tree_util.tree_flatten_with_path(state)
        shardings = jax.tree_util.tree_unflatten(treedef, [
            NamedSharding(mesh, spec_for_param(_kp_str(kp), getattr(leaf, 'shape', ()), mesh, rules, min_shard_size))
            for kp, leaf in flat
        ])
        nnx.update(model, jax.device_put(state, shardings))
        return model


def _spec_shard_count(spec: P, mesh: Mesh) -> int:
    """How many ways a spec splits a tensor: the product of the mesh sizes of
    every named axis in it (a 2-D ('fsdp','model') spec divides bytes by
    fsdp_size * tp_size, not fsdp_size alone)."""
    n = 1
    for ax in spec:
        if ax is None:
            continue
        for a in (ax if isinstance(ax, tuple) else (ax,)):
            n *= int(mesh.shape[a])
    return n


def leaf_itemsize(dtype) -> int:
    """Physical bytes per element, tolerant of extended dtypes: typed PRNG
    key leaves (``key<fry>`` — swin-style blocks keep their DropPath/attn
    Rngs in state) have no numpy dtype; count their uint32 key data
    (threefry = 2 words) instead of crashing the byte accounting."""
    try:
        return np.dtype(dtype).itemsize
    except TypeError:
        return 8


def param_bytes_per_device(tree, mesh: Mesh,
                           rules: Optional[Sequence[PartitionRule]] = None) -> Tuple[int, int]:
    """(replicated_bytes, sharded_bytes) a single device would hold for
    `tree` under the rule set — the PERF.md 'Sharding & memory' numbers.
    Sharded bytes divide by the product of EVERY mesh axis in the param's
    spec (fsdp x model for the 2-D tensor-parallel kernels)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    rep = shard = 0
    for kp, leaf in flat:
        nbytes = int(np.prod(getattr(leaf, 'shape', ()) or (1,))) * leaf_itemsize(leaf.dtype)
        rep += nbytes
        spec = spec_for_param(_kp_str(kp), getattr(leaf, 'shape', ()), mesh, rules)
        shard += nbytes // _spec_shard_count(spec, mesh)
    return rep, shard


def activation_bytes_per_device(
        mesh: Mesh,
        *,
        batch_size: int,
        seq_len: int,
        width: int,
        depth: int,
        mlp_ratio: float = 4.0,
        bytes_per_elem: int = 4,
) -> Tuple[int, int]:
    """(unconstrained_bytes, constrained_bytes) of transformer-block
    activations one device holds per step — the PERF.md companion to
    `param_bytes_per_device` for fsdp x tp grids.

    Counts the dominant per-block tensors (residual stream, q/k/v, MLP
    hidden ~ seq_len x width x (4 + mlp_ratio) elements) across `depth`
    blocks. 'Unconstrained' is the PR-5 state: the batch dim shards over the
    non-'model' axes but channels replicate, so adding tp devices buys no
    activation memory (this is exactly the involuntary-remat regime).
    'Constrained' applies the parallel/constraints.py specs: channel/head/
    hidden dims additionally shard over 'model' where divisible, so
    activation bytes scale ~1/tp. With tp=1 the two numbers are equal.
    """
    n_tp = tp_size(mesh)
    n_batch = max(1, int(np.prod([int(s) for s in mesh.shape.values()])) // n_tp)
    hidden = int(width * mlp_ratio)

    def elems(channel_div: bool) -> int:
        resid_qkv = 4 * seq_len * width // (n_tp if channel_div and width % n_tp == 0 else 1)
        mlp = seq_len * hidden // (n_tp if channel_div and hidden % n_tp == 0 else 1)
        return batch_size * depth * (resid_qkv + mlp)

    unconstrained = elems(False) * bytes_per_elem // n_batch
    constrained = elems(True) * bytes_per_elem // n_batch
    return unconstrained, constrained
