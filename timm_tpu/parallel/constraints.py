"""Activation sharding constraints for tensor parallelism.

Parameter sharding alone (parallel/sharding.py) leaves GSPMD free to pick
activation layouts, and on a ('data', 'fsdp', 'model') mesh it picks badly:
PERF.md records XLA "involuntary full rematerialization" notes where
replicated activations meet model-sharded kernels inside the scanned block
body — every device all-gathers the full hidden tensor it was supposed to
never materialize. `jax.lax.with_sharding_constraint` pins the layout at the
three places that matter (the MaxText/big_vision idiom):

  * 'residual' — the (B, N, C) stream between blocks AND the lax.scan carry
    (models/_manipulate.py), batch over the non-'model' axes, channels over
    'model';
  * 'heads'    — (B, H, N, D) attention tensors, heads over 'model';
  * 'hidden'   — (B, N, hidden) MLP/attention intermediates, hidden over
    'model';
  * 'channels' — the (B, H, W, C) NHWC residual stream of hierarchical
    models (convnext/metaformer/regnet/... stage scan carries), channels
    over 'model'.

Everything degrades to a no-op: no global mesh, no 'model' axis, a rank the
kind does not expect (vmapped calls see rank-2 slices), a dim not
divisible by its axis size, or a token extent below the tiny-geometry
miscompile floor (`_MIN_TOKENS`, see below) — so single-device eval, tp=1
meshes, and odd head counts all run today's programs unchanged. Constraints are sharding
METADATA, not collectives: tp=1 output is bit-identical, and under tp>1 any
numeric difference is fp reduction order only.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import nonmodel_batch_axes, peek_global_mesh

__all__ = ['shard_activation']

# kind -> (expected rank, model-sharded dim, token dims)
_KINDS = {
    'residual': (3, 2, (1,)),   # (B, N, C): channels over 'model'
    'heads': (4, 1, (2,)),      # (B, H, N, head_dim): heads over 'model'
    'hidden': (3, 2, (1,)),     # (B, N, hidden): hidden features over 'model'
    'channels': (4, 3, (1, 2)),  # (B, H, W, C) NHWC hierarchical stream
}

# Tiny-geometry miscompile guard. On a ('data', 'fsdp', 'model') mesh,
# XLA:CPU's SPMD partitioner CORRUPTS the interior batch shards of a
# constrained residual stream the moment it meets the megatron-sharded MLP
# in a residual add — bisected on test_vit@img32: `h + mlp(norm2(h))` with
# h pinned to P(('data','fsdp'), None, 'model') is off by ~5e-2 on batch
# rows 2-5 (patch tokens only; the replicated cls row masks it), while
# either operand alone, `h + h`, and `h + norm2(h)` are all bit-exact.
# Token extents 4/5/9/10/16 reproduce it; 17/25/26/36 agree to 1e-6
# (same program, same params). Below the observed-safe floor the
# constraint is skipped — these geometries are test-only, the replicated
# program is exact, and a perf hint is worthless at 16 tokens anyway.
_MIN_TOKENS = int(os.environ.get('TIMM_TPU_TP_MIN_TOKENS', '17') or 17)


def shard_activation(x, kind: str, mesh: Optional[Mesh] = None):
    """Constrain one activation tensor's layout; identity when the mesh (or
    tensor) can't honour it.

    Inside jit this lowers to a sharding_constraint op — the presence the
    remat regression test greps for in the scan-body jaxpr. Outside jit (or
    when no constraint applies) it returns `x` untouched, so eager layer
    calls and unit tests never pay for it.
    """
    if kind not in _KINDS:
        raise ValueError(f'unknown activation kind {kind!r}; expected one of {sorted(_KINDS)}')
    mesh = mesh if mesh is not None else peek_global_mesh()
    if mesh is None or 'model' not in mesh.axis_names:
        return x
    rank, model_dim, token_dims = _KINDS[kind]
    shape = getattr(x, 'shape', None)
    if shape is None or len(shape) != rank:
        return x
    n_tokens = 1
    for d in token_dims:
        n_tokens *= int(shape[d])
    if n_tokens < _MIN_TOKENS:
        return x
    batch_axes = nonmodel_batch_axes(mesh)
    n_batch = 1
    for a in batch_axes:
        n_batch *= int(mesh.shape[a])
    if n_batch > 1 and shape[0] % n_batch != 0:
        return x
    spec = [None] * rank
    if n_batch > 1:
        spec[0] = batch_axes if len(batch_axes) > 1 else batch_axes[0]
    if shape[model_dim] % int(mesh.shape['model']) == 0:
        spec[model_dim] = 'model'
    if all(s is None for s in spec):
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))
