"""Multi-host initialization + rank utilities
(reference: timm/utils/distributed.py:17-159).

The reference builds a torch.distributed process group (NCCL/gloo) from
torchrun/SLURM env vars. On TPU pods the equivalent is
`jax.distributed.initialize()` (one process per host), after which
`jax.devices()` spans the pod and collectives are emitted by XLA — there is
no explicit communication backend to select.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

_logger = logging.getLogger(__name__)

__all__ = ['is_distributed_env', 'init_distributed_device', 'world_info', 'is_primary',
           'reduce_tensor', 'all_hosts_flag', 'coordination_client', 'barrier_timeout_s']

_INITIALIZED = False

# Per-name monotonic sequence numbers for the KV-store consensus path. Every
# process calls a named consensus at the same points in the same order (it is
# a collective by contract), so independently-maintained counters agree and
# each round reads fresh keys even though the KV store never forgets.
_FLAG_SEQ: Dict[str, int] = {}
_FLAG_LOCK = threading.Lock()


def is_distributed_env() -> bool:
    """Detect a multi-host launch (JAX coordinator / SLURM / OpenMPI vars)."""
    for var in ('COORDINATOR_ADDRESS', 'JAX_COORDINATOR_ADDRESS'):
        if os.environ.get(var):
            return True
    if os.environ.get('SLURM_NTASKS') and int(os.environ['SLURM_NTASKS']) > 1:
        return True
    if os.environ.get('OMPI_COMM_WORLD_SIZE') and int(os.environ['OMPI_COMM_WORLD_SIZE']) > 1:
        return True
    return False


def init_distributed_device(args=None) -> Tuple[int, int, int]:
    """Initialize multi-host JAX if needed; returns (world_size, global_rank,
    local_rank) in *process* terms. Mirrors the reference contract of
    init_distributed_device(args) mutating args.{distributed,world_size,rank,local_rank}.
    """
    global _INITIALIZED
    forced = bool(getattr(args, 'distributed', False))
    if not _INITIALIZED and coordination_client() is not None:
        # train.py's _bootstrap_distributed (or a host harness) already ran
        # jax.distributed.initialize() — importing timm_tpu touches the XLA
        # backend, so the bring-up must happen before this module can load
        _INITIALIZED = True
    if (is_distributed_env() or forced) and not _INITIALIZED:
        coord = os.environ.get('COORDINATOR_ADDRESS') or os.environ.get('JAX_COORDINATOR_ADDRESS')
        kwargs = {}
        if coord:
            kwargs['coordinator_address'] = coord
            if os.environ.get('NUM_PROCESSES'):
                kwargs['num_processes'] = int(os.environ['NUM_PROCESSES'])
            if os.environ.get('PROCESS_ID'):
                kwargs['process_id'] = int(os.environ['PROCESS_ID'])
        try:
            jax.distributed.initialize(**kwargs)
            _INITIALIZED = True
            _logger.info(f'Initialized multi-host JAX: process {jax.process_index()}/{jax.process_count()}')
        except Exception:
            if not forced or is_distributed_env():
                raise
            # --distributed without any cluster env: fall back to single-process
            _logger.warning('--distributed requested but no coordinator/cluster '
                            'env detected; continuing single-process')

    world_size = jax.process_count()
    rank = jax.process_index()
    local_rank = 0
    if args is not None:
        args.distributed = world_size > 1
        args.world_size = world_size
        args.rank = rank
        args.local_rank = local_rank
        args.device = str(jax.devices()[0]).lower()
    return world_size, rank, local_rank


def world_info() -> Tuple[int, int]:
    return jax.process_count(), jax.process_index()


def is_primary(args=None) -> bool:
    return jax.process_index() == 0


def coordination_client():
    """The distributed coordination-service client, or None outside a
    multi-process run. Its key-value RPCs are plain gRPC — thread-safe and,
    unlike device collectives, they FAIL (timeout) instead of deadlocking
    when a peer process has died. That makes them the only safe transport
    for consensus in the presence of host loss."""
    try:
        from jax._src import distributed as _dist
        return _dist.global_state.client
    except Exception:
        return None


def barrier_timeout_s() -> float:
    """How long a named consensus waits for a peer before declaring it lost
    (TIMM_TPU_BARRIER_TIMEOUT seconds, default 20)."""
    try:
        return float(os.environ.get('TIMM_TPU_BARRIER_TIMEOUT', '20'))
    except ValueError:
        return 20.0


def _kv_flag_consensus(client, local_flag: bool, mode: str, name: str,
                       timeout_s: Optional[float]) -> bool:
    """Named consensus over the coordination service's KV store.

    Dead-peer semantics: a peer that never publishes its flag within the
    timeout is treated as LOST, which resolves to True under mode='any'
    (a lost host means the pod must stop) and False under mode='all'
    (an unconfirmed shard means the manifest must not commit). Both
    degradations are safe: the worst case is an extra recovery cycle or a
    skipped checkpoint commit, never a deadlock or a torn manifest."""
    with _FLAG_LOCK:
        seq = _FLAG_SEQ.get(name, 0)
        _FLAG_SEQ[name] = seq + 1
    rank, world = jax.process_index(), jax.process_count()
    timeout_ms = max(1, int(1000 * (barrier_timeout_s() if timeout_s is None else timeout_s)))

    def key(p: int) -> str:
        return f'timm_tpu/flag/{name}/{seq}/p{p}'

    try:
        client.key_value_set(key(rank), '1' if local_flag else '0')
    except Exception:  # coordinator unreachable: behave like a lost peer
        return mode == 'any'
    result_any, result_all, lost = bool(local_flag), bool(local_flag), False
    for p in range(world):
        if p == rank:
            continue
        try:
            v = client.blocking_key_value_get(key(p), timeout_ms)
            result_any = result_any or v == '1'
            result_all = result_all and v == '1'
        except Exception:
            lost = True
    if mode == 'any':
        return True if lost else result_any
    return False if lost else result_all


def all_hosts_flag(local_flag: bool, mode: str = 'any',
                   name: Optional[str] = None,
                   timeout_s: Optional[float] = None) -> bool:
    """Cross-host boolean consensus for HOST-LOCAL signals (a SIGTERM may be
    delivered to only some hosts of a pod, but every host must act on the
    same step or the next collective deadlocks). Single-process: identity.
    `mode` is 'any' or 'all'. Every host must call this at the same point in
    its step sequence.

    With a `name`, consensus runs over the coordination service's KV store
    (see `_kv_flag_consensus`): it survives a dead peer by timing out and
    resolving 'any'->True / 'all'->False instead of hanging. Without a name
    (or outside jax.distributed.initialize) it is a device allgather, which
    requires every host alive."""
    if jax.process_count() <= 1:
        return bool(local_flag)
    if name is not None:
        client = coordination_client()
        if client is not None:
            return _kv_flag_consensus(client, local_flag, mode, name, timeout_s)
    from jax.experimental import multihost_utils
    flags = multihost_utils.process_allgather(jnp.asarray([1 if local_flag else 0], jnp.int32))
    import numpy as np
    flags = np.asarray(flags)
    return bool(flags.any()) if mode == 'any' else bool(flags.all())


def reduce_tensor(tensor, n: Optional[int] = None):
    """Mean across data-parallel replicas (reference utils/distributed.py:17).

    Under pjit, per-step metrics computed from a globally-sharded batch are
    already global — this is the identity then. It exists for API parity and
    for host-local values: a host-local numpy value is averaged across
    processes via a tiny all-reduce.
    """
    import numpy as np
    if isinstance(tensor, (int, float)) or (hasattr(tensor, 'ndim') and not isinstance(tensor, jax.Array)):
        if jax.process_count() == 1:
            return tensor
        from jax.experimental import multihost_utils
        val = multihost_utils.process_allgather(jnp.asarray(tensor))
        return np.asarray(val).mean(axis=0)  # element-wise mean across processes
    return tensor
