"""Multi-host initialization + rank utilities
(reference: timm/utils/distributed.py:17-159).

The reference builds a torch.distributed process group (NCCL/gloo) from
torchrun/SLURM env vars. On TPU pods the equivalent is
`jax.distributed.initialize()` (one process per host), after which
`jax.devices()` spans the pod and collectives are emitted by XLA — there is
no explicit communication backend to select.
"""
from __future__ import annotations

import logging
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_logger = logging.getLogger(__name__)

__all__ = ['is_distributed_env', 'init_distributed_device', 'world_info', 'is_primary',
           'reduce_tensor', 'all_hosts_flag']

_INITIALIZED = False


def is_distributed_env() -> bool:
    """Detect a multi-host launch (JAX coordinator / SLURM / OpenMPI vars)."""
    for var in ('COORDINATOR_ADDRESS', 'JAX_COORDINATOR_ADDRESS'):
        if os.environ.get(var):
            return True
    if os.environ.get('SLURM_NTASKS') and int(os.environ['SLURM_NTASKS']) > 1:
        return True
    if os.environ.get('OMPI_COMM_WORLD_SIZE') and int(os.environ['OMPI_COMM_WORLD_SIZE']) > 1:
        return True
    return False


def init_distributed_device(args=None) -> Tuple[int, int, int]:
    """Initialize multi-host JAX if needed; returns (world_size, global_rank,
    local_rank) in *process* terms. Mirrors the reference contract of
    init_distributed_device(args) mutating args.{distributed,world_size,rank,local_rank}.
    """
    global _INITIALIZED
    if is_distributed_env() and not _INITIALIZED:
        coord = os.environ.get('COORDINATOR_ADDRESS') or os.environ.get('JAX_COORDINATOR_ADDRESS')
        kwargs = {}
        if coord:
            kwargs['coordinator_address'] = coord
            if os.environ.get('NUM_PROCESSES'):
                kwargs['num_processes'] = int(os.environ['NUM_PROCESSES'])
            if os.environ.get('PROCESS_ID'):
                kwargs['process_id'] = int(os.environ['PROCESS_ID'])
        jax.distributed.initialize(**kwargs)
        _INITIALIZED = True
        _logger.info(f'Initialized multi-host JAX: process {jax.process_index()}/{jax.process_count()}')

    world_size = jax.process_count()
    rank = jax.process_index()
    local_rank = 0
    if args is not None:
        args.distributed = world_size > 1
        args.world_size = world_size
        args.rank = rank
        args.local_rank = local_rank
        args.device = str(jax.devices()[0]).lower()
    return world_size, rank, local_rank


def world_info() -> Tuple[int, int]:
    return jax.process_count(), jax.process_index()


def is_primary(args=None) -> bool:
    return jax.process_index() == 0


def all_hosts_flag(local_flag: bool, mode: str = 'any') -> bool:
    """Cross-host boolean consensus for HOST-LOCAL signals (a SIGTERM may be
    delivered to only some hosts of a pod, but every host must act on the
    same step or the next collective deadlocks). Single-process: identity.
    Multi-host: a tiny allgather; every host must call this at the same point
    in its step sequence (it is a collective). `mode` is 'any' or 'all'."""
    if jax.process_count() <= 1:
        return bool(local_flag)
    from jax.experimental import multihost_utils
    flags = multihost_utils.process_allgather(jnp.asarray([1 if local_flag else 0], jnp.int32))
    import numpy as np
    flags = np.asarray(flags)
    return bool(flags.any()) if mode == 'any' else bool(flags.all())


def reduce_tensor(tensor, n: Optional[int] = None):
    """Mean across data-parallel replicas (reference utils/distributed.py:17).

    Under pjit, per-step metrics computed from a globally-sharded batch are
    already global — this is the identity then. It exists for API parity and
    for host-local values: a host-local numpy value is averaged across
    processes via a tiny all-reduce.
    """
    import numpy as np
    if isinstance(tensor, (int, float)) or (hasattr(tensor, 'ndim') and not isinstance(tensor, jax.Array)):
        if jax.process_count() == 1:
            return tensor
        from jax.experimental import multihost_utils
        val = multihost_utils.process_allgather(jnp.asarray(tensor))
        return np.asarray(val).mean(axis=0)  # element-wise mean across processes
    return tensor
