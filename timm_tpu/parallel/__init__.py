from .mesh import (
    create_mesh, data_sharding, get_global_mesh, replicate_sharding, set_global_mesh, shard_batch,
)
from .distributed import (
    all_hosts_flag, init_distributed_device, is_distributed_env, is_primary, reduce_tensor,
    world_info,
)
