from .mesh import (
    batch_axes, create_mesh, data_sharding, get_global_mesh, mesh_process_count,
    nonmodel_batch_axes, peek_global_mesh, place_global,
    replicate_sharding, resolve_elastic_axes, set_global_mesh, shard_batch,
)
from .distributed import (
    all_hosts_flag, barrier_timeout_s, coordination_client, init_distributed_device,
    is_distributed_env, is_primary, reduce_tensor, world_info,
)
from .sharding import (
    PartitionRule, abstract_init_sharded, activation_bytes_per_device, build_opt_shardings,
    build_param_shardings, create_sharded_model, default_partition_rules, fsdp_size,
    build_quant_shardings, inherit_param_specs, match_rule, param_bytes_per_device,
    path_specs, quant_path_specs, quant_scale_spec, replicated_like,
    shard_pytree, spec_for_param, tp_size,
)
from .constraints import shard_activation
