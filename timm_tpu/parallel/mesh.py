"""Device-mesh and sharding helpers.

TPU-native replacement for the reference's DDP/NCCL stack
(reference: timm/utils/distributed.py:79-159, task/classification.py:64-66).

Data parallelism is expressed as a mesh, not processes: batches are sharded
over the 'data' axis, params are replicated, and XLA emits the grad
all-reduce over ICI/DCN. For multi-host pods the mesh is 2-level
('dcn' × 'ici') so collectives ride ICI within a slice.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    'create_mesh', 'data_sharding', 'replicate_sharding', 'shard_batch',
    'get_global_mesh', 'set_global_mesh',
]

_GLOBAL_MESH: Optional[Mesh] = None


def create_mesh(
        devices: Optional[Sequence] = None,
        data_axis: str = 'data',
        num_slices: Optional[int] = None,
) -> Mesh:
    """1-D data-parallel mesh, or ('dcn', 'data') 2-level when multiple DCN
    slices are present. Shardings in this framework reference the 'data' axis
    (and 'dcn' when present) for the batch dimension.
    """
    devices = list(devices) if devices is not None else jax.devices()
    if num_slices is None:
        # group by process/slice when running multi-host
        slice_ids = {getattr(d, 'slice_index', 0) for d in devices}
        num_slices = len(slice_ids)
    if num_slices > 1:
        dev_array = np.array(devices).reshape(num_slices, -1)
        return Mesh(dev_array, ('dcn', data_axis))
    return Mesh(np.array(devices), (data_axis,))


def set_global_mesh(mesh: Mesh):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_global_mesh() -> Mesh:
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None:
        _GLOBAL_MESH = create_mesh()
    return _GLOBAL_MESH


def _batch_axes(mesh: Mesh) -> Tuple[str, ...]:
    return tuple(n for n in mesh.axis_names)  # batch sharded over all mesh axes


def data_sharding(mesh: Mesh, ndim: int = 4) -> NamedSharding:
    """Shard the leading (batch) dim over every mesh axis; replicate the rest."""
    return NamedSharding(mesh, P(_batch_axes(mesh), *([None] * (ndim - 1))))


def replicate_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Optional[Mesh] = None):
    """Place a host batch (pytree of arrays) sharded over the mesh batch axis.
    Non-array leaves pass through; 0-d arrays are replicated (a rank-0 value
    has no batch dim to shard — seq_len/step counters in dict batches)."""
    mesh = mesh or get_global_mesh()

    def put(x):
        ndim = getattr(x, 'ndim', None)
        if ndim is None:
            return x
        if ndim == 0:
            return jax.device_put(x, replicate_sharding(mesh))
        return jax.device_put(x, data_sharding(mesh, ndim=ndim))
    return jax.tree.map(put, batch)
