"""Device-mesh and sharding helpers.

TPU-native replacement for the reference's DDP/NCCL stack
(reference: timm/utils/distributed.py:79-159, task/classification.py:64-66).

Data parallelism is expressed as a mesh, not processes: batches are sharded
over the batch axes, params are replicated (or fsdp/tensor-sharded, see
parallel/sharding.py), and XLA emits the grad all-reduce over ICI/DCN.

Mesh shapes:
  * `('data',)` — plain data parallelism (the default);
  * `('dcn', 'data')` — multi-host pods with multiple DCN slices, so
    collectives ride ICI within a slice;
  * `('data', 'fsdp')` / `('dcn', 'data', 'fsdp')` — ZeRO-style sharding:
    the BATCH is sharded over the product of every axis (all devices see
    different samples), while params/optimizer state shard over 'fsdp' only;
  * `('data', 'fsdp', 'model')` — adds Megatron-style tensor parallelism:
    attention QKV/proj kernels shard heads and MLP fc1/fc2 kernels shard the
    hidden dim over 'model', and activation sharding constraints
    (parallel/constraints.py) keep the residual stream and attention/MLP
    internals sharded inside the block scan. The INPUT batch still shards
    over the product of all axes (maximum host→device transfer parallelism);
    the model's first residual constraint redistributes it to
    (batch over data×fsdp) × (channels over model).
"""
from __future__ import annotations

import math
import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    'create_mesh', 'data_sharding', 'replicate_sharding', 'shard_batch',
    'get_global_mesh', 'set_global_mesh', 'peek_global_mesh', 'batch_axes',
    'nonmodel_batch_axes', 'resolve_elastic_axes', 'place_global',
    'mesh_process_count',
]

_GLOBAL_MESH: Optional[Mesh] = None


def _mesh_axes_str(axes) -> str:
    """'data=2, fsdp=2, model=2 (8 devices)' from {axis: size} pairs."""
    items = list(axes.items() if isinstance(axes, dict) else axes)
    total = int(np.prod([s for _, s in items])) if items else 1
    return ', '.join(f'{n}={s}' for n, s in items) + f' ({total} devices)'


def create_mesh(
        devices: Optional[Sequence] = None,
        data_axis: str = 'data',
        num_slices: Optional[int] = None,
        fsdp: Optional[int] = None,
        tp: Optional[int] = None,
) -> Mesh:
    """Data-parallel mesh, optionally with 'fsdp' (parameter sharding) and
    'model' (tensor parallelism) axes.

    `fsdp=N` (or env TIMM_TPU_FSDP) folds N devices of each data group into a
    second axis; `tp=M` (or env TIMM_TPU_TP) folds M more into a trailing
    'model' axis: 8 devices with fsdp=2, tp=2 gives a
    ``('data', 'fsdp', 'model')`` mesh of shape (2, 2, 2). Batches shard over
    the product of ALL axes (see `shard_batch`); params/optimizer state shard
    over 'fsdp', and attention-head / MLP-hidden kernel dims (plus the
    activation constraints) shard over 'model' (parallel/sharding.py). With
    multiple DCN slices the mesh is ``('dcn', data_axis[, 'fsdp'][, 'model'])``
    so collectives ride ICI within a slice. `fsdp=1`/`tp=1` (the defaults)
    omit their axes entirely, reproducing the smaller-mesh behaviour exactly.
    """
    devices = list(devices) if devices is not None else jax.devices()
    if fsdp is None:
        fsdp = int(os.environ.get('TIMM_TPU_FSDP', '1') or 1)
    fsdp = max(1, fsdp)
    if tp is None:
        tp = int(os.environ.get('TIMM_TPU_TP', '1') or 1)
    tp = max(1, tp)
    if num_slices is None:
        # group by slice when the platform reports one (TPU pods); otherwise
        # one DCN group per host process — this is what makes the 'dcn' axis
        # real for multi-process CPU clusters, where devices carry a
        # process_index but no slice_index. jax.devices() is process-major,
        # so reshape(num_slices, -1) puts each process's devices in one row.
        slice_ids = {getattr(d, 'slice_index', None) for d in devices}
        if len(slice_ids) == 1 and getattr(devices[0], 'platform', '') == 'cpu':
            # multi-process CPU clusters report one slice (or none), but the
            # cross-process links are gRPC — DCN-class, not ICI. Group by
            # process so the 'dcn' axis is real. Single-slice TPU pods keep
            # their all-ICI mesh (one slice, no dcn axis).
            slice_ids = {getattr(d, 'process_index', 0) for d in devices}
        num_slices = len(slice_ids)
    # trailing axes (closest ICI neighbours) host the most collective-hungry
    # parallelism: fsdp before model, model innermost
    trailing = []
    if fsdp > 1:
        trailing.append(('fsdp', fsdp))
    if tp > 1:
        trailing.append(('model', tp))
    if trailing:
        per_slice = len(devices) // max(num_slices, 1)
        n_trail = fsdp * tp
        if per_slice % n_trail != 0:
            axes = [('data', per_slice // n_trail if n_trail and per_slice % n_trail == 0 else '?'),
                    ('fsdp', fsdp), ('model', tp)]
            raise ValueError(
                f'mesh axes fsdp={fsdp} x tp={tp} = {n_trail} must divide the {per_slice} '
                f'devices per slice ({len(devices)} devices / {num_slices} slice(s)); '
                f'requested mesh would be ({", ".join(f"{n}={s}" for n, s in axes)})')
        shape = [-1] + [s for _, s in trailing]
        names = (data_axis,) + tuple(n for n, _ in trailing)
        if num_slices > 1:
            dev_array = np.array(devices).reshape(num_slices, *shape)
            return Mesh(dev_array, ('dcn',) + names)
        return Mesh(np.array(devices).reshape(*shape), names)
    if num_slices > 1:
        dev_array = np.array(devices).reshape(num_slices, -1)
        return Mesh(dev_array, ('dcn', data_axis))
    return Mesh(np.array(devices), (data_axis,))


def resolve_elastic_axes(
        n_devices: int,
        fsdp: Optional[int] = None,
        tp: Optional[int] = None,
        num_slices: int = 1,
) -> Tuple[Optional[int], Optional[int]]:
    """Clamp requested fsdp/tp axis sizes to the LIVE topology.

    An elastic restart reuses the dead run's ``--fsdp``/``--tp`` flags, but
    the surviving device count may no longer divide the same way. Each
    request is clamped to the largest divisor of the available per-slice
    device count not exceeding it — tp first (innermost, most
    collective-hungry axis), then fsdp within the remaining factor — so
    ``create_mesh(fsdp=..., tp=...)`` is guaranteed to accept the result.
    Returns ``(fsdp, tp)`` with None where the axis should be omitted,
    matching create_mesh's treatment of ``fsdp=1``/``tp=1``.

    This largest-divisor policy is the DOCUMENTED FALLBACK of elastic resume:
    `plan_elastic_resume` first asks the autotune solver
    (`timm_tpu.autotune.resolve_config_for_topology`) to re-solve
    (fsdp, tp, batch, accum) by cost rank for the new topology — a still-legal
    requested config passes through unchanged — and lands here whenever the
    solver refuses (no model dims, no legal point, any solver error). The
    clamp is topology-only: it guarantees a mesh, not a good one.
    """
    per_slice = max(1, int(n_devices) // max(1, int(num_slices)))

    def largest_divisor(request: int, limit: int) -> int:
        d = min(int(request), limit)
        while limit % d:
            d -= 1
        return d

    tp_eff = largest_divisor(tp, per_slice) if tp and int(tp) > 1 else 1
    fsdp_eff = largest_divisor(fsdp, per_slice // tp_eff) if fsdp and int(fsdp) > 1 else 1
    return (fsdp_eff if fsdp_eff > 1 else None, tp_eff if tp_eff > 1 else None)


def set_global_mesh(mesh: Mesh):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_global_mesh() -> Mesh:
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None:
        _GLOBAL_MESH = create_mesh()
    return _GLOBAL_MESH


def peek_global_mesh() -> Optional[Mesh]:
    """The global mesh if one was set, WITHOUT creating a default one — the
    zero-cost probe the activation-constraint helpers use on every layer call
    (parallel/constraints.py): no mesh or no 'model' axis → no-op."""
    return _GLOBAL_MESH


def batch_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Batch is sharded over EVERY mesh axis — including 'fsdp' and 'model':
    from the host's view all devices are data-parallel workers; only the
    parameter placement and the in-model activation constraints distinguish
    the fsdp/model sub-axes."""
    return tuple(n for n in mesh.axis_names)


def nonmodel_batch_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Batch axes for ACTIVATIONS inside the model: everything but 'model'.
    Under tensor parallelism the 'model' axis carries head/hidden channel
    shards, so the activation batch dim shards over the remaining axes only
    (the residual-stream constraint redistributes the input batch once)."""
    return tuple(n for n in mesh.axis_names if n != 'model')


_batch_axes = batch_axes  # backwards-compat private alias


def data_sharding(mesh: Mesh, ndim: int = 4) -> NamedSharding:
    """Shard the leading (batch) dim over every mesh axis; replicate the rest."""
    return NamedSharding(mesh, P(batch_axes(mesh), *([None] * (ndim - 1))))


def replicate_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_process_count(mesh: Mesh) -> int:
    """How many distinct host processes own devices of this mesh (1 for every
    single-process run, regardless of device count)."""
    return len({getattr(d, 'process_index', 0) for d in mesh.devices.flat})


def place_global(x, sharding: NamedSharding):
    """`jax.device_put` that also works for non-fully-addressable shardings.

    In a multi-process run a sharding spanning other hosts' devices cannot be
    device_put from host data; `make_array_from_callback` builds the global
    array from the locally-addressable pieces instead (each process supplies
    only the index slices its own devices need). Single-process shardings take
    the plain device_put fast path, byte-for-byte identical to before."""
    if getattr(sharding, 'is_fully_addressable', True):
        return jax.device_put(x, sharding)
    xnp = np.asarray(x)
    return jax.make_array_from_callback(xnp.shape, sharding, lambda idx: xnp[idx])


def shard_batch(batch, mesh: Optional[Mesh] = None):
    """Place a host batch (pytree of arrays) sharded over the mesh batch axes
    (their product for multi-axis ('data', 'fsdp'[, 'model']) meshes).
    Non-array leaves pass through; 0-d arrays are replicated (a rank-0 value
    has no batch dim to shard — seq_len/step counters in dict batches).

    Multi-process meshes: each process passes its PROCESS-LOCAL batch (the
    loaders shard by process_index); the global batch is assembled via
    `jax.make_array_from_process_local_data`, with the global batch dim =
    local rows x participating processes. Device order is process-major, so
    process p contributes rows [p*local, (p+1)*local) of the global batch.

    Raises a loud ValueError when the global batch is not divisible by the
    total batch-shard count — the alternative is an opaque XLA reshape error
    from deep inside the jitted step."""
    mesh = mesh or get_global_mesh()
    axes = batch_axes(mesh)
    sizes = [(a, int(mesh.shape[a])) for a in axes]
    n_shards = int(np.prod([s for _, s in sizes]))
    n_procs = mesh_process_count(mesh)

    def put(x):
        ndim = getattr(x, 'ndim', None)
        if ndim is None:
            return x
        if ndim == 0:
            return place_global(x, replicate_sharding(mesh))
        global_b = x.shape[0] * n_procs
        if global_b % n_shards != 0:
            b = x.shape[0]
            step = n_shards * n_procs // math.gcd(n_shards, n_procs)
            lo, hi = (global_b // step) * step, -(-global_b // step) * step
            nearest = f'{hi}' if lo == 0 else f'{lo} or {hi}'
            local_hint = '' if n_procs == 1 else (
                f' ({lo // n_procs} or {hi // n_procs} local rows per process)')
            raise ValueError(
                f'Global batch dim {global_b} ({b} local rows x {n_procs} process(es)) '
                f'is not divisible by the mesh batch-shard '
                f'count {n_shards}: the batch shards over the product of ALL mesh axes '
                f'({_mesh_axes_str(sizes)}). Nearest legal global batch: '
                f'{nearest}{local_hint}. '
                f'Pad the batch or pick a batch size that divides evenly — e.g. '
                f'validate.py pads the final partial batch.')
        sharding = data_sharding(mesh, ndim=ndim)
        if n_procs > 1:
            xnp = np.asarray(x)
            return jax.make_array_from_process_local_data(
                sharding, xnp, (global_b,) + xnp.shape[1:])
        return jax.device_put(x, sharding)
    return jax.tree.map(put, batch)
