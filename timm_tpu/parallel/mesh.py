"""Device-mesh and sharding helpers.

TPU-native replacement for the reference's DDP/NCCL stack
(reference: timm/utils/distributed.py:79-159, task/classification.py:64-66).

Data parallelism is expressed as a mesh, not processes: batches are sharded
over the batch axes, params are replicated (or fsdp-sharded, see
parallel/sharding.py), and XLA emits the grad all-reduce over ICI/DCN.

Mesh shapes:
  * `('data',)` — plain data parallelism (the default);
  * `('dcn', 'data')` — multi-host pods with multiple DCN slices, so
    collectives ride ICI within a slice;
  * `('data', 'fsdp')` / `('dcn', 'data', 'fsdp')` — ZeRO-style sharding:
    the BATCH is sharded over the product of every axis (all devices see
    different samples), while params/optimizer state shard over 'fsdp' only.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    'create_mesh', 'data_sharding', 'replicate_sharding', 'shard_batch',
    'get_global_mesh', 'set_global_mesh', 'batch_axes',
]

_GLOBAL_MESH: Optional[Mesh] = None


def create_mesh(
        devices: Optional[Sequence] = None,
        data_axis: str = 'data',
        num_slices: Optional[int] = None,
        fsdp: Optional[int] = None,
) -> Mesh:
    """Data-parallel mesh, optionally with an 'fsdp' parameter-sharding axis.

    `fsdp=N` (or env TIMM_TPU_FSDP) folds the trailing N devices of each
    data group into a second axis: 8 devices with fsdp=4 gives a
    ``('data', 'fsdp')`` mesh of shape (2, 4). Batches still shard over all
    8 devices (see `shard_batch`); params/optimizer state shard over the 4
    fsdp devices per data group (parallel/sharding.py). With multiple DCN
    slices the mesh is ``('dcn', data_axis[, 'fsdp'])`` so collectives ride
    ICI within a slice.
    """
    devices = list(devices) if devices is not None else jax.devices()
    if fsdp is None:
        fsdp = int(os.environ.get('TIMM_TPU_FSDP', '1') or 1)
    fsdp = max(1, fsdp)
    if num_slices is None:
        # group by process/slice when running multi-host
        slice_ids = {getattr(d, 'slice_index', 0) for d in devices}
        num_slices = len(slice_ids)
    if fsdp > 1:
        per_slice = len(devices) // max(num_slices, 1)
        if per_slice % fsdp != 0:
            raise ValueError(
                f'fsdp={fsdp} must divide the {per_slice} devices per slice '
                f'({len(devices)} devices / {num_slices} slice(s))')
        if num_slices > 1:
            dev_array = np.array(devices).reshape(num_slices, -1, fsdp)
            return Mesh(dev_array, ('dcn', data_axis, 'fsdp'))
        return Mesh(np.array(devices).reshape(-1, fsdp), (data_axis, 'fsdp'))
    if num_slices > 1:
        dev_array = np.array(devices).reshape(num_slices, -1)
        return Mesh(dev_array, ('dcn', data_axis))
    return Mesh(np.array(devices), (data_axis,))


def set_global_mesh(mesh: Mesh):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_global_mesh() -> Mesh:
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None:
        _GLOBAL_MESH = create_mesh()
    return _GLOBAL_MESH


def batch_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Batch is sharded over EVERY mesh axis — including 'fsdp': under ZeRO
    all devices are data-parallel workers; only the parameter/optimizer
    placement distinguishes the fsdp sub-axis."""
    return tuple(n for n in mesh.axis_names)


_batch_axes = batch_axes  # backwards-compat private alias


def data_sharding(mesh: Mesh, ndim: int = 4) -> NamedSharding:
    """Shard the leading (batch) dim over every mesh axis; replicate the rest."""
    return NamedSharding(mesh, P(batch_axes(mesh), *([None] * (ndim - 1))))


def replicate_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Optional[Mesh] = None):
    """Place a host batch (pytree of arrays) sharded over the mesh batch axes
    (their product for a 2-axis ('data', 'fsdp') mesh). Non-array leaves pass
    through; 0-d arrays are replicated (a rank-0 value has no batch dim to
    shard — seq_len/step counters in dict batches).

    Raises a loud ValueError when the global batch is not divisible by the
    total batch-shard count — the alternative is an opaque XLA reshape error
    from deep inside the jitted step."""
    mesh = mesh or get_global_mesh()
    n_shards = int(np.prod([mesh.shape[a] for a in batch_axes(mesh)]))

    def put(x):
        ndim = getattr(x, 'ndim', None)
        if ndim is None:
            return x
        if ndim == 0:
            return jax.device_put(x, replicate_sharding(mesh))
        if x.shape[0] % n_shards != 0:
            raise ValueError(
                f'Global batch dim {x.shape[0]} is not divisible by the mesh batch-shard '
                f'count {n_shards} (mesh {dict(mesh.shape)}; the batch shards over '
                f'{"x".join(batch_axes(mesh))}). Pad the batch or pick a batch size that '
                f'divides evenly — e.g. validate.py pads the final partial batch.')
        return jax.device_put(x, data_sharding(mesh, ndim=ndim))
    return jax.tree.map(put, batch)
