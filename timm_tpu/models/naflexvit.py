"""NaFlexVit — variable-resolution, sequence-packed ViT, TPU-native.

Re-designed from the reference (timm/models/naflexvit.py:59-2122). The
reference's variable shapes become **bucketed static shapes**: the loader
emits batches padded to a fixed seq-len bucket, so each bucket compiles once
and never again (XLA-friendly — see SURVEY §5 long-context notes).

Inputs are pre-patchified on the host:
  patches      (B, L, P*P*C) float
  patch_coord  (B, L, 2)     int (y, x) grid coords per token
  patch_valid  (B, L)        bool

Position embeddings are gather-based (factorized row+col tables or a 2D
learned grid indexed by coords) instead of the reference's per-sample
interpolation loops — same capability, no dynamic resize inside jit.

Contract parity: forward_features/forward_head/__call__,
get/reset_classifier, group_matcher, set_grad_checkpointing, no_weight_decay.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    Dropout, LayerNorm, Mlp, calculate_drop_path_rates, get_norm_layer,
    trunc_normal_, zeros_,
)
from ._builder import build_model_with_cfg
from ._registry import generate_default_cfgs, register_model
from .vision_transformer import Block

__all__ = ['NaFlexVit']


def create_attention_mask(patch_valid, num_prefix_tokens: int = 0, symmetric: bool = True, dtype=jnp.bool_):
    """Token-validity → attention mask (reference naflexvit.py:972).

    Returns (B, 1, L, L) bool when symmetric else key-only (B, 1, 1, L).
    """
    patch_valid = patch_valid.astype(jnp.bool_)  # tolerate uint8/int masks post-transfer
    B, L = patch_valid.shape
    if num_prefix_tokens:
        prefix = jnp.ones((B, num_prefix_tokens), jnp.bool_)
        patch_valid = jnp.concatenate([prefix, patch_valid], axis=1)
    if symmetric:
        mask = patch_valid[:, None, :, None] & patch_valid[:, None, None, :]
    else:
        mask = patch_valid[:, None, None, :]
    return mask


def global_pool_naflex(x, patch_valid, pool_type: str = 'avg', num_prefix_tokens: int = 0):
    """Masked pooling over valid tokens (reference naflexvit.py:1041)."""
    if pool_type == 'token':
        return x[:, 0]
    if num_prefix_tokens:
        x = x[:, num_prefix_tokens:]
    w = patch_valid.astype(x.dtype)[..., None]
    if pool_type == 'avg':
        return (x * w).sum(axis=1) / jnp.maximum(w.sum(axis=1), 1.0)
    if pool_type == 'max':
        neg = jnp.finfo(x.dtype).min
        return jnp.where(w > 0, x, neg).max(axis=1)
    raise ValueError(f'Unsupported NaFlex pool type {pool_type}')


class NaFlexEmbeds(nnx.Module):
    """Linear patch projection + gather-based pos embed
    (reference naflexvit.py:339)."""

    def __init__(
            self,
            patch_size: int = 16,
            in_chans: int = 3,
            embed_dim: int = 768,
            max_grid_size: int = 64,
            pos_embed: str = 'factorized',
            pos_drop_rate: float = 0.0,
            class_token: bool = False,
            reg_tokens: int = 0,
            norm_layer: Optional[Callable] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert pos_embed in ('factorized', 'learn', 'none')
        self.patch_size = patch_size
        self.in_chans = in_chans
        self.embed_dim = embed_dim
        self.max_grid_size = max_grid_size
        self.pos_embed_type = pos_embed
        self.num_prefix_tokens = (1 if class_token else 0) + reg_tokens
        self.num_reg_tokens = reg_tokens

        patch_dim = patch_size * patch_size * in_chans
        self.proj = nnx.Linear(
            patch_dim, embed_dim, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm = norm_layer(embed_dim, rngs=rngs) if norm_layer is not None else None

        self.cls_token = nnx.Param(jnp.zeros((1, 1, embed_dim), param_dtype)) if class_token else None
        self.reg_token = nnx.Param(
            trunc_normal_(std=0.02)(rngs.params(), (1, reg_tokens, embed_dim), param_dtype)) if reg_tokens else None

        if pos_embed == 'factorized':
            self.pos_embed_y = nnx.Param(
                trunc_normal_(std=0.02)(rngs.params(), (max_grid_size, embed_dim), param_dtype))
            self.pos_embed_x = nnx.Param(
                trunc_normal_(std=0.02)(rngs.params(), (max_grid_size, embed_dim), param_dtype))
            self.pos_embed_grid = None
        elif pos_embed == 'learn':
            self.pos_embed_grid = nnx.Param(
                trunc_normal_(std=0.02)(rngs.params(), (max_grid_size, max_grid_size, embed_dim), param_dtype))
            self.pos_embed_y = self.pos_embed_x = None
        else:
            self.pos_embed_grid = self.pos_embed_y = self.pos_embed_x = None
        self.pos_drop = Dropout(pos_drop_rate, rngs=rngs)

    def _proj(self, patches, patch_size: Optional[int]):
        if patch_size is None or patch_size == self.patch_size:
            return self.proj(patches)
        # variable patch size: PI-resample the projection kernel to the
        # batch's patch size at trace time (static per bucket — FlexiViT-style,
        # reference naflexvit.py resample_patch_embed path)
        from ..layers.patch_embed import resample_patch_embed
        P, C, D = self.patch_size, self.in_chans, self.embed_dim
        kernel = self.proj.kernel[...].reshape(P, P, C, D)
        kernel = resample_patch_embed(kernel, (patch_size, patch_size))
        kernel = kernel.reshape(patch_size * patch_size * C, D)
        y = patches @ kernel.astype(patches.dtype)
        if self.proj.bias is not None:
            y = y + self.proj.bias[...].astype(y.dtype)
        return y

    def __call__(self, patches, patch_coord, patch_size: Optional[int] = None):
        # patches (B, L, P*P*C), patch_coord (B, L, 2) int
        x = self._proj(patches, patch_size)
        B, L, D = x.shape
        yy = jnp.clip(patch_coord[..., 0], 0, self.max_grid_size - 1)
        xx = jnp.clip(patch_coord[..., 1], 0, self.max_grid_size - 1)
        if self.pos_embed_type == 'factorized':
            pos = jnp.take(self.pos_embed_y[...], yy, axis=0) + jnp.take(self.pos_embed_x[...], xx, axis=0)
            x = x + pos.astype(x.dtype)
        elif self.pos_embed_type == 'learn':
            pos = self.pos_embed_grid[...][yy, xx]
            x = x + pos.astype(x.dtype)

        to_cat = []
        if self.cls_token is not None:
            to_cat.append(jnp.broadcast_to(self.cls_token[...].astype(x.dtype), (B, 1, D)))
        if self.reg_token is not None:
            to_cat.append(jnp.broadcast_to(self.reg_token[...].astype(x.dtype), (B, self.num_reg_tokens, D)))
        if to_cat:
            x = jnp.concatenate(to_cat + [x], axis=1)
        if self.norm is not None:
            x = self.norm(x)
        return self.pos_drop(x)


class NaFlexVit(nnx.Module):
    def __init__(
            self,
            patch_size: int = 16,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            embed_dim: int = 768,
            depth: int = 12,
            num_heads: int = 12,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = True,
            qk_norm: bool = False,
            init_values: Optional[float] = None,
            class_token: bool = False,
            reg_tokens: int = 0,
            pos_embed: str = 'factorized',
            max_grid_size: int = 64,
            final_norm: bool = True,
            fc_norm: Optional[bool] = None,
            drop_rate: float = 0.0,
            pos_drop_rate: float = 0.0,
            proj_drop_rate: float = 0.0,
            attn_drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            norm_layer: Optional[Union[str, Callable]] = None,
            act_layer: Union[str, Callable] = 'gelu',
            block_fn: Callable = Block,
            mlp_layer: Callable = Mlp,
            mask_mode: str = 'symmetric',
            img_size=None,  # accepted for factory compatibility; unused
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert global_pool in ('', 'avg', 'max', 'token')
        assert not (global_pool == 'token' and not class_token)
        norm_layer = get_norm_layer(norm_layer) or LayerNorm
        self.num_classes = num_classes
        self.global_pool = global_pool
        self.num_features = self.head_hidden_size = self.embed_dim = embed_dim
        self.mask_mode = mask_mode  # 'symmetric' (full LxL) or 'key' (key-only)
        self.grad_checkpointing = False

        self.embeds = NaFlexEmbeds(
            patch_size=patch_size,
            in_chans=in_chans,
            embed_dim=embed_dim,
            max_grid_size=max_grid_size,
            pos_embed=pos_embed,
            pos_drop_rate=pos_drop_rate,
            class_token=class_token,
            reg_tokens=reg_tokens,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )
        self.num_prefix_tokens = self.embeds.num_prefix_tokens

        dpr = calculate_drop_path_rates(drop_path_rate, depth)
        self.blocks = nnx.List([
            block_fn(
                dim=embed_dim,
                num_heads=num_heads,
                mlp_ratio=mlp_ratio,
                qkv_bias=qkv_bias,
                qk_norm=qk_norm,
                init_values=init_values,
                proj_drop=proj_drop_rate,
                attn_drop=attn_drop_rate,
                drop_path=dpr[i],
                norm_layer=norm_layer,
                act_layer=act_layer,
                mlp_layer=mlp_layer,
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
            for i in range(depth)
        ])
        if fc_norm is None:
            fc_norm = global_pool == 'avg'
        self.norm = norm_layer(embed_dim, rngs=rngs) if final_norm and not fc_norm else None
        self.fc_norm = norm_layer(embed_dim, rngs=rngs) if final_norm and fc_norm else None
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.head = nnx.Linear(
            embed_dim, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'embeds.cls_token', 'embeds.reg_token', 'embeds.pos_embed_y',
                'embeds.pos_embed_x', 'embeds.pos_embed_grid'}

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^embeds',
            blocks=[(r'^blocks\.(\d+)', None), (r'^norm|^fc_norm', (99999,))],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.head = nnx.Linear(
            self.embed_dim, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def forward_features(self, patches, patch_coord, patch_valid=None, patch_size=None):
        x = self.embeds(patches, patch_coord, patch_size=patch_size)
        attn_mask = None
        if patch_valid is not None:
            attn_mask = create_attention_mask(
                patch_valid, num_prefix_tokens=self.num_prefix_tokens,
                symmetric=self.mask_mode == 'symmetric')
        for blk in self.blocks:
            x = blk(x, attn_mask=attn_mask)
        if self.norm is not None:
            x = self.norm(x)
        return x

    def forward_head(self, x, patch_valid=None, pre_logits: bool = False):
        if not self.global_pool:
            return x  # '' → unpooled tokens (matches global_pool_nlc contract)
        if patch_valid is None:
            # mask covers patch tokens only; prefix tokens are appended inside x
            patch_valid = jnp.ones((x.shape[0], x.shape[1] - self.num_prefix_tokens), jnp.bool_)
        x = global_pool_naflex(
            x, patch_valid, pool_type=self.global_pool,
            num_prefix_tokens=self.num_prefix_tokens)
        if self.fc_norm is not None:
            x = self.fc_norm(x)
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        return self.head(x)

    def __call__(self, patches, patch_coord=None, patch_valid=None):
        """Accepts either a NaFlex dict batch or (patches, coord, valid) arrays.

        For compatibility with image-tensor callers, a 4D NHWC input is
        patchified on the fly (all patches valid)."""
        if isinstance(patches, dict):
            d = patches
            patches, patch_coord, patch_valid = d['patches'], d['patch_coord'], d.get('patch_valid')
        elif patches.ndim == 4:
            patches, patch_coord, patch_valid = patchify_image(patches, self.embeds.patch_size)
        # variable patch size is derived STATICALLY from the patch dim (shape),
        # so each (seq_len, patch_size) bucket traces its own program — no
        # dependence on traced ints in the batch dict
        patch_size = None
        pd = patches.shape[-1]
        if pd != self.embeds.patch_size ** 2 * self.embeds.in_chans:
            import math as _math
            patch_size = int(_math.isqrt(pd // self.embeds.in_chans))
        x = self.forward_features(patches, patch_coord, patch_valid, patch_size=patch_size)
        return self.forward_head(x, patch_valid)

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        """Collect per-block token outputs; NHWC reshape only possible for
        image-tensor inputs (dict/pre-patchified callers get NLC)."""
        from ._features import feature_take_indices
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        grid = None
        if not isinstance(x, dict) and x.ndim == 4:
            B, H, W, _ = x.shape
            P = self.embeds.patch_size
            grid = (H // P, W // P)
            patches, patch_coord, patch_valid = patchify_image(x, P)
        elif isinstance(x, dict):
            patches, patch_coord, patch_valid = x['patches'], x['patch_coord'], x.get('patch_valid')
        else:
            raise ValueError('forward_intermediates expects an NHWC image or a NaFlex dict')
        if output_fmt == 'NHWC' and grid is None:
            output_fmt = 'NLC'

        tokens = self.embeds(patches, patch_coord)
        attn_mask = None
        if patch_valid is not None:
            attn_mask = create_attention_mask(
                patch_valid, num_prefix_tokens=self.num_prefix_tokens,
                symmetric=self.mask_mode == 'symmetric')
        intermediates = []
        blocks = self.blocks if not stop_early else list(self.blocks)[:max_index + 1]
        for i, blk in enumerate(blocks):
            tokens = blk(tokens, attn_mask=attn_mask)
            if i in take_indices:
                y = self.norm(tokens) if (norm and self.norm is not None) else tokens
                y = y[:, self.num_prefix_tokens:]
                if output_fmt == 'NHWC':
                    y = y.reshape(y.shape[0], grid[0], grid[1], -1)
                intermediates.append(y)
        if intermediates_only:
            return intermediates
        if self.norm is not None:
            tokens = self.norm(tokens)
        return tokens, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        from ._features import feature_take_indices
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        self.blocks = nnx.List(list(self.blocks)[:max_index + 1])
        if prune_norm:
            self.norm = None
        if prune_head:
            self.fc_norm = None
            self.reset_classifier(0)
        return take_indices


def patchify_image(x, patch_size: int):
    """NHWC image → (patches, coords, valid) (reference naflex_transforms.py:751)."""
    B, H, W, C = x.shape
    P = patch_size
    gh, gw = H // P, W // P
    x = x[:, :gh * P, :gw * P]
    x = x.reshape(B, gh, P, gw, P, C).transpose(0, 1, 3, 2, 4, 5).reshape(B, gh * gw, P * P * C)
    yy, xx = jnp.meshgrid(jnp.arange(gh), jnp.arange(gw), indexing='ij')
    coord = jnp.stack([yy, xx], axis=-1).reshape(1, gh * gw, 2)
    coord = jnp.broadcast_to(coord, (B, gh * gw, 2))
    valid = jnp.ones((B, gh * gw), jnp.bool_)
    return x, coord, valid


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 384, 384),
        'pool_size': None,
        'crop_pct': 1.0,
        'interpolation': 'bicubic',
        'mean': (0.5, 0.5, 0.5),
        'std': (0.5, 0.5, 0.5),
        'first_conv': 'embeds.proj',
        'classifier': 'head',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'naflexvit_base_patch16_gap.e300_s576_in1k': _cfg(hf_hub_id='timm/'),
    'naflexvit_base_patch16_par_gap.e300_s576_in1k': _cfg(hf_hub_id='timm/'),
    'naflexvit_base_patch16_map.untrained': _cfg(),
    'naflexvit_so150m2_patch16_reg1_gap.untrained': _cfg(),
    'test_naflexvit.untrained': _cfg(input_size=(3, 160, 160)),
})


def _create_naflexvit(variant: str, pretrained: bool = False, **kwargs) -> NaFlexVit:
    from ._torch_convert import convert_torch_state_dict
    return build_model_with_cfg(
        NaFlexVit, variant, pretrained,
        pretrained_filter_fn=convert_torch_state_dict,
        **kwargs,
    )


@register_model
def naflexvit_base_patch16_gap(pretrained=False, **kwargs) -> NaFlexVit:
    """ViT-B/16 NaFlex w/ global average pooling."""
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, global_pool='avg',
        pos_embed='factorized', reg_tokens=0)
    return _create_naflexvit('naflexvit_base_patch16_gap', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def naflexvit_base_patch16_par_gap(pretrained=False, **kwargs) -> NaFlexVit:
    """ViT-B/16 NaFlex w/ patch-aspect-ratio training + GAP (reference cfg)."""
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, global_pool='avg',
        pos_embed='factorized', reg_tokens=0)
    return _create_naflexvit('naflexvit_base_patch16_par_gap', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def naflexvit_base_patch16_map(pretrained=False, **kwargs) -> NaFlexVit:
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, global_pool='avg',
        pos_embed='factorized', reg_tokens=1)
    return _create_naflexvit('naflexvit_base_patch16_map', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def naflexvit_so150m2_patch16_reg1_gap(pretrained=False, **kwargs) -> NaFlexVit:
    model_args = dict(
        patch_size=16, embed_dim=832, depth=21, num_heads=13, mlp_ratio=34 / 8,
        global_pool='avg', pos_embed='factorized', reg_tokens=1, qkv_bias=False)
    return _create_naflexvit('naflexvit_so150m2_patch16_reg1_gap', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_naflexvit(pretrained=False, **kwargs) -> NaFlexVit:
    model_args = dict(
        patch_size=16, embed_dim=64, depth=2, num_heads=2, mlp_ratio=3,
        global_pool='avg', pos_embed='factorized', max_grid_size=24)
    return _create_naflexvit('test_naflexvit', pretrained=pretrained, **dict(model_args, **kwargs))
