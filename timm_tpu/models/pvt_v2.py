"""Pyramid Vision Transformer v2 (reference: timm/models/pvt_v2.py:1-594),
TPU-native NHWC/NLC.

Overlapping patch embeds between stages, spatial-reduction (strided-conv or
adaptive-pool 'linear') attention on flattened tokens, and an MLP with a
depthwise 3x3 conv between fc1 and the activation. Tokens stay NLC; the dw
conv reshapes to NHWC with static feat sizes, so everything compiles to fixed
shapes.
"""
from __future__ import annotations

import math
from typing import Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    DropPath, LayerNorm, calculate_drop_path_rates, create_conv2d, get_act_fn,
    scaled_dot_product_attention, to_2tuple, to_ntuple, trunc_normal_, zeros_,
)
from ..layers.drop import Dropout
from ._builder import build_model_with_cfg
from ._manipulate import (
    BlockStackError, resolve_stage_scan, scan_stage_stack, warn_scan_fallback,
)
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model

__all__ = ['PyramidVisionTransformerV2']


def _adaptive_avg_pool(x, out_size: int):
    """NHWC adaptive average pool to (out, out) with torch's bin edges."""
    B, H, W, C = x.shape
    if H % out_size == 0 and W % out_size == 0:
        kh, kw = H // out_size, W // out_size
        out = jax.lax.reduce_window(
            x, 0.0, jax.lax.add, (1, kh, kw, 1), (1, kh, kw, 1), 'VALID')
        return out / (kh * kw)
    rows = []
    for i in range(out_size):
        h0, h1 = (i * H) // out_size, -(-((i + 1) * H) // out_size)
        cols = []
        for j in range(out_size):
            w0, w1 = (j * W) // out_size, -(-((j + 1) * W) // out_size)
            cols.append(x[:, h0:h1, w0:w1].mean(axis=(1, 2)))
        rows.append(jnp.stack(cols, axis=1))
    return jnp.stack(rows, axis=1)


class MlpWithDepthwiseConv(nnx.Module):
    """fc1 → (relu) → dw3x3 → act → fc2 (reference pvt_v2.py:27-60)."""

    def __init__(self, in_features, hidden_features=None, out_features=None,
                 act_layer='gelu', drop=0.0, extra_relu=False,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        out_features = out_features or in_features
        hidden_features = hidden_features or in_features
        linear = lambda i, o: nnx.Linear(
            i, o, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.fc1 = linear(in_features, hidden_features)
        self.extra_relu = extra_relu
        self.dwconv = create_conv2d(
            hidden_features, hidden_features, 3, padding=1, depthwise=True, bias=True,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.act = get_act_fn(act_layer)
        self.fc2 = linear(hidden_features, out_features)
        self.drop = Dropout(drop, rngs=rngs)

    def __call__(self, x, feat_size):
        x = self.fc1(x)
        B, N, C = x.shape
        x = x.reshape(B, feat_size[0], feat_size[1], C)
        if self.extra_relu:
            x = jax.nn.relu(x)
        x = self.dwconv(x).reshape(B, N, C)
        x = self.drop(self.act(x))
        return self.drop(self.fc2(x))


class PvtAttention(nnx.Module):
    """Spatial-reduction attention (reference pvt_v2.py:62-134): kv come from
    a strided-conv (sr_ratio) or adaptive-pool-7 ('linear') reduced map."""

    def __init__(self, dim, num_heads=8, sr_ratio=1, linear_attn=False, qkv_bias=True,
                 attn_drop=0.0, proj_drop=0.0, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        assert dim % num_heads == 0
        self.dim = dim
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.scale = self.head_dim ** -0.5
        self.linear_attn = linear_attn
        self.sr_ratio = sr_ratio
        linear = lambda i, o, b=True: nnx.Linear(
            i, o, use_bias=b, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.q = linear(dim, dim, qkv_bias)
        self.kv = linear(dim, dim * 2, qkv_bias)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.proj = linear(dim, dim)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        if not linear_attn:
            if sr_ratio > 1:
                self.sr = create_conv2d(dim, dim, sr_ratio, stride=sr_ratio, padding=0, bias=True, **kw)
                self.norm = LayerNorm(dim, eps=1e-5, rngs=rngs)
            else:
                self.sr = None
                self.norm = None
        else:
            self.sr = create_conv2d(dim, dim, 1, stride=1, padding=0, bias=True, **kw)
            self.norm = LayerNorm(dim, eps=1e-5, rngs=rngs)

    def __call__(self, x, feat_size):
        B, N, C = x.shape
        H, W = feat_size
        q = self.q(x).reshape(B, N, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        if self.linear_attn:
            xs = _adaptive_avg_pool(x.reshape(B, H, W, C), 7)
            xs = self.sr(xs).reshape(B, -1, C)
            xs = jax.nn.gelu(self.norm(xs), approximate=False)
            kv_in = xs
        elif self.sr is not None:
            xs = self.sr(x.reshape(B, H, W, C)).reshape(B, -1, C)
            kv_in = self.norm(xs)
        else:
            kv_in = x
        kv = self.kv(kv_in).reshape(B, -1, 2, self.num_heads, self.head_dim).transpose(2, 0, 3, 1, 4)
        k, v = kv[0], kv[1]
        from ..layers.drop import dropout_rng_key
        dropout_p = 0.0 if self.attn_drop.deterministic else self.attn_drop.rate
        dropout_key = dropout_rng_key(self.attn_drop) if dropout_p > 0.0 else None
        x = scaled_dot_product_attention(
            q, k, v, dropout_p=dropout_p, dropout_key=dropout_key, scale=self.scale)
        x = x.transpose(0, 2, 1, 3).reshape(B, N, C)
        return self.proj_drop(self.proj(x))


class PvtBlock(nnx.Module):
    def __init__(self, dim, num_heads, mlp_ratio=4.0, sr_ratio=1, linear_attn=False,
                 qkv_bias=False, proj_drop=0.0, attn_drop=0.0, drop_path=0.0,
                 act_layer='gelu', norm_layer=LayerNorm,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm1 = norm_layer(dim, rngs=rngs)
        self.attn = PvtAttention(
            dim, num_heads=num_heads, sr_ratio=sr_ratio, linear_attn=linear_attn,
            qkv_bias=qkv_bias, attn_drop=attn_drop, proj_drop=proj_drop, **kw)
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(dim, rngs=rngs)
        self.mlp = MlpWithDepthwiseConv(
            dim, int(dim * mlp_ratio), act_layer=act_layer, drop=proj_drop,
            extra_relu=linear_attn, **kw)
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

    def __call__(self, x, feat_size):
        x = x + self.drop_path1(self.attn(self.norm1(x), feat_size))
        x = x + self.drop_path2(self.mlp(self.norm2(x), feat_size))
        return x


class OverlapPatchEmbed(nnx.Module):
    """(reference pvt_v2.py:178-204)."""

    def __init__(self, patch_size=7, stride=4, in_chans=3, embed_dim=768,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        patch_size = to_2tuple(patch_size)
        assert max(patch_size) > stride
        self.proj = create_conv2d(
            in_chans, embed_dim, patch_size, stride=stride,
            padding=patch_size[0] // 2, bias=True,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm = LayerNorm(embed_dim, eps=1e-5, rngs=rngs)

    def __call__(self, x):
        return self.norm(self.proj(x))


class PvtStage(nnx.Module):
    """(reference pvt_v2.py:206-266)."""

    def __init__(self, dim, dim_out, depth, downsample=True, num_heads=8, sr_ratio=1,
                 linear_attn=False, mlp_ratio=4.0, qkv_bias=True, proj_drop=0.0,
                 attn_drop=0.0, drop_path=0.0, norm_layer=LayerNorm,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.grad_checkpointing = False
        self.stage_scan = False
        if downsample:
            self.downsample = OverlapPatchEmbed(
                patch_size=3, stride=2, in_chans=dim, embed_dim=dim_out, **kw)
        else:
            assert dim == dim_out
            self.downsample = None
        self.blocks = nnx.List([
            PvtBlock(
                dim=dim_out, num_heads=num_heads, sr_ratio=sr_ratio, linear_attn=linear_attn,
                mlp_ratio=mlp_ratio, qkv_bias=qkv_bias, proj_drop=proj_drop,
                attn_drop=attn_drop,
                drop_path=drop_path[i] if isinstance(drop_path, (list, tuple)) else drop_path,
                norm_layer=norm_layer, **kw)
            for i in range(depth)])
        self.norm = norm_layer(dim_out, rngs=rngs)

    def __call__(self, x):
        if self.downsample is not None:
            x = self.downsample(x)
        B, H, W, C = x.shape
        feat_size = (H, W)
        x = x.reshape(B, -1, C)
        if self.stage_scan:
            try:
                x = scan_stage_stack(
                    self.blocks, x,
                    call_block=lambda blk, xx: blk(xx, feat_size),
                    remat=self.grad_checkpointing)
                x = self.norm(x)
                return x.reshape(B, H, W, -1)
            except BlockStackError as e:
                warn_scan_fallback(type(self).__name__, e, what='stage_scan')
        if self.grad_checkpointing:
            def run_block(blk, x_, fs):
                return blk(x_, fs)
            remat_block = nnx.remat(run_block, static_argnums=(2,))
            for blk in self.blocks:
                x = remat_block(blk, x, feat_size)
        else:
            for blk in self.blocks:
                x = blk(x, feat_size)
        x = self.norm(x)
        return x.reshape(B, H, W, -1)


class PyramidVisionTransformerV2(nnx.Module):
    """(reference pvt_v2.py:268-434)."""

    def __init__(
            self,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            depths: Tuple[int, ...] = (3, 4, 6, 3),
            embed_dims: Tuple[int, ...] = (64, 128, 256, 512),
            num_heads: Tuple[int, ...] = (1, 2, 4, 8),
            sr_ratios: Tuple[int, ...] = (8, 4, 2, 1),
            mlp_ratios=(8.0, 8.0, 4.0, 4.0),
            qkv_bias: bool = True,
            linear: bool = False,
            drop_rate: float = 0.0,
            proj_drop_rate: float = 0.0,
            attn_drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            norm_layer: Callable = LayerNorm,
            stage_scan: Optional[bool] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert global_pool in ('avg', '')
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.num_classes = num_classes
        self.global_pool = global_pool
        self.depths = depths
        num_stages = len(depths)
        mlp_ratios = to_ntuple(num_stages)(mlp_ratios)
        num_heads = to_ntuple(num_stages)(num_heads)
        sr_ratios = to_ntuple(num_stages)(sr_ratios)
        assert len(embed_dims) == num_stages
        self.feature_info = []

        self.patch_embed = OverlapPatchEmbed(
            patch_size=7, stride=4, in_chans=in_chans, embed_dim=embed_dims[0], **kw)

        dpr = calculate_drop_path_rates(drop_path_rate, depths, stagewise=True)
        prev_dim = embed_dims[0]
        stages = []
        for i in range(num_stages):
            stages.append(PvtStage(
                dim=prev_dim, dim_out=embed_dims[i], depth=depths[i], downsample=i > 0,
                num_heads=num_heads[i], sr_ratio=sr_ratios[i], mlp_ratio=mlp_ratios[i],
                linear_attn=linear, qkv_bias=qkv_bias, proj_drop=proj_drop_rate,
                attn_drop=attn_drop_rate, drop_path=dpr[i], norm_layer=norm_layer, **kw))
            prev_dim = embed_dims[i]
            self.feature_info += [dict(num_chs=prev_dim, reduction=4 * 2 ** i, module=f'stages.{i}')]
        self.stages = nnx.List(stages)
        self.set_stage_scan(resolve_stage_scan(stage_scan))

        self.num_features = self.head_hidden_size = embed_dims[-1]
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.head = nnx.Linear(
            embed_dims[-1], num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            **kw) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(stem=r'^patch_embed', blocks=r'^stages\.(\d+)')

    def set_grad_checkpointing(self, enable: bool = True):
        for s in self.stages:
            s.grad_checkpointing = enable

    def set_stage_scan(self, enable: bool = True):
        for s in self.stages:
            s.stage_scan = enable

    # stage scan IS this family's scan-over-layers: generic machinery that
    # toggles `set_block_scan` (bench replay, probes) reaches it too
    set_block_scan = set_stage_scan

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            assert global_pool in ('avg', '')
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.head = nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.patch_embed(x)
        for stage in self.stages:
            x = stage(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        if self.global_pool:
            x = x.mean(axis=(1, 2))
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        return self.head(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        x = self.patch_embed(x)
        intermediates = []
        stages = self.stages if not stop_early else list(self.stages)[:max_index + 1]
        for i, stage in enumerate(stages):
            x = stage(x)
            if i in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        self.stages = nnx.List(list(self.stages)[:max_index + 1])
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    """Remap original PVT checkpoints → timm layout, then torch→nnx
    (reference pvt_v2.py:436-452)."""
    import re

    from ._torch_convert import convert_torch_state_dict
    if 'patch_embed.proj.weight' not in state_dict:
        out = {}
        for k, v in state_dict.items():
            if k.startswith('patch_embed'):
                k = k.replace('patch_embed1', 'patch_embed')
                k = k.replace('patch_embed2', 'stages.1.downsample')
                k = k.replace('patch_embed3', 'stages.2.downsample')
                k = k.replace('patch_embed4', 'stages.3.downsample')
            k = k.replace('dwconv.dwconv', 'dwconv')
            k = re.sub(r'block(\d+).(\d+)', lambda x: f'stages.{int(x.group(1)) - 1}.blocks.{x.group(2)}', k)
            k = re.sub(r'^norm(\d+)', lambda x: f'stages.{int(x.group(1)) - 1}.norm', k)
            out[k] = v
        state_dict = out
    state_dict = {k.replace('.mlp.dwconv.dwconv.', '.mlp.dwconv.'): v for k, v in state_dict.items()}
    return convert_torch_state_dict(state_dict, model)


def _create_pvt2(variant, pretrained=False, **kwargs):
    out_indices = kwargs.pop('out_indices', (0, 1, 2, 3))
    return build_model_with_cfg(
        PyramidVisionTransformerV2, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


def _cfg(url='', **kwargs):
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.9, 'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'patch_embed.proj', 'classifier': 'head', 'fixed_input_size': False,
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'pvt_v2_b0.in1k': _cfg(hf_hub_id='timm/'),
    'pvt_v2_b1.in1k': _cfg(hf_hub_id='timm/'),
    'pvt_v2_b2.in1k': _cfg(hf_hub_id='timm/'),
    'pvt_v2_b3.in1k': _cfg(hf_hub_id='timm/'),
    'pvt_v2_b4.in1k': _cfg(hf_hub_id='timm/'),
    'pvt_v2_b5.in1k': _cfg(hf_hub_id='timm/'),
    'pvt_v2_b2_li.in1k': _cfg(hf_hub_id='timm/'),
})


@register_model
def pvt_v2_b0(pretrained=False, **kwargs) -> PyramidVisionTransformerV2:
    model_args = dict(depths=(2, 2, 2, 2), embed_dims=(32, 64, 160, 256), num_heads=(1, 2, 5, 8))
    return _create_pvt2('pvt_v2_b0', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def pvt_v2_b1(pretrained=False, **kwargs) -> PyramidVisionTransformerV2:
    model_args = dict(depths=(2, 2, 2, 2), embed_dims=(64, 128, 320, 512), num_heads=(1, 2, 5, 8))
    return _create_pvt2('pvt_v2_b1', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def pvt_v2_b2(pretrained=False, **kwargs) -> PyramidVisionTransformerV2:
    model_args = dict(depths=(3, 4, 6, 3), embed_dims=(64, 128, 320, 512), num_heads=(1, 2, 5, 8))
    return _create_pvt2('pvt_v2_b2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def pvt_v2_b3(pretrained=False, **kwargs) -> PyramidVisionTransformerV2:
    model_args = dict(depths=(3, 4, 18, 3), embed_dims=(64, 128, 320, 512), num_heads=(1, 2, 5, 8))
    return _create_pvt2('pvt_v2_b3', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def pvt_v2_b4(pretrained=False, **kwargs) -> PyramidVisionTransformerV2:
    model_args = dict(depths=(3, 8, 27, 3), embed_dims=(64, 128, 320, 512), num_heads=(1, 2, 5, 8))
    return _create_pvt2('pvt_v2_b4', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def pvt_v2_b5(pretrained=False, **kwargs) -> PyramidVisionTransformerV2:
    model_args = dict(
        depths=(3, 6, 40, 3), embed_dims=(64, 128, 320, 512), num_heads=(1, 2, 5, 8), mlp_ratios=(4, 4, 4, 4))
    return _create_pvt2('pvt_v2_b5', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def pvt_v2_b2_li(pretrained=False, **kwargs) -> PyramidVisionTransformerV2:
    model_args = dict(
        depths=(3, 4, 6, 3), embed_dims=(64, 128, 320, 512), num_heads=(1, 2, 5, 8), linear=True)
    return _create_pvt2('pvt_v2_b2_li', pretrained=pretrained, **dict(model_args, **kwargs))
