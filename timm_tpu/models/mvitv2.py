"""MViTv2: Improved Multiscale Vision Transformers, TPU-native
(reference: timm/models/mvitv2.py:1-1160; Li et al. 2022).

A pooling-attention pyramid: q/k/v are depthwise-conv-pooled inside
attention, queries shrink the resolution at stage starts, and a decomposed
(row + column) relative position bias is added to the logits. TPU-first
notes: feature sizes are static python ints threaded through the stage loop
(no dynamic shapes under jit); the rel-pos gather indices are trace-time
numpy constants; the cls-token bias row/col is handled by zero-padding the
decomposed bias rather than in-place slice assignment.

`pool_first` (MViT-v1 ordering) is not implemented — no v2 config uses it.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from flax import nnx

from ..layers import (
    Dropout, DropPath, LayerNorm, Mlp, to_2tuple, trunc_normal_tf_, zeros_,
    calculate_drop_path_rates,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model

__all__ = ['MultiScaleVit', 'MultiScaleVitCfg']


@dataclass
class MultiScaleVitCfg:
    """Config schema kept field-compatible with the reference
    (mvitv2.py:37-83) so recipes transfer."""
    depths: Tuple[int, ...] = (2, 3, 16, 3)
    embed_dim: Union[int, Tuple[int, ...]] = 96
    num_heads: Union[int, Tuple[int, ...]] = 1
    mlp_ratio: float = 4.0
    pool_first: bool = False
    expand_attn: bool = True
    qkv_bias: bool = True
    use_cls_token: bool = False
    use_abs_pos: bool = False
    residual_pooling: bool = True
    mode: str = 'conv'
    kernel_qkv: Tuple[int, int] = (3, 3)
    stride_q: Optional[Tuple[Tuple[int, int], ...]] = ((1, 1), (2, 2), (2, 2), (2, 2))
    stride_kv: Optional[Tuple[Tuple[int, int], ...]] = None
    stride_kv_adaptive: Optional[Tuple[int, int]] = (4, 4)
    patch_kernel: Tuple[int, int] = (7, 7)
    patch_stride: Tuple[int, int] = (4, 4)
    patch_padding: Tuple[int, int] = (3, 3)
    pool_type: str = 'max'
    rel_pos_type: str = 'spatial'
    act_layer: Union[str, Tuple[str, str]] = 'gelu'
    norm_layer: Union[str, Tuple[str, str]] = 'layernorm'
    norm_eps: float = 1e-6

    def __post_init__(self):
        num_stages = len(self.depths)
        if not isinstance(self.embed_dim, (tuple, list)):
            self.embed_dim = tuple(self.embed_dim * 2 ** i for i in range(num_stages))
        assert len(self.embed_dim) == num_stages
        if not isinstance(self.num_heads, (tuple, list)):
            self.num_heads = tuple(self.num_heads * 2 ** i for i in range(num_stages))
        assert len(self.num_heads) == num_stages
        if self.stride_kv_adaptive is not None and self.stride_kv is None:
            _stride_kv = self.stride_kv_adaptive
            pool_kv_stride = []
            for i in range(num_stages):
                if min(self.stride_q[i]) > 1:
                    _stride_kv = [max(_stride_kv[d] // self.stride_q[i][d], 1)
                                  for d in range(len(_stride_kv))]
                pool_kv_stride.append(tuple(_stride_kv))
            self.stride_kv = tuple(pool_kv_stride)


def _rel_pos_dist_idx(q_size: int, k_size: int) -> np.ndarray:
    """Static (q, k) index into a rel-pos table (reference cal_rel_pos_type
    distance computation, mvitv2.py:152-185)."""
    q_ratio = max(k_size / q_size, 1.0)
    k_ratio = max(q_size / k_size, 1.0)
    dist = (np.arange(q_size)[:, None] * q_ratio - np.arange(k_size)[None, :] * k_ratio)
    dist += (k_size - 1) * k_ratio
    return dist.astype(np.int64)


class MultiScalePatchEmbed(nnx.Module):
    """Overlapping conv patch embed (reference mvitv2.py:89-121)."""

    def __init__(self, dim_in=3, dim_out=768, kernel=(7, 7), stride=(4, 4), padding=(3, 3),
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.proj = nnx.Conv(
            dim_in, dim_out, kernel_size=kernel, strides=stride,
            padding=[(padding[0], padding[0]), (padding[1], padding[1])],
            kernel_init=trunc_normal_tf_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        x = self.proj(x)
        B, H, W, C = x.shape
        return x.reshape(B, H * W, C), (H, W)


def _pool_tokens(x, pool_fn, feat_size, num_heads, has_cls):
    """(B, heads, N, d) → pooled (B, heads, N', d) + new feat size."""
    H, W = feat_size
    if has_cls:
        cls_tok, x = x[:, :, :1], x[:, :, 1:]
    else:
        cls_tok = None
    B, nh, N, d = x.shape
    x = x.reshape(B * nh, H, W, d)
    x = pool_fn(x)
    Hp, Wp = x.shape[1], x.shape[2]
    x = x.reshape(B, nh, Hp * Wp, d)
    if cls_tok is not None:
        x = jnp.concatenate([cls_tok, x], axis=2)
    return x, (Hp, Wp)


class MultiScaleAttention(nnx.Module):
    """Pooling attention w/ decomposed rel-pos bias (reference mvitv2.py:378-540)."""

    def __init__(
            self, dim, dim_out, feat_size, num_heads=8, qkv_bias=True, mode='conv',
            kernel_q=(1, 1), kernel_kv=(1, 1), stride_q=(1, 1), stride_kv=(1, 1),
            has_cls_token=True, rel_pos_type='spatial', residual_pooling=True,
            norm_layer: Callable = LayerNorm,
            *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.num_heads = num_heads
        self.dim_out = dim_out
        self.head_dim = dim_out // num_heads
        self.scale = self.head_dim ** -0.5
        self.has_cls_token = has_cls_token
        padding_q = tuple(int(q // 2) for q in kernel_q)
        padding_kv = tuple(int(kv // 2) for kv in kernel_kv)

        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_tf_(std=0.02), bias_init=zeros_, rngs=rngs)
        self.qkv = linear(dim, dim_out * 3, use_bias=qkv_bias)
        self.proj = linear(dim_out, dim_out)

        import math
        if math.prod(kernel_q) == 1 and math.prod(stride_q) == 1:
            kernel_q = None
        if math.prod(kernel_kv) == 1 and math.prod(stride_kv) == 1:
            kernel_kv = None
        self.mode = mode
        norm_q = norm_k = norm_v = None
        pool_q = pool_k = pool_v = None
        if mode in ('avg', 'max'):
            if kernel_q:
                pool_q = _MaxAvgPool(kernel_q, stride_q, padding_q, mode)
            if kernel_kv:
                pool_k = _MaxAvgPool(kernel_kv, stride_kv, padding_kv, mode)
                pool_v = _MaxAvgPool(kernel_kv, stride_kv, padding_kv, mode)
        elif mode == 'conv':
            dim_conv = dim_out // num_heads
            conv = partial(
                nnx.Conv, use_bias=False, feature_group_count=dim_conv,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            if kernel_q:
                pool_q = conv(dim_conv, dim_conv, kernel_size=kernel_q, strides=stride_q,
                              padding=[(padding_q[0], padding_q[0]), (padding_q[1], padding_q[1])])
                norm_q = norm_layer(dim_conv, rngs=rngs)
            if kernel_kv:
                pool_k = conv(dim_conv, dim_conv, kernel_size=kernel_kv, strides=stride_kv,
                              padding=[(padding_kv[0], padding_kv[0]), (padding_kv[1], padding_kv[1])])
                norm_k = norm_layer(dim_conv, rngs=rngs)
                pool_v = conv(dim_conv, dim_conv, kernel_size=kernel_kv, strides=stride_kv,
                              padding=[(padding_kv[0], padding_kv[0]), (padding_kv[1], padding_kv[1])])
                norm_v = norm_layer(dim_conv, rngs=rngs)
        else:
            raise NotImplementedError(f'Unsupported mode {mode} (pool_first/conv_unshared not used by v2 cfgs)')
        self.pool_q, self.pool_k, self.pool_v = pool_q, pool_k, pool_v
        self.norm_q, self.norm_k, self.norm_v = norm_q, norm_k, norm_v

        self.rel_pos_type = rel_pos_type
        if rel_pos_type == 'spatial':
            assert feat_size[0] == feat_size[1]
            size = feat_size[0]
            q_size = size // stride_q[1] if len(stride_q) > 0 else size
            kv_size = size // stride_kv[1] if len(stride_kv) > 0 else size
            rel_sp_dim = 2 * max(q_size, kv_size) - 1
            self.rel_pos_h = nnx.Param(
                trunc_normal_tf_(std=0.02)(rngs.params(), (rel_sp_dim, self.head_dim), param_dtype))
            self.rel_pos_w = nnx.Param(
                trunc_normal_tf_(std=0.02)(rngs.params(), (rel_sp_dim, self.head_dim), param_dtype))
        self.residual_pooling = residual_pooling

    def _rel_pos_bias(self, q, q_size, k_size):
        """Decomposed spatial rel-pos bias (reference cal_rel_pos_type)."""
        sp = 1 if self.has_cls_token else 0
        q_h, q_w = q_size
        k_h, k_w = k_size
        idx_h = jnp.asarray(_rel_pos_dist_idx(q_h, k_h))
        idx_w = jnp.asarray(_rel_pos_dist_idx(q_w, k_w))
        rel_h = self.rel_pos_h[...][idx_h]  # (q_h, k_h, d)
        rel_w = self.rel_pos_w[...][idx_w]  # (q_w, k_w, d)
        B, nh, _, d = q.shape
        r_q = q[:, :, sp:].reshape(B, nh, q_h, q_w, d)
        bh = jnp.einsum('byhwc,hkc->byhwk', r_q, rel_h.astype(q.dtype))
        bw = jnp.einsum('byhwc,wkc->byhwk', r_q, rel_w.astype(q.dtype))
        bias = bh[..., :, None] + bw[..., None, :]  # (B, nh, q_h, q_w, k_h, k_w)
        bias = bias.reshape(B, nh, q_h * q_w, k_h * k_w)
        if sp:
            bias = jnp.pad(bias, ((0, 0), (0, 0), (1, 0), (1, 0)))
        return bias

    def __call__(self, x, feat_size):
        B, N, _ = x.shape
        qkv = self.qkv(x).reshape(B, N, 3, self.num_heads, -1).transpose(2, 0, 3, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]

        if self.pool_q is not None:
            q, q_size = _pool_tokens(q, self.pool_q, feat_size, self.num_heads, self.has_cls_token)
        else:
            q_size = feat_size
        if self.norm_q is not None:
            q = self.norm_q(q)
        if self.pool_k is not None:
            k, k_size = _pool_tokens(k, self.pool_k, feat_size, self.num_heads, self.has_cls_token)
        else:
            k_size = feat_size
        if self.norm_k is not None:
            k = self.norm_k(k)
        if self.pool_v is not None:
            v, _ = _pool_tokens(v, self.pool_v, feat_size, self.num_heads, self.has_cls_token)
        if self.norm_v is not None:
            v = self.norm_v(v)

        attn = jnp.einsum('bhnd,bhmd->bhnm', q * self.scale, k)
        if self.rel_pos_type == 'spatial':
            attn = attn + self._rel_pos_bias(q, q_size, k_size)
        attn = jax.nn.softmax(attn, axis=-1)
        x = jnp.einsum('bhnm,bhmd->bhnd', attn, v)
        if self.residual_pooling:
            x = x + q
        x = x.transpose(0, 2, 1, 3).reshape(B, -1, self.dim_out)
        return self.proj(x), q_size


class _MaxAvgPool:
    """SAME-style torch-padded max/avg pool over NHWC (static shapes)."""

    def __init__(self, kernel, stride, padding, mode):
        self.kernel = kernel
        self.stride = stride
        self.padding = padding
        self.mode = mode

    def __call__(self, x):
        pads = ((0, 0), (self.padding[0], self.padding[0]), (self.padding[1], self.padding[1]), (0, 0))
        if self.mode == 'max':
            init = -jnp.inf
            x = jax.lax.reduce_window(
                jnp.pad(x, pads, constant_values=-jnp.inf), init, jax.lax.max,
                (1, self.kernel[0], self.kernel[1], 1), (1, self.stride[0], self.stride[1], 1), 'VALID')
            return x
        x = jax.lax.reduce_window(
            jnp.pad(x, pads), 0.0, jax.lax.add,
            (1, self.kernel[0], self.kernel[1], 1), (1, self.stride[0], self.stride[1], 1), 'VALID')
        return x / (self.kernel[0] * self.kernel[1])


class MultiScaleBlock(nnx.Module):
    """Pooling-attention block w/ pooled shortcut (reference mvitv2.py:537-639)."""

    def __init__(
            self, dim, dim_out, num_heads, feat_size, mlp_ratio=4.0, qkv_bias=True,
            drop_path=0.0, norm_layer: Callable = LayerNorm, kernel_q=(1, 1), kernel_kv=(1, 1),
            stride_q=(1, 1), stride_kv=(1, 1), mode='conv', has_cls_token=True,
            expand_attn=False, rel_pos_type='spatial', residual_pooling=True,
            *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        import math
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        proj_needed = dim != dim_out
        self.dim = dim
        self.dim_out = dim_out
        self.has_cls_token = has_cls_token

        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_tf_(std=0.02), bias_init=zeros_, rngs=rngs)
        self.norm1 = norm_layer(dim, rngs=rngs)
        self.shortcut_proj_attn = linear(dim, dim_out) if proj_needed and expand_attn else None
        if stride_q and math.prod(stride_q) > 1:
            kernel_skip = tuple(s + 1 if s > 1 else s for s in stride_q)
            padding_skip = tuple(int(k // 2) for k in kernel_skip)
            self.shortcut_pool_attn = _MaxAvgPool(kernel_skip, stride_q, padding_skip, 'max')
        else:
            self.shortcut_pool_attn = None

        att_dim = dim_out if expand_attn else dim
        self.attn = MultiScaleAttention(
            dim, att_dim, num_heads=num_heads, feat_size=feat_size, qkv_bias=qkv_bias,
            kernel_q=kernel_q, kernel_kv=kernel_kv, stride_q=stride_q, stride_kv=stride_kv,
            norm_layer=norm_layer, has_cls_token=has_cls_token, mode=mode,
            rel_pos_type=rel_pos_type, residual_pooling=residual_pooling, **kw)
        self.drop_path1 = DropPath(drop_path, rngs=rngs)

        self.norm2 = norm_layer(att_dim, rngs=rngs)
        self.shortcut_proj_mlp = linear(dim, dim_out) if proj_needed and not expand_attn else None
        self.mlp = Mlp(att_dim, hidden_features=int(att_dim * mlp_ratio), out_features=dim_out, **kw)
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

    def _shortcut_pool(self, x, feat_size):
        if self.shortcut_pool_attn is None:
            return x
        if self.has_cls_token:
            cls_tok, x = x[:, :1], x[:, 1:]
        else:
            cls_tok = None
        B, L, C = x.shape
        H, W = feat_size
        x = self.shortcut_pool_attn(x.reshape(B, H, W, C))
        x = x.reshape(B, -1, C)
        if cls_tok is not None:
            x = jnp.concatenate([cls_tok, x], axis=1)
        return x

    def __call__(self, x, feat_size):
        x_norm = self.norm1(x)
        # reference quirk preserved: shortcut uses UN-normalized input unless projected
        x_shortcut = x if self.shortcut_proj_attn is None else self.shortcut_proj_attn(x_norm)
        x_shortcut = self._shortcut_pool(x_shortcut, feat_size)
        x, feat_size_new = self.attn(x_norm, feat_size)
        x = x_shortcut + self.drop_path1(x)

        x_norm = self.norm2(x)
        x_shortcut = x if self.shortcut_proj_mlp is None else self.shortcut_proj_mlp(x_norm)
        x = x_shortcut + self.drop_path2(self.mlp(x_norm))
        return x, feat_size_new


class MultiScaleVitStage(nnx.Module):
    def __init__(
            self, dim, dim_out, depth, num_heads, feat_size, mlp_ratio=4.0, qkv_bias=True,
            kernel_q=(1, 1), kernel_kv=(1, 1), stride_q=(1, 1), stride_kv=(1, 1),
            mode='conv', has_cls_token=True, expand_attn=False, rel_pos_type='spatial',
            residual_pooling=True, norm_layer: Callable = LayerNorm, drop_path=0.0,
            *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.grad_checkpointing = False
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        if expand_attn:
            out_dims = (dim_out,) * depth
        else:
            out_dims = (dim,) * (depth - 1) + (dim_out,)
        blocks = []
        for i in range(depth):
            blocks.append(MultiScaleBlock(
                dim=dim, dim_out=out_dims[i], num_heads=num_heads, feat_size=feat_size,
                mlp_ratio=mlp_ratio, qkv_bias=qkv_bias, kernel_q=kernel_q, kernel_kv=kernel_kv,
                stride_q=stride_q if i == 0 else (1, 1), stride_kv=stride_kv, mode=mode,
                has_cls_token=has_cls_token, rel_pos_type=rel_pos_type,
                residual_pooling=residual_pooling, expand_attn=expand_attn,
                norm_layer=norm_layer,
                drop_path=drop_path[i] if isinstance(drop_path, (list, tuple)) else drop_path, **kw))
            dim = out_dims[i]
            if i == 0:
                feat_size = tuple(s // st for s, st in zip(feat_size, stride_q))
        self.blocks = nnx.List(blocks)
        self.feat_size = feat_size

    def __call__(self, x, feat_size):
        if self.grad_checkpointing:
            remat_block = nnx.remat(lambda blk, x_, fs: blk(x_, fs), static_argnums=(2,))
            for blk in self.blocks:
                x, feat_size = remat_block(blk, x, tuple(feat_size))
        else:
            for blk in self.blocks:
                x, feat_size = blk(x, feat_size)
        return x, feat_size


class _Head(nnx.Module):
    def __init__(self, in_features, num_classes, drop_rate, *, dtype=None,
                 param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.drop = Dropout(drop_rate, rngs=rngs)
        self.fc = nnx.Linear(
            in_features, num_classes, kernel_init=trunc_normal_tf_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None

    def __call__(self, x):
        x = self.drop(x)
        return self.fc(x) if self.fc is not None else x


class MultiScaleVit(nnx.Module):
    """MViTv2 with the reference's model contract (reference mvitv2.py:715-975)."""

    def __init__(
            self,
            cfg: MultiScaleVitCfg,
            img_size: Union[int, Tuple[int, int]] = (224, 224),
            in_chans: int = 3,
            global_pool: Optional[str] = None,
            num_classes: int = 1000,
            drop_path_rate: float = 0.0,
            drop_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        img_size = to_2tuple(img_size)
        norm_layer = partial(LayerNorm, eps=cfg.norm_eps)
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        if global_pool is None:
            global_pool = 'token' if cfg.use_cls_token else 'avg'
        self.global_pool = global_pool
        self.depths = tuple(cfg.depths)
        self.expand_attn = cfg.expand_attn
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        embed_dim = cfg.embed_dim[0]
        self.patch_embed = MultiScalePatchEmbed(
            dim_in=in_chans, dim_out=embed_dim, kernel=cfg.patch_kernel,
            stride=cfg.patch_stride, padding=cfg.patch_padding, **kw)
        patch_dims = (img_size[0] // cfg.patch_stride[0], img_size[1] // cfg.patch_stride[1])
        num_patches = patch_dims[0] * patch_dims[1]

        if cfg.use_cls_token:
            self.cls_token = nnx.Param(
                trunc_normal_tf_(std=0.02)(rngs.params(), (1, 1, embed_dim), param_dtype))
            self.num_prefix_tokens = 1
            pos_embed_dim = num_patches + 1
        else:
            self.num_prefix_tokens = 0
            self.cls_token = None
            pos_embed_dim = num_patches

        if cfg.use_abs_pos:
            self.pos_embed = nnx.Param(
                trunc_normal_tf_(std=0.02)(rngs.params(), (1, pos_embed_dim, embed_dim), param_dtype))
        else:
            self.pos_embed = None

        num_stages = len(cfg.embed_dim)
        feat_size = patch_dims
        curr_stride = max(cfg.patch_stride)
        dpr = calculate_drop_path_rates(drop_path_rate, list(cfg.depths), stagewise=True)
        stages = []
        self.feature_info = []
        for i in range(num_stages):
            if cfg.expand_attn:
                dim_out = cfg.embed_dim[i]
            else:
                dim_out = cfg.embed_dim[min(i + 1, num_stages - 1)]
            stage = MultiScaleVitStage(
                dim=embed_dim, dim_out=dim_out, depth=cfg.depths[i], num_heads=cfg.num_heads[i],
                feat_size=feat_size, mlp_ratio=cfg.mlp_ratio, qkv_bias=cfg.qkv_bias,
                mode=cfg.mode, expand_attn=cfg.expand_attn, kernel_q=cfg.kernel_qkv,
                kernel_kv=cfg.kernel_qkv, stride_q=cfg.stride_q[i], stride_kv=cfg.stride_kv[i],
                has_cls_token=cfg.use_cls_token, rel_pos_type=cfg.rel_pos_type,
                residual_pooling=cfg.residual_pooling, norm_layer=norm_layer, drop_path=dpr[i], **kw)
            curr_stride *= max(cfg.stride_q[i])
            self.feature_info += [dict(module=f'stages.{i}', num_chs=dim_out, reduction=curr_stride)]
            embed_dim = dim_out
            feat_size = stage.feat_size
            stages.append(stage)
        self.stages = nnx.List(stages)

        self.num_features = self.head_hidden_size = embed_dim
        self.norm = norm_layer(embed_dim, rngs=rngs)
        self.head = _Head(self.num_features, num_classes, drop_rate, **kw)
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self):
        return {'pos_embed', 'rel_pos_h', 'rel_pos_w', 'cls_token'}

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^patch_embed',
            blocks=[(r'^stages\.(\d+)', None), (r'^norm', (99999,))],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        for s in self.stages:
            s.grad_checkpointing = enable

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.head = _Head(self.num_features, num_classes, self.drop_rate,
                          dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs)

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x, feat_size = self.patch_embed(x)
        B = x.shape[0]
        if self.cls_token is not None:
            cls = jnp.broadcast_to(self.cls_token[...].astype(x.dtype), (B, 1, x.shape[-1]))
            x = jnp.concatenate([cls, x], axis=1)
        if self.pos_embed is not None:
            x = x + self.pos_embed[...].astype(x.dtype)
        for stage in self.stages:
            x, feat_size = stage(x, feat_size)
        return self.norm(x) if self.norm is not None else x

    def forward_head(self, x, pre_logits: bool = False):
        if self.global_pool:
            if self.global_pool == 'avg':
                x = x[:, self.num_prefix_tokens:].mean(axis=1)
            else:
                x = x[:, 0]
        if pre_logits:
            return x
        return self.head(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt in ('NHWC', 'NLC')
        reshape = output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        x, feat_size = self.patch_embed(x)
        B = x.shape[0]
        if self.cls_token is not None:
            cls = jnp.broadcast_to(self.cls_token[...].astype(x.dtype), (B, 1, x.shape[-1]))
            x = jnp.concatenate([cls, x], axis=1)
        if self.pos_embed is not None:
            x = x + self.pos_embed[...].astype(x.dtype)

        intermediates = []
        last_idx = len(self.stages) - 1
        feat_idx = 0
        for feat_idx, stage in enumerate(self.stages):
            x, feat_size = stage(x, feat_size)
            if feat_idx in take_indices:
                x_inter = self.norm(x) if (norm and self.norm is not None and feat_idx == last_idx) else x
                if reshape:
                    if self.cls_token is not None:
                        x_inter = x_inter[:, 1:]
                    x_inter = x_inter.reshape(B, feat_size[0], feat_size[1], -1)
                intermediates.append(x_inter)
        if intermediates_only:
            return intermediates
        if feat_idx == last_idx and self.norm is not None:
            x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, _ = feature_take_indices(len(self.stages), indices)
        if prune_norm:
            self.norm = None
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    if 'model_state' in state_dict:
        state_dict = state_dict['model_state']
    return convert_torch_state_dict(state_dict, model)


model_cfgs = dict(
    mvitv2_tiny=MultiScaleVitCfg(depths=(1, 2, 5, 2)),
    mvitv2_small=MultiScaleVitCfg(depths=(1, 2, 11, 2)),
    mvitv2_base=MultiScaleVitCfg(depths=(2, 3, 16, 3)),
    mvitv2_large=MultiScaleVitCfg(depths=(2, 6, 36, 4), embed_dim=144, num_heads=2, expand_attn=False),
    mvitv2_small_cls=MultiScaleVitCfg(depths=(1, 2, 11, 2), use_cls_token=True),
    mvitv2_base_cls=MultiScaleVitCfg(depths=(2, 3, 16, 3), use_cls_token=True),
    mvitv2_large_cls=MultiScaleVitCfg(
        depths=(2, 6, 36, 4), embed_dim=144, num_heads=2, use_cls_token=True, expand_attn=True),
    mvitv2_huge_cls=MultiScaleVitCfg(
        depths=(4, 8, 60, 8), embed_dim=192, num_heads=3, use_cls_token=True, expand_attn=True),
    test_mvitv2=MultiScaleVitCfg(depths=(1, 1, 1), embed_dim=32, num_heads=1,
                                 stride_q=((1, 1), (2, 2), (2, 2)), patch_stride=(8, 8),
                                 patch_kernel=(7, 7), patch_padding=(3, 3)),
)


def _create_mvitv2(variant, cfg_variant=None, pretrained=False, **kwargs):
    out_indices = kwargs.pop('out_indices', 4)
    return build_model_with_cfg(
        MultiScaleVit, variant, pretrained,
        model_cfg=model_cfgs[variant] if not cfg_variant else model_cfgs[cfg_variant],
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': None,
        'crop_pct': 0.9,
        'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406),
        'std': (0.229, 0.224, 0.225),
        'first_conv': 'patch_embed.proj',
        'classifier': 'head.fc',
        'fixed_input_size': True,
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'mvitv2_tiny.fb_in1k': _cfg(hf_hub_id='timm/'),
    'mvitv2_small.fb_in1k': _cfg(hf_hub_id='timm/'),
    'mvitv2_base.fb_in1k': _cfg(hf_hub_id='timm/'),
    'mvitv2_large.fb_in1k': _cfg(hf_hub_id='timm/'),
    'mvitv2_small_cls.untrained': _cfg(),
    'mvitv2_base_cls.fb_inw21k': _cfg(hf_hub_id='timm/', num_classes=19168),
    'mvitv2_large_cls.fb_inw21k': _cfg(hf_hub_id='timm/', num_classes=19168),
    'mvitv2_huge_cls.fb_inw21k': _cfg(hf_hub_id='timm/', num_classes=19168),
    'test_mvitv2.untrained': _cfg(input_size=(3, 96, 96)),
})


@register_model
def mvitv2_tiny(pretrained=False, **kwargs) -> MultiScaleVit:
    return _create_mvitv2('mvitv2_tiny', pretrained=pretrained, **kwargs)


@register_model
def mvitv2_small(pretrained=False, **kwargs) -> MultiScaleVit:
    return _create_mvitv2('mvitv2_small', pretrained=pretrained, **kwargs)


@register_model
def mvitv2_base(pretrained=False, **kwargs) -> MultiScaleVit:
    return _create_mvitv2('mvitv2_base', pretrained=pretrained, **kwargs)


@register_model
def mvitv2_large(pretrained=False, **kwargs) -> MultiScaleVit:
    return _create_mvitv2('mvitv2_large', pretrained=pretrained, **kwargs)


@register_model
def mvitv2_small_cls(pretrained=False, **kwargs) -> MultiScaleVit:
    return _create_mvitv2('mvitv2_small_cls', pretrained=pretrained, **kwargs)


@register_model
def mvitv2_base_cls(pretrained=False, **kwargs) -> MultiScaleVit:
    return _create_mvitv2('mvitv2_base_cls', pretrained=pretrained, **kwargs)


@register_model
def mvitv2_large_cls(pretrained=False, **kwargs) -> MultiScaleVit:
    return _create_mvitv2('mvitv2_large_cls', pretrained=pretrained, **kwargs)


@register_model
def mvitv2_huge_cls(pretrained=False, **kwargs) -> MultiScaleVit:
    return _create_mvitv2('mvitv2_huge_cls', pretrained=pretrained, **kwargs)


@register_model
def test_mvitv2(pretrained=False, **kwargs) -> MultiScaleVit:
    return _create_mvitv2('test_mvitv2', pretrained=pretrained, **kwargs)
