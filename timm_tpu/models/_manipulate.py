"""Parameter grouping / model manipulation
(reference: timm/models/_manipulate.py:29-346).

Parameter "names" are the dotted flat-state paths produced by
`model_state_dict`; `group_matcher` specs are the same regex-tuple structures
the reference uses, matched against those names.
"""
from __future__ import annotations

import re
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

MATCH_PREV_GROUP = (99999,)

__all__ = ['group_parameters', 'group_with_matcher', 'named_parameters', 'checkpoint_seq']


def named_parameters(model) -> Dict[str, Any]:
    """Flat {dotted.name: array} of trainable params only."""
    from flax import nnx
    out = {}
    state = nnx.state(model, nnx.Param)
    for path, leaf in nnx.to_flat_state(state):
        key = '.'.join(str(getattr(p, 'key', p)) for p in path)
        if 'rngs' in key:
            continue
        out[key] = leaf[...]
    return out


def group_with_matcher(
        named_objects,
        group_matcher: Union[Dict, Callable],
        return_values: bool = False,
        reverse: bool = False,
):
    """(reference _manipulate.py:80-140)."""
    if isinstance(group_matcher, dict):
        compiled = []
        for group_ordinal, (group_name, mspec) in enumerate(group_matcher.items()):
            if mspec is None:
                continue
            if isinstance(mspec, (tuple, list)):
                for sspec in mspec:
                    compiled += [(group_ordinal, group_name, re.compile(sspec[0]), sspec[1])]
            else:
                compiled += [(group_ordinal, group_name, re.compile(mspec), None)]
        group_matcher = compiled

    def _get_grouping(name):
        if isinstance(group_matcher, (list, tuple)):
            for grp_ordinal, _, pattern, suffix in group_matcher:
                r = pattern.match(name)
                if r:
                    parts = (grp_ordinal,) + r.groups()
                    if suffix is not None:
                        parts = parts + (tuple(suffix) if isinstance(suffix, (tuple, list)) else (suffix,))
                    flat = []
                    for p in parts:
                        if p is None:
                            continue
                        if isinstance(p, (tuple, list)):
                            flat.extend(float(q) for q in p if q is not None)
                        else:
                            flat.append(float(p))
                    return tuple(flat)
            return (float('inf'),)
        ord_ = group_matcher(name)
        if not isinstance(ord_, collections_abc_iterable()):
            return (ord_,)
        return tuple(ord_)

    grouping = defaultdict(list)
    for name, obj in named_objects:
        grouping[_get_grouping(name)].append(obj if return_values else name)

    # remap to integers, ordered
    layer_id_to_param = defaultdict(list)
    lid = -1
    for k in sorted(filter(lambda x: x is not None, grouping.keys())):
        if lid < 0 or k[-1] != MATCH_PREV_GROUP[0]:
            lid += 1
        layer_id_to_param[lid].extend(grouping[k])

    if reverse:
        assert not return_values, 'reverse mapping only supported for name output'
        param_to_layer_id = {}
        for lid_, names in layer_id_to_param.items():
            for n in names:
                param_to_layer_id[n] = lid_
        return param_to_layer_id
    return layer_id_to_param


def collections_abc_iterable():
    import collections.abc
    return collections.abc.Iterable


def group_parameters(model, group_matcher, return_values: bool = False, reverse: bool = False):
    return group_with_matcher(
        named_parameters(model).items(), group_matcher, return_values=return_values, reverse=reverse)


def _run_modules(modules, x):
    for m in modules:
        x = m(x)
    return x


def checkpoint_seq(functions, x, every: int = 1, flatten: bool = False, skip_last: bool = False,
                   policy=None):
    """Apply a sequence of nnx modules with rematerialisation every `every`
    modules (reference _manipulate.py:213 checkpoint_seq). Trades recompute
    for HBM — the TPU equivalent of torch activation checkpointing.

    `policy` is a `jax.checkpoint_policies` predicate (e.g. ``dots_saveable``)
    selecting which intermediates are saved vs recomputed in the backward pass;
    None = save nothing (maximum memory saving, maximum recompute).
    """
    from flax import nnx
    functions = list(functions)
    end = len(functions) - 1 if skip_last else len(functions)
    remat_run = nnx.remat(_run_modules, policy=policy)
    idx = 0
    while idx < end:
        chunk = tuple(functions[idx:min(idx + every, end)])
        x = remat_run(chunk, x)
        idx += every
    if skip_last:
        x = functions[-1](x)
    return x
