"""Parameter grouping / model manipulation
(reference: timm/models/_manipulate.py:29-346).

Parameter "names" are the dotted flat-state paths produced by
`model_state_dict`; `group_matcher` specs are the same regex-tuple structures
the reference uses, matched against those names.
"""
from __future__ import annotations

import re
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

MATCH_PREV_GROUP = (99999,)

__all__ = [
    'group_parameters', 'group_with_matcher', 'named_parameters', 'checkpoint_seq',
    'BlockStackError', 'iter_submodules', 'build_block_stack', 'scan_block_stack',
    'drop_path_scan_inputs', 'resolve_block_scan', 'warn_scan_fallback',
]


def named_parameters(model) -> Dict[str, Any]:
    """Flat {dotted.name: array} of trainable params only."""
    from flax import nnx
    out = {}
    state = nnx.state(model, nnx.Param)
    for path, leaf in nnx.to_flat_state(state):
        key = '.'.join(str(getattr(p, 'key', p)) for p in path)
        if 'rngs' in key:
            continue
        out[key] = leaf[...]
    return out


def group_with_matcher(
        named_objects,
        group_matcher: Union[Dict, Callable],
        return_values: bool = False,
        reverse: bool = False,
):
    """(reference _manipulate.py:80-140)."""
    if isinstance(group_matcher, dict):
        compiled = []
        for group_ordinal, (group_name, mspec) in enumerate(group_matcher.items()):
            if mspec is None:
                continue
            if isinstance(mspec, (tuple, list)):
                for sspec in mspec:
                    compiled += [(group_ordinal, group_name, re.compile(sspec[0]), sspec[1])]
            else:
                compiled += [(group_ordinal, group_name, re.compile(mspec), None)]
        group_matcher = compiled

    def _get_grouping(name):
        if isinstance(group_matcher, (list, tuple)):
            for grp_ordinal, _, pattern, suffix in group_matcher:
                r = pattern.match(name)
                if r:
                    parts = (grp_ordinal,) + r.groups()
                    if suffix is not None:
                        parts = parts + (tuple(suffix) if isinstance(suffix, (tuple, list)) else (suffix,))
                    flat = []
                    for p in parts:
                        if p is None:
                            continue
                        if isinstance(p, (tuple, list)):
                            flat.extend(float(q) for q in p if q is not None)
                        else:
                            flat.append(float(p))
                    return tuple(flat)
            return (float('inf'),)
        ord_ = group_matcher(name)
        if not isinstance(ord_, collections_abc_iterable()):
            return (ord_,)
        return tuple(ord_)

    grouping = defaultdict(list)
    for name, obj in named_objects:
        grouping[_get_grouping(name)].append(obj if return_values else name)

    # remap to integers, ordered
    layer_id_to_param = defaultdict(list)
    lid = -1
    for k in sorted(filter(lambda x: x is not None, grouping.keys())):
        if lid < 0 or k[-1] != MATCH_PREV_GROUP[0]:
            lid += 1
        layer_id_to_param[lid].extend(grouping[k])

    if reverse:
        assert not return_values, 'reverse mapping only supported for name output'
        param_to_layer_id = {}
        for lid_, names in layer_id_to_param.items():
            for n in names:
                param_to_layer_id[n] = lid_
        return param_to_layer_id
    return layer_id_to_param


def collections_abc_iterable():
    import collections.abc
    return collections.abc.Iterable


def group_parameters(model, group_matcher, return_values: bool = False, reverse: bool = False):
    return group_with_matcher(
        named_parameters(model).items(), group_matcher, return_values=return_values, reverse=reverse)


def _run_modules(modules, x):
    for m in modules:
        x = m(x)
    return x


# ---- scan-over-layers block stacking ----------------------------------------
#
# A depth-L transformer traced as a Python loop costs O(L) trace time and O(L)
# XLA subgraphs to compile. For homogeneous block stacks the params can instead
# be stacked into leading-axis pytrees and the stack run as ONE lax.scan whose
# body is traced/compiled once — O(1) in depth (the MaxText/Flax big-model
# recipe). The helpers below implement that generically for any nnx block list
# so every ViT-family model (vision_transformer, deit, beit, eva) shares one
# code path.


class BlockStackError(RuntimeError):
    """Raised when a block list cannot be stacked for lax.scan execution
    (heterogeneous types/statics/shapes, live inner dropout RNG, <2 blocks).
    Callers fall back to the Python loop."""


def resolve_block_scan(flag) -> bool:
    """Resolve a model's ``block_scan`` constructor arg: an explicit bool wins;
    None reads the ``TIMM_TPU_BLOCK_SCAN`` env toggle (default off)."""
    if flag is not None:
        return bool(flag)
    import os
    return os.environ.get('TIMM_TPU_BLOCK_SCAN', '').lower() in ('1', 'true', 'yes', 'on')


_SCAN_FALLBACK_WARNED = set()


def warn_scan_fallback(model_name: str, err):
    """Log (once per model-class/reason) that block_scan fell back to the loop."""
    key = (model_name, str(err))
    if key not in _SCAN_FALLBACK_WARNED:
        _SCAN_FALLBACK_WARNED.add(key)
        import logging
        logging.getLogger(__name__).warning(
            f'{model_name}: block_scan fell back to the Python block loop: {err}')


def iter_submodules(module):
    """Yield `module` and every nnx.Module reachable through its attributes
    (including list/tuple containers), in deterministic attribute order."""
    from flax import nnx
    seen = set()

    def _walk(m):
        if id(m) in seen:
            return
        seen.add(id(m))
        yield m
        for v in vars(m).values():
            if isinstance(v, nnx.Module):
                yield from _walk(v)
            elif isinstance(v, (list, tuple)):
                for item in v:
                    if isinstance(item, nnx.Module):
                        yield from _walk(item)

    yield from _walk(module)


_MEM_ADDR_RE = re.compile(r'0x[0-9a-fA-F]+')


def _masked_graphdef_repr(graphdef) -> str:
    """Graphdef repr with memory addresses masked: per-block init-fn closures
    (`trunc_normal_.<locals>.init at 0x...`) are identity-distinct but
    computation-irrelevant, while genuinely different statics (a depth-indexed
    lambda_init float, a different submodule layout) stay visible."""
    return _MEM_ADDR_RE.sub('0x', repr(graphdef))


def build_block_stack(blocks, validate: bool = True):
    """Split a homogeneous block list into ``(graphdef, rng_state, stacked)``
    where ``stacked`` is the blocks' non-RNG state with a leading depth axis.

    DropPath statics (per-layer rate float + forked stream) are neutralized
    before splitting so a linearly-ramped stochastic-depth schedule doesn't
    make the graphdefs heterogeneous: in scan mode the per-layer rates ride a
    scanned rate vector and the keys are drawn eagerly outside the scan
    (see `drop_path_scan_inputs`), so the merged blocks' DropPath modules must
    be structural no-ops.

    Raises BlockStackError when stacking is impossible or would silently
    change semantics (different block types, depth-dependent statics, live
    inner-dropout RNG that the scan body could not advance).
    """
    import jax
    import jax.numpy as jnp
    from flax import nnx

    from ..layers.drop import DropPath

    blocks = list(blocks)
    if len(blocks) < 2:
        raise BlockStackError('need at least 2 blocks to scan')
    if any(type(b) is not type(blocks[0]) for b in blocks[1:]):
        raise BlockStackError(
            f'heterogeneous block types: {sorted({type(b).__name__ for b in blocks})}')

    if validate:
        # an inner Dropout with a live stream would consume RNG state inside
        # the scan body with no way to write the advanced counts back — every
        # step would reuse the same mask. DropPath is exempt (handled via the
        # scanned rate vector + eagerly drawn keys).
        for b in blocks:
            for sm in iter_submodules(b):
                if isinstance(sm, nnx.Dropout) and sm.rngs is not None \
                        and not sm.deterministic and sm.rate > 0:
                    raise BlockStackError(
                        'active inner dropout (train mode, rate>0) cannot run under scan')

    dp_saved = []
    for b in blocks:
        for sm in iter_submodules(b):
            if isinstance(sm, DropPath):
                dp_saved.append((sm, sm.drop_prob, sm.rngs))
                sm.drop_prob = 0.0
                sm.rngs = None
    try:
        splits = [nnx.split(b, nnx.RngState, ...) for b in blocks]
    finally:
        for sm, p, r in dp_saved:
            sm.drop_prob = p
            sm.rngs = r

    graphdef, rng_state, _ = splits[0]
    if validate:
        ref = _masked_graphdef_repr(graphdef)
        for i, (gd, _, _) in enumerate(splits[1:], start=1):
            if _masked_graphdef_repr(gd) != ref:
                raise BlockStackError(
                    f'block 0 and block {i} differ in static structure '
                    '(depth-dependent statics or layout)')
    try:
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *[s[2] for s in splits])
    except (ValueError, TypeError) as e:
        raise BlockStackError(f'block states are not stackable: {e}') from e
    return graphdef, rng_state, stacked


def drop_path_scan_inputs(blocks):
    """Per-layer DropPath inputs for scan mode: ``(rates[L, S], keys[L, S])``
    over the S DropPath sites of each of the L blocks, or None when no site is
    active (eval mode, or every rate 0). Keys are drawn from each block's own
    forked stream — the stream counts advance exactly as in loop mode."""
    import jax.numpy as jnp

    from ..layers.drop import DropPath

    rows = [[sm for sm in iter_submodules(b) if isinstance(sm, DropPath)] for b in blocks]
    n_sites = len(rows[0])
    if n_sites == 0 or any(len(r) != n_sites for r in rows):
        return None
    if not any(m.drop_prob > 0 and m.rngs is not None and not m.deterministic
               for row in rows for m in row):
        return None
    rates, keys, ref_key = [], [], None
    for row in rows:
        rrow, krow = [], []
        for m in row:
            live = m.rngs is not None and not m.deterministic and m.drop_prob > 0
            rrow.append(m.drop_prob if live else 0.0)
            k = m.rngs.dropout() if live else None
            if k is not None:
                ref_key = k
            krow.append(k)
        rates.append(rrow)
        keys.append(krow)
    # rate-0 sites keep everything regardless of key; reuse a drawn key there
    keys = [[k if k is not None else ref_key for k in row] for row in keys]
    return (jnp.asarray(rates, jnp.float32),
            jnp.stack([jnp.stack(row) for row in keys]))


def scan_block_stack(blocks, x, call_block=None, *, per_layer=None, remat: bool = False,
                     remat_policy=None, collect: bool = False, validate: bool = True):
    """Run a homogeneous block list as one ``jax.lax.scan`` over stacked
    per-layer state: trace/compile cost is O(1) in depth.

    ``call_block(block, x, extra)`` runs one merged block; ``extra`` is the
    per-layer slice of the ``per_layer`` pytree (or None). ``remat=True``
    wraps the body in `jax.checkpoint` (remat-inside-scan replaces
    `checkpoint_seq` for scanned stacks). ``collect=True`` additionally
    returns the stacked per-layer outputs ``[L, ...]`` (forward_intermediates).

    On a mesh with a 'model' axis the scan CARRY is pinned to the residual
    sharding (batch over data/fsdp, channels over 'model') — both the initial
    carry and the per-step output. Without the in-body constraint GSPMD must
    pick one layout for the whole while-loop and picks replicated, which is
    the involuntary-remat pattern PERF.md documents; with it, activations
    stay model-sharded across all L layers. No-op on tp=1 meshes.
    """
    import jax

    from ..parallel import shard_activation

    graphdef, rng_state, stacked = build_block_stack(blocks, validate=validate)
    if call_block is None:
        call_block = lambda blk, xx, extra: blk(xx)

    from flax import nnx

    x = shard_activation(x, 'residual')

    def body(carry, xs):
        layer_state, extra = xs
        blk = nnx.merge(graphdef, rng_state, layer_state)
        y = call_block(blk, carry, extra)
        y = shard_activation(y, 'residual')
        return y, (y if collect else None)

    if remat:
        body = jax.checkpoint(body, policy=remat_policy)
    out, ys = jax.lax.scan(body, x, (stacked, per_layer))
    return (out, ys) if collect else out


def checkpoint_seq(functions, x, every: int = 1, flatten: bool = False, skip_last: bool = False,
                   policy=None):
    """Apply a sequence of nnx modules with rematerialisation every `every`
    modules (reference _manipulate.py:213 checkpoint_seq). Trades recompute
    for HBM — the TPU equivalent of torch activation checkpointing.

    `policy` is a `jax.checkpoint_policies` predicate (e.g. ``dots_saveable``)
    selecting which intermediates are saved vs recomputed in the backward pass;
    None = save nothing (maximum memory saving, maximum recompute).
    """
    from flax import nnx
    functions = list(functions)
    end = len(functions) - 1 if skip_last else len(functions)
    remat_run = nnx.remat(_run_modules, policy=policy)
    idx = 0
    while idx < end:
        chunk = tuple(functions[idx:min(idx + every, end)])
        x = remat_run(chunk, x)
        idx += every
    if skip_last:
        x = functions[-1](x)
    return x
