"""Parameter grouping / model manipulation
(reference: timm/models/_manipulate.py:29-346).

Parameter "names" are the dotted flat-state paths produced by
`model_state_dict`; `group_matcher` specs are the same regex-tuple structures
the reference uses, matched against those names.
"""
from __future__ import annotations

import re
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

MATCH_PREV_GROUP = (99999,)

__all__ = [
    'group_parameters', 'group_with_matcher', 'named_parameters', 'checkpoint_seq',
    'BlockStackError', 'iter_submodules', 'build_block_stack', 'scan_block_stack',
    'drop_path_scan_inputs', 'resolve_block_scan', 'warn_scan_fallback',
    'build_stage_stack', 'scan_stage_stack', 'plan_stage_stack', 'resolve_stage_scan',
]


def named_parameters(model) -> Dict[str, Any]:
    """Flat {dotted.name: array} of trainable params only."""
    from flax import nnx
    out = {}
    state = nnx.state(model, nnx.Param)
    for path, leaf in nnx.to_flat_state(state):
        key = '.'.join(str(getattr(p, 'key', p)) for p in path)
        if 'rngs' in key:
            continue
        out[key] = leaf[...]
    return out


def group_with_matcher(
        named_objects,
        group_matcher: Union[Dict, Callable],
        return_values: bool = False,
        reverse: bool = False,
):
    """(reference _manipulate.py:80-140)."""
    if isinstance(group_matcher, dict):
        compiled = []
        for group_ordinal, (group_name, mspec) in enumerate(group_matcher.items()):
            if mspec is None:
                continue
            if isinstance(mspec, (tuple, list)):
                for sspec in mspec:
                    compiled += [(group_ordinal, group_name, re.compile(sspec[0]), sspec[1])]
            else:
                compiled += [(group_ordinal, group_name, re.compile(mspec), None)]
        group_matcher = compiled

    def _get_grouping(name):
        if isinstance(group_matcher, (list, tuple)):
            for grp_ordinal, _, pattern, suffix in group_matcher:
                r = pattern.match(name)
                if r:
                    parts = (grp_ordinal,) + r.groups()
                    if suffix is not None:
                        parts = parts + (tuple(suffix) if isinstance(suffix, (tuple, list)) else (suffix,))
                    flat = []
                    for p in parts:
                        if p is None:
                            continue
                        if isinstance(p, (tuple, list)):
                            flat.extend(float(q) for q in p if q is not None)
                        else:
                            flat.append(float(p))
                    return tuple(flat)
            return (float('inf'),)
        ord_ = group_matcher(name)
        if not isinstance(ord_, collections_abc_iterable()):
            return (ord_,)
        return tuple(ord_)

    grouping = defaultdict(list)
    for name, obj in named_objects:
        grouping[_get_grouping(name)].append(obj if return_values else name)

    # remap to integers, ordered
    layer_id_to_param = defaultdict(list)
    lid = -1
    for k in sorted(filter(lambda x: x is not None, grouping.keys())):
        if lid < 0 or k[-1] != MATCH_PREV_GROUP[0]:
            lid += 1
        layer_id_to_param[lid].extend(grouping[k])

    if reverse:
        assert not return_values, 'reverse mapping only supported for name output'
        param_to_layer_id = {}
        for lid_, names in layer_id_to_param.items():
            for n in names:
                param_to_layer_id[n] = lid_
        return param_to_layer_id
    return layer_id_to_param


def collections_abc_iterable():
    import collections.abc
    return collections.abc.Iterable


def group_parameters(model, group_matcher, return_values: bool = False, reverse: bool = False):
    return group_with_matcher(
        named_parameters(model).items(), group_matcher, return_values=return_values, reverse=reverse)


def _run_modules(modules, x):
    for m in modules:
        x = m(x)
    return x


# ---- scan-over-layers block stacking ----------------------------------------
#
# A depth-L transformer traced as a Python loop costs O(L) trace time and O(L)
# XLA subgraphs to compile. For homogeneous block stacks the params can instead
# be stacked into leading-axis pytrees and the stack run as ONE lax.scan whose
# body is traced/compiled once — O(1) in depth (the MaxText/Flax big-model
# recipe). The helpers below implement that generically for any nnx block list
# so every ViT-family model (vision_transformer, deit, beit, eva) shares one
# code path.


class BlockStackError(RuntimeError):
    """Raised when a block list cannot be stacked for lax.scan execution
    (heterogeneous types/statics/shapes, live inner dropout RNG, <2 blocks).
    Callers fall back to the Python loop."""


def resolve_block_scan(flag) -> bool:
    """Resolve a model's ``block_scan`` constructor arg: an explicit bool wins;
    None reads the ``TIMM_TPU_BLOCK_SCAN`` env toggle (default off)."""
    if flag is not None:
        return bool(flag)
    import os
    return os.environ.get('TIMM_TPU_BLOCK_SCAN', '').lower() in ('1', 'true', 'yes', 'on')


_SCAN_FALLBACK_WARNED = set()


def warn_scan_fallback(model_name: str, err, what: str = 'block_scan'):
    """Log (once per model-class/reason) that block/stage scan fell back to
    the loop."""
    key = (model_name, str(err))
    if key not in _SCAN_FALLBACK_WARNED:
        _SCAN_FALLBACK_WARNED.add(key)
        import logging
        logging.getLogger(__name__).warning(
            f'{model_name}: {what} fell back to the Python block loop: {err}')


def iter_submodules(module):
    """Yield `module` and every nnx.Module reachable through its attributes
    (including list/tuple containers), in deterministic attribute order."""
    from flax import nnx
    seen = set()

    def _walk(m):
        if id(m) in seen:
            return
        seen.add(id(m))
        yield m
        for v in vars(m).values():
            if isinstance(v, nnx.Module):
                yield from _walk(v)
            elif isinstance(v, (list, tuple)):
                for item in v:
                    if isinstance(item, nnx.Module):
                        yield from _walk(item)

    yield from _walk(module)


_MEM_ADDR_RE = re.compile(r'0x[0-9a-fA-F]+')


def _masked_graphdef_repr(graphdef) -> str:
    """Graphdef repr with memory addresses masked: per-block init-fn closures
    (`trunc_normal_.<locals>.init at 0x...`) are identity-distinct but
    computation-irrelevant, while genuinely different statics (a depth-indexed
    lambda_init float, a different submodule layout) stay visible."""
    return _MEM_ADDR_RE.sub('0x', repr(graphdef))


def build_block_stack(blocks, validate: bool = True):
    """Split a homogeneous block list into ``(graphdef, rng_state, stacked)``
    where ``stacked`` is the blocks' non-RNG state with a leading depth axis.

    DropPath statics (per-layer rate float + forked stream) are neutralized
    before splitting so a linearly-ramped stochastic-depth schedule doesn't
    make the graphdefs heterogeneous: in scan mode the per-layer rates ride a
    scanned rate vector and the keys are drawn eagerly outside the scan
    (see `drop_path_scan_inputs`), so the merged blocks' DropPath modules must
    be structural no-ops.

    Raises BlockStackError when stacking is impossible or would silently
    change semantics (different block types, depth-dependent statics, live
    inner-dropout RNG that the scan body could not advance).
    """
    import jax
    import jax.numpy as jnp
    from flax import nnx

    from ..layers.drop import DropPath

    blocks = list(blocks)
    if len(blocks) < 2:
        raise BlockStackError('need at least 2 blocks to scan')
    if any(type(b) is not type(blocks[0]) for b in blocks[1:]):
        raise BlockStackError(
            f'heterogeneous block types: {sorted({type(b).__name__ for b in blocks})}')

    if validate:
        # an inner Dropout with a live stream would consume RNG state inside
        # the scan body with no way to write the advanced counts back — every
        # step would reuse the same mask. DropPath is exempt (handled via the
        # scanned rate vector + eagerly drawn keys).
        for b in blocks:
            for sm in iter_submodules(b):
                if isinstance(sm, nnx.Dropout) and sm.rngs is not None \
                        and not sm.deterministic and sm.rate > 0:
                    raise BlockStackError(
                        'active inner dropout (train mode, rate>0) cannot run under scan')

    dp_saved = []
    for b in blocks:
        for sm in iter_submodules(b):
            if isinstance(sm, DropPath):
                dp_saved.append((sm, sm.drop_prob, sm.rngs))
                sm.drop_prob = 0.0
                sm.rngs = None
    try:
        splits = [nnx.split(b, nnx.RngState, ...) for b in blocks]
    finally:
        for sm, p, r in dp_saved:
            sm.drop_prob = p
            sm.rngs = r

    graphdef, rng_state, _ = splits[0]
    if validate:
        ref = _masked_graphdef_repr(graphdef)
        for i, (gd, _, _) in enumerate(splits[1:], start=1):
            if _masked_graphdef_repr(gd) != ref:
                raise BlockStackError(
                    f'block 0 and block {i} differ in static structure '
                    '(depth-dependent statics or layout)')
    try:
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *[s[2] for s in splits])
    except (ValueError, TypeError) as e:
        raise BlockStackError(f'block states are not stackable: {e}') from e
    return graphdef, rng_state, stacked


def drop_path_scan_inputs(blocks):
    """Per-layer DropPath inputs for scan mode: ``(rates[L, S], keys[L, S])``
    over the S DropPath sites of each of the L blocks, or None when no site is
    active (eval mode, or every rate 0). Keys are drawn from each block's own
    forked stream — the stream counts advance exactly as in loop mode."""
    import jax.numpy as jnp

    from ..layers.drop import DropPath

    rows = [[sm for sm in iter_submodules(b) if isinstance(sm, DropPath)] for b in blocks]
    n_sites = len(rows[0])
    if n_sites == 0 or any(len(r) != n_sites for r in rows):
        return None
    if not any(m.drop_prob > 0 and m.rngs is not None and not m.deterministic
               for row in rows for m in row):
        return None
    rates, keys, ref_key = [], [], None
    for row in rows:
        rrow, krow = [], []
        for m in row:
            live = m.rngs is not None and not m.deterministic and m.drop_prob > 0
            rrow.append(m.drop_prob if live else 0.0)
            k = m.rngs.dropout() if live else None
            if k is not None:
                ref_key = k
            krow.append(k)
        rates.append(rrow)
        keys.append(krow)
    # rate-0 sites keep everything regardless of key; reuse a drawn key there
    keys = [[k if k is not None else ref_key for k in row] for row in keys]
    return (jnp.asarray(rates, jnp.float32),
            jnp.stack([jnp.stack(row) for row in keys]))


def scan_block_stack(blocks, x, call_block=None, *, per_layer=None, remat: bool = False,
                     remat_policy=None, collect: bool = False, validate: bool = True):
    """Run a homogeneous block list as one ``jax.lax.scan`` over stacked
    per-layer state: trace/compile cost is O(1) in depth.

    ``call_block(block, x, extra)`` runs one merged block; ``extra`` is the
    per-layer slice of the ``per_layer`` pytree (or None). ``remat=True``
    wraps the body in `jax.checkpoint` (remat-inside-scan replaces
    `checkpoint_seq` for scanned stacks). ``collect=True`` additionally
    returns the stacked per-layer outputs ``[L, ...]`` (forward_intermediates).

    On a mesh with a 'model' axis the scan CARRY is pinned to the residual
    sharding (batch over data/fsdp, channels over 'model') — both the initial
    carry and the per-step output. Without the in-body constraint GSPMD must
    pick one layout for the whole while-loop and picks replicated, which is
    the involuntary-remat pattern PERF.md documents; with it, activations
    stay model-sharded across all L layers. No-op on tp=1 meshes.
    """
    import jax

    from ..parallel import shard_activation

    graphdef, rng_state, stacked = build_block_stack(blocks, validate=validate)
    if call_block is None:
        call_block = lambda blk, xx, extra: blk(xx)

    from flax import nnx

    x = shard_activation(x, 'residual')

    def body(carry, xs):
        layer_state, extra = xs
        blk = nnx.merge(graphdef, rng_state, layer_state)
        y = call_block(blk, carry, extra)
        y = shard_activation(y, 'residual')
        return y, (y if collect else None)

    if remat:
        body = jax.checkpoint(body, policy=remat_policy)
    out, ys = jax.lax.scan(body, x, (stacked, per_layer))
    return (out, ys) if collect else out


# ---- stage-level scan (hierarchical models) ---------------------------------
#
# Hierarchical models (convnext, swin, metaformer, pvt_v2, regnet, mambaout)
# run N stages of homogeneous blocks separated by downsample boundaries.
# Within one stage the block_scan recipe applies unchanged — stack per-layer
# state, run ONE lax.scan — but two structural wrinkles need planning that
# ViT stacks never see:
#
#   * an EAGER PREFIX: the first block of a stage often differs from the rest
#     (regnet's stride-2/downsample block, convnext's in_chs != out_chs
#     shortcut block). Those k blocks run as a Python loop and the
#     homogeneous suffix scans.
#   * a PERIOD: swin alternates shifted/unshifted blocks (period 2), so the
#     graphdefs repeat with period p rather than being all-equal. Blocks are
#     stacked per offset-column (blocks [j, j+p, j+2p, ...]) and the scan
#     body runs p merged blocks per step.
#
# `plan_stage_stack` searches (eager_prefix, period) in a fixed cheap order;
# a stage with no valid plan raises BlockStackError and the caller falls back
# to the loop (logged once per model class — never silently slow).


def resolve_stage_scan(flag) -> bool:
    """Resolve a hierarchical model's ``stage_scan`` constructor arg: an
    explicit bool wins; None reads the ``TIMM_TPU_STAGE_SCAN`` env toggle
    (default off, mirroring ``resolve_block_scan``)."""
    if flag is not None:
        return bool(flag)
    import os
    return os.environ.get('TIMM_TPU_STAGE_SCAN', '').lower() in ('1', 'true', 'yes', 'on')


def _stage_block_reprs(blocks):
    """Masked graphdef repr per block, with DropPath statics neutralized the
    same way `build_block_stack` does, so a ramped stochastic-depth schedule
    doesn't read as heterogeneity during planning."""
    from flax import nnx

    from ..layers.drop import DropPath

    reprs = []
    for b in blocks:
        dp_saved = []
        for sm in iter_submodules(b):
            if isinstance(sm, DropPath):
                dp_saved.append((sm, sm.drop_prob, sm.rngs))
                sm.drop_prob = 0.0
                sm.rngs = None
        try:
            graphdef, _, _ = nnx.split(b, nnx.RngState, ...)
            reprs.append(_masked_graphdef_repr(graphdef))
        finally:
            for sm, p, r in dp_saved:
                sm.drop_prob = p
                sm.rngs = r
    return reprs


def plan_stage_stack(blocks) -> Tuple[int, int]:
    """Find ``(eager_prefix, period)`` for a stage's block list: the first
    `eager_prefix` blocks run eagerly, the rest scan with period `period`
    (each offset-column homogeneous, >=2 scan steps). Searched smallest-first
    so a fully homogeneous stage plans as (0, 1). Raises BlockStackError when
    no candidate fits."""
    blocks = list(blocks)
    if len(blocks) < 2:
        raise BlockStackError('need at least 2 blocks to scan')
    types = [type(b) for b in blocks]
    reprs = _stage_block_reprs(blocks)
    for prefix in (0, 1):
        for period in (1, 2):
            rest = len(blocks) - prefix
            if rest < 2 * period or rest % period:
                continue
            cols_ok = all(
                all(types[prefix + j + i * period] is types[prefix + j]
                    and reprs[prefix + j + i * period] == reprs[prefix + j]
                    for i in range(rest // period))
                for j in range(period))
            if cols_ok:
                return prefix, period
    raise BlockStackError(
        'no (eager_prefix, period) plan makes the stage scannable: block '
        'statics vary beyond a length-1 prefix and period-2 alternation')


def build_stage_stack(blocks, period: int = 1, validate: bool = True):
    """Stack a stage's scannable blocks per offset-column: returns
    ``(graphdefs, rng_states, stackeds)``, each a length-`period` list, where
    ``stackeds[j]`` is the stacked state of blocks ``[j, j+period, ...]``.
    Period 1 is exactly one `build_block_stack`."""
    blocks = list(blocks)
    if len(blocks) % period:
        raise BlockStackError(
            f'{len(blocks)} blocks do not divide into period-{period} columns')
    graphdefs, rng_states, stackeds = [], [], []
    for j in range(period):
        graphdef, rng_state, stacked = build_block_stack(blocks[j::period], validate=validate)
        graphdefs.append(graphdef)
        rng_states.append(rng_state)
        stackeds.append(stacked)
    return graphdefs, rng_states, stackeds


def _check_no_train_batch_stats(blocks):
    """Batch-stat modules (BatchNorm & friends expose `use_running_average`)
    update running mean/var as a side effect of a train-mode call; a scan
    body cannot write those updates back to the real modules, so scanning
    would silently freeze the stats. Raise and let the loop handle it."""
    for b in blocks:
        for sm in iter_submodules(b):
            if getattr(sm, 'use_running_average', None) is False:
                raise BlockStackError(
                    f'{type(sm).__name__} in training mode: running-stat '
                    'updates inside a scan body would be silently discarded')


def _set_drop_path_overrides(block, rates, keys):
    """Pin the scanned per-layer (rate, key) onto the merged block's DropPath
    sites, in the same deterministic `iter_submodules` order
    `drop_path_scan_inputs` drew them in."""
    from ..layers.drop import DropPath
    site = 0
    for sm in iter_submodules(block):
        if isinstance(sm, DropPath):
            sm._scan_override = (rates[site], keys[site])
            site += 1


def scan_stage_stack(blocks, x, call_block=None, *, remat: bool = False,
                     remat_policy=None, validate: bool = True):
    """Run one stage's block list as ONE ``jax.lax.scan``: trace/compile cost
    O(1) in stage depth, with an eager prefix for a heterogeneous first block
    and period-p column stacking for alternating statics (swin's shift).

    ``call_block(block, x)`` runs one merged block (default ``block(x)``;
    pvt_v2 passes its static feat_size through a closure). Per-layer DropPath
    rates/keys ride the scanned inputs exactly as in `scan_block_stack`,
    except they are pinned onto the merged blocks' DropPath modules (stage
    blocks take no override argument). ``remat=True`` wraps the body in
    `jax.checkpoint` — remat-inside-scan replaces `checkpoint_seq`.

    The carry is pinned to the NHWC 'channels' layout on 'model' meshes
    (rank-3 stages like pvt get 'residual'); without the in-body constraint
    GSPMD picks one (replicated) layout for the whole while-loop — the
    involuntary-remat regime PERF.md documents.

    Raises BlockStackError (train-mode batch stats, no valid plan,
    unstackable states); callers fall back to the bit-identical Python loop.
    """
    import jax
    import jax.numpy as jnp
    from flax import nnx

    from ..parallel import shard_activation

    blocks = list(blocks)
    if call_block is None:
        call_block = lambda blk, xx: blk(xx)
    if validate:
        _check_no_train_batch_stats(blocks)
    prefix, period = plan_stage_stack(blocks)
    kind = 'channels' if getattr(x, 'ndim', 0) == 4 else 'residual'

    for blk in blocks[:prefix]:
        x = call_block(blk, x)
    scanned = blocks[prefix:]
    graphdefs, rng_states, stackeds = build_stage_stack(scanned, period, validate=validate)
    n_steps = len(scanned) // period

    dp = drop_path_scan_inputs(scanned)
    if dp is not None:
        # [L, S] -> [n_steps, period, S]: lax.scan slices the step axis,
        # the body indexes the period offset
        rates, keys = dp
        dp = (rates.reshape(n_steps, period, -1),
              keys.reshape((n_steps, period) + keys.shape[1:]))

    x = shard_activation(x, kind)

    def body(carry, xs):
        layer_states, extra = xs
        y = carry
        for j in range(period):
            blk = nnx.merge(graphdefs[j], rng_states[j], layer_states[j])
            if extra is not None:
                _set_drop_path_overrides(blk, extra[0][j], extra[1][j])
            y = call_block(blk, y)
            y = shard_activation(y, kind)
        return y, None

    if remat:
        body = jax.checkpoint(body, policy=remat_policy)
    out, _ = jax.lax.scan(body, x, (tuple(stackeds), dp))
    return out


def checkpoint_seq(functions, x, every: int = 1, flatten: bool = False, skip_last: bool = False,
                   policy=None):
    """Apply a sequence of nnx modules with rematerialisation every `every`
    modules (reference _manipulate.py:213 checkpoint_seq). Trades recompute
    for HBM — the TPU equivalent of torch activation checkpointing.

    `policy` is a `jax.checkpoint_policies` predicate (e.g. ``dots_saveable``)
    selecting which intermediates are saved vs recomputed in the backward pass;
    None = save nothing (maximum memory saving, maximum recompute).
    """
    from flax import nnx
    functions = list(functions)
    end = len(functions) - 1 if skip_last else len(functions)
    remat_run = nnx.remat(_run_modules, policy=policy)
    idx = 0
    while idx < end:
        chunk = tuple(functions[idx:min(idx + every, end)])
        x = remat_run(chunk, x)
        idx += every
    if skip_last:
        x = functions[-1](x)
    return x
