"""Vision Transformer, TPU-native.

Re-designed from the reference's VisionTransformer
(reference: timm/models/vision_transformer.py:711-1302) for JAX/XLA:
NLC tokens, explicit RNG streams, trace-time pos-embed resampling for
dynamic image sizes, rematerialised blocks for grad checkpointing.

Model contract parity (reference vision_transformer.py):
  forward_features / forward_head / __call__, get_classifier / reset_classifier,
  group_matcher, set_grad_checkpointing, forward_intermediates,
  prune_intermediate_layers, no_weight_decay, set_input_size.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    Attention, AttentionPoolLatent, DropPath, Dropout, LayerNorm, LayerScale,
    Mlp, PatchDropout, PatchEmbed, calculate_drop_path_rates, get_act_fn,
    get_norm_layer, global_pool_nlc, resample_abs_pos_embed, trunc_normal_,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model

__all__ = ['VisionTransformer', 'Block', 'ResPostBlock']


class Block(nnx.Module):
    """Pre-norm transformer block (reference vision_transformer.py:128-216)."""

    def __init__(
            self,
            dim: int,
            num_heads: int,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = False,
            qk_norm: bool = False,
            proj_bias: bool = True,
            proj_drop: float = 0.0,
            attn_drop: float = 0.0,
            init_values: Optional[float] = None,
            drop_path: float = 0.0,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Callable = LayerNorm,
            mlp_layer: Callable = Mlp,
            attn_layer: Optional[Callable] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        attn_layer = attn_layer or Attention
        self.norm1 = norm_layer(dim, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.attn = attn_layer(
            dim,
            num_heads=num_heads,
            qkv_bias=qkv_bias,
            qk_norm=qk_norm,
            proj_bias=proj_bias,
            attn_drop=attn_drop,
            proj_drop=proj_drop,
            norm_layer=norm_layer,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )
        self.ls1 = LayerScale(dim, init_values=init_values, param_dtype=param_dtype, rngs=rngs) if init_values else None
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(dim, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.mlp = mlp_layer(
            dim,
            hidden_features=int(dim * mlp_ratio),
            act_layer=act_layer,
            drop=proj_drop,
            bias=proj_bias,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )
        self.ls2 = LayerScale(dim, init_values=init_values, param_dtype=param_dtype, rngs=rngs) if init_values else None
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

    def __call__(self, x, attn_mask=None):
        y = self.attn(self.norm1(x), attn_mask=attn_mask)
        if self.ls1 is not None:
            y = self.ls1(y)
        x = x + self.drop_path1(y)
        y = self.mlp(self.norm2(x))
        if self.ls2 is not None:
            y = self.ls2(y)
        x = x + self.drop_path2(y)
        return x


class ResPostBlock(nnx.Module):
    """Post-norm residual block (reference vision_transformer.py:217-291)."""

    def __init__(
            self,
            dim: int,
            num_heads: int,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = False,
            qk_norm: bool = False,
            proj_bias: bool = True,
            proj_drop: float = 0.0,
            attn_drop: float = 0.0,
            init_values: Optional[float] = None,
            drop_path: float = 0.0,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Callable = LayerNorm,
            mlp_layer: Callable = Mlp,
            attn_layer: Optional[Callable] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.init_values = init_values
        attn_cls = attn_layer or Attention
        self.attn = attn_cls(
            dim, num_heads=num_heads, qkv_bias=qkv_bias, qk_norm=qk_norm, proj_bias=proj_bias,
            attn_drop=attn_drop, proj_drop=proj_drop, norm_layer=norm_layer,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs,
        )
        self.norm1 = norm_layer(dim, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.mlp = mlp_layer(
            dim, hidden_features=int(dim * mlp_ratio), act_layer=act_layer, drop=proj_drop,
            bias=proj_bias, dtype=dtype, param_dtype=param_dtype, rngs=rngs,
        )
        self.norm2 = norm_layer(dim, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path2 = DropPath(drop_path, rngs=rngs)
        # reference init: scale norm weights by init_values when provided
        if init_values is not None:
            self.norm1.scale[...] = self.norm1.scale[...] * init_values
            self.norm2.scale[...] = self.norm2.scale[...] * init_values

    def __call__(self, x, attn_mask=None):
        x = x + self.drop_path1(self.norm1(self.attn(x, attn_mask=attn_mask)))
        x = x + self.drop_path2(self.norm2(self.mlp(x)))
        return x


class VisionTransformer(nnx.Module):
    """ViT with the reference's full model contract."""

    dynamic_img_size: bool

    def __init__(
            self,
            img_size: Union[int, Tuple[int, int]] = 224,
            patch_size: Union[int, Tuple[int, int]] = 16,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'token',
            embed_dim: int = 768,
            depth: int = 12,
            num_heads: int = 12,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = True,
            qk_norm: bool = False,
            proj_bias: bool = True,
            init_values: Optional[float] = None,
            class_token: bool = True,
            pos_embed: str = 'learn',
            no_embed_class: bool = False,
            reg_tokens: int = 0,
            pre_norm: bool = False,
            final_norm: bool = True,
            fc_norm: Optional[bool] = None,
            dynamic_img_size: bool = False,
            dynamic_img_pad: bool = False,
            drop_rate: float = 0.0,
            pos_drop_rate: float = 0.0,
            patch_drop_rate: float = 0.0,
            proj_drop_rate: float = 0.0,
            attn_drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            weight_init: str = '',
            fix_init: bool = False,
            embed_layer: Callable = PatchEmbed,
            norm_layer: Optional[Union[str, Callable]] = None,
            act_layer: Optional[Union[str, Callable]] = None,
            block_fn: Callable = Block,
            mlp_layer: Callable = Mlp,
            attn_layer: Optional[Union[str, Callable]] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert global_pool in ('', 'avg', 'avgmax', 'max', 'token', 'map')
        assert class_token or global_pool != 'token'
        assert pos_embed in ('', 'none', 'learn')
        norm_layer = get_norm_layer(norm_layer) or LayerNorm
        act_layer = act_layer or 'gelu'

        self.num_classes = num_classes
        self.global_pool = global_pool
        self.num_features = self.head_hidden_size = self.embed_dim = embed_dim
        self.num_prefix_tokens = 1 if class_token else 0
        self.num_prefix_tokens += reg_tokens
        self.num_reg_tokens = reg_tokens
        self.has_class_token = class_token
        self.no_embed_class = no_embed_class
        self.dynamic_img_size = dynamic_img_size
        self.grad_checkpointing = False
        self.depth = depth

        embed_args = {}
        if dynamic_img_size:
            embed_args.update(dict(strict_img_size=False))
        self.patch_embed = embed_layer(
            img_size=img_size,
            patch_size=patch_size,
            in_chans=in_chans,
            embed_dim=embed_dim,
            bias=not pre_norm,  # pre-norm (CLIP) ViTs have no patch-proj bias
            dynamic_img_pad=dynamic_img_pad,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
            **embed_args,
        )
        num_patches = self.patch_embed.num_patches
        if hasattr(self.patch_embed, 'feat_ratio'):
            # hybrid embeds: backbone stride x patch size (reference vision_transformer.py:552)
            reduction = self.patch_embed.feat_ratio()
        elif hasattr(self.patch_embed, 'patch_size'):
            reduction = self.patch_embed.patch_size[0]
        else:
            reduction = 16

        self.cls_token = nnx.Param(
            jnp.zeros((1, 1, embed_dim), param_dtype)) if class_token else None
        self.reg_token = nnx.Param(
            trunc_normal_(std=0.02)(rngs.params(), (1, reg_tokens, embed_dim), param_dtype)) if reg_tokens else None

        embed_len = num_patches if no_embed_class else num_patches + self.num_prefix_tokens
        if not pos_embed or pos_embed == 'none':
            self.pos_embed = None
        else:
            self.pos_embed = nnx.Param(
                trunc_normal_(std=0.02)(rngs.params(), (1, embed_len, embed_dim), param_dtype))
        self.pos_drop = Dropout(pos_drop_rate, rngs=rngs)
        if patch_drop_rate > 0:
            self.patch_drop = PatchDropout(patch_drop_rate, num_prefix_tokens=self.num_prefix_tokens, rngs=rngs)
        else:
            self.patch_drop = None
        self.norm_pre = norm_layer(embed_dim, rngs=rngs) if pre_norm else None

        def _resolve_attn_layer(i: int):
            if attn_layer is None:
                return None
            if attn_layer == 'diff':
                from ..layers.diff_attention import DiffAttention
                return partial(DiffAttention, depth=i)  # depth-dependent lambda_init
            return attn_layer

        dpr = calculate_drop_path_rates(drop_path_rate, depth)
        self.blocks = nnx.List([
            block_fn(
                dim=embed_dim,
                num_heads=num_heads,
                mlp_ratio=mlp_ratio,
                qkv_bias=qkv_bias,
                qk_norm=qk_norm,
                proj_bias=proj_bias,
                init_values=init_values,
                proj_drop=proj_drop_rate,
                attn_drop=attn_drop_rate,
                drop_path=dpr[i],
                norm_layer=norm_layer,
                act_layer=act_layer,
                mlp_layer=mlp_layer,
                attn_layer=_resolve_attn_layer(i),
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
            for i in range(depth)
        ])
        self.feature_info = [
            dict(module=f'blocks.{i}', num_chs=embed_dim, reduction=reduction) for i in range(depth)]

        # feature norm (pre-pool) vs fc norm (post-pool)
        if fc_norm is None:
            fc_norm = global_pool == 'avg'
        self.norm = norm_layer(embed_dim, rngs=rngs) if final_norm and not fc_norm else None

        # head
        if global_pool == 'map':
            self.attn_pool = AttentionPoolLatent(
                self.embed_dim,
                num_heads=num_heads,
                mlp_ratio=mlp_ratio,
                norm_layer=norm_layer,
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
        else:
            self.attn_pool = None
        self.fc_norm = norm_layer(embed_dim, rngs=rngs) if final_norm and fc_norm else None
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.head = nnx.Linear(
            self.embed_dim, num_classes,
            kernel_init=trunc_normal_(std=0.02),
            bias_init=lambda key, shape, dtype=jnp.float32: jnp.zeros(shape, dtype),
            dtype=dtype, param_dtype=param_dtype, rngs=rngs,
        ) if num_classes > 0 else None

        self._dtype = dtype
        self._param_dtype = param_dtype

        if fix_init:
            self.fix_init_weight()

    def fix_init_weight(self):
        """Rescale block projections by depth (reference vision_transformer.py:~980)."""
        for layer_id, block in enumerate(self.blocks):
            scale = math.sqrt(2.0 * (layer_id + 1))
            block.attn.proj.kernel[...] = block.attn.proj.kernel[...] / scale
            block.mlp.fc2.kernel[...] = block.mlp.fc2.kernel[...] / scale

    # ---- contract methods -------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'pos_embed', 'cls_token', 'reg_token', 'dist_token'}

    def group_matcher(self, coarse: bool = False) -> Dict:
        return dict(
            stem=r'^cls_token|pos_embed|patch_embed|reg_token',
            blocks=[(r'^blocks\.(\d+)', None), (r'^norm', (99999,))],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs: Optional[nnx.Rngs] = None):
        self.num_classes = num_classes
        if global_pool is not None:
            assert global_pool in ('', 'avg', 'avgmax', 'max', 'token', 'map')
            if global_pool == 'map' and self.attn_pool is None:
                raise AssertionError("Cannot currently add attention pooling in reset_classifier().")
            if global_pool != 'map':
                self.attn_pool = None
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.head = nnx.Linear(
            self.embed_dim, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs,
        ) if num_classes > 0 else None

    def set_input_size(self, img_size=None, patch_size=None):
        """Resample learned pos embed for a new static input size
        (reference vision_transformer.py:1013)."""
        if img_size is None:
            return
        prev_grid = self.patch_embed.grid_size
        self.patch_embed.set_input_size(img_size=img_size, patch_size=patch_size)
        new_grid = self.patch_embed.grid_size
        if self.pos_embed is not None and new_grid != prev_grid:
            # shape changes, so the Param must be replaced, not assigned into
            self.pos_embed = nnx.Param(resample_abs_pos_embed(
                self.pos_embed[...],
                new_size=new_grid,
                old_size=prev_grid,
                num_prefix_tokens=0 if self.no_embed_class else self.num_prefix_tokens,
            ))

    # ---- forward ----------------------------------------------------------
    def _pos_embed(self, x, grid_size: Optional[Tuple[int, int]] = None):
        B = x.shape[0]
        if self.pos_embed is None:
            pos_embed = None
        else:
            pos_embed = self.pos_embed[...].astype(x.dtype)
            if self.dynamic_img_size and grid_size is not None and grid_size != self.patch_embed.grid_size:
                pos_embed = resample_abs_pos_embed(
                    pos_embed,
                    new_size=grid_size,
                    old_size=self.patch_embed.grid_size,
                    num_prefix_tokens=0 if self.no_embed_class else self.num_prefix_tokens,
                )

        to_cat = []
        if self.cls_token is not None:
            to_cat.append(jnp.broadcast_to(self.cls_token[...].astype(x.dtype), (B, 1, x.shape[-1])))
        if self.reg_token is not None:
            to_cat.append(jnp.broadcast_to(self.reg_token[...].astype(x.dtype), (B, self.num_reg_tokens, x.shape[-1])))

        if self.no_embed_class:
            if pos_embed is not None:
                x = x + pos_embed
            if to_cat:
                x = jnp.concatenate(to_cat + [x], axis=1)
        else:
            if to_cat:
                x = jnp.concatenate(to_cat + [x], axis=1)
            if pos_embed is not None:
                x = x + pos_embed
        return self.pos_drop(x)

    def forward_features(self, x, attn_mask=None):
        grid_size = None
        if self.dynamic_img_size:
            grid_size = self.patch_embed.dynamic_feat_size(x.shape[1:3])
        x = self.patch_embed(x)
        x = self._pos_embed(x, grid_size=grid_size)
        if self.patch_drop is not None:
            x = self.patch_drop(x)
        if self.norm_pre is not None:
            x = self.norm_pre(x)
        if self.grad_checkpointing and attn_mask is None:
            x = checkpoint_seq(self.blocks, x)
        else:
            for blk in self.blocks:
                x = blk(x, attn_mask=attn_mask)
        if self.norm is not None:
            x = self.norm(x)
        return x

    def pool(self, x, pool_type: Optional[str] = None):
        if self.attn_pool is not None:
            return self.attn_pool(x)
        pool_type = self.global_pool if pool_type is None else pool_type
        return global_pool_nlc(x, pool_type=pool_type, num_prefix_tokens=self.num_prefix_tokens)

    def forward_head(self, x, pre_logits: bool = False):
        x = self.pool(x)
        if self.fc_norm is not None:
            x = self.fc_norm(x)
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        return self.head(x)

    def __call__(self, x, attn_mask=None):
        x = self.forward_features(x, attn_mask=attn_mask)
        x = self.forward_head(x)
        return x

    # ---- intermediates ----------------------------------------------------
    def forward_intermediates(
            self,
            x,
            indices: Optional[Union[int, List[int]]] = None,
            return_prefix_tokens: bool = False,
            norm: bool = False,
            stop_early: bool = False,
            output_fmt: str = 'NHWC',
            intermediates_only: bool = False,
            attn_mask=None,
    ):
        """Collect intermediate block outputs (reference vision_transformer.py:1077)."""
        assert output_fmt in ('NHWC', 'NLC'), 'Output format must be NHWC or NLC.'
        reshape = output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)

        B, H, W, _ = x.shape
        grid_size = self.patch_embed.dynamic_feat_size((H, W)) if self.dynamic_img_size \
            else self.patch_embed.grid_size
        x = self.patch_embed(x)
        x = self._pos_embed(x, grid_size=grid_size if self.dynamic_img_size else None)
        if self.patch_drop is not None:
            x = self.patch_drop(x)
        if self.norm_pre is not None:
            x = self.norm_pre(x)

        intermediates = []
        blocks = self.blocks if not stop_early else self.blocks[:max_index + 1]
        for i, blk in enumerate(blocks):
            x = blk(x, attn_mask=attn_mask)
            if i in take_indices:
                intermediates.append(self.norm(x) if (norm and self.norm is not None) else x)

        # split prefix tokens, reshape spatial
        prefix_tokens = None
        if self.num_prefix_tokens:
            prefix_tokens = [y[:, 0:self.num_prefix_tokens] for y in intermediates]
            intermediates = [y[:, self.num_prefix_tokens:] for y in intermediates]
        if reshape:
            intermediates = [
                y.reshape(B, grid_size[0], grid_size[1], -1) for y in intermediates]
        if return_prefix_tokens and prefix_tokens is not None:
            intermediates = list(zip(intermediates, prefix_tokens))

        if intermediates_only:
            return intermediates
        if self.norm is not None:
            x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(
            self,
            indices: Union[int, List[int]] = 1,
            prune_norm: bool = False,
            prune_head: bool = True,
    ):
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        self.blocks = nnx.List(list(self.blocks)[:max_index + 1])
        if prune_norm:
            self.norm = None
        if prune_head:
            self.fc_norm = None
            self.attn_pool = None
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict: Dict, model) -> Dict:
    """Convert reference-timm torch checkpoints → this module's state layout."""
    from ._torch_convert import convert_torch_state_dict
    return convert_torch_state_dict(state_dict, model)


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': None,
        'crop_pct': 0.9,
        'interpolation': 'bicubic',
        'fixed_input_size': True,
        'mean': (0.5, 0.5, 0.5),
        'std': (0.5, 0.5, 0.5),
        'first_conv': 'patch_embed.proj',
        'classifier': 'head',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'vit_tiny_patch16_224.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'vit_tiny_patch16_384.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_small_patch32_224.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'vit_small_patch16_224.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'vit_small_patch16_384.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_base_patch32_224.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'vit_base_patch16_224.augreg2_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'vit_base_patch16_224.augreg_in1k': _cfg(hf_hub_id='timm/'),
    'vit_base_patch16_384.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_base_patch8_224.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'vit_large_patch16_224.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'vit_dlittle_patch16_reg1_gap_256.sbb_nadamuon_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_little_patch16_reg4_gap_256.sbb_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_medium_patch16_reg4_gap_256.sbb_in12k_ft_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_large_patch14_224.untrained': _cfg(url=''),
    'vit_huge_patch14_224.untrained': _cfg(url=''),
    'vit_so400m_patch14_siglip_224.untrained': _cfg(url=''),
    'vit_tiny_patch16_224.untrained': _cfg(url=''),
    # tiny test fixtures (reference vision_transformer.py:4802-4833)
    'test_vit.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), crop_pct=0.95),
    'test_vit2.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), crop_pct=0.95),
    'test_vit3.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), crop_pct=0.95),
    'test_vit4.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), crop_pct=0.95),
})


def _create_vision_transformer(variant: str, pretrained: bool = False, **kwargs) -> VisionTransformer:
    out_indices = kwargs.pop('out_indices', 3)
    return build_model_with_cfg(
        VisionTransformer,
        variant,
        pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


@register_model
def vit_tiny_patch16_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=16, embed_dim=192, depth=12, num_heads=3)
    return _create_vision_transformer('vit_tiny_patch16_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_tiny_patch16_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=16, embed_dim=192, depth=12, num_heads=3)
    return _create_vision_transformer('vit_tiny_patch16_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch32_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=32, embed_dim=384, depth=12, num_heads=6)
    return _create_vision_transformer('vit_small_patch32_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch16_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=16, embed_dim=384, depth=12, num_heads=6)
    return _create_vision_transformer('vit_small_patch16_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch16_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=16, embed_dim=384, depth=12, num_heads=6)
    return _create_vision_transformer('vit_small_patch16_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch32_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=32, embed_dim=768, depth=12, num_heads=12)
    return _create_vision_transformer('vit_base_patch32_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=16, embed_dim=768, depth=12, num_heads=12)
    return _create_vision_transformer('vit_base_patch16_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=16, embed_dim=768, depth=12, num_heads=12)
    return _create_vision_transformer('vit_base_patch16_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch8_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=8, embed_dim=768, depth=12, num_heads=12)
    return _create_vision_transformer('vit_base_patch8_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_dlittle_patch16_reg1_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """Differential-attention 'little' ViT (sbb recipe, reference
    vision_transformer.py:4440)."""
    model_args = dict(
        patch_size=16, embed_dim=320, depth=14, num_heads=5, init_values=1e-5, mlp_ratio=5.6,
        class_token=False, no_embed_class=True, reg_tokens=1, global_pool='avg', attn_layer='diff',
        img_size=256,
    )
    return _create_vision_transformer(
        'vit_dlittle_patch16_reg1_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_little_patch16_reg4_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=320, depth=14, num_heads=5, init_values=1e-5, mlp_ratio=5.6,
        class_token=False, no_embed_class=True, reg_tokens=4, global_pool='avg', img_size=256,
    )
    return _create_vision_transformer(
        'vit_little_patch16_reg4_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_medium_patch16_reg4_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=512, depth=12, num_heads=8, init_values=1e-5,
        class_token=False, no_embed_class=True, reg_tokens=4, global_pool='avg', img_size=256,
    )
    return _create_vision_transformer(
        'vit_medium_patch16_reg4_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch16_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=16, embed_dim=1024, depth=24, num_heads=16)
    return _create_vision_transformer('vit_large_patch16_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch14_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=14, embed_dim=1024, depth=24, num_heads=16)
    return _create_vision_transformer('vit_large_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_huge_patch14_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=14, embed_dim=1280, depth=32, num_heads=16)
    return _create_vision_transformer('vit_huge_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch14_siglip_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=14, embed_dim=1152, depth=27, num_heads=16, mlp_ratio=3.7362,
        class_token=False, global_pool='map',
    )
    return _create_vision_transformer('vit_so400m_patch14_siglip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_vit(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """Minimal test ViT (reference vision_transformer.py:4802)."""
    model_args = dict(img_size=160, patch_size=16, embed_dim=64, depth=2, num_heads=2, mlp_ratio=3)
    return _create_vision_transformer('test_vit', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_vit2(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """Test ViT w/ global avg pool + reg tokens + layer scale."""
    model_args = dict(
        img_size=160, patch_size=16, embed_dim=64, depth=2, num_heads=2, mlp_ratio=3,
        class_token=False, reg_tokens=1, global_pool='avg', init_values=1e-5,
    )
    return _create_vision_transformer('test_vit2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_vit3(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """Test ViT w/ qk-norm + map pooling."""
    model_args = dict(
        img_size=160, patch_size=16, embed_dim=96, depth=9, num_heads=3, mlp_ratio=2,
        class_token=False, reg_tokens=1, global_pool='map', qk_norm=True,
    )
    return _create_vision_transformer('test_vit3', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_vit4(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """Test ViT w/ dynamic img size + patch dropout."""
    model_args = dict(
        img_size=160, patch_size=16, embed_dim=64, depth=2, num_heads=2, mlp_ratio=3,
        dynamic_img_size=True, patch_drop_rate=0.25,
    )
    return _create_vision_transformer('test_vit4', pretrained=pretrained, **dict(model_args, **kwargs))
