"""Vision Transformer, TPU-native.

Re-designed from the reference's VisionTransformer
(reference: timm/models/vision_transformer.py:711-1302) for JAX/XLA:
NLC tokens, explicit RNG streams, trace-time pos-embed resampling for
dynamic image sizes, rematerialised blocks for grad checkpointing.

Model contract parity (reference vision_transformer.py):
  forward_features / forward_head / __call__, get_classifier / reset_classifier,
  group_matcher, set_grad_checkpointing, forward_intermediates,
  prune_intermediate_layers, no_weight_decay, set_input_size.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    Attention, AttentionPoolLatent, DropPath, Dropout, LayerNorm, LayerScale,
    Mlp, PatchDropout, PatchEmbed, RmsNorm, SwiGLU, SwiGLUPacked, calculate_drop_path_rates,
    get_act_fn, get_norm_layer, global_pool_nlc, maybe_add_mask,
    resample_abs_pos_embed, scaled_dot_product_attention, trunc_normal_, zeros_,
)
from ..layers.drop import apply_drop_path
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import (
    BlockStackError, checkpoint_seq, drop_path_scan_inputs, resolve_block_scan,
    scan_block_stack, warn_scan_fallback,
)
from ._registry import generate_default_cfgs, register_model

__all__ = ['VisionTransformer', 'Block', 'ResPostBlock']


class Block(nnx.Module):
    """Pre-norm transformer block (reference vision_transformer.py:128-216)."""

    def __init__(
            self,
            dim: int,
            num_heads: int,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = False,
            qk_norm: bool = False,
            scale_attn_norm: bool = False,
            scale_mlp_norm: bool = False,
            proj_bias: bool = True,
            proj_drop: float = 0.0,
            attn_drop: float = 0.0,
            init_values: Optional[float] = None,
            drop_path: float = 0.0,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Callable = LayerNorm,
            mlp_layer: Callable = Mlp,
            attn_layer: Optional[Callable] = None,
            depth: int = 0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        attn_layer = attn_layer or Attention
        self.norm1 = norm_layer(dim, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.attn = attn_layer(
            dim,
            num_heads=num_heads,
            qkv_bias=qkv_bias,
            qk_norm=qk_norm,
            scale_norm=scale_attn_norm,
            proj_bias=proj_bias,
            attn_drop=attn_drop,
            proj_drop=proj_drop,
            norm_layer=norm_layer,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )
        self.ls1 = LayerScale(dim, init_values=init_values, param_dtype=param_dtype, rngs=rngs) if init_values else None
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(dim, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.mlp = mlp_layer(
            dim,
            hidden_features=int(dim * mlp_ratio),
            act_layer=act_layer,
            norm_layer=norm_layer if scale_mlp_norm else None,
            drop=proj_drop,
            bias=proj_bias,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )
        self.ls2 = LayerScale(dim, init_values=init_values, param_dtype=param_dtype, rngs=rngs) if init_values else None
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

    def __call__(self, x, attn_mask=None, drop_path_override=None):
        y = self.attn(self.norm1(x), attn_mask=attn_mask)
        if self.ls1 is not None:
            y = self.ls1(y)
        x = x + apply_drop_path(y, self.drop_path1, drop_path_override, 0)
        y = self.mlp(self.norm2(x))
        if self.ls2 is not None:
            y = self.ls2(y)
        x = x + apply_drop_path(y, self.drop_path2, drop_path_override, 1)
        return x


class ResPostBlock(nnx.Module):
    """Post-norm residual block (reference vision_transformer.py:217-291)."""

    def __init__(
            self,
            dim: int,
            num_heads: int,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = False,
            qk_norm: bool = False,
            scale_attn_norm: bool = False,
            scale_mlp_norm: bool = False,
            proj_bias: bool = True,
            proj_drop: float = 0.0,
            attn_drop: float = 0.0,
            init_values: Optional[float] = None,
            drop_path: float = 0.0,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Callable = LayerNorm,
            mlp_layer: Callable = Mlp,
            attn_layer: Optional[Callable] = None,
            depth: int = 0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.init_values = init_values
        attn_cls = attn_layer or Attention
        self.attn = attn_cls(
            dim, num_heads=num_heads, qkv_bias=qkv_bias, qk_norm=qk_norm,
            scale_norm=scale_attn_norm, proj_bias=proj_bias,
            attn_drop=attn_drop, proj_drop=proj_drop, norm_layer=norm_layer,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs,
        )
        self.norm1 = norm_layer(dim, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.mlp = mlp_layer(
            dim, hidden_features=int(dim * mlp_ratio), act_layer=act_layer,
            norm_layer=norm_layer if scale_mlp_norm else None, drop=proj_drop,
            bias=proj_bias, dtype=dtype, param_dtype=param_dtype, rngs=rngs,
        )
        self.norm2 = norm_layer(dim, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path2 = DropPath(drop_path, rngs=rngs)
        # reference init: scale norm weights by init_values when provided
        if init_values is not None:
            self.norm1.scale[...] = self.norm1.scale[...] * init_values
            self.norm2.scale[...] = self.norm2.scale[...] * init_values

    def __call__(self, x, attn_mask=None, drop_path_override=None):
        x = x + apply_drop_path(
            self.norm1(self.attn(x, attn_mask=attn_mask)), self.drop_path1, drop_path_override, 0)
        x = x + apply_drop_path(
            self.norm2(self.mlp(x)), self.drop_path2, drop_path_override, 1)
        return x


class ParallelScalingBlock(nnx.Module):
    """ViT-22B-style parallel block: one fused input projection computes the
    qkv AND the MLP hidden activations from a single norm, and the attention /
    MLP branch outputs are summed into the residual
    (reference vision_transformer.py:292-421).

    TPU note: the fused in_proj is exactly the layout the MXU wants — one
    (N, C) x (C, 3C+H) matmul per block instead of two smaller ones.
    """

    def __init__(
            self,
            dim: int,
            num_heads: int,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = False,
            qk_norm: bool = False,
            scale_attn_norm: bool = False,
            scale_mlp_norm: bool = False,
            proj_bias: bool = True,
            proj_drop: float = 0.0,
            attn_drop: float = 0.0,
            init_values: Optional[float] = None,
            drop_path: float = 0.0,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Callable = LayerNorm,
            mlp_layer: Optional[Callable] = None,  # unused, fused design
            attn_layer: Optional[Callable] = None,  # unused, fused design
            depth: int = 0,  # unused
            fuse_out_proj: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert dim % num_heads == 0, 'dim should be divisible by num_heads'
        assert not scale_attn_norm and not scale_mlp_norm, 'Scale norms not supported'
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.scale = self.head_dim ** -0.5
        mlp_hidden_dim = int(mlp_ratio * dim)
        self.mlp_hidden_dim = mlp_hidden_dim

        linear = partial(nnx.Linear, dtype=dtype, param_dtype=param_dtype,
                         kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs)
        self.in_norm = norm_layer(dim, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.in_proj = linear(dim, mlp_hidden_dim + 3 * dim, use_bias=qkv_bias)
        # when in_proj has no bias, the MLP branch still gets its own bias
        self.mlp_bias = None if qkv_bias else nnx.Param(jnp.zeros((mlp_hidden_dim,), param_dtype))
        self.q_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.k_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.attn_drop_rate = attn_drop
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.mlp_drop = Dropout(proj_drop, rngs=rngs)
        self.mlp_act = get_act_fn(act_layer)
        if fuse_out_proj:
            self.out_proj = linear(dim + mlp_hidden_dim, dim, use_bias=proj_bias)
            self.attn_out_proj = None
            self.mlp_out_proj = None
        else:
            self.out_proj = None
            self.attn_out_proj = linear(dim, dim, use_bias=proj_bias)
            self.mlp_out_proj = linear(mlp_hidden_dim, dim, use_bias=proj_bias)
        self.ls = LayerScale(dim, init_values=init_values, param_dtype=param_dtype, rngs=rngs) \
            if init_values is not None else None
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def __call__(self, x, attn_mask=None):
        B, N, C = x.shape
        y = self.in_proj(self.in_norm(x))
        x_mlp, qkv = jnp.split(y, [self.mlp_hidden_dim], axis=-1)
        if self.mlp_bias is not None:
            x_mlp = x_mlp + self.mlp_bias[...].astype(x_mlp.dtype)

        q, k, v = jnp.split(qkv.reshape(B, N, 3, self.num_heads, self.head_dim)
                            .transpose(2, 0, 3, 1, 4), 3, axis=0)
        q, k, v = q[0], k[0], v[0]
        if self.q_norm is not None:
            q = self.q_norm(q)
        if self.k_norm is not None:
            k = self.k_norm(k)
        from ..layers.drop import dropout_rng_key
        dropout_p = 0.0 if self.attn_drop.deterministic else self.attn_drop_rate
        dropout_key = dropout_rng_key(self.attn_drop) if dropout_p > 0.0 else None
        x_attn = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=dropout_p, dropout_key=dropout_key, scale=self.scale)
        x_attn = x_attn.transpose(0, 2, 1, 3).reshape(B, N, C)

        x_mlp = self.mlp_drop(self.mlp_act(x_mlp))
        if self.out_proj is not None:
            y = self.out_proj(jnp.concatenate([x_attn, x_mlp], axis=-1))
        else:
            y = self.attn_out_proj(x_attn) + self.mlp_out_proj(x_mlp)
        if self.ls is not None:
            y = self.ls(y)
        return x + self.drop_path(y)


class DiffParallelScalingBlock(nnx.Module):
    """Parallel fused block with differential attention
    (reference vision_transformer.py:424-595): two softmax attention maps from
    split half-dim heads are subtracted with a learned per-layer lambda, then
    RMS-normed per head before the fused output projection."""

    def __init__(
            self,
            dim: int,
            num_heads: int,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = False,
            qk_norm: bool = False,
            scale_attn_norm: bool = False,
            scale_mlp_norm: bool = False,
            proj_bias: bool = True,
            proj_drop: float = 0.0,
            attn_drop: float = 0.0,
            init_values: Optional[float] = None,
            drop_path: float = 0.0,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Callable = LayerNorm,
            mlp_layer: Optional[Callable] = None,  # unused
            attn_layer: Optional[Callable] = None,  # unused
            depth: int = 0,
            dual_lambda: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert dim % num_heads == 0, 'dim should be divisible by num_heads'
        assert not scale_attn_norm and not scale_mlp_norm, 'Scale norms not supported'
        self.num_heads = num_heads
        self.head_dim = dim // num_heads // 2  # half head_dim for diff attention
        self.scale = self.head_dim ** -0.5
        mlp_hidden_dim = int(mlp_ratio * dim)
        self.mlp_hidden_dim = mlp_hidden_dim

        linear = partial(nnx.Linear, dtype=dtype, param_dtype=param_dtype,
                         kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs)
        self.in_norm = norm_layer(dim, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.in_proj = linear(dim, mlp_hidden_dim + 3 * dim, use_bias=qkv_bias)
        self.mlp_bias = None if qkv_bias else nnx.Param(jnp.zeros((mlp_hidden_dim,), param_dtype))
        self.q_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.k_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.sub_norm = RmsNorm(2 * self.head_dim, eps=1e-5, rngs=rngs)
        self.dual_lambda = dual_lambda
        key = rngs.params()
        if dual_lambda:
            self.lambda_a = nnx.Param(jnp.zeros((), jnp.float32))
            self.lambda_b = nnx.Param(jnp.zeros((), jnp.float32))
            self.lambda_q1 = self.lambda_k1 = self.lambda_q2 = self.lambda_k2 = None
        else:
            ks = jax.random.split(key, 4)
            self.lambda_a = self.lambda_b = None
            self.lambda_q1 = nnx.Param(jax.random.normal(ks[0], (self.head_dim,), jnp.float32) * 0.1)
            self.lambda_k1 = nnx.Param(jax.random.normal(ks[1], (self.head_dim,), jnp.float32) * 0.1)
            self.lambda_q2 = nnx.Param(jax.random.normal(ks[2], (self.head_dim,), jnp.float32) * 0.1)
            self.lambda_k2 = nnx.Param(jax.random.normal(ks[3], (self.head_dim,), jnp.float32) * 0.1)
        self.mlp_drop = Dropout(proj_drop, rngs=rngs)
        self.mlp_act = get_act_fn(act_layer)
        self.out_proj = linear(dim + mlp_hidden_dim, dim, use_bias=proj_bias)
        self.ls = LayerScale(dim, init_values=init_values, param_dtype=param_dtype, rngs=rngs) \
            if init_values is not None else None
        self.drop_path = DropPath(drop_path, rngs=rngs)
        self.lambda_init = 0.8 - 0.6 * math.exp(-0.3 * depth)

    def _compute_lambda(self):
        if self.lambda_a is not None:
            l1 = jnp.exp(self.lambda_a[...])
            l2 = jnp.exp(self.lambda_b[...])
        else:
            l1 = jnp.exp(jnp.sum(self.lambda_q1[...] * self.lambda_k1[...]))
            l2 = jnp.exp(jnp.sum(self.lambda_q2[...] * self.lambda_k2[...]))
        return l1 - l2 + self.lambda_init

    def __call__(self, x, attn_mask=None):
        B, N, C = x.shape
        y = self.in_proj(self.in_norm(x))
        x_mlp, qkv = jnp.split(y, [self.mlp_hidden_dim], axis=-1)
        if self.mlp_bias is not None:
            x_mlp = x_mlp + self.mlp_bias[...].astype(x_mlp.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        # 2x heads with half head_dim for q/k; v keeps full head width
        q = q.reshape(B, N, 2 * self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        k = k.reshape(B, N, 2 * self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        v = v.reshape(B, N, self.num_heads, 2 * self.head_dim).transpose(0, 2, 1, 3)
        if self.q_norm is not None:
            q = self.q_norm(q)
        if self.k_norm is not None:
            k = self.k_norm(k)
        lambda_full = self._compute_lambda().astype(q.dtype)

        attn = (q * self.scale) @ k.transpose(0, 1, 3, 2)
        attn = maybe_add_mask(attn, attn_mask)
        attn = jax.nn.softmax(attn, axis=-1)
        attn = self.attn_drop(attn)
        attn = attn.reshape(B, self.num_heads, 2, N, N)
        attn = attn[:, :, 0] - lambda_full * attn[:, :, 1]
        x_attn = attn @ v
        x_attn = self.sub_norm(x_attn)
        x_attn = x_attn * (1 - self.lambda_init)
        x_attn = x_attn.transpose(0, 2, 1, 3).reshape(B, N, C)

        x_mlp = self.mlp_drop(self.mlp_act(x_mlp))
        y = self.out_proj(jnp.concatenate([x_attn, x_mlp], axis=-1))
        if self.ls is not None:
            y = self.ls(y)
        return x + self.drop_path(y)


class _AttnBranch(nnx.Module):
    """norm → attn → layer-scale → drop-path branch of ParallelThingsBlock
    (keeps the reference's ``attns.N.{norm,attn,ls}`` state naming)."""

    def __init__(self, dim, attn_cls, norm_cls, init_values, drop_path, *,
                 param_dtype=jnp.float32, rngs: nnx.Rngs, **attn_kwargs):
        self.norm = norm_cls(dim, rngs=rngs)
        self.attn = attn_cls(dim, **attn_kwargs, rngs=rngs)
        self.ls = LayerScale(dim, init_values=init_values, param_dtype=param_dtype, rngs=rngs) \
            if init_values else None
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def __call__(self, x, attn_mask=None):
        y = self.attn(self.norm(x), attn_mask=attn_mask)
        if self.ls is not None:
            y = self.ls(y)
        return self.drop_path(y)


class _FfnBranch(nnx.Module):
    """norm → mlp → layer-scale → drop-path branch of ParallelThingsBlock."""

    def __init__(self, dim, mlp_layer, norm_cls, init_values, drop_path, *,
                 param_dtype=jnp.float32, rngs: nnx.Rngs, **mlp_kwargs):
        self.norm = norm_cls(dim, rngs=rngs)
        self.mlp = mlp_layer(dim, **mlp_kwargs, rngs=rngs)
        self.ls = LayerScale(dim, init_values=init_values, param_dtype=param_dtype, rngs=rngs) \
            if init_values else None
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def __call__(self, x):
        y = self.mlp(self.norm(x))
        if self.ls is not None:
            y = self.ls(y)
        return self.drop_path(y)


class ParallelThingsBlock(nnx.Module):
    """'Three things' parallel block: N parallel attentions then N parallel
    MLPs, each branch summed into the residual
    (reference vision_transformer.py:598-682)."""

    def __init__(
            self,
            dim: int,
            num_heads: int,
            num_parallel: int = 2,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = False,
            qk_norm: bool = False,
            scale_attn_norm: bool = False,
            scale_mlp_norm: bool = False,
            proj_bias: bool = True,
            init_values: Optional[float] = None,
            proj_drop: float = 0.0,
            attn_drop: float = 0.0,
            drop_path: float = 0.0,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Callable = LayerNorm,
            mlp_layer: Callable = Mlp,
            attn_layer: Optional[Callable] = None,
            depth: int = 0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        attn_cls = attn_layer or Attention
        self.num_parallel = num_parallel
        self.attns = nnx.List([
            _AttnBranch(
                dim, attn_cls, norm_layer, init_values, drop_path,
                num_heads=num_heads, qkv_bias=qkv_bias, qk_norm=qk_norm,
                scale_norm=scale_attn_norm, proj_bias=proj_bias, attn_drop=attn_drop,
                proj_drop=proj_drop, norm_layer=norm_layer,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs,
            ) for _ in range(num_parallel)])
        self.ffns = nnx.List([
            _FfnBranch(
                dim, mlp_layer, norm_layer, init_values, drop_path,
                hidden_features=int(dim * mlp_ratio), act_layer=act_layer,
                norm_layer=norm_layer if scale_mlp_norm else None,
                bias=proj_bias, drop=proj_drop,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs,
            ) for _ in range(num_parallel)])

    def __call__(self, x, attn_mask=None):
        x = x + sum(attn(x, attn_mask=attn_mask) for attn in self.attns)
        x = x + sum(ffn(x) for ffn in self.ffns)
        return x


class VisionTransformer(nnx.Module):
    """ViT with the reference's full model contract."""

    dynamic_img_size: bool

    def __init__(
            self,
            img_size: Union[int, Tuple[int, int]] = 224,
            patch_size: Union[int, Tuple[int, int]] = 16,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'token',
            embed_dim: int = 768,
            depth: int = 12,
            num_heads: int = 12,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = True,
            qk_norm: bool = False,
            scale_attn_norm: bool = False,
            scale_mlp_norm: bool = False,
            proj_bias: bool = True,
            init_values: Optional[float] = None,
            class_token: bool = True,
            pos_embed: str = 'learn',
            no_embed_class: bool = False,
            reg_tokens: int = 0,
            pre_norm: bool = False,
            final_norm: bool = True,
            fc_norm: Optional[bool] = None,
            dynamic_img_size: bool = False,
            dynamic_img_pad: bool = False,
            drop_rate: float = 0.0,
            pos_drop_rate: float = 0.0,
            patch_drop_rate: float = 0.0,
            proj_drop_rate: float = 0.0,
            attn_drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            weight_init: str = '',
            fix_init: bool = False,
            embed_layer: Callable = PatchEmbed,
            embed_norm_layer: Optional[Union[str, Callable]] = None,
            norm_layer: Optional[Union[str, Callable]] = None,
            act_layer: Optional[Union[str, Callable]] = None,
            block_fn: Callable = Block,
            mlp_layer: Callable = Mlp,
            attn_layer: Optional[Union[str, Callable]] = None,
            pad_tokens_to: Optional[Union[int, str]] = None,
            block_scan: Optional[bool] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert global_pool in ('', 'avg', 'avgmax', 'max', 'token', 'map')
        assert class_token or global_pool != 'token'
        assert pos_embed in ('', 'none', 'learn')
        norm_layer = get_norm_layer(norm_layer) or LayerNorm
        act_layer = act_layer or 'gelu'

        # TPU tile alignment: pad the token sequence once at embed time so the
        # (B·H, N, N) attention matmuls and softmax land on lane/sublane tile
        # boundaries (PERF.md §2 item 1: N=197 wastes up to ~23% of MXU issue
        # on ~28% of ViT FLOPs). 'auto' rounds up to the next sublane multiple
        # (197 → 200); an int pads to exactly that count (e.g. 256 for a full
        # lane tile). Pad keys are excluded via a key-padding mask threaded
        # through every block, and the pad is stripped again before
        # forward_head, so outputs match the unpadded model to fp precision.
        # None (default) traces the exact pre-padding graph.
        if pad_tokens_to is not None and pad_tokens_to != 'auto':
            pad_tokens_to = int(pad_tokens_to)
            if pad_tokens_to == 0:
                pad_tokens_to = None
        if pad_tokens_to is not None and patch_drop_rate > 0:
            raise ValueError(
                'pad_tokens_to is incompatible with patch_drop_rate > 0: '
                'PatchDropout re-indexes the token sequence, invalidating the pad mask')
        self.pad_tokens_to = pad_tokens_to

        self.num_classes = num_classes
        self.global_pool = global_pool
        self.num_features = self.head_hidden_size = self.embed_dim = embed_dim
        self.num_prefix_tokens = 1 if class_token else 0
        self.num_prefix_tokens += reg_tokens
        self.num_reg_tokens = reg_tokens
        self.has_class_token = class_token
        self.no_embed_class = no_embed_class
        self.dynamic_img_size = dynamic_img_size
        self.grad_checkpointing = False
        self.depth = depth
        # scan-over-layers execution: one lax.scan over stacked per-layer
        # params instead of a Python loop over L traced block subgraphs —
        # O(1)-in-depth trace/compile. None → TIMM_TPU_BLOCK_SCAN env toggle.
        self.block_scan = resolve_block_scan(block_scan)

        embed_args = {}
        if dynamic_img_size:
            embed_args.update(dict(strict_img_size=False))
        if embed_norm_layer is not None:
            embed_args['norm_layer'] = get_norm_layer(embed_norm_layer)
        self.patch_embed = embed_layer(
            img_size=img_size,
            patch_size=patch_size,
            in_chans=in_chans,
            embed_dim=embed_dim,
            bias=not pre_norm,  # pre-norm (CLIP) ViTs have no patch-proj bias
            dynamic_img_pad=dynamic_img_pad,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
            **embed_args,
        )
        num_patches = self.patch_embed.num_patches
        if hasattr(self.patch_embed, 'feat_ratio'):
            # hybrid embeds: backbone stride x patch size (reference vision_transformer.py:552)
            reduction = self.patch_embed.feat_ratio()
        elif hasattr(self.patch_embed, 'patch_size'):
            reduction = self.patch_embed.patch_size[0]
        else:
            reduction = 16

        self.cls_token = nnx.Param(
            jnp.zeros((1, 1, embed_dim), param_dtype)) if class_token else None
        self.reg_token = nnx.Param(
            trunc_normal_(std=0.02)(rngs.params(), (1, reg_tokens, embed_dim), param_dtype)) if reg_tokens else None

        embed_len = num_patches if no_embed_class else num_patches + self.num_prefix_tokens
        if not pos_embed or pos_embed == 'none':
            self.pos_embed = None
        else:
            self.pos_embed = nnx.Param(
                trunc_normal_(std=0.02)(rngs.params(), (1, embed_len, embed_dim), param_dtype))
        self.pos_drop = Dropout(pos_drop_rate, rngs=rngs)
        if patch_drop_rate > 0:
            self.patch_drop = PatchDropout(patch_drop_rate, num_prefix_tokens=self.num_prefix_tokens, rngs=rngs)
        else:
            self.patch_drop = None
        self.norm_pre = norm_layer(embed_dim, rngs=rngs) if pre_norm else None

        def _resolve_attn_layer(i: int):
            if attn_layer is None:
                return None
            if attn_layer == 'diff':
                from ..layers.diff_attention import DiffAttention
                return partial(DiffAttention, depth=i)  # depth-dependent lambda_init
            return attn_layer

        dpr = calculate_drop_path_rates(drop_path_rate, depth)
        self.blocks = nnx.List([
            block_fn(
                dim=embed_dim,
                num_heads=num_heads,
                mlp_ratio=mlp_ratio,
                qkv_bias=qkv_bias,
                qk_norm=qk_norm,
                scale_attn_norm=scale_attn_norm,
                scale_mlp_norm=scale_mlp_norm,
                proj_bias=proj_bias,
                init_values=init_values,
                proj_drop=proj_drop_rate,
                attn_drop=attn_drop_rate,
                drop_path=dpr[i],
                norm_layer=norm_layer,
                act_layer=act_layer,
                mlp_layer=mlp_layer,
                attn_layer=_resolve_attn_layer(i),
                depth=i,
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
            for i in range(depth)
        ])
        self.feature_info = [
            dict(module=f'blocks.{i}', num_chs=embed_dim, reduction=reduction) for i in range(depth)]

        # feature norm (pre-pool) vs fc norm (post-pool)
        if fc_norm is None:
            fc_norm = global_pool == 'avg'
        self.norm = norm_layer(embed_dim, rngs=rngs) if final_norm and not fc_norm else None

        # head
        if global_pool == 'map':
            self.attn_pool = AttentionPoolLatent(
                self.embed_dim,
                num_heads=num_heads,
                mlp_ratio=mlp_ratio,
                norm_layer=norm_layer,
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
        else:
            self.attn_pool = None
        self.fc_norm = norm_layer(embed_dim, rngs=rngs) if final_norm and fc_norm else None
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.head = nnx.Linear(
            self.embed_dim, num_classes,
            kernel_init=trunc_normal_(std=0.02),
            bias_init=lambda key, shape, dtype=jnp.float32: jnp.zeros(shape, dtype),
            dtype=dtype, param_dtype=param_dtype, rngs=rngs,
        ) if num_classes > 0 else None

        self._dtype = dtype
        self._param_dtype = param_dtype

        if fix_init:
            self.fix_init_weight()

    def fix_init_weight(self):
        """Rescale block projections by depth (reference vision_transformer.py:~980)."""
        for layer_id, block in enumerate(self.blocks):
            scale = math.sqrt(2.0 * (layer_id + 1))
            block.attn.proj.kernel[...] = block.attn.proj.kernel[...] / scale
            block.mlp.fc2.kernel[...] = block.mlp.fc2.kernel[...] / scale

    # ---- contract methods -------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'pos_embed', 'cls_token', 'reg_token', 'dist_token'}

    def group_matcher(self, coarse: bool = False) -> Dict:
        return dict(
            stem=r'^cls_token|pos_embed|patch_embed|reg_token',
            blocks=[(r'^blocks\.(\d+)', None), (r'^norm', (99999,))],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def set_block_scan(self, enable: bool = True):
        """Toggle scan-over-layers execution of the block stack. When the
        stack is not scannable (heterogeneous blocks, active inner dropout),
        each forward transparently falls back to the Python loop (logged once)."""
        self.block_scan = enable

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs: Optional[nnx.Rngs] = None):
        self.num_classes = num_classes
        if global_pool is not None:
            assert global_pool in ('', 'avg', 'avgmax', 'max', 'token', 'map')
            if global_pool == 'map' and self.attn_pool is None:
                raise AssertionError("Cannot currently add attention pooling in reset_classifier().")
            if global_pool != 'map':
                self.attn_pool = None
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.head = nnx.Linear(
            self.embed_dim, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs,
        ) if num_classes > 0 else None

    def set_input_size(self, img_size=None, patch_size=None):
        """Resample learned pos embed for a new static input size
        (reference vision_transformer.py:1013)."""
        if img_size is None:
            return
        prev_grid = self.patch_embed.grid_size
        self.patch_embed.set_input_size(img_size=img_size, patch_size=patch_size)
        new_grid = self.patch_embed.grid_size
        if self.pos_embed is not None and new_grid != prev_grid:
            # shape changes, so the Param must be replaced, not assigned into
            self.pos_embed = nnx.Param(resample_abs_pos_embed(
                self.pos_embed[...],
                new_size=new_grid,
                old_size=prev_grid,
                num_prefix_tokens=0 if self.no_embed_class else self.num_prefix_tokens,
            ))

    # ---- forward ----------------------------------------------------------
    def _resolve_pad_len(self, n: int, pad_tokens_to=None) -> int:
        """Padded sequence length for an n-token sequence (== n when the
        padding knob is off or n is already aligned)."""
        pad = pad_tokens_to if pad_tokens_to is not None else self.pad_tokens_to
        if not pad:
            return n
        if pad == 'auto':
            return -(-n // 8) * 8  # next sublane multiple: 197 → 200
        target = int(pad)
        if target < n:
            raise ValueError(f'pad_tokens_to={target} is smaller than the token count {n}')
        return target

    def _pos_embed(self, x, grid_size: Optional[Tuple[int, int]] = None, pad_tokens_to=None):
        """Prefix-token concat + position embedding, then (optionally) the
        tile-alignment pad. `pad_tokens_to` overrides the constructor knob for
        this call (0 disables). Returns (tokens, key_padding_mask, orig_len);
        the mask is None and orig_len == tokens.shape[1] when no pad was added.
        """
        B = x.shape[0]
        if self.pos_embed is None:
            pos_embed = None
        else:
            pos_embed = self.pos_embed[...].astype(x.dtype)
            if self.dynamic_img_size and grid_size is not None and grid_size != self.patch_embed.grid_size:
                pos_embed = resample_abs_pos_embed(
                    pos_embed,
                    new_size=grid_size,
                    old_size=self.patch_embed.grid_size,
                    num_prefix_tokens=0 if self.no_embed_class else self.num_prefix_tokens,
                )

        to_cat = []
        if self.cls_token is not None:
            to_cat.append(jnp.broadcast_to(self.cls_token[...].astype(x.dtype), (B, 1, x.shape[-1])))
        if self.reg_token is not None:
            to_cat.append(jnp.broadcast_to(self.reg_token[...].astype(x.dtype), (B, self.num_reg_tokens, x.shape[-1])))

        if self.no_embed_class:
            if pos_embed is not None:
                x = x + pos_embed
            if to_cat:
                x = jnp.concatenate(to_cat + [x], axis=1)
        else:
            if to_cat:
                x = jnp.concatenate(to_cat + [x], axis=1)
            if pos_embed is not None:
                x = x + pos_embed
        x = self.pos_drop(x)
        return self._pad_token_seq(x, pad_tokens_to)

    def _pad_token_seq(self, x, pad_tokens_to=None):
        """Apply the tile-alignment pad to (B, N, C) tokens.
        Returns (tokens, key_padding_mask, orig_len); mask is None when no
        pad was added."""
        B, n = x.shape[0], x.shape[1]
        n_pad = self._resolve_pad_len(n, pad_tokens_to)
        if n_pad == n:
            return x, None, n
        x = jnp.pad(x, ((0, 0), (0, n_pad - n), (0, 0)))
        # key-padding mask, True = real token, broadcast over heads/queries
        mask = jnp.broadcast_to((jnp.arange(n_pad) < n)[None, None, None, :], (B, 1, 1, n_pad))
        return x, mask, n

    def forward_features(self, x, attn_mask=None):
        grid_size = None
        if self.dynamic_img_size:
            grid_size = self.patch_embed.dynamic_feat_size(x.shape[1:3])
        x = self.patch_embed(x)
        # an externally supplied attn_mask is sized for the UNPADDED sequence,
        # so the alignment pad is skipped for that call
        x, pad_mask, orig_len = self._pos_embed(
            x, grid_size=grid_size, pad_tokens_to=0 if attn_mask is not None else None)
        if pad_mask is not None:
            attn_mask = pad_mask
        if self.patch_drop is not None:
            x = self.patch_drop(x)
        if self.norm_pre is not None:
            x = self.norm_pre(x)
        x = self._forward_block_stack(x, attn_mask=attn_mask)
        if self.norm is not None:
            x = self.norm(x)
        if x.shape[1] != orig_len:
            x = x[:, :orig_len]  # strip the alignment pad before the head
        return x

    def _forward_block_stack(self, x, attn_mask=None, collect=False, blocks=None):
        """Execute the block stack. With `block_scan` on and a homogeneous
        stack: one lax.scan over stacked per-layer params (O(1)-in-depth
        trace/compile; remat-inside-scan replaces checkpoint_seq when grad
        checkpointing is on; per-layer DropPath rates ride a scanned rate
        vector). Otherwise: the Python loop (checkpoint_seq when grad
        checkpointing and unmasked). `collect=True` additionally returns the
        list of per-layer outputs (forward_intermediates). Either path pins
        the residual stream to the tensor-parallel layout on 'model' meshes
        (scan does it on the carry inside scan_block_stack)."""
        from ..parallel import shard_activation
        blocks = self.blocks if blocks is None else blocks
        if self.block_scan:
            try:
                dp = drop_path_scan_inputs(blocks)

                def call(blk, xx, extra):
                    return blk(xx, attn_mask=attn_mask, drop_path_override=extra)

                out = scan_block_stack(
                    blocks, x, call, per_layer=dp,
                    remat=self.grad_checkpointing, collect=collect)
                if collect:
                    final, ys = out
                    return final, [ys[i] for i in range(ys.shape[0])]
                return out
            except BlockStackError as e:
                warn_scan_fallback(type(self).__name__, e)
        x = shard_activation(x, 'residual')
        if collect:
            outs = []
            for blk in blocks:
                x = shard_activation(blk(x, attn_mask=attn_mask), 'residual')
                outs.append(x)
            return x, outs
        if self.grad_checkpointing and attn_mask is None:
            return checkpoint_seq(blocks, x)
        for blk in blocks:
            x = shard_activation(blk(x, attn_mask=attn_mask), 'residual')
        return x

    def pool(self, x, pool_type: Optional[str] = None, mask=None):
        """`mask` (optional key-padding mask, True = valid) supports pooling a
        still-padded token sequence; the standard forward path strips the
        alignment pad before the head, so it passes None."""
        if self.attn_pool is not None:
            return self.attn_pool(x, attn_mask=mask)
        pool_type = self.global_pool if pool_type is None else pool_type
        return global_pool_nlc(x, pool_type=pool_type, num_prefix_tokens=self.num_prefix_tokens, mask=mask)

    def forward_head(self, x, pre_logits: bool = False):
        x = self.pool(x)
        if self.fc_norm is not None:
            x = self.fc_norm(x)
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        return self.head(x)

    def __call__(self, x, attn_mask=None):
        x = self.forward_features(x, attn_mask=attn_mask)
        x = self.forward_head(x)
        return x

    # ---- intermediates ----------------------------------------------------
    def forward_intermediates(
            self,
            x,
            indices: Optional[Union[int, List[int]]] = None,
            return_prefix_tokens: bool = False,
            norm: bool = False,
            stop_early: bool = False,
            output_fmt: str = 'NHWC',
            intermediates_only: bool = False,
            attn_mask=None,
    ):
        """Collect intermediate block outputs (reference vision_transformer.py:1077).

        With `block_scan` on, the full-depth path runs the scan with stacked
        per-layer outputs and gathers `indices` from them. `stop_early=True`
        slices the Python block list, which a stacked scan cannot represent —
        that path (like a pruned model, see `prune_intermediate_layers`) always
        uses the Python loop, so results never silently disagree with the
        sliced `self.blocks`.
        """
        assert output_fmt in ('NHWC', 'NLC'), 'Output format must be NHWC or NLC.'
        reshape = output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)

        B, H, W, _ = x.shape
        grid_size = self.patch_embed.dynamic_feat_size((H, W)) if self.dynamic_img_size \
            else self.patch_embed.grid_size
        x = self.patch_embed(x)
        # no alignment pad here: intermediates are reshaped to spatial grids
        x, _, _ = self._pos_embed(x, grid_size=grid_size if self.dynamic_img_size else None, pad_tokens_to=0)
        if self.patch_drop is not None:
            x = self.patch_drop(x)
        if self.norm_pre is not None:
            x = self.norm_pre(x)

        if stop_early:
            # scan runs the full stacked depth; early stop needs the loop
            intermediates = []
            for i, blk in enumerate(self.blocks[:max_index + 1]):
                x = blk(x, attn_mask=attn_mask)
                if i in take_indices:
                    intermediates.append(self.norm(x) if (norm and self.norm is not None) else x)
        else:
            x, outs = self._forward_block_stack(x, attn_mask=attn_mask, collect=True)
            intermediates = [
                self.norm(outs[i]) if (norm and self.norm is not None) else outs[i]
                for i in range(len(outs)) if i in take_indices]

        # split prefix tokens, reshape spatial
        prefix_tokens = None
        if self.num_prefix_tokens:
            prefix_tokens = [y[:, 0:self.num_prefix_tokens] for y in intermediates]
            intermediates = [y[:, self.num_prefix_tokens:] for y in intermediates]
        if reshape:
            intermediates = [
                y.reshape(B, grid_size[0], grid_size[1], -1) for y in intermediates]
        if return_prefix_tokens and prefix_tokens is not None:
            intermediates = list(zip(intermediates, prefix_tokens))

        if intermediates_only:
            return intermediates
        if self.norm is not None:
            x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(
            self,
            indices: Union[int, List[int]] = 1,
            prune_norm: bool = False,
            prune_head: bool = True,
    ):
        """Safe under `block_scan`: the scan stacks whatever `self.blocks`
        currently holds at call time, so a pruned stack scans at its pruned
        depth (and a single remaining block falls back to the loop)."""
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        self.blocks = nnx.List(list(self.blocks)[:max_index + 1])
        if prune_norm:
            self.norm = None
        if prune_head:
            self.fc_norm = None
            self.attn_pool = None
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict: Dict, model) -> Dict:
    """Convert reference-timm torch checkpoints → this module's state layout."""
    from ._torch_convert import convert_torch_state_dict
    return convert_torch_state_dict(state_dict, model)


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': None,
        'crop_pct': 0.9,
        'interpolation': 'bicubic',
        'fixed_input_size': True,
        'mean': (0.5, 0.5, 0.5),
        'std': (0.5, 0.5, 0.5),
        'first_conv': 'patch_embed.proj',
        'classifier': 'head',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'vit_tiny_patch16_224.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'vit_tiny_patch16_384.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_small_patch32_224.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'vit_small_patch16_224.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'vit_small_patch16_384.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_base_patch32_224.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'vit_base_patch16_224.augreg2_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'vit_base_patch16_224.augreg_in1k': _cfg(hf_hub_id='timm/'),
    'vit_base_patch16_384.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_base_patch8_224.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'vit_large_patch16_224.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'vit_dlittle_patch16_reg1_gap_256.sbb_nadamuon_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_little_patch16_reg4_gap_256.sbb_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_medium_patch16_reg4_gap_256.sbb_in12k_ft_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_large_patch14_224.untrained': _cfg(url=''),
    'vit_huge_patch14_224.untrained': _cfg(url=''),
    'vit_so400m_patch14_siglip_224.untrained': _cfg(url=''),
    'vit_tiny_patch16_224.untrained': _cfg(url=''),
    # tiny test fixtures (reference vision_transformer.py:4802-4833)
    'vit_small_patch32_384.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_small_patch8_224.dino': _cfg(hf_hub_id='timm/', num_classes=0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'vit_base_patch32_384.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_base_patch32_384.augreg_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_large_patch32_224.orig_in21k': _cfg(hf_hub_id='timm/', num_classes=0),
    'vit_large_patch32_384.orig_in21k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_large_patch16_384.augreg_in21k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_giant_patch14_224.untrained': _cfg(),
    'vit_gigantic_patch14_224.untrained': _cfg(),
    'vit_base_patch16_224_miil.in21k': _cfg(hf_hub_id='timm/', num_classes=11221, crop_pct=0.875, interpolation='bilinear', mean=(0.0, 0.0, 0.0), std=(1.0, 1.0, 1.0)),
    'vit_base_patch16_224_miil.in21k_ft_in1k': _cfg(hf_hub_id='timm/', crop_pct=0.875, interpolation='bilinear', mean=(0.0, 0.0, 0.0), std=(1.0, 1.0, 1.0)),
    'vit_medium_patch16_gap_240.sw_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 240, 240), crop_pct=0.95),
    'vit_medium_patch16_gap_256.sw_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_medium_patch16_gap_384.sw_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=0.95, crop_mode='squash'),
    'vit_betwixt_patch16_gap_256.untrained': _cfg(input_size=(3, 256, 256), crop_pct=0.95),
    'vit_base_patch16_gap_224.untrained': _cfg(),
    'vit_huge_patch14_gap_224.in1k_ijepa': _cfg(num_classes=0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'vit_huge_patch14_gap_224.in22k_ijepa': _cfg(num_classes=0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'vit_huge_patch16_gap_448.in1k_ijepa': _cfg(num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'vit_giant_patch16_gap_224.in22k_ijepa': _cfg(num_classes=0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'vit_xsmall_patch16_clip_224.tinyclip_yfcc15m': _cfg(hf_hub_id='timm/', num_classes=512, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_medium_patch32_clip_224.tinyclip_laion400m': _cfg(hf_hub_id='timm/', num_classes=512, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_medium_patch16_clip_224.tinyclip_yfcc15m': _cfg(hf_hub_id='timm/', num_classes=512, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_betwixt_patch32_clip_224.tinyclip_laion400m': _cfg(hf_hub_id='timm/', num_classes=512, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_224.laion2b_ft_in12k_in1k': _cfg(hf_hub_id='timm/', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_224.openai_ft_in12k_in1k': _cfg(mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_224.laion2b_ft_in1k': _cfg(hf_hub_id='timm/', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_224.openai_ft_in1k': _cfg(hf_hub_id='timm/', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_224.laion2b': _cfg(hf_hub_id='timm/', num_classes=512, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_224.laion400m_e32': _cfg(hf_hub_id='timm/', num_classes=512, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_224.datacompxl': _cfg(hf_hub_id='timm/', num_classes=512, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_224.metaclip_2pt5b': _cfg(hf_hub_id='timm/', num_classes=512, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_224.metaclip_400m': _cfg(hf_hub_id='timm/', num_classes=512, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_224.openai': _cfg(hf_hub_id='timm/', num_classes=512, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_256.datacompxl': _cfg(hf_hub_id='timm/', num_classes=512, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_384.laion2b_ft_in12k_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_384.openai_ft_in12k_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=0.95, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_448.laion2b_ft_in12k_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 448, 448), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_224.laion2b_ft_in12k_in1k': _cfg(hf_hub_id='timm/', crop_pct=0.95, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_224.openai_ft_in12k_in1k': _cfg(hf_hub_id='timm/', crop_pct=0.95, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_224.laion2b_ft_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_224.openai_ft_in1k': _cfg(hf_hub_id='timm/', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_224.laion2b_ft_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_224.openai_ft_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_224.laion2b': _cfg(hf_hub_id='timm/', num_classes=512, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_224.laion400m_e32': _cfg(hf_hub_id='timm/', num_classes=512, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_224.datacompxl': _cfg(hf_hub_id='timm/', num_classes=512, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_224.dfn2b': _cfg(hf_hub_id='timm/', num_classes=512, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_224.metaclip_2pt5b': _cfg(hf_hub_id='timm/', num_classes=512, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_224.metaclip_400m': _cfg(hf_hub_id='timm/', num_classes=512, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_224.openai': _cfg(hf_hub_id='timm/', num_classes=512, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_384.laion2b_ft_in12k_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_384.openai_ft_in12k_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=0.95, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_384.laion2b_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_384.openai_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_plus_clip_240.laion400m_e32': _cfg(hf_hub_id='timm/', num_classes=640, input_size=(3, 240, 240), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_224.laion2b_ft_in12k_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.0),
    'vit_large_patch14_clip_224.openai_ft_in12k_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_224.laion2b_ft_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.0),
    'vit_large_patch14_clip_224.openai_ft_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_224.laion2b_ft_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, crop_pct=1.0),
    'vit_large_patch14_clip_224.openai_ft_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_224.laion2b': _cfg(hf_hub_id='timm/', num_classes=768, crop_pct=1.0),
    'vit_large_patch14_clip_224.laion400m_e32': _cfg(hf_hub_id='timm/', num_classes=768, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_224.datacompxl': _cfg(hf_hub_id='timm/', num_classes=768, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_224.dfn2b_s39b': _cfg(hf_hub_id='timm/', num_classes=768, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_224.dfn2b': _cfg(hf_hub_id='timm/', num_classes=768, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_224.metaclip_2pt5b': _cfg(hf_hub_id='timm/', num_classes=768, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_224.metaclip_400m': _cfg(hf_hub_id='timm/', num_classes=768, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_224.openai': _cfg(hf_hub_id='timm/', num_classes=768, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_224.apple_mclip2_dfndr2b': _cfg(hf_hub_id='timm/', num_classes=768, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_336.laion2b_ft_in12k_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 336, 336), crop_pct=1.0, crop_mode='squash'),
    'vit_large_patch14_clip_336.openai_ft_in12k_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 336, 336), crop_pct=1.0, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_336.laion2b_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 336, 336), crop_pct=1.0, crop_mode='squash'),
    'vit_large_patch14_clip_336.openai': _cfg(hf_hub_id='timm/', num_classes=768, input_size=(3, 336, 336), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_224.laion2b_ft_in12k_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_224.laion2b_ft_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_224.laion2b_ft_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_224.laion2b': _cfg(hf_hub_id='timm/', num_classes=1024, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_224.dfn5b': _cfg(hf_hub_id='timm/', num_classes=1024, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_224.metaclip2_worldwide': _cfg(hf_hub_id='timm/', num_classes=1024, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_224.metaclip_2pt5b': _cfg(hf_hub_id='timm/', num_classes=1024, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_224.metaclip_altogether': _cfg(hf_hub_id='timm/', num_classes=1024, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_336.laion2b_ft_in12k_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 336, 336), crop_pct=1.0, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_336.laion2b_ft_in1k': _cfg(input_size=(3, 336, 336), crop_pct=1.0, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_378.dfn5b': _cfg(hf_hub_id='timm/', num_classes=1024, input_size=(3, 378, 378), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_378.metaclip2_worldwide': _cfg(hf_hub_id='timm/', num_classes=1024, input_size=(3, 378, 378), crop_pct=1.0, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_giant_patch14_clip_224.laion2b': _cfg(hf_hub_id='timm/', num_classes=1024, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_gigantic_patch14_clip_224.laion2b': _cfg(hf_hub_id='timm/', num_classes=1280, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_gigantic_patch14_clip_224.metaclip2_worldwide': _cfg(hf_hub_id='timm/', num_classes=1280, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_gigantic_patch14_clip_224.metaclip_2pt5b': _cfg(hf_hub_id='timm/', num_classes=1280, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_gigantic_patch14_clip_378.metaclip2_worldwide': _cfg(hf_hub_id='timm/', num_classes=1280, input_size=(3, 378, 378), crop_pct=1.0, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_quickgelu_224.laion400m_e32': _cfg(hf_hub_id='timm/', num_classes=512, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_quickgelu_224.metaclip_2pt5b': _cfg(hf_hub_id='timm/', num_classes=512, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_quickgelu_224.metaclip_400m': _cfg(hf_hub_id='timm/', num_classes=512, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_clip_quickgelu_224.openai': _cfg(hf_hub_id='timm/', num_classes=512, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_quickgelu_224.metaclip_2pt5b': _cfg(hf_hub_id='timm/', num_classes=512, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_quickgelu_224.metaclip_400m': _cfg(hf_hub_id='timm/', num_classes=512, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch16_clip_quickgelu_224.openai': _cfg(hf_hub_id='timm/', num_classes=512, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_quickgelu_224.dfn2b': _cfg(hf_hub_id='timm/', num_classes=768, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_quickgelu_224.metaclip_2pt5b': _cfg(hf_hub_id='timm/', num_classes=768, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_quickgelu_224.metaclip_400m': _cfg(hf_hub_id='timm/', num_classes=768, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_quickgelu_224.openai': _cfg(hf_hub_id='timm/', num_classes=768, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_large_patch14_clip_quickgelu_336.openai': _cfg(hf_hub_id='timm/', num_classes=768, input_size=(3, 336, 336), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_quickgelu_224.dfn5b': _cfg(hf_hub_id='timm/', num_classes=1024, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_quickgelu_224.metaclip2_worldwide': _cfg(hf_hub_id='timm/', num_classes=1024, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_quickgelu_224.metaclip_2pt5b': _cfg(hf_hub_id='timm/', num_classes=1024, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_huge_patch14_clip_quickgelu_378.dfn5b': _cfg(hf_hub_id='timm/', num_classes=1024, input_size=(3, 378, 378), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_gigantic_patch14_clip_quickgelu_224.metaclip_2pt5b': _cfg(hf_hub_id='timm/', num_classes=1280, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'vit_base_patch32_plus_256.untrained': _cfg(input_size=(3, 256, 256), crop_pct=0.95),
    'vit_base_patch16_plus_240.untrained': _cfg(input_size=(3, 240, 240), crop_pct=0.95),
    'vit_base_patch16_rpn_224.sw_in1k': _cfg(hf_hub_id='timm/'),
    'vit_small_patch16_36x1_224.untrained': _cfg(),
    'vit_small_patch16_18x2_224.untrained': _cfg(),
    'vit_base_patch16_18x2_224.untrained': _cfg(),
    'eva_large_patch14_196.in22k_ft_in22k_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 196, 196), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'eva_large_patch14_196.in22k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 196, 196), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'eva_large_patch14_336.in22k_ft_in22k_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 336, 336), crop_pct=1.0, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'eva_large_patch14_336.in22k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 336, 336), crop_pct=1.0, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'flexivit_small.1200ep_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 240, 240), crop_pct=0.95),
    'flexivit_small.600ep_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 240, 240), crop_pct=0.95),
    'flexivit_small.300ep_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 240, 240), crop_pct=0.95),
    'flexivit_base.1200ep_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 240, 240), crop_pct=0.95),
    'flexivit_base.600ep_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 240, 240), crop_pct=0.95),
    'flexivit_base.300ep_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 240, 240), crop_pct=0.95),
    'flexivit_base.1000ep_in21k': _cfg(hf_hub_id='timm/', num_classes=21843, input_size=(3, 240, 240), crop_pct=0.95),
    'flexivit_base.300ep_in21k': _cfg(hf_hub_id='timm/', num_classes=21843, input_size=(3, 240, 240), crop_pct=0.95),
    'flexivit_base.patch16_in21k': _cfg(hf_hub_id='timm/', num_classes=21843, input_size=(3, 240, 240), crop_pct=0.95),
    'flexivit_base.patch30_in21k': _cfg(hf_hub_id='timm/', num_classes=21843, input_size=(3, 240, 240), crop_pct=0.95),
    'flexivit_large.1200ep_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 240, 240), crop_pct=0.95),
    'flexivit_large.600ep_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 240, 240), crop_pct=0.95),
    'flexivit_large.300ep_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 240, 240), crop_pct=0.95),
    'vit_base_patch16_xp_224.untrained': _cfg(),
    'vit_large_patch14_xp_224.untrained': _cfg(),
    'vit_huge_patch14_xp_224.untrained': _cfg(),
    'vit_small_patch14_dinov2.lvd142m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 518, 518), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'vit_base_patch14_dinov2.lvd142m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 518, 518), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'vit_large_patch14_dinov2.lvd142m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 518, 518), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'vit_giant_patch14_dinov2.lvd142m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 518, 518), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'vit_small_patch14_reg4_dinov2.lvd142m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 518, 518), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'vit_base_patch14_reg4_dinov2.lvd142m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 518, 518), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'vit_large_patch14_reg4_dinov2.lvd142m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 518, 518), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'vit_giant_patch14_reg4_dinov2.lvd142m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 518, 518), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'vit_base_patch14_reg1_tipsv2.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.0, 0.0, 0.0), std=(1.0, 1.0, 1.0)),
    'vit_large_patch14_reg1_tipsv2.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.0, 0.0, 0.0), std=(1.0, 1.0, 1.0)),
    'vit_so400m_patch14_reg1_tipsv2.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.0, 0.0, 0.0), std=(1.0, 1.0, 1.0)),
    'vit_giant_patch14_reg1_tipsv2.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.0, 0.0, 0.0), std=(1.0, 1.0, 1.0)),
    'vit_base_patch32_siglip_256.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_base_patch16_siglip_224.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0),
    'vit_base_patch16_siglip_224.webli': _cfg(hf_hub_id='timm/', num_classes=0),
    'vit_base_patch16_siglip_256.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_base_patch16_siglip_256.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_base_patch16_siglip_256.webli_i18n': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_base_patch16_siglip_384.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 384, 384)),
    'vit_base_patch16_siglip_384.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 384, 384)),
    'vit_base_patch16_siglip_512.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 512, 512)),
    'vit_base_patch16_siglip_512.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 512, 512)),
    'vit_large_patch16_siglip_256.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_large_patch16_siglip_256.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_large_patch16_siglip_384.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 384, 384)),
    'vit_large_patch16_siglip_384.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 384, 384)),
    'vit_large_patch16_siglip_512.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 512, 512)),
    'vit_so400m_patch14_siglip_378.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 378, 378)),
    'vit_so400m_patch14_siglip_378.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 378, 378)),
    'vit_so400m_patch14_siglip_378.webli_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 378, 378), crop_pct=1.0, crop_mode='squash'),
    'vit_so400m_patch14_siglip_384.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 384, 384)),
    'vit_so400m_patch16_siglip_256.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_so400m_patch16_siglip_256.webli_i18n': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_so400m_patch16_siglip_384.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 384, 384)),
    'vit_so400m_patch16_siglip_512.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 512, 512)),
    'vit_giantopt_patch16_siglip_256.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_giantopt_patch16_siglip_384.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 384, 384)),
    'vit_base_patch32_siglip_gap_256.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_base_patch16_siglip_gap_224.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0),
    'vit_base_patch16_siglip_gap_224.webli': _cfg(hf_hub_id='timm/', num_classes=0),
    'vit_base_patch16_siglip_gap_256.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_base_patch16_siglip_gap_256.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_base_patch16_siglip_gap_256.webli_i18n': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_base_patch16_siglip_gap_384.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 384, 384)),
    'vit_base_patch16_siglip_gap_384.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 384, 384)),
    'vit_base_patch16_siglip_gap_512.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 512, 512)),
    'vit_base_patch16_siglip_gap_512.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 512, 512)),
    'vit_large_patch16_siglip_gap_256.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_large_patch16_siglip_gap_256.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_large_patch16_siglip_gap_384.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 384, 384)),
    'vit_large_patch16_siglip_gap_384.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 384, 384)),
    'vit_large_patch16_siglip_gap_512.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 512, 512)),
    'vit_so400m_patch14_siglip_gap_224.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0),
    'vit_so400m_patch14_siglip_gap_224.webli': _cfg(hf_hub_id='timm/', num_classes=0),
    'vit_so400m_patch14_siglip_gap_224.pali_mix': _cfg(hf_hub_id='timm/', num_classes=0),
    'vit_so400m_patch14_siglip_gap_224.pali_pt': _cfg(hf_hub_id='timm/', num_classes=0),
    'vit_so400m_patch14_siglip_gap_224.pali2_3b_pt': _cfg(hf_hub_id='timm/', num_classes=0),
    'vit_so400m_patch14_siglip_gap_224.pali2_10b_pt': _cfg(hf_hub_id='timm/', num_classes=0),
    'vit_so400m_patch14_siglip_gap_378.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 378, 378)),
    'vit_so400m_patch14_siglip_gap_378.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 378, 378), crop_pct=1.0),
    'vit_so400m_patch14_siglip_gap_378.webli_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 378, 378), crop_pct=1.0, crop_mode='squash'),
    'vit_so400m_patch14_siglip_gap_384.webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 384, 384), crop_pct=1.0),
    'vit_so400m_patch14_siglip_gap_448.pali_mix': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0),
    'vit_so400m_patch14_siglip_gap_448.pali_pt': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0),
    'vit_so400m_patch14_siglip_gap_448.pali_refcoco_seg': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0),
    'vit_so400m_patch14_siglip_gap_448.pali_ocrvqa': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0),
    'vit_so400m_patch14_siglip_gap_448.pali2_3b_pt': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0),
    'vit_so400m_patch14_siglip_gap_448.pali2_10b_pt': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0),
    'vit_so400m_patch14_siglip_gap_448.pali2_3b_docci': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0),
    'vit_so400m_patch14_siglip_gap_448.pali2_10b_docci': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0),
    'vit_so400m_patch14_siglip_gap_896.pali_pt': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 896, 896), crop_pct=1.0),
    'vit_so400m_patch14_siglip_gap_896.pali_refcoco_seg': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 896, 896), crop_pct=1.0),
    'vit_so400m_patch14_siglip_gap_896.pali_ocrvqa': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 896, 896), crop_pct=1.0),
    'vit_so400m_patch14_siglip_gap_896.pali2_3b_pt': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 896, 896), crop_pct=1.0),
    'vit_so400m_patch14_siglip_gap_896.pali2_10b_pt': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 896, 896), crop_pct=1.0),
    'vit_so400m_patch16_siglip_gap_256.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_so400m_patch16_siglip_gap_256.webli_i18n': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_so400m_patch16_siglip_gap_384.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 384, 384)),
    'vit_so400m_patch16_siglip_gap_512.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 512, 512)),
    'vit_giantopt_patch16_siglip_gap_256.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256)),
    'vit_giantopt_patch16_siglip_gap_384.v2_webli': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 384, 384)),
    'vit_wee_patch16_reg1_gap_256.sbb_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_dwee_patch16_reg1_gap_256.sbb_nadamuon_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_dwee_patch16_reg1_gap_256.sbb_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_pwee_patch16_reg1_gap_256.sbb_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_dpwee_patch16_reg1_gap_256.sbb_nadamuon_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_dpwee_patch16_reg1_gap_256.sbb_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_little_patch16_reg1_gap_256.sbb_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_little_patch16_reg1_gap_256.sbb_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 256, 256), crop_pct=0.95),
    'vit_medium_patch16_reg1_gap_256.sbb_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_mediumd_patch16_reg4_gap_256.sbb2_e200_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_mediumd_patch16_reg4_gap_256.sbb_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_mediumd_patch16_reg4_gap_256.sbb2_e200_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 256, 256), crop_pct=0.95),
    'vit_mediumd_patch16_reg4_gap_256.sbb_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 256, 256), crop_pct=0.95),
    'vit_mediumd_patch16_reg4_gap_384.sbb2_e200_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_betwixt_patch16_reg1_gap_256.sbb_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_betwixt_patch16_reg4_gap_256.sbb2_e200_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_betwixt_patch16_reg4_gap_256.sbb_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_betwixt_patch16_reg4_gap_256.sbb_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_betwixt_patch16_reg4_gap_256.sbb2_e200_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 256, 256), crop_pct=0.95),
    'vit_betwixt_patch16_reg4_gap_256.sbb_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 256, 256), crop_pct=0.95),
    'vit_betwixt_patch16_reg4_gap_384.sbb2_e200_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_base_patch16_reg4_gap_256.untrained': _cfg(input_size=(3, 256, 256)),
    'vit_so150m_patch16_reg4_map_256.untrained': _cfg(input_size=(3, 256, 256)),
    'vit_so150m_patch16_reg4_gap_256.sbb_e250_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95),
    'vit_so150m_patch16_reg4_gap_256.sbb_e250_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 256, 256), crop_pct=0.95),
    'vit_so150m_patch16_reg4_gap_384.sbb_e250_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_so150m2_patch16_reg1_gap_256.sbb_e200_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=1.0),
    'vit_so150m2_patch16_reg1_gap_256.sbb_e200_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 256, 256), crop_pct=1.0),
    'vit_so150m2_patch16_reg1_gap_384.sbb_e200_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_so150m2_patch16_reg1_gap_448.sbb_e200_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 448, 448), crop_pct=1.0, crop_mode='squash'),
    'vit_intern300m_patch14_448.ogvl_dist': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'vit_intern300m_patch14_448.ogvl_2pt5': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'aimv2_large_patch14_224.apple_pt': _cfg(hf_hub_id='timm/', num_classes=0, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'aimv2_large_patch14_224.apple_pt_dist': _cfg(hf_hub_id='timm/', num_classes=0, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'aimv2_huge_patch14_224.apple_pt': _cfg(hf_hub_id='timm/', num_classes=0, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'aimv2_1b_patch14_224.apple_pt': _cfg(hf_hub_id='timm/', num_classes=0, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'aimv2_3b_patch14_224.apple_pt': _cfg(hf_hub_id='timm/', num_classes=0, crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'aimv2_large_patch14_336.apple_pt': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 336, 336), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'aimv2_large_patch14_336.apple_pt_dist': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 336, 336), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'aimv2_huge_patch14_336.apple_pt': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 336, 336), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'aimv2_1b_patch14_336.apple_pt': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 336, 336), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'aimv2_3b_patch14_336.apple_pt': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 336, 336), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'aimv2_large_patch14_448.apple_pt': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'aimv2_huge_patch14_448.apple_pt': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'aimv2_1b_patch14_448.apple_pt': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'aimv2_3b_patch14_448.apple_pt': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711)),
    'beit3_base_patch16_224.in22k_ft_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'beit3_base_patch16_224.indomain_in22k_ft_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'beit3_base_patch16_224.pt': _cfg(hf_hub_id='timm/', num_classes=0, crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'beit3_base_patch16_224.indomain_pt': _cfg(hf_hub_id='timm/', num_classes=0, crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'beit3_large_patch16_224.in22k_ft_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'beit3_large_patch16_224.indomain_in22k_ft_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'beit3_large_patch16_224.pt': _cfg(hf_hub_id='timm/', num_classes=0, crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'beit3_large_patch16_224.indomain_pt': _cfg(hf_hub_id='timm/', num_classes=0, crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'beit3_giant_patch14_224.untrained': _cfg(crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'beit3_giant_patch14_336.untrained': _cfg(input_size=(3, 336, 336), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'test_vit.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), crop_pct=0.95),
    'test_vit2.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), crop_pct=0.95),
    'test_vit3.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), crop_pct=0.95),
    'test_vit4.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), crop_pct=0.95),
})


def _create_vision_transformer(variant: str, pretrained: bool = False, **kwargs) -> VisionTransformer:
    out_indices = kwargs.pop('out_indices', 3)
    return build_model_with_cfg(
        VisionTransformer,
        variant,
        pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


@register_model
def vit_tiny_patch16_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=16, embed_dim=192, depth=12, num_heads=3)
    return _create_vision_transformer('vit_tiny_patch16_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_tiny_patch16_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=16, embed_dim=192, depth=12, num_heads=3)
    return _create_vision_transformer('vit_tiny_patch16_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch32_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=32, embed_dim=384, depth=12, num_heads=6)
    return _create_vision_transformer('vit_small_patch32_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch16_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=16, embed_dim=384, depth=12, num_heads=6)
    return _create_vision_transformer('vit_small_patch16_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch16_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=16, embed_dim=384, depth=12, num_heads=6)
    return _create_vision_transformer('vit_small_patch16_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch32_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=32, embed_dim=768, depth=12, num_heads=12)
    return _create_vision_transformer('vit_base_patch32_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=16, embed_dim=768, depth=12, num_heads=12)
    return _create_vision_transformer('vit_base_patch16_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=16, embed_dim=768, depth=12, num_heads=12)
    return _create_vision_transformer('vit_base_patch16_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch8_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=8, embed_dim=768, depth=12, num_heads=12)
    return _create_vision_transformer('vit_base_patch8_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_dlittle_patch16_reg1_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """Differential-attention 'little' ViT (sbb recipe, reference
    vision_transformer.py:4440)."""
    model_args = dict(
        patch_size=16, embed_dim=320, depth=14, num_heads=5, init_values=1e-5, mlp_ratio=5.6,
        class_token=False, no_embed_class=True, reg_tokens=1, global_pool='avg', attn_layer='diff',
        img_size=256,
    )
    return _create_vision_transformer(
        'vit_dlittle_patch16_reg1_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_little_patch16_reg4_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=320, depth=14, num_heads=5, init_values=1e-5, mlp_ratio=5.6,
        class_token=False, no_embed_class=True, reg_tokens=4, global_pool='avg', img_size=256,
    )
    return _create_vision_transformer(
        'vit_little_patch16_reg4_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_medium_patch16_reg4_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=512, depth=12, num_heads=8, init_values=1e-5,
        class_token=False, no_embed_class=True, reg_tokens=4, global_pool='avg', img_size=256,
    )
    return _create_vision_transformer(
        'vit_medium_patch16_reg4_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch16_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=16, embed_dim=1024, depth=24, num_heads=16)
    return _create_vision_transformer('vit_large_patch16_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch14_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=14, embed_dim=1024, depth=24, num_heads=16)
    return _create_vision_transformer('vit_large_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_huge_patch14_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(patch_size=14, embed_dim=1280, depth=32, num_heads=16)
    return _create_vision_transformer('vit_huge_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch14_siglip_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=14, embed_dim=1152, depth=27, num_heads=16, mlp_ratio=3.7362,
        class_token=False, global_pool='map',
    )
    return _create_vision_transformer('vit_so400m_patch14_siglip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_vit(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """Minimal test ViT (reference vision_transformer.py:4802)."""
    model_args = dict(img_size=160, patch_size=16, embed_dim=64, depth=2, num_heads=2, mlp_ratio=3)
    return _create_vision_transformer('test_vit', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_vit2(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """Test ViT w/ global avg pool + reg tokens + layer scale."""
    model_args = dict(
        img_size=160, patch_size=16, embed_dim=64, depth=2, num_heads=2, mlp_ratio=3,
        class_token=False, reg_tokens=1, global_pool='avg', init_values=1e-5,
    )
    return _create_vision_transformer('test_vit2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_vit3(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """Test ViT w/ qk-norm + map pooling."""
    model_args = dict(
        img_size=160, patch_size=16, embed_dim=96, depth=9, num_heads=3, mlp_ratio=2,
        class_token=False, reg_tokens=1, global_pool='map', qk_norm=True,
    )
    return _create_vision_transformer('test_vit3', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_vit4(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """Test ViT w/ dynamic img size + patch dropout."""
    model_args = dict(
        img_size=160, patch_size=16, embed_dim=64, depth=2, num_heads=2, mlp_ratio=3,
        dynamic_img_size=True, patch_drop_rate=0.25,
    )
    return _create_vision_transformer('test_vit4', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch32_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Small (ViT-S/32) at 384x384."""
    model_args = dict(patch_size=32, embed_dim=384, depth=12, num_heads=6)
    return _create_vision_transformer('vit_small_patch32_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch8_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Small (ViT-S/8)"""
    model_args = dict(patch_size=8, embed_dim=384, depth=12, num_heads=6)
    return _create_vision_transformer('vit_small_patch8_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch32_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Base model (ViT-B/32) from original paper (https://arxiv.org/abs/2010.11929)."""
    model_args = dict(patch_size=32, embed_dim=768, depth=12, num_heads=12)
    return _create_vision_transformer('vit_base_patch32_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch32_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Large model (ViT-L/32) from original paper (https://arxiv.org/abs/2010.11929). No pretrained weights."""
    model_args = dict(patch_size=32, embed_dim=1024, depth=24, num_heads=16)
    return _create_vision_transformer('vit_large_patch32_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch32_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Large model (ViT-L/32) from original paper (https://arxiv.org/abs/2010.11929)."""
    model_args = dict(patch_size=32, embed_dim=1024, depth=24, num_heads=16)
    return _create_vision_transformer('vit_large_patch32_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch16_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Large model (ViT-L/16) from original paper (https://arxiv.org/abs/2010.11929)."""
    model_args = dict(patch_size=16, embed_dim=1024, depth=24, num_heads=16)
    return _create_vision_transformer('vit_large_patch16_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_giant_patch14_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Giant (little-g) model (ViT-g/14) from `Scaling Vision Transformers` - https://arxiv.org/abs/2106.04560"""
    model_args = dict(patch_size=14, embed_dim=1408, mlp_ratio=48/11, depth=40, num_heads=16)
    return _create_vision_transformer('vit_giant_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_gigantic_patch14_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Gigantic (big-G) model (ViT-G/14) from `Scaling Vision Transformers` - https://arxiv.org/abs/2106.04560"""
    model_args = dict(patch_size=14, embed_dim=1664, mlp_ratio=64/13, depth=48, num_heads=16)
    return _create_vision_transformer('vit_gigantic_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_224_miil(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Base (ViT-B/16) from original paper (https://arxiv.org/abs/2010.11929)."""
    model_args = dict(patch_size=16, embed_dim=768, depth=12, num_heads=12, qkv_bias=False)
    return _create_vision_transformer('vit_base_patch16_224_miil', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_medium_patch16_gap_240(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Medium (ViT-M/16) w/o class token, w/ avg-pool @ 240x240"""
    model_args = dict(
        patch_size=16, embed_dim=512, depth=12, num_heads=8, class_token=False,
        global_pool='avg', qkv_bias=False, init_values=1e-6, fc_norm=False)
    return _create_vision_transformer('vit_medium_patch16_gap_240', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_medium_patch16_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Medium (ViT-M/16) w/o class token, w/ avg-pool @ 256x256"""
    model_args = dict(
        patch_size=16, embed_dim=512, depth=12, num_heads=8, class_token=False,
        global_pool='avg', qkv_bias=False, init_values=1e-6, fc_norm=False)
    return _create_vision_transformer('vit_medium_patch16_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_medium_patch16_gap_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Medium (ViT-M/16) w/o class token, w/ avg-pool @ 384x384"""
    model_args = dict(
        patch_size=16, embed_dim=512, depth=12, num_heads=8, class_token=False,
        global_pool='avg', qkv_bias=False, init_values=1e-6, fc_norm=False)
    return _create_vision_transformer('vit_medium_patch16_gap_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_betwixt_patch16_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Betwixt (ViT-b/16) w/o class token, w/ avg-pool @ 256x256"""
    model_args = dict(
        patch_size=16, embed_dim=640, depth=12, num_heads=10, class_token=False,
        global_pool='avg', qkv_bias=False, init_values=1e-6, fc_norm=False)
    return _create_vision_transformer('vit_betwixt_patch16_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_gap_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Base (ViT-B/16) w/o class token, w/ avg-pool @ 224x224"""
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=16, class_token=False, global_pool='avg', fc_norm=False)
    return _create_vision_transformer('vit_base_patch16_gap_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_huge_patch14_gap_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Huge model (ViT-H/14) w/ no class token, avg pool"""
    model_args = dict(
        patch_size=14, embed_dim=1280, depth=32, num_heads=16, class_token=False, global_pool='avg', fc_norm=False)
    return _create_vision_transformer('vit_huge_patch14_gap_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_huge_patch16_gap_448(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Huge model (ViT-H/16) w/ no class token, avg pool @ 448x448"""
    model_args = dict(
        patch_size=16, embed_dim=1280, depth=32, num_heads=16, class_token=False, global_pool='avg', fc_norm=False)
    return _create_vision_transformer('vit_huge_patch16_gap_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_giant_patch16_gap_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Giant (little-gg) model (ViT-g/16) w/ no class token, avg pool"""
    model_args = dict(
        patch_size=16, embed_dim=1408, depth=40, num_heads=16, mlp_ratio=48/11,
        class_token=False, global_pool='avg', fc_norm=False)
    return _create_vision_transformer('vit_giant_patch16_gap_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_xsmall_patch16_clip_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(embed_dim=256, depth=10, num_heads=4, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_xsmall_patch16_clip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_medium_patch32_clip_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=32, embed_dim=512, depth=12, num_heads=8, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_medium_patch32_clip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_medium_patch16_clip_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(embed_dim=512, depth=12, num_heads=8, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_medium_patch16_clip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_betwixt_patch32_clip_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=32, embed_dim=640, depth=12, num_heads=10, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_betwixt_patch32_clip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch32_clip_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-B/32 CLIP image tower @ 224x224"""
    model_args = dict(
        patch_size=32, embed_dim=768, depth=12, num_heads=12, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_base_patch32_clip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch32_clip_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-B/32 CLIP image tower @ 256x256"""
    model_args = dict(
        patch_size=32, embed_dim=768, depth=12, num_heads=12, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_base_patch32_clip_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch32_clip_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-B/32 CLIP image tower @ 384x384"""
    model_args = dict(
        patch_size=32, embed_dim=768, depth=12, num_heads=12, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_base_patch32_clip_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch32_clip_448(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-B/32 CLIP image tower @ 448x448"""
    model_args = dict(
        patch_size=32, embed_dim=768, depth=12, num_heads=12, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_base_patch32_clip_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_clip_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-B/16 CLIP image tower"""
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_base_patch16_clip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_clip_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-B/16 CLIP image tower @ 384x384"""
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_base_patch16_clip_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_plus_clip_240(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Base (ViT-B/16+) CLIP image tower @ 240x240"""
    model_args = dict(
        patch_size=16, embed_dim=896, depth=12, num_heads=14, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_base_patch16_plus_clip_240', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch14_clip_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Large model (ViT-L/14) CLIP image tower"""
    model_args = dict(
        patch_size=14, embed_dim=1024, depth=24, num_heads=16, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_large_patch14_clip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch14_clip_336(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Large model (ViT-L/14) CLIP image tower @ 336x336"""
    model_args = dict(
        patch_size=14, embed_dim=1024, depth=24, num_heads=16, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_large_patch14_clip_336', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_huge_patch14_clip_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Huge model (ViT-H/14) CLIP image tower."""
    model_args = dict(
        patch_size=14, embed_dim=1280, depth=32, num_heads=16, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_huge_patch14_clip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_huge_patch14_clip_336(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Huge model (ViT-H/14) CLIP image tower @ 336x336"""
    model_args = dict(
        patch_size=14, embed_dim=1280, depth=32, num_heads=16, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_huge_patch14_clip_336', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_huge_patch14_clip_378(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Huge model (ViT-H/14) CLIP image tower @ 378x378"""
    model_args = dict(
        patch_size=14, embed_dim=1280, depth=32, num_heads=16, pre_norm=True, norm_layer=partial(LayerNorm, eps=1e-5))
    return _create_vision_transformer('vit_huge_patch14_clip_378', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_giant_patch14_clip_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Giant (little-g) model (ViT-g/14) from `Scaling Vision Transformers` - https://arxiv.org/abs/2106.04560"""
    model_args = dict(
        patch_size=14, embed_dim=1408, mlp_ratio=48/11, depth=40, num_heads=16, pre_norm=True,
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_vision_transformer('vit_giant_patch14_clip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_gigantic_patch14_clip_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-bigG model (ViT-G/14) from `Scaling Vision Transformers` - https://arxiv.org/abs/2106.04560"""
    model_args = dict(
        patch_size=14, embed_dim=1664, mlp_ratio=64/13, depth=48, num_heads=16, pre_norm=True,
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_vision_transformer('vit_gigantic_patch14_clip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_gigantic_patch14_clip_378(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-bigG model (ViT-G/14) from `Scaling Vision Transformers` - https://arxiv.org/abs/2106.04560"""
    model_args = dict(
        patch_size=14, embed_dim=1664, mlp_ratio=64/13, depth=48, num_heads=16, pre_norm=True,
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_vision_transformer('vit_gigantic_patch14_clip_378', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch32_clip_quickgelu_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-B/32 CLIP image tower @ 224x224"""
    model_args = dict(
        patch_size=32, embed_dim=768, depth=12, num_heads=12, pre_norm=True,
        norm_layer=partial(LayerNorm, eps=1e-5), act_layer='quick_gelu'
    )
    return _create_vision_transformer('vit_base_patch32_clip_quickgelu_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_clip_quickgelu_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-B/16 CLIP image tower w/ QuickGELU act"""
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, pre_norm=True,
        norm_layer=partial(LayerNorm, eps=1e-5), act_layer='quick_gelu'
    )
    return _create_vision_transformer('vit_base_patch16_clip_quickgelu_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch14_clip_quickgelu_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Large model (ViT-L/14) CLIP image tower w/ QuickGELU act"""
    model_args = dict(
        patch_size=14, embed_dim=1024, depth=24, num_heads=16, pre_norm=True,
        norm_layer=partial(LayerNorm, eps=1e-5), act_layer='quick_gelu'
    )
    return _create_vision_transformer('vit_large_patch14_clip_quickgelu_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch14_clip_quickgelu_336(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Large model (ViT-L/14) CLIP image tower @ 336x336 w/ QuickGELU act"""
    model_args = dict(
        patch_size=14, embed_dim=1024, depth=24, num_heads=16, pre_norm=True,
        norm_layer=partial(LayerNorm, eps=1e-5), act_layer='quick_gelu'
    )
    return _create_vision_transformer('vit_large_patch14_clip_quickgelu_336', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_huge_patch14_clip_quickgelu_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Huge model (ViT-H/14) CLIP image tower w/ QuickGELU act."""
    model_args = dict(
        patch_size=14, embed_dim=1280, depth=32, num_heads=16, pre_norm=True,
        norm_layer=partial(LayerNorm, eps=1e-5), act_layer='quick_gelu'
    )
    return _create_vision_transformer('vit_huge_patch14_clip_quickgelu_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_huge_patch14_clip_quickgelu_378(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Huge model (ViT-H/14) CLIP image tower @ 378x378 w/ QuickGELU act"""
    model_args = dict(
        patch_size=14, embed_dim=1280, depth=32, num_heads=16, pre_norm=True,
        norm_layer=partial(LayerNorm, eps=1e-5), act_layer='quick_gelu'
    )
    return _create_vision_transformer('vit_huge_patch14_clip_quickgelu_378', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_gigantic_patch14_clip_quickgelu_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-bigG model (ViT-G/14) w/ QuickGELU act"""
    model_args = dict(
        patch_size=14, embed_dim=1664, mlp_ratio=64/13, depth=48, num_heads=16, pre_norm=True,
        norm_layer=partial(LayerNorm, eps=1e-5), act_layer='quick_gelu'
    )
    return _create_vision_transformer('vit_gigantic_patch14_clip_quickgelu_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch32_plus_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Base (ViT-B/32+)"""
    model_args = dict(patch_size=32, embed_dim=896, depth=12, num_heads=14, init_values=1e-5)
    return _create_vision_transformer('vit_base_patch32_plus_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_plus_240(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Base (ViT-B/16+)"""
    model_args = dict(patch_size=16, embed_dim=896, depth=12, num_heads=14, init_values=1e-5)
    return _create_vision_transformer('vit_base_patch16_plus_240', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_rpn_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Base (ViT-B/16) w/ residual post-norm"""
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, qkv_bias=False, init_values=1e-5,
        class_token=False, block_fn=ResPostBlock, global_pool='avg')
    return _create_vision_transformer('vit_base_patch16_rpn_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch16_36x1_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Base w/ LayerScale + 36 x 1 (36 block serial) config. Experimental, may remove."""
    model_args = dict(patch_size=16, embed_dim=384, depth=36, num_heads=6, init_values=1e-5)
    return _create_vision_transformer('vit_small_patch16_36x1_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch16_18x2_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Small w/ LayerScale + 18 x 2 (36 block parallel) config. Experimental, may remove."""
    model_args = dict(
        patch_size=16, embed_dim=384, depth=18, num_heads=6, init_values=1e-5, block_fn=ParallelThingsBlock)
    return _create_vision_transformer('vit_small_patch16_18x2_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_18x2_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Base w/ LayerScale + 18 x 2 (36 block parallel) config. Experimental, may remove."""
    model_args = dict(
        patch_size=16, embed_dim=768, depth=18, num_heads=12, init_values=1e-5, block_fn=ParallelThingsBlock)
    return _create_vision_transformer('vit_base_patch16_18x2_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def eva_large_patch14_196(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """EVA-large model https://arxiv.org/abs/2211.07636 /via MAE MIM pretrain"""
    model_args = dict(patch_size=14, embed_dim=1024, depth=24, num_heads=16, global_pool='avg')
    return _create_vision_transformer('eva_large_patch14_196', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def eva_large_patch14_336(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """EVA-large model https://arxiv.org/abs/2211.07636 via MAE MIM pretrain"""
    model_args = dict(patch_size=14, embed_dim=1024, depth=24, num_heads=16, global_pool='avg')
    return _create_vision_transformer('eva_large_patch14_336', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def flexivit_small(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """FlexiViT-Small"""
    model_args = dict(patch_size=16, embed_dim=384, depth=12, num_heads=6, no_embed_class=True)
    return _create_vision_transformer('flexivit_small', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def flexivit_base(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """FlexiViT-Base"""
    model_args = dict(patch_size=16, embed_dim=768, depth=12, num_heads=12, no_embed_class=True)
    return _create_vision_transformer('flexivit_base', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def flexivit_large(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """FlexiViT-Large"""
    model_args = dict(patch_size=16, embed_dim=1024, depth=24, num_heads=16, no_embed_class=True)
    return _create_vision_transformer('flexivit_large', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_xp_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Large model (ViT-L/14) w/ parallel blocks and qk norm enabled."""
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, pre_norm=True, no_embed_class=True,
        norm_layer=RmsNorm, block_fn=ParallelScalingBlock, qkv_bias=False, qk_norm=True,
    )
    return _create_vision_transformer('vit_base_patch16_xp_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch14_xp_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Large model (ViT-L/14) w/ parallel blocks and qk norm enabled."""
    model_args = dict(
        patch_size=14, embed_dim=1024, depth=24, num_heads=16, pre_norm=True, no_embed_class=True,
        norm_layer=RmsNorm, block_fn=ParallelScalingBlock, qkv_bias=False, qk_norm=True,
    )
    return _create_vision_transformer('vit_large_patch14_xp_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_huge_patch14_xp_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-Huge model (ViT-H/14) w/ parallel blocks and qk norm enabled."""
    model_args = dict(
        patch_size=14, embed_dim=1280, depth=32, num_heads=16, pre_norm=True, no_embed_class=True,
        norm_layer=RmsNorm, block_fn=ParallelScalingBlock, qkv_bias=False, qk_norm=True,
    )
    return _create_vision_transformer('vit_huge_patch14_xp_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch14_dinov2(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-S/14 for DINOv2"""
    model_args = dict(patch_size=14, embed_dim=384, depth=12, num_heads=6, init_values=1e-5)
    return _create_vision_transformer('vit_small_patch14_dinov2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch14_dinov2(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-B/14 for DINOv2"""
    model_args = dict(patch_size=14, embed_dim=768, depth=12, num_heads=12, init_values=1e-5)
    return _create_vision_transformer('vit_base_patch14_dinov2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch14_dinov2(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-L/14 for DINOv2"""
    model_args = dict(patch_size=14, embed_dim=1024, depth=24, num_heads=16, init_values=1e-5)
    return _create_vision_transformer('vit_large_patch14_dinov2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_giant_patch14_dinov2(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-G/14 for DINOv2"""
    model_args = dict(
        patch_size=14, embed_dim=1536, depth=40, num_heads=24, init_values=1e-5,
        mlp_ratio=2.66667 * 2, mlp_layer=SwiGLUPacked, act_layer='silu'
    )
    return _create_vision_transformer('vit_giant_patch14_dinov2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch14_reg4_dinov2(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-S/14 for DINOv2 w/ 4 registers"""
    model_args = dict(
        patch_size=14, embed_dim=384, depth=12, num_heads=6, init_values=1e-5,
        reg_tokens=4, no_embed_class=True,
    )
    return _create_vision_transformer('vit_small_patch14_reg4_dinov2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch14_reg4_dinov2(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-B/14 for DINOv2 w/ 4 registers"""
    model_args = dict(
        patch_size=14, embed_dim=768, depth=12, num_heads=12, init_values=1e-5,
        reg_tokens=4, no_embed_class=True,
    )
    return _create_vision_transformer('vit_base_patch14_reg4_dinov2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch14_reg4_dinov2(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-L/14 for DINOv2 w/ 4 registers"""
    model_args = dict(
        patch_size=14, embed_dim=1024, depth=24, num_heads=16, init_values=1e-5,
        reg_tokens=4, no_embed_class=True,
    )
    return _create_vision_transformer('vit_large_patch14_reg4_dinov2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_giant_patch14_reg4_dinov2(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-G/14 for DINOv2"""
    model_args = dict(
        patch_size=14, embed_dim=1536, depth=40, num_heads=24, init_values=1e-5, mlp_ratio=2.66667 * 2,
        mlp_layer=SwiGLUPacked, act_layer='silu', reg_tokens=4, no_embed_class=True,
    )
    return _create_vision_transformer('vit_giant_patch14_reg4_dinov2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch14_reg1_tipsv2(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-B/14 for TIPSv2 (DINOv2-style w/ 1 register token, LayerScale init=1.0)."""
    model_args = dict(
        patch_size=14, embed_dim=768, depth=12, num_heads=12, init_values=1.0,
        reg_tokens=1, no_embed_class=True,
    )
    return _create_vision_transformer('vit_base_patch14_reg1_tipsv2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch14_reg1_tipsv2(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-L/14 for TIPSv2 (DINOv2-style w/ 1 register token, LayerScale init=1.0)."""
    model_args = dict(
        patch_size=14, embed_dim=1024, depth=24, num_heads=16, init_values=1.0,
        reg_tokens=1, no_embed_class=True,
    )
    return _create_vision_transformer('vit_large_patch14_reg1_tipsv2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch14_reg1_tipsv2(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """SoViT-400M/14 for TIPSv2 (DINOv2-style w/ 1 register token, LayerScale init=1.0)."""
    model_args = dict(
        patch_size=14, embed_dim=1152, depth=27, num_heads=16, init_values=1.0,
        mlp_ratio=4304 / 1152, reg_tokens=1, no_embed_class=True,
    )
    return _create_vision_transformer('vit_so400m_patch14_reg1_tipsv2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_giant_patch14_reg1_tipsv2(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT-G/14 for TIPSv2 (DINOv2-style w/ SwiGLU FFN, 1 register token, LayerScale init=1.0)."""
    model_args = dict(
        patch_size=14, embed_dim=1536, depth=40, num_heads=24, init_values=1.0,
        mlp_ratio=2.66667 * 2, mlp_layer=SwiGLUPacked, act_layer='silu',
        reg_tokens=1, no_embed_class=True,
    )
    return _create_vision_transformer('vit_giant_patch14_reg1_tipsv2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch32_siglip_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=32, embed_dim=768, depth=12, num_heads=12, class_token=False, global_pool='map',
        act_layer='gelu_tanh',
    )
    return _create_vision_transformer('vit_base_patch32_siglip_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_siglip_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, class_token=False, global_pool='map',
    )
    return _create_vision_transformer('vit_base_patch16_siglip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_siglip_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, class_token=False, global_pool='map',
    )
    return _create_vision_transformer('vit_base_patch16_siglip_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_siglip_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, class_token=False, global_pool='map',
    )
    return _create_vision_transformer('vit_base_patch16_siglip_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_siglip_512(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, class_token=False, global_pool='map',
    )
    return _create_vision_transformer('vit_base_patch16_siglip_512', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch16_siglip_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=1024, depth=24, num_heads=16, class_token=False, global_pool='map',
    )
    return _create_vision_transformer('vit_large_patch16_siglip_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch16_siglip_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=1024, depth=24, num_heads=16, class_token=False, global_pool='map',
    )
    return _create_vision_transformer('vit_large_patch16_siglip_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch16_siglip_512(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=1024, depth=24, num_heads=16, class_token=False, global_pool='map',
        act_layer='gelu_tanh'
    )
    return _create_vision_transformer('vit_large_patch16_siglip_512', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch14_siglip_378(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=14, embed_dim=1152, depth=27, num_heads=16, mlp_ratio=3.7362, class_token=False, global_pool='map',
    )
    return _create_vision_transformer('vit_so400m_patch14_siglip_378', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch14_siglip_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=14, embed_dim=1152, depth=27, num_heads=16, mlp_ratio=3.7362, class_token=False, global_pool='map',
    )
    return _create_vision_transformer('vit_so400m_patch14_siglip_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch16_siglip_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=1152, depth=27, num_heads=16, mlp_ratio=3.7362, class_token=False, global_pool='map',
        act_layer='gelu_tanh',
    )
    return _create_vision_transformer('vit_so400m_patch16_siglip_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch16_siglip_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=1152, depth=27, num_heads=16, mlp_ratio=3.7362, class_token=False, global_pool='map',
        act_layer='gelu_tanh',
    )
    return _create_vision_transformer('vit_so400m_patch16_siglip_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch16_siglip_512(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=1152, depth=27, num_heads=16, mlp_ratio=3.7362, class_token=False, global_pool='map',
        act_layer='gelu_tanh',
    )
    return _create_vision_transformer('vit_so400m_patch16_siglip_512', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_giantopt_patch16_siglip_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=1536, depth=40, num_heads=16, class_token=False, global_pool='map',
        act_layer='gelu_tanh',
    )
    return _create_vision_transformer('vit_giantopt_patch16_siglip_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_giantopt_patch16_siglip_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=1536, depth=40, num_heads=16, class_token=False, global_pool='map',
        act_layer='gelu_tanh',
    )
    return _create_vision_transformer('vit_giantopt_patch16_siglip_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch32_siglip_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=32, embed_dim=768, depth=12, num_heads=12, class_token=False, global_pool='avg', fc_norm=False,
        act_layer='gelu_tanh',
    )
    return _create_vision_transformer('vit_base_patch32_siglip_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_siglip_gap_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """A SigLIP variant of ViT with global average pooling (GAP) instead of attention pooling (MAP)."""
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, class_token=False, global_pool='avg', fc_norm=False,
    )
    return _create_vision_transformer('vit_base_patch16_siglip_gap_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_siglip_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """A SigLIP variant of ViT with global average pooling (GAP) instead of attention pooling (MAP)."""
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, class_token=False, global_pool='avg', fc_norm=False,
    )
    return _create_vision_transformer('vit_base_patch16_siglip_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_siglip_gap_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """A SigLIP variant of ViT with global average pooling (GAP) instead of attention pooling (MAP)."""
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, class_token=False, global_pool='avg', fc_norm=False,
    )
    return _create_vision_transformer('vit_base_patch16_siglip_gap_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_siglip_gap_512(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """A SigLIP variant of ViT with global average pooling (GAP) instead of attention pooling (MAP)."""
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, class_token=False, global_pool='avg', fc_norm=False,
    )
    return _create_vision_transformer('vit_base_patch16_siglip_gap_512', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch16_siglip_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """A SigLIP variant of ViT with global average pooling (GAP) instead of attention pooling (MAP)."""
    model_args = dict(
        patch_size=16, embed_dim=1024, depth=24, num_heads=16, class_token=False, global_pool='avg', fc_norm=False,
    )
    return _create_vision_transformer('vit_large_patch16_siglip_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch16_siglip_gap_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """A SigLIP variant of ViT with global average pooling (GAP) instead of attention pooling (MAP)."""
    model_args = dict(
        patch_size=16, embed_dim=1024, depth=24, num_heads=16, class_token=False, global_pool='avg', fc_norm=False,
    )
    return _create_vision_transformer('vit_large_patch16_siglip_gap_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch16_siglip_gap_512(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=1024, depth=24, num_heads=16, class_token=False,
        global_pool='avg', fc_norm=False, act_layer='gelu_tanh'
    )
    return _create_vision_transformer('vit_large_patch16_siglip_gap_512', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch14_siglip_gap_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """A SigLIP variant of ViT with global average pooling (GAP) instead of attention pooling (MAP)."""
    model_args = dict(
        patch_size=14, embed_dim=1152, depth=27, num_heads=16, mlp_ratio=3.7362,
        class_token=False, global_pool='avg', fc_norm=False,
    )
    return _create_vision_transformer('vit_so400m_patch14_siglip_gap_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch14_siglip_gap_378(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """A SigLIP variant of ViT with global average pooling (GAP) instead of attention pooling (MAP)."""
    model_args = dict(
        patch_size=14, embed_dim=1152, depth=27, num_heads=16, mlp_ratio=3.7362,
        class_token=False, global_pool='avg', fc_norm=False,
    )
    return _create_vision_transformer('vit_so400m_patch14_siglip_gap_378', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch14_siglip_gap_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """A SigLIP variant of ViT with global average pooling (GAP) instead of attention pooling (MAP)."""
    model_args = dict(
        patch_size=14, embed_dim=1152, depth=27, num_heads=16, mlp_ratio=3.7362,
        class_token=False, global_pool='avg', fc_norm=False,
    )
    return _create_vision_transformer('vit_so400m_patch14_siglip_gap_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch14_siglip_gap_448(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """A SigLIP variant of ViT with global average pooling (GAP) instead of attention pooling (MAP)."""
    model_args = dict(
        patch_size=14, embed_dim=1152, depth=27, num_heads=16, mlp_ratio=3.7362,
        class_token=False, global_pool='avg', fc_norm=False,
    )
    return _create_vision_transformer('vit_so400m_patch14_siglip_gap_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch14_siglip_gap_896(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """A SigLIP variant of ViT with global average pooling (GAP) instead of attention pooling (MAP)."""
    model_args = dict(
        patch_size=14, embed_dim=1152, depth=27, num_heads=16, mlp_ratio=3.7362,
        class_token=False, global_pool='avg', fc_norm=False,
    )
    return _create_vision_transformer('vit_so400m_patch14_siglip_gap_896', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch16_siglip_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """A SigLIP variant of ViT with global average pooling (GAP) instead of attention pooling (MAP)."""
    model_args = dict(
        patch_size=16, embed_dim=1152, depth=27, num_heads=16, mlp_ratio=3.7362,
        class_token=False, global_pool='avg', fc_norm=False, act_layer='gelu_tanh',
    )
    return _create_vision_transformer('vit_so400m_patch16_siglip_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch16_siglip_gap_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=1152, depth=27, num_heads=16, mlp_ratio=3.7362, class_token=False,
        global_pool='avg', fc_norm=False, act_layer='gelu_tanh'
    )
    return _create_vision_transformer('vit_so400m_patch16_siglip_gap_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so400m_patch16_siglip_gap_512(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=1152, depth=27, num_heads=16, mlp_ratio=3.7362, class_token=False,
        global_pool='avg', fc_norm=False, act_layer='gelu_tanh'
    )
    return _create_vision_transformer('vit_so400m_patch16_siglip_gap_512', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_giantopt_patch16_siglip_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=1536, depth=40, num_heads=16, class_token=False,
        global_pool='avg', fc_norm=False, act_layer='gelu_tanh'
    )
    return _create_vision_transformer('vit_giantopt_patch16_siglip_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_giantopt_patch16_siglip_gap_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=1536, depth=40, num_heads=16, class_token=False,
        global_pool='avg', fc_norm=False, act_layer='gelu_tanh'
    )
    return _create_vision_transformer('vit_giantopt_patch16_siglip_gap_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_wee_patch16_reg1_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=256, depth=14, num_heads=4, init_values=1e-5, mlp_ratio=5,
        class_token=False, no_embed_class=True, reg_tokens=1, global_pool='avg',
    )
    return _create_vision_transformer('vit_wee_patch16_reg1_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_dwee_patch16_reg1_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=256, depth=14, num_heads=4, init_values=1e-5, mlp_ratio=5,
        class_token=False, no_embed_class=True, reg_tokens=1, global_pool='avg', attn_layer='diff',
    )
    return _create_vision_transformer('vit_dwee_patch16_reg1_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_pwee_patch16_reg1_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=256, depth=16, num_heads=4, init_values=1e-5, mlp_ratio=5,
        class_token=False, no_embed_class=True, reg_tokens=1, global_pool='avg', block_fn=ParallelScalingBlock,
    )
    return _create_vision_transformer('vit_pwee_patch16_reg1_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_dpwee_patch16_reg1_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=256, depth=16, num_heads=4, init_values=1e-5, mlp_ratio=5,
        class_token=False, no_embed_class=True, reg_tokens=1, global_pool='avg', block_fn=DiffParallelScalingBlock,
    )
    return _create_vision_transformer('vit_dpwee_patch16_reg1_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_little_patch16_reg1_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=320, depth=14, num_heads=5, init_values=1e-5, mlp_ratio=5.6,
        class_token=False, no_embed_class=True, reg_tokens=1, global_pool='avg',
    )
    return _create_vision_transformer('vit_little_patch16_reg1_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_medium_patch16_reg1_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=512, depth=12, num_heads=8, init_values=1e-5,
        class_token=False, no_embed_class=True, reg_tokens=1, global_pool='avg',
    )
    return _create_vision_transformer('vit_medium_patch16_reg1_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_mediumd_patch16_reg4_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=512, depth=20, num_heads=8, init_values=1e-5,
        class_token=False, no_embed_class=True, reg_tokens=4, global_pool='avg',
    )
    return _create_vision_transformer('vit_mediumd_patch16_reg4_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_mediumd_patch16_reg4_gap_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=512, depth=20, num_heads=8, init_values=1e-5,
        class_token=False, no_embed_class=True, reg_tokens=4, global_pool='avg',
    )
    return _create_vision_transformer('vit_mediumd_patch16_reg4_gap_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_betwixt_patch16_reg1_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=640, depth=12, num_heads=10, init_values=1e-5,
        class_token=False, no_embed_class=True, reg_tokens=1, global_pool='avg',
    )
    return _create_vision_transformer('vit_betwixt_patch16_reg1_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_betwixt_patch16_reg4_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=640, depth=12, num_heads=10, init_values=1e-5,
        class_token=False, no_embed_class=True, reg_tokens=4, global_pool='avg',
    )
    return _create_vision_transformer('vit_betwixt_patch16_reg4_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_betwixt_patch16_reg4_gap_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=640, depth=12, num_heads=10, init_values=1e-5,
        class_token=False, no_embed_class=True, reg_tokens=4, global_pool='avg',
    )
    return _create_vision_transformer('vit_betwixt_patch16_reg4_gap_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_reg4_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, class_token=False,
        no_embed_class=True, global_pool='avg', reg_tokens=4,
    )
    return _create_vision_transformer('vit_base_patch16_reg4_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so150m_patch16_reg4_map_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """SO150M (shape optimized, but diff than paper def, optimized for GPU)"""
    model_args = dict(
        patch_size=16, embed_dim=896, depth=18, num_heads=14, mlp_ratio=2.572,
        class_token=False, reg_tokens=4, global_pool='map',
    )
    return _create_vision_transformer('vit_so150m_patch16_reg4_map_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so150m_patch16_reg4_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """SO150M (shape optimized, but diff than paper def, optimized for GPU)"""
    model_args = dict(
        patch_size=16, embed_dim=896, depth=18, num_heads=14, mlp_ratio=2.572,
        class_token=False, reg_tokens=4, global_pool='avg', fc_norm=False,
    )
    return _create_vision_transformer('vit_so150m_patch16_reg4_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so150m_patch16_reg4_gap_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """SO150M (shape optimized, but diff than paper def, optimized for GPU)"""
    model_args = dict(
        patch_size=16, embed_dim=896, depth=18, num_heads=14, mlp_ratio=2.572,
        class_token=False, reg_tokens=4, global_pool='avg', fc_norm=False,
    )
    return _create_vision_transformer('vit_so150m_patch16_reg4_gap_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so150m2_patch16_reg1_gap_256(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """SO150M v2 (shape optimized, but diff than paper def, optimized for GPU)"""
    model_args = dict(
        patch_size=16, embed_dim=832, depth=21, num_heads=13, mlp_ratio=34/13, init_values=1e-5,
        qkv_bias=False, class_token=False, reg_tokens=1, global_pool='avg',
    )
    return _create_vision_transformer('vit_so150m2_patch16_reg1_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so150m2_patch16_reg1_gap_384(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """SO150M v2 (shape optimized, but diff than paper def, optimized for GPU)"""
    model_args = dict(
        patch_size=16, embed_dim=832, depth=21, num_heads=13, mlp_ratio=34/13, init_values=1e-5,
        qkv_bias=False, class_token=False, reg_tokens=1, global_pool='avg',
    )
    return _create_vision_transformer('vit_so150m2_patch16_reg1_gap_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_so150m2_patch16_reg1_gap_448(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """SO150M v2 (shape optimized, but diff than paper def, optimized for GPU)"""
    model_args = dict(
        patch_size=16, embed_dim=832, depth=21, num_heads=13, mlp_ratio=34/13, init_values=1e-5,
        qkv_bias=False, class_token=False, reg_tokens=1, global_pool='avg',
    )
    return _create_vision_transformer('vit_so150m2_patch16_reg1_gap_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_intern300m_patch14_448(pretrained: bool = False, **kwargs) -> VisionTransformer:
    model_args = dict(
        patch_size=14, embed_dim=1024, depth=24, num_heads=16,
        init_values=0.1, final_norm=False, dynamic_img_size=True,
    )
    return _create_vision_transformer('vit_intern300m_patch14_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def aimv2_large_patch14_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT Large AIM-v2 model"""
    model_args = dict(
        patch_size=14, embed_dim=1024, depth=24, num_heads=8, class_token=False, fc_norm=False,
        mlp_ratio=2.75, global_pool='avg', qkv_bias=False, proj_bias=False, act_layer='silu',
        norm_layer=partial(RmsNorm, eps=1e-5), embed_norm_layer=partial(RmsNorm, eps=1e-5), mlp_layer=SwiGLU,
    )
    return _create_vision_transformer('aimv2_large_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def aimv2_huge_patch14_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT Huge AIM-v2 model"""
    model_args = dict(
        patch_size=14, embed_dim=1536, depth=24, num_heads=12, class_token=False, fc_norm=False,
        mlp_ratio=2.6667, global_pool='avg', qkv_bias=False, proj_bias=False, act_layer='silu',
        norm_layer=partial(RmsNorm, eps=1e-5), embed_norm_layer=partial(RmsNorm, eps=1e-5), mlp_layer=SwiGLU,
    )
    return _create_vision_transformer('aimv2_huge_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def aimv2_1b_patch14_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT 1B AIM-v2 model"""
    model_args = dict(
        patch_size=14, embed_dim=2048, depth=24, num_heads=16, class_token=False, fc_norm=False,
        mlp_ratio=2.75, global_pool='avg', qkv_bias=False, proj_bias=False, act_layer='silu',
        norm_layer=partial(RmsNorm, eps=1e-5), embed_norm_layer=partial(RmsNorm, eps=1e-5), mlp_layer=SwiGLU,
    )
    return _create_vision_transformer('aimv2_1b_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def aimv2_3b_patch14_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT 3B AIM-v2 model"""
    model_args = dict(
        patch_size=14, embed_dim=3072, depth=24, num_heads=24, class_token=False, fc_norm=False,
        mlp_ratio=2.6667, global_pool='avg', qkv_bias=False, proj_bias=False, act_layer='silu',
        norm_layer=partial(RmsNorm, eps=1e-5), embed_norm_layer=partial(RmsNorm, eps=1e-5), mlp_layer=SwiGLU,
    )
    return _create_vision_transformer('aimv2_3b_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def aimv2_large_patch14_336(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT Large AIM-v2 model"""
    model_args = dict(
        patch_size=14, embed_dim=1024, depth=24, num_heads=8, class_token=False, fc_norm=False,
        mlp_ratio=2.75, global_pool='avg', qkv_bias=False, proj_bias=False, act_layer='silu',
        norm_layer=partial(RmsNorm, eps=1e-5), embed_norm_layer=partial(RmsNorm, eps=1e-5), mlp_layer=SwiGLU,
    )
    return _create_vision_transformer('aimv2_large_patch14_336', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def aimv2_huge_patch14_336(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT Huge AIM-v2 model"""
    model_args = dict(
        patch_size=14, embed_dim=1536, depth=24, num_heads=12, class_token=False, fc_norm=False,
        mlp_ratio=2.6667, global_pool='avg', qkv_bias=False, proj_bias=False, act_layer='silu',
        norm_layer=partial(RmsNorm, eps=1e-5), embed_norm_layer=partial(RmsNorm, eps=1e-5), mlp_layer=SwiGLU,
    )
    return _create_vision_transformer('aimv2_huge_patch14_336', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def aimv2_1b_patch14_336(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT 1B AIM-v2 model"""
    model_args = dict(
        patch_size=14, embed_dim=2048, depth=24, num_heads=16, class_token=False, fc_norm=False,
        mlp_ratio=2.75, global_pool='avg', qkv_bias=False, proj_bias=False, act_layer='silu',
        norm_layer=partial(RmsNorm, eps=1e-5), embed_norm_layer=partial(RmsNorm, eps=1e-5), mlp_layer=SwiGLU,
    )
    return _create_vision_transformer('aimv2_1b_patch14_336', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def aimv2_3b_patch14_336(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT 3B AIM-v2 model"""
    model_args = dict(
        patch_size=14, embed_dim=3072, depth=24, num_heads=24, class_token=False, fc_norm=False,
        mlp_ratio=2.6667, global_pool='avg', qkv_bias=False, proj_bias=False, act_layer='silu',
        norm_layer=partial(RmsNorm, eps=1e-5), embed_norm_layer=partial(RmsNorm, eps=1e-5), mlp_layer=SwiGLU,
    )
    return _create_vision_transformer('aimv2_3b_patch14_336', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def aimv2_large_patch14_448(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT Large AIM-v2 model"""
    model_args = dict(
        patch_size=14, embed_dim=1024, depth=24, num_heads=8, class_token=False, fc_norm=False,
        mlp_ratio=2.75, global_pool='avg', qkv_bias=False, proj_bias=False, act_layer='silu',
        norm_layer=partial(RmsNorm, eps=1e-5), embed_norm_layer=partial(RmsNorm, eps=1e-5), mlp_layer=SwiGLU,
    )
    return _create_vision_transformer('aimv2_large_patch14_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def aimv2_huge_patch14_448(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT Huge AIM-v2 model"""
    model_args = dict(
        patch_size=14, embed_dim=1536, depth=24, num_heads=12, class_token=False, fc_norm=False,
        mlp_ratio=2.6667, global_pool='avg', qkv_bias=False, proj_bias=False, act_layer='silu',
        norm_layer=partial(RmsNorm, eps=1e-5), embed_norm_layer=partial(RmsNorm, eps=1e-5), mlp_layer=SwiGLU,
    )
    return _create_vision_transformer('aimv2_huge_patch14_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def aimv2_1b_patch14_448(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT 1B AIM-v2 model"""
    model_args = dict(
        patch_size=14, embed_dim=2048, depth=24, num_heads=16, class_token=False, fc_norm=False,
        mlp_ratio=2.75, global_pool='avg', qkv_bias=False, proj_bias=False, act_layer='silu',
        norm_layer=partial(RmsNorm, eps=1e-5), embed_norm_layer=partial(RmsNorm, eps=1e-5), mlp_layer=SwiGLU,
    )
    return _create_vision_transformer('aimv2_1b_patch14_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def aimv2_3b_patch14_448(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """ViT 3B AIM-v2 model"""
    model_args = dict(
        patch_size=14, embed_dim=3072, depth=24, num_heads=24, class_token=False, fc_norm=False,
        mlp_ratio=2.6667, global_pool='avg', qkv_bias=False, proj_bias=False, act_layer='silu',
        norm_layer=partial(RmsNorm, eps=1e-5), embed_norm_layer=partial(RmsNorm, eps=1e-5), mlp_layer=SwiGLU,
    )
    return _create_vision_transformer('aimv2_3b_patch14_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def beit3_base_patch16_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """BEiT3 Base model (ViT-Base size) with patch size 16x16."""
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, mlp_ratio=4,
        scale_attn_norm=True, scale_mlp_norm=True, class_token=True, global_pool='avg',
        norm_layer=partial(LayerNorm, eps=1e-5)
    )
    return _create_vision_transformer('beit3_base_patch16_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def beit3_large_patch16_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """BEiT3 Large model (ViT-Large size) with patch size 16x16."""
    model_args = dict(
        patch_size=16, embed_dim=1024, depth=24, num_heads=16, mlp_ratio=4,
        scale_attn_norm=True, scale_mlp_norm=True, class_token=True, global_pool='avg',
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_vision_transformer('beit3_large_patch16_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def beit3_giant_patch14_224(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """BEiT3 Giant model with patch size 14x14."""
    model_args = dict(
        patch_size=14, embed_dim=1408, depth=40, num_heads=16, mlp_ratio=4.3637,
        scale_attn_norm=True, scale_mlp_norm=True, class_token=True, global_pool='avg',
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_vision_transformer('beit3_giant_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def beit3_giant_patch14_336(pretrained: bool = False, **kwargs) -> VisionTransformer:
    """BEiT3 Giant model with patch size 14x14 and image size 336x336."""
    model_args = dict(
        img_size=336, patch_size=14, embed_dim=1408, depth=40, num_heads=16, mlp_ratio=4.3637,
        scale_attn_norm=True, scale_mlp_norm=True, class_token=True, global_pool='avg',
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_vision_transformer('beit3_giant_patch14_336', pretrained=pretrained, **dict(model_args, **kwargs))
