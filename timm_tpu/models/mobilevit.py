"""MobileViT v1/v2 — mobile conv-transformer hybrids on ByobNet (NHWC / nnx).

Re-implements reference timm/models/mobilevit.py:1-710: MobileViT stacks
inverted-residual ByobNet stages with blocks that unfold the feature map into
non-overlapping patches, run transformers across patches, and fold back. V1
uses standard MHSA across patch positions (one sequence per intra-patch
pixel); V2 uses separable linear self-attention in a (B, P, N, C) layout.

TPU notes: unfold/fold are static reshape/transpose chains (channels-last, so
no NCHW permutes); the v2 linear attention is elementwise + two reductions
over the patch axis — XLA fuses it into a handful of kernels. The rare
non-divisible resize path uses statically-built bilinear weight matrices
(einsum), exact for both align_corners conventions.
"""
import math
from functools import partial
import jax
import jax.numpy as jnp
import numpy as np
from flax import nnx

from ..layers import ConvMlp, Dropout, DropPath, GroupNorm1, LayerNorm, make_divisible, to_2tuple
from ._builder import build_model_with_cfg
from ._registry import generate_default_cfgs, register_model
from .byobnet import ByoBlockCfg, ByoModelCfg, ByobNet, LayerFn, num_groups, register_block
from .vision_transformer import Block as TransformerBlock

__all__ = []


def _inverted_residual_block(d, c, s, br=4.0):
    return ByoBlockCfg(
        type='bottle', d=d, c=c, s=s, gs=1, br=br,
        block_kwargs=dict(bottle_in=True, linear_out=True))


def _mobilevit_block(d, c, s, transformer_dim, transformer_depth, patch_size=4, br=4.0):
    return (
        _inverted_residual_block(d=d, c=c, s=s, br=br),
        ByoBlockCfg(
            type='mobilevit', d=1, c=c, s=1,
            block_kwargs=dict(
                transformer_dim=transformer_dim,
                transformer_depth=transformer_depth,
                patch_size=patch_size)),
    )


def _mobilevitv2_block(d, c, s, transformer_depth, patch_size=2, br=2.0, transformer_br=0.5):
    return (
        _inverted_residual_block(d=d, c=c, s=s, br=br),
        ByoBlockCfg(
            type='mobilevit2', d=1, c=c, s=1, br=transformer_br, gs=1,
            block_kwargs=dict(
                transformer_depth=transformer_depth,
                patch_size=patch_size)),
    )


def _mobilevitv2_cfg(multiplier=1.0):
    chs = (64, 128, 256, 384, 512)
    if multiplier != 1.0:
        chs = tuple([int(c * multiplier) for c in chs])
    return ByoModelCfg(
        blocks=(
            _inverted_residual_block(d=1, c=chs[0], s=1, br=2.0),
            _inverted_residual_block(d=2, c=chs[1], s=2, br=2.0),
            _mobilevitv2_block(d=1, c=chs[2], s=2, transformer_depth=2),
            _mobilevitv2_block(d=1, c=chs[3], s=2, transformer_depth=4),
            _mobilevitv2_block(d=1, c=chs[4], s=2, transformer_depth=3),
        ),
        stem_chs=int(32 * multiplier),
        stem_type='3x3',
        stem_pool='',
        downsample='',
        act_layer='silu',
    )


model_cfgs = dict(
    mobilevit_xxs=ByoModelCfg(
        blocks=(
            _inverted_residual_block(d=1, c=16, s=1, br=2.0),
            _inverted_residual_block(d=3, c=24, s=2, br=2.0),
            _mobilevit_block(d=1, c=48, s=2, transformer_dim=64, transformer_depth=2, patch_size=2, br=2.0),
            _mobilevit_block(d=1, c=64, s=2, transformer_dim=80, transformer_depth=4, patch_size=2, br=2.0),
            _mobilevit_block(d=1, c=80, s=2, transformer_dim=96, transformer_depth=3, patch_size=2, br=2.0),
        ),
        stem_chs=16, stem_type='3x3', stem_pool='', downsample='',
        act_layer='silu', num_features=320,
    ),
    mobilevit_xs=ByoModelCfg(
        blocks=(
            _inverted_residual_block(d=1, c=32, s=1),
            _inverted_residual_block(d=3, c=48, s=2),
            _mobilevit_block(d=1, c=64, s=2, transformer_dim=96, transformer_depth=2, patch_size=2),
            _mobilevit_block(d=1, c=80, s=2, transformer_dim=120, transformer_depth=4, patch_size=2),
            _mobilevit_block(d=1, c=96, s=2, transformer_dim=144, transformer_depth=3, patch_size=2),
        ),
        stem_chs=16, stem_type='3x3', stem_pool='', downsample='',
        act_layer='silu', num_features=384,
    ),
    mobilevit_s=ByoModelCfg(
        blocks=(
            _inverted_residual_block(d=1, c=32, s=1),
            _inverted_residual_block(d=3, c=64, s=2),
            _mobilevit_block(d=1, c=96, s=2, transformer_dim=144, transformer_depth=2, patch_size=2),
            _mobilevit_block(d=1, c=128, s=2, transformer_dim=192, transformer_depth=4, patch_size=2),
            _mobilevit_block(d=1, c=160, s=2, transformer_dim=240, transformer_depth=3, patch_size=2),
        ),
        stem_chs=16, stem_type='3x3', stem_pool='', downsample='',
        act_layer='silu', num_features=640,
    ),
    mobilevitv2_050=_mobilevitv2_cfg(.50),
    mobilevitv2_075=_mobilevitv2_cfg(.75),
    mobilevitv2_125=_mobilevitv2_cfg(1.25),
    mobilevitv2_100=_mobilevitv2_cfg(1.0),
    mobilevitv2_150=_mobilevitv2_cfg(1.5),
    mobilevitv2_175=_mobilevitv2_cfg(1.75),
    mobilevitv2_200=_mobilevitv2_cfg(2.0),
)


def _bilinear_resize(x, out_h, out_w, align_corners: bool):
    """Exact bilinear resize via static weight matrices (NHWC einsum).

    Shapes are compile-time constants, so the (out, in) weight matrices are
    numpy-built at trace time; supports align_corners=True (v2 blocks) which
    jax.image.resize does not."""
    B, H, W, C = x.shape
    if H == out_h and W == out_w:
        return x

    def weights(n_in, n_out):
        w = np.zeros((n_out, n_in), np.float32)
        for o in range(n_out):
            if align_corners and n_out > 1:
                pos = o * (n_in - 1) / (n_out - 1)
            else:
                pos = max((o + 0.5) * n_in / n_out - 0.5, 0.0)
            lo = min(int(math.floor(pos)), n_in - 1)
            hi = min(lo + 1, n_in - 1)
            frac = pos - lo
            w[o, lo] += 1.0 - frac
            w[o, hi] += frac
        return jnp.asarray(w)

    wh = weights(H, out_h)
    ww = weights(W, out_w)
    x = jnp.einsum('oh,bhwc->bowc', wh.astype(x.dtype), x)
    return jnp.einsum('pw,bowc->bopc', ww.astype(x.dtype), x)


class MobileVitBlock(nnx.Module):
    """Local conv + patch-unfolded transformer + fold + fusion
    (reference mobilevit.py:165-280)."""

    def __init__(
            self, in_chs, out_chs=None, kernel_size=3, stride=1, bottle_ratio=1.0,
            group_size=None, dilation=(1, 1), mlp_ratio=2.0, transformer_dim=None,
            transformer_depth=2, patch_size=8, num_heads=4, attn_drop=0., drop=0.,
            no_fusion=False, drop_path_rate=0., layers: LayerFn = None,
            transformer_norm_layer=partial(LayerNorm, eps=1e-5),  # torch nn.LayerNorm default
            *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs, **kwargs):
        layers = layers or LayerFn()
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        groups = num_groups(group_size, in_chs)
        out_chs = out_chs or in_chs
        transformer_dim = transformer_dim or make_divisible(bottle_ratio * in_chs)

        self.conv_kxk = layers.conv_norm_act(
            in_chs, in_chs, kernel_size=kernel_size, stride=stride,
            groups=groups, dilation=dilation[0], **dd)
        self.conv_1x1 = nnx.Conv(
            in_chs, transformer_dim, kernel_size=(1, 1), use_bias=False,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.transformer = nnx.List([
            TransformerBlock(
                transformer_dim, mlp_ratio=mlp_ratio, num_heads=num_heads, qkv_bias=True,
                attn_drop=attn_drop, proj_drop=drop, drop_path=drop_path_rate,
                act_layer=layers.act, norm_layer=transformer_norm_layer, **dd)
            for _ in range(transformer_depth)])
        self.norm = transformer_norm_layer(transformer_dim, rngs=rngs)
        self.conv_proj = layers.conv_norm_act(transformer_dim, out_chs, kernel_size=1, stride=1, **dd)
        self.conv_fusion = None if no_fusion else layers.conv_norm_act(
            in_chs + out_chs, out_chs, kernel_size=kernel_size, stride=1, **dd)
        self.patch_size = to_2tuple(patch_size)

    def __call__(self, x):
        shortcut = x
        x = self.conv_kxk(x)
        x = self.conv_1x1(x)

        ph, pw = self.patch_size
        B, H, W, C = x.shape
        new_h, new_w = math.ceil(H / ph) * ph, math.ceil(W / pw) * pw
        nh, nw = new_h // ph, new_w // pw
        interpolate = new_h != H or new_w != W
        if interpolate:
            x = _bilinear_resize(x, new_h, new_w, align_corners=False)

        # unfold: one sequence of N patches per intra-patch pixel (B*P, N, C)
        x = x.reshape(B, nh, ph, nw, pw, C).transpose(0, 2, 4, 1, 3, 5)
        x = x.reshape(B * ph * pw, nh * nw, C)
        for blk in self.transformer:
            x = blk(x)
        x = self.norm(x)
        # fold back
        x = x.reshape(B, ph, pw, nh, nw, C).transpose(0, 3, 1, 4, 2, 5)
        x = x.reshape(B, new_h, new_w, C)
        if interpolate:
            x = _bilinear_resize(x, H, W, align_corners=False)

        x = self.conv_proj(x)
        if self.conv_fusion is not None:
            x = self.conv_fusion(jnp.concatenate([shortcut, x], axis=-1))
        return x


class LinearSelfAttention(nnx.Module):
    """Separable linear self-attention over the patch axis; input laid out
    (B, P, N, C) with 1x1 convs over C (reference mobilevit.py:281-402)."""

    def __init__(self, embed_dim, attn_drop=0.0, proj_drop=0.0, bias=True,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.embed_dim = embed_dim
        self.qkv_proj = nnx.Conv(
            embed_dim, 1 + 2 * embed_dim, kernel_size=(1, 1), use_bias=bias,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.out_proj = nnx.Conv(
            embed_dim, embed_dim, kernel_size=(1, 1), use_bias=bias,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.out_drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x):
        # x: (B, P, N, C)
        qkv = self.qkv_proj(x)
        query, key, value = jnp.split(qkv, [1, 1 + self.embed_dim], axis=-1)
        context_scores = jax.nn.softmax(query, axis=2)  # softmax over patches N
        context_scores = self.attn_drop(context_scores)
        context_vector = (key * context_scores).sum(axis=2, keepdims=True)  # (B, P, 1, d)
        out = jax.nn.relu(value) * context_vector
        return self.out_drop(self.out_proj(out))


class LinearTransformerBlock(nnx.Module):
    """Pre-norm linear-attention transformer in (B, P, N, C)
    (reference mobilevit.py:405-465)."""

    def __init__(self, embed_dim, mlp_ratio=2.0, drop=0.0, attn_drop=0.0, drop_path=0.0,
                 act_layer=None, norm_layer=None,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        act_layer = act_layer or 'silu'
        norm_layer = norm_layer or GroupNorm1
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm1 = norm_layer(embed_dim, rngs=rngs)
        self.attn = LinearSelfAttention(embed_dim, attn_drop=attn_drop, proj_drop=drop, **dd)
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(embed_dim, rngs=rngs)
        self.mlp = ConvMlp(embed_dim, int(embed_dim * mlp_ratio), act_layer=act_layer, drop=drop, **dd)
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

    def __call__(self, x):
        x = x + self.drop_path1(self.attn(self.norm1(x)))
        return x + self.drop_path2(self.mlp(self.norm2(x)))


class MobileVitV2Block(nnx.Module):
    """MobileViTv2 block with separable linear attention
    (reference mobilevit.py:468-571)."""

    def __init__(
            self, in_chs, out_chs=None, kernel_size=3, bottle_ratio=1.0, group_size=1,
            dilation=(1, 1), mlp_ratio=2.0, transformer_dim=None, transformer_depth=2,
            patch_size=8, attn_drop=0., drop=0., drop_path_rate=0.,
            layers: LayerFn = None, transformer_norm_layer=GroupNorm1,
            *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs, **kwargs):
        layers = layers or LayerFn()
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        groups = num_groups(group_size, in_chs)
        out_chs = out_chs or in_chs
        transformer_dim = transformer_dim or make_divisible(bottle_ratio * in_chs)

        self.conv_kxk = layers.conv_norm_act(
            in_chs, in_chs, kernel_size=kernel_size, stride=1,
            groups=groups, dilation=dilation[0], **dd)
        self.conv_1x1 = nnx.Conv(
            in_chs, transformer_dim, kernel_size=(1, 1), use_bias=False,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.transformer = nnx.List([
            LinearTransformerBlock(
                transformer_dim, mlp_ratio=mlp_ratio, attn_drop=attn_drop, drop=drop,
                drop_path=drop_path_rate, act_layer=layers.act,
                norm_layer=transformer_norm_layer, **dd)
            for _ in range(transformer_depth)])
        self.norm = transformer_norm_layer(transformer_dim, rngs=rngs)
        self.conv_proj = layers.conv_norm_act(
            transformer_dim, out_chs, kernel_size=1, stride=1, apply_act=False, **dd)
        self.patch_size = to_2tuple(patch_size)

    def __call__(self, x):
        B, H, W, C = x.shape
        ph, pw = self.patch_size
        new_h, new_w = math.ceil(H / ph) * ph, math.ceil(W / pw) * pw
        nh, nw = new_h // ph, new_w // pw
        if new_h != H or new_w != W:
            x = _bilinear_resize(x, new_h, new_w, align_corners=True)

        x = self.conv_kxk(x)
        x = self.conv_1x1(x)

        # unfold to (B, P, N, C)
        C = x.shape[-1]
        x = x.reshape(B, nh, ph, nw, pw, C).transpose(0, 2, 4, 1, 3, 5)
        x = x.reshape(B, ph * pw, nh * nw, C)
        for blk in self.transformer:
            x = blk(x)
        x = self.norm(x)
        # fold back
        x = x.reshape(B, ph, pw, nh, nw, C).transpose(0, 3, 1, 4, 2, 5)
        x = x.reshape(B, new_h, new_w, C)

        return self.conv_proj(x)


register_block('mobilevit', MobileVitBlock)
register_block('mobilevit2', MobileVitV2Block)


def _create_mobilevit(variant, cfg_variant=None, pretrained=False, **kwargs):
    return build_model_with_cfg(
        ByobNet, variant, pretrained,
        model_cfg=model_cfgs[variant] if not cfg_variant else model_cfgs[cfg_variant],
        feature_cfg=dict(flatten_sequential=True),
        **kwargs)


def _cfg(url: str = '', **kwargs):
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 256, 256), 'pool_size': (8, 8),
        'crop_pct': 0.9, 'interpolation': 'bicubic',
        'mean': (0., 0., 0.), 'std': (1., 1., 1.),
        'first_conv': 'stem.conv', 'classifier': 'head.fc',
        'fixed_input_size': False, 'license': 'cvnets-license',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'mobilevit_xxs.cvnets_in1k': _cfg(),
    'mobilevit_xs.cvnets_in1k': _cfg(),
    'mobilevit_s.cvnets_in1k': _cfg(),
    'mobilevitv2_050.cvnets_in1k': _cfg(crop_pct=0.888),
    'mobilevitv2_075.cvnets_in1k': _cfg(crop_pct=0.888),
    'mobilevitv2_100.cvnets_in1k': _cfg(crop_pct=0.888),
    'mobilevitv2_125.cvnets_in1k': _cfg(crop_pct=0.888),
    'mobilevitv2_150.cvnets_in1k': _cfg(crop_pct=0.888),
    'mobilevitv2_175.cvnets_in1k': _cfg(crop_pct=0.888),
    'mobilevitv2_200.cvnets_in1k': _cfg(crop_pct=0.888),
    'mobilevitv2_150.cvnets_in22k_ft_in1k': _cfg(crop_pct=0.888),
    'mobilevitv2_175.cvnets_in22k_ft_in1k': _cfg(crop_pct=0.888),
    'mobilevitv2_200.cvnets_in22k_ft_in1k': _cfg(crop_pct=0.888),
    'mobilevitv2_150.cvnets_in22k_ft_in1k_384': _cfg(input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0),
    'mobilevitv2_175.cvnets_in22k_ft_in1k_384': _cfg(input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0),
    'mobilevitv2_200.cvnets_in22k_ft_in1k_384': _cfg(input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0),
})


@register_model
def mobilevit_xxs(pretrained=False, **kwargs) -> ByobNet:
    return _create_mobilevit('mobilevit_xxs', pretrained=pretrained, **kwargs)


@register_model
def mobilevit_xs(pretrained=False, **kwargs) -> ByobNet:
    return _create_mobilevit('mobilevit_xs', pretrained=pretrained, **kwargs)


@register_model
def mobilevit_s(pretrained=False, **kwargs) -> ByobNet:
    return _create_mobilevit('mobilevit_s', pretrained=pretrained, **kwargs)


@register_model
def mobilevitv2_050(pretrained=False, **kwargs) -> ByobNet:
    return _create_mobilevit('mobilevitv2_050', pretrained=pretrained, **kwargs)


@register_model
def mobilevitv2_075(pretrained=False, **kwargs) -> ByobNet:
    return _create_mobilevit('mobilevitv2_075', pretrained=pretrained, **kwargs)


@register_model
def mobilevitv2_100(pretrained=False, **kwargs) -> ByobNet:
    return _create_mobilevit('mobilevitv2_100', pretrained=pretrained, **kwargs)


@register_model
def mobilevitv2_125(pretrained=False, **kwargs) -> ByobNet:
    return _create_mobilevit('mobilevitv2_125', pretrained=pretrained, **kwargs)


@register_model
def mobilevitv2_150(pretrained=False, **kwargs) -> ByobNet:
    return _create_mobilevit('mobilevitv2_150', pretrained=pretrained, **kwargs)


@register_model
def mobilevitv2_175(pretrained=False, **kwargs) -> ByobNet:
    return _create_mobilevit('mobilevitv2_175', pretrained=pretrained, **kwargs)


@register_model
def mobilevitv2_200(pretrained=False, **kwargs) -> ByobNet:
    return _create_mobilevit('mobilevitv2_200', pretrained=pretrained, **kwargs)
